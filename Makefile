# Builds the native core: libbrpc_core.so (C++ host runtime).
# The compute path is JAX/XLA; this library is the bRPC-shaped host core:
# IOBuf, resource pools, work-stealing executor, timers, epoll socket core,
# wire framing, and bvar combiners.  Python binds it via ctypes
# (brpc_tpu/_core/lib.py).

CXX      ?= g++
CXXFLAGS ?= -O2 -g -std=c++20 -fPIC -Wall -Wextra -Wno-unused-parameter -pthread
LDFLAGS  ?= -shared -pthread

SRC := $(wildcard src/cc/butil/*.cc) \
       $(wildcard src/cc/bthread/*.cc) \
       $(wildcard src/cc/net/*.cc) \
       $(wildcard src/cc/bvar/*.cc) \
       $(filter-out src/cc/fastrpc_module.cc,$(wildcard src/cc/*.cc))
OBJ := $(SRC:.cc=.o)
PYOBJ := src/cc/fastrpc_module.o
DEP := $(OBJ:.o=.d) $(PYOBJ:.o=.d)
LIB := brpc_tpu/_core/libbrpc_core.so
# CPython C-extension for the RPC hot boundary (no ctypes marshalling).
PYEXT := brpc_tpu/_core/_fastrpc.so
PYINC := $(shell python3-config --includes)

all: $(LIB) $(PYEXT)

$(LIB): $(OBJ)
	$(CXX) $(LDFLAGS) -o $@ $(OBJ)

# Built via the %.o pattern rule so -MMD tracks net/ and butil/ headers: a
# struct-layout change must rebuild the extension, not leave a stale .so.
$(PYOBJ): CXXFLAGS += $(PYINC)

$(PYEXT): $(PYOBJ) $(LIB)
	$(CXX) $(LDFLAGS) -o $@ $(PYOBJ) \
	    -Lbrpc_tpu/_core -lbrpc_core -Wl,-rpath,'$$ORIGIN'

# -MMD -MP: auto header dependencies (a struct-layout change in a header
# must rebuild every TU that includes it, or TUs disagree on offsets).
%.o: %.cc
	$(CXX) $(CXXFLAGS) -MMD -MP -Isrc/cc -c -o $@ $<

-include $(DEP)

clean:
	rm -f $(OBJ) $(PYOBJ) $(DEP) $(LIB) $(PYEXT)

test: $(LIB)
	python -m pytest tests/ -x -q

.PHONY: all clean test
