# Builds the native core: libbrpc_core.so (C++ host runtime).
# The compute path is JAX/XLA; this library is the bRPC-shaped host core:
# IOBuf, resource pools, work-stealing executor, timers, epoll socket core,
# wire framing, and bvar combiners.  Python binds it via ctypes
# (brpc_tpu/_core/lib.py).

CXX      ?= g++
CXXFLAGS ?= -O2 -g -std=c++20 -fPIC -Wall -Wextra -Wno-unused-parameter -pthread
# g++ 10 gates C++20 coroutines behind -fcoroutines (11+ turn them on with
# -std=c++20 alone; clang rejects the flag) — probe instead of hardcoding.
# := so the compiler probe runs ONCE, not on every $(CXXFLAGS) expansion.
COROUTINE_FLAG := $(shell echo 'int main(){}' | $(CXX) -std=c++20 \
    -fcoroutines -x c++ - -o /dev/null 2>/dev/null && echo -fcoroutines)
CXXFLAGS += $(COROUTINE_FLAG)
LDFLAGS  ?= -shared -pthread

SRC := $(wildcard src/cc/butil/*.cc) \
       $(wildcard src/cc/bthread/*.cc) \
       $(wildcard src/cc/net/*.cc) \
       $(wildcard src/cc/bvar/*.cc) \
       $(filter-out src/cc/fastrpc_module.cc,$(wildcard src/cc/*.cc))
OBJ := $(SRC:.cc=.o)
PYOBJ := src/cc/fastrpc_module.o
DEP := $(OBJ:.o=.d) $(PYOBJ:.o=.d)
LIB := brpc_tpu/_core/libbrpc_core.so
# CPython C-extension for the RPC hot boundary (no ctypes marshalling).
PYEXT := brpc_tpu/_core/_fastrpc.so
PYINC := $(shell python3-config --includes)

all: $(LIB) $(PYEXT)

$(LIB): $(OBJ)
	$(CXX) $(LDFLAGS) -o $@ $(OBJ)

# Built via the %.o pattern rule so -MMD tracks net/ and butil/ headers: a
# struct-layout change must rebuild the extension, not leave a stale .so.
$(PYOBJ): CXXFLAGS += $(PYINC)

$(PYEXT): $(PYOBJ) $(LIB)
	$(CXX) $(LDFLAGS) -o $@ $(PYOBJ) \
	    -Lbrpc_tpu/_core -lbrpc_core -Wl,-rpath,'$$ORIGIN'

# -MMD -MP: auto header dependencies (a struct-layout change in a header
# must rebuild every TU that includes it, or TUs disagree on offsets).
%.o: %.cc
	$(CXX) $(CXXFLAGS) -MMD -MP -Isrc/cc -c -o $@ $<

-include $(DEP)

clean:
	rm -f $(OBJ) $(PYOBJ) $(DEP) $(LIB) $(PYEXT)
	rm -rf build

test: $(LIB)
	python -m pytest tests/ -x -q -m "not slow"

# Chaos suite (README "Fault injection"): seeded fault-injection
# scenarios over the full RPC/ICI data path, three fixed seeds so every
# run replays the same schedule.  Includes slow-marked scenarios.
chaos: $(LIB) $(PYEXT)
	BRPC_CHAOS_SEEDS=101,202,303 JAX_PLATFORMS=cpu \
	    python -m pytest tests/test_chaos.py -q

# Serving suite (README "Serving"): dynamic batcher + continuous-decode
# engine + RPC/HTTP glue, on the CPU jit path (no device needed).
serving: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py -q

# KV-cache suite (README "KV cache"): paged KV pages over the BlockPool,
# radix prefix reuse, copy-on-write forks, eviction safety, engine and
# batcher integration, prefix-affinity routing.  CPU jit path.
kvcache: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_kvcache.py -q

# Recovery suite (README "Fault tolerance & degradation"): engine
# supervision, crash/wedge failover over the surviving KV cache,
# degradation ladder, flapping-replica quarantine.  CPU jit path; the
# timed recovery rung runs via `python bench.py` (recovery section).
recovery: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_supervisor.py -q

# Migration suite (README "Cross-host data plane"): KV page migration
# over the _kvmig wire — export/splice round-trips, rollback on
# mid-splice faults, offer-table bounds, migrate-on-rebalance, the
# /migration console page.  CPU jit path; the timed migrate-vs-
# recompute rung runs via `python bench.py migrate`.
migrate: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_migrate.py -q

# Disaggregation suite (README "Cross-host data plane"): the
# prefill/decode split over DcnChannel + cross-process failover
# through the standby's write-ahead record.  CPU jit path.
disagg: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_disagg.py -q

# Cluster suite (README "Cluster front door"): the ClusterRouter —
# resumable client sessions (drop/reconnect, replica kill, router
# restart), prefix-affinity routing with quarantine remap, and the
# 4-level overload gradient's ordering proof.  CPU jit path; the timed
# router-vs-direct rung runs via `python bench.py cluster` and feeds
# the same perf_diff gate `make bench` ends with.
cluster: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_router.py -q

# Durable control plane (README "Durable control plane", ISSUE 16):
# the session-WAL suite (write-ahead discipline, torn tails,
# compaction, adoption) plus the timed WAL-tax / crash->first-token
# rung (3-trial median+spread, feeds the same perf_diff gate `make
# bench` ends with).  CPU jit path.
durable: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_session_wal.py -q
	JAX_PLATFORMS=cpu python bench.py durable

# Multi-model plane (README "Multi-model plane", ISSUE 18): the
# deployment/catalog/canary suite (named deployments, (model, prefix)
# routing, model-aware WAL adoption, lifecycle fencing, misroute
# counters) plus the timed two-model-tax / 95-5-canary-split rung
# (3-trial median+spread, feeds the same perf_diff gate `make bench`
# ends with).  CPU jit path.
multimodel: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_modelplane.py -q
	JAX_PLATFORMS=cpu python bench.py multimodel

# Fleet telemetry plane (README "Fleet telemetry", ISSUE 20): the
# collector/SLO/stitching suite, then the collection-overhead rung —
# front-door generations/s with the 20 Hz collector+SLO tick off vs
# on (<=2% acceptance, 3-trial median+spread, perf_diff gated).
telemetry: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_telemetry.py -q
	JAX_PLATFORMS=cpu python bench.py telemetry

# Real model serving (README "Real model serving", ISSUE 10): the
# paged-attention equivalence suite (gather + pallas-interpret vs the
# dense reference at page boundaries / COW forks / evict-readmit), the
# ModelRunner protocol + TransformerRunner end-to-end tests, then the
# timed runner-vs-harness tokens/s rung (3-trial median+spread, feeds
# perf_diff).  CPU jit path throughout.
model: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_paged_attention.py \
	    tests/test_model_runner.py -q
	JAX_PLATFORMS=cpu python bench.py model

# Parameter server (README "Parameter server", ISSUE 12): the sharded
# embedding service — PSClient bit-identity vs the dense oracle at
# partition counts 1/2/4/8 (RPC fan-out AND collective lowering),
# batcher coalescing, idempotent updates — then the timed
# batched-vs-unbatched + framework-vs-raw-collectives rung (3-trial
# median+spread, feeds perf_diff).
psserve: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_psserve.py -q
	JAX_PLATFORMS=cpu python bench.py embedding

# Training plane (README "Training plane", ISSUE 17): the
# trainer-in-the-loop suite — fused co-located optimizer bit-identity
# vs the dense oracle at partitions 1/2/4 (RPC AND lowered),
# retried-wave exactly-once, bounded-staleness gating, arbiter shed
# ordering — then the timed wire-optimizer vs pull-compute-push rung
# (wire >= baseline beyond spread is the acceptance bar) plus the
# serving-coexistence tokens/s ratio (3-trial median+spread, feeds
# the same perf_diff gate `make bench` ends with).
train: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_train.py -q
	JAX_PLATFORMS=cpu python bench.py train

# Binary tensor wire (README "Binary tensor wire", ISSUE 13): the
# frame identity/golden/fuzz suite + PS bit-identity over tensorframe
# vs JSON vs the dense oracle + the ICI fast path, then the embedding
# bench rung's serializer axis (json vs tensorframe vs lowered,
# tax_reduction_x >= 5x beyond spread is the acceptance bar).
tensorframe: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_tensorframe.py \
	  tests/test_fuzz_parsers.py::test_fuzz_tensorframe_frames -q
	JAX_PLATFORMS=cpu python bench.py embedding

# Speculative decoding (README "Speculative decoding", ISSUE 11): the
# identity suite (spec output == plain greedy at depths 2/4/8 — cold,
# warm, mixed slots, draft trees, through Serving.Generate), the
# draft-lease/fork lifecycle units, then the timed plain-vs-spec
# tokens/s rung (3-trial interleaved median+spread, feeds perf_diff).
speculative: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_speculative.py -q
	JAX_PLATFORMS=cpu python bench.py speculative

# Tracing suite (README "Observability"): rpcz generation tracing —
# per-trace head sampling, span-tree timelines, TTFT/ITL math, trace
# continuity across crash recovery, DCN span joins, console pages.
trace: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python -m pytest tests/test_tracing.py -q

# Hotspot attribution (README "Observability", ISSUE 6): burst-profile
# a local serving run — always-on stage-tagged sampler ring, a 100Hz
# burst, the lock-contention ledger, and the host-CPU-per-token rollup.
hotspots: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python tools/hotspots_burst.py

# Per-stage host micro-benchmark suite (bench.py microbench): frame
# pump, batch assembly, radix prefix match, page alloc/release, emit
# fan-out, span submit, host-us-per-token, stream scaling, sampler
# overhead — CPU-valid, 3-trial median+spread.  The de-GIL'd stages
# publish a native-vs-python A/B per round (ISSUE 9, README "Native
# host path").
microbench: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python bench.py microbench

# De-GIL perf gate (ISSUE 9): run the per-stage host microbench suite,
# nest its output under "microbench" to match the round wrappers'
# detail tree, and perf_diff it against the freshest BENCH_r*.json —
# exits 1 on any beyond-spread regression, so the per-stage trajectory
# (emit_fanout, batch_assembly, span_submit, host_us_per_token and
# their native_speedup A/Bs) gates future PRs by default.  Wire this
# next to `make test` in a verify loop; MICROBENCH.json is the
# sidecar a later round can diff against directly.
perf: $(LIB) $(PYEXT)
	JAX_PLATFORMS=cpu python bench.py microbench \
	    | python -c "import json,sys; json.dump({'microbench': \
	    json.load(sys.stdin)}, open('MICROBENCH.json','w'), indent=1)"
	JAX_PLATFORMS=cpu python bench.py model \
	    | python -c "import json,sys; json.dump({'model': \
	    json.load(sys.stdin)}, open('MODELBENCH.json','w'), indent=1)"
	python tools/perf_diff.py \
	    "$$(ls BENCH_r*.json 2>/dev/null | sort -V | tail -1)" \
	    MICROBENCH.json
	python tools/perf_diff.py \
	    "$$(ls BENCH_r*.json 2>/dev/null | sort -V | tail -1)" \
	    MODELBENCH.json

# brpc-check (ISSUE 14, README "Static analysis"): the repo-invariant
# AST analysis suite — lock-order cycles, bounded-decode discipline,
# one-compile-per-bucket jit, the fault-site registry, InstrumentedLock
# hygiene, wedge hygiene — against the committed CHECK_BASELINE.json.
# Runs in a few seconds; exits 1 on any NON-baseline finding.  Also
# `make bench`'s preflight, so perf rounds can't ride on eroded
# invariants.
check:
	python tools/brpc_check.py

# Wedge hunt (ISSUE 15): loop the native test modules with the flight
# recorder armed and archive the first wedge-guard deadline-miss dump
# (lock witness + native flight tail) under build/wedge_hunt/ — turns
# the "intermittent, ~half of 8 runs" tier-1 wedge into a harvestable
# artifact.  Exits 0 with the artifact path on a catch, 3 on a clean
# hunt.
wedge-hunt: $(LIB) $(PYEXT)
	python tools/wedge_hunt.py

# Full bench run ending in a delta-vs-previous-round table: perf_diff
# compares the freshest BENCH_r*.json against this run's
# BENCH_DETAILS.json and flags beyond-spread regressions (the leading
# `-` keeps the table from failing the build; run perf_diff directly
# for the gating exit code).
bench: $(LIB) $(PYEXT) check
	python bench.py
	-python tools/perf_diff.py \
	    "$$(ls BENCH_r*.json 2>/dev/null | sort -V | tail -1)" \
	    BENCH_DETAILS.json

# Sanitizer stress targets (VERDICT r2 task 7; reference fights lock-free
# races with stress tests + sanitizer builds, SURVEY.md §5.3).  The whole
# native core + src/cc/test/stress_main.cc compile as ONE binary with the
# sanitizer, then run: Chase-Lev pop/steal, executor churn, butex claim
# races, fiber mutex, timer churn, write-stack drainer handoff vs
# SetFailed.  A clean exit means no reports (halt_on_error aborts).
# Known -Wtsan warning: TSAN doesn't model standalone atomic_thread_fence
# (Chase-Lev pop/steal) — it may MISS fence-dependent races but cannot
# false-positive here since all racing accesses are atomics.
STRESS_SRC := $(SRC) src/cc/test/stress_main.cc

# ISSUE 14: probe whether this toolchain can BUILD AND LINK
# -fsanitize=thread (:= so it runs once).  Sanitizer targets skip —
# never fail — when the probe comes back empty (e.g. no libtsan on the
# image), so `make tsan` is safe to wire into any verify loop.
TSAN_FLAG := $(shell echo 'int main(){}' | $(CXX) -fsanitize=thread \
    -pthread -x c++ - -o /dev/null 2>/dev/null && echo -fsanitize=thread)

# Ring stress (ISSUE 14): the serving hot path's TokenRing
# (serving_hotpath.cc — step-loop push_many vs emitter pop_many,
# racing terminals exactly-once, live-count baseline) and the spanq
# MPSC Treiber stack (src/cc/spanq.h — the exact algorithm
# fastrpc_module.cc's py_spanq_* run on PyObject*, extracted so it
# links without Python) under TSAN.  ISSUE 15 adds the flight-recorder
# ring (butil/flight.cc): concurrent writers + dump-while-writing —
# the seqlock slots are all relaxed atomics, so TSAN stays sound here.
RING_STRESS_SRC := src/cc/serving_hotpath.cc src/cc/butil/flight.cc \
    src/cc/test/ring_stress_main.cc

tsan:
	@if [ -z "$(TSAN_FLAG)" ]; then \
	    echo "tsan: $(CXX) cannot link -fsanitize=thread on this" \
	         "image — SKIPPING (not a failure)"; exit 0; fi
	@mkdir -p build
	$(CXX) -std=c++20 -O1 -g $(TSAN_FLAG) -pthread -Isrc/cc \
	    $(RING_STRESS_SRC) -o build/ring_stress_tsan
	RING_STRESS_POP_TIMEOUT_US=0 TSAN_OPTIONS="halt_on_error=1" \
	    ./build/ring_stress_tsan

# Whole-core TSAN (stress_main.cc).  CAVEAT on gcc-10 images: libtsan
# there does not intercept pthread_cond_clockwait (glibc's timed-wait
# path), so every mutex guarding condvar-timed-wait state loses its
# happens-before edge and TSAN reports bogus double-locks/races — the
# executor/timer/butex stress below is EXPECTED to false-positive on
# such toolchains (the ring stress above deliberately avoids timed
# waits and stays sound).  Run this target on a gcc>=11/clang image.
tsan-core:
	@if [ -z "$(TSAN_FLAG)" ]; then \
	    echo "tsan-core: $(CXX) cannot link -fsanitize=thread on this" \
	         "image — SKIPPING (not a failure)"; exit 0; fi
	@mkdir -p build
	$(CXX) -std=c++20 -O1 -g $(COROUTINE_FLAG) $(TSAN_FLAG) -pthread \
	    -Isrc/cc $(STRESS_SRC) -o build/stress_tsan -ldl
	TSAN_OPTIONS="halt_on_error=1" ./build/stress_tsan

# The ring stress is also valid (and fast) without a sanitizer — run it
# plain when TSAN is unavailable or as a quick semantic check.
ring-stress:
	@mkdir -p build
	$(CXX) -std=c++20 -O2 -g -pthread -Isrc/cc \
	    $(RING_STRESS_SRC) -o build/ring_stress_plain
	./build/ring_stress_plain

asan:
	@mkdir -p build
	$(CXX) -std=c++20 -O1 -g $(COROUTINE_FLAG) -fsanitize=address,undefined \
	    -pthread -Isrc/cc $(STRESS_SRC) -o build/stress_asan -ldl
	ASAN_OPTIONS="detect_leaks=1:halt_on_error=1" ./build/stress_asan

stress:
	@mkdir -p build
	$(CXX) -std=c++20 -O2 -g $(COROUTINE_FLAG) -pthread -Isrc/cc \
	    $(STRESS_SRC) -o build/stress_plain -ldl
	./build/stress_plain

.PHONY: all clean test chaos serving kvcache recovery migrate disagg \
    cluster durable model speculative trace hotspots microbench perf \
    bench tsan tsan-core asan stress check ring-stress wedge-hunt \
    psserve tensorframe train multimodel telemetry
