# Builds the native core: libbrpc_core.so (C++ host runtime).
# The compute path is JAX/XLA; this library is the bRPC-shaped host core:
# IOBuf, resource pools, work-stealing executor, timers, epoll socket core,
# wire framing, and bvar combiners.  Python binds it via ctypes
# (brpc_tpu/_core/lib.py).

CXX      ?= g++
CXXFLAGS ?= -O2 -g -std=c++20 -fPIC -Wall -Wextra -Wno-unused-parameter -pthread
LDFLAGS  ?= -shared -pthread

SRC := $(wildcard src/cc/butil/*.cc) \
       $(wildcard src/cc/bthread/*.cc) \
       $(wildcard src/cc/net/*.cc) \
       $(wildcard src/cc/bvar/*.cc) \
       $(wildcard src/cc/*.cc)
OBJ := $(SRC:.cc=.o)
LIB := brpc_tpu/_core/libbrpc_core.so

all: $(LIB)

$(LIB): $(OBJ)
	$(CXX) $(LDFLAGS) -o $@ $(OBJ)

%.o: %.cc
	$(CXX) $(CXXFLAGS) -Isrc/cc -c -o $@ $<

clean:
	rm -f $(OBJ) $(LIB)

test: $(LIB)
	python -m pytest tests/ -x -q

.PHONY: all clean test
