"""Benchmark driver entry — prints ONE JSON line on stdout.

Headline metric: streaming tensor-pipe throughput (the streaming_echo
config re-targeted at HBM, BASELINE.md north star) vs the reference's best
published number, 2.3 GB/s same-host multi-connection throughput
(docs/cn/benchmark.md:104).  Details carry the other configs: unary echo
QPS (python service and native echo), p99s, and the 64B-64MB ICI ladder
(rdma_performance analog).

Runs on whatever jax platform the environment provides (the real TPU chip
under the driver; CPU elsewhere).  All progress goes to stderr; stdout is
exactly one JSON object.
"""
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_GBPS = 2.3

# Native sockets hold raw pointers to ctypes trampolines; pin every callback
# for process lifetime (EOF callbacks fire after the bench function returns).
_KEEP = []


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def bench_unary_echo(duration_s=2.0, threads=4):
    """example/echo_c++ + multi_threaded_echo_c++ analog over loopback."""
    import brpc_tpu as brpc

    class Echo(brpc.Service):
        @brpc.method(request="raw", response="raw")
        def Echo(self, cntl, req):
            return req

    server = brpc.Server()
    server.add_service(Echo())
    server.start("127.0.0.1", 0)
    ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=5000)
    payload = b"x" * 128
    # warmup
    for _ in range(50):
        ch.call_sync("Echo", "Echo", payload, serializer="raw")
    counts = [0] * threads
    lats = []
    lat_lock = threading.Lock()
    stop = time.monotonic() + duration_s

    def worker(i):
        my_lats = []
        while time.monotonic() < stop:
            t0 = time.monotonic()
            ch.call_sync("Echo", "Echo", payload, serializer="raw")
            my_lats.append(time.monotonic() - t0)
            counts[i] += 1
        with lat_lock:
            lats.extend(my_lats)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    t0 = time.monotonic()
    [t.start() for t in ts]
    [t.join() for t in ts]
    wall = time.monotonic() - t0
    lats.sort()
    qps = sum(counts) / wall
    p99 = lats[int(len(lats) * 0.99)] * 1e6 if lats else 0
    p50 = lats[len(lats) // 2] * 1e6 if lats else 0
    server.stop()
    server.join()
    return {"qps": round(qps, 1), "p50_us": round(p50, 1),
            "p99_us": round(p99, 1), "threads": threads}


def bench_native_echo(n_frames=20000, payload_len=128):
    """Native-service echo: frames never surface to Python on the server."""
    import ctypes

    from brpc_tpu._core import (FAILED_CB, IOBuf, MESSAGE_CB, ACCEPTED_CB,
                                core, core_init)
    core_init()
    keep = _KEEP
    msg_cb = MESSAGE_CB(lambda *a: None)
    fail_cb = FAILED_CB(lambda *a: None)
    acc_cb = ACCEPTED_CB(lambda *a: None)
    keep += [msg_cb, fail_cb, acc_cb]
    sid = ctypes.c_uint64()
    port = ctypes.c_int()
    rc = core.brpc_listen(b"127.0.0.1", 0, msg_cb, fail_cb, acc_cb, None, 1,
                          ctypes.byref(sid), ctypes.byref(port))
    assert rc == 0
    got = {"n": 0}
    done = threading.Event()

    @MESSAGE_CB
    def on_resp(s, kind, meta, meta_len, body, user):
        IOBuf(handle=body)
        got["n"] += 1
        if got["n"] >= n_frames:
            done.set()

    keep.append(on_resp)
    cid = ctypes.c_uint64()
    assert core.brpc_connect(b"127.0.0.1", port.value, on_resp, fail_cb,
                             None, ctypes.byref(cid)) == 0
    payload = b"y" * payload_len
    t0 = time.monotonic()
    for _ in range(n_frames):
        core.brpc_socket_write_frame(cid.value, b"m", 1, payload,
                                     len(payload), None)
    ok = done.wait(60)
    wall = time.monotonic() - t0
    core.brpc_socket_set_failed(cid.value, 0)
    core.brpc_socket_set_failed(sid.value, 0)
    qps = got["n"] / wall if wall > 0 else 0
    return {"qps": round(qps, 1), "frames": got["n"], "completed": ok}


def _per_pass_seconds(x, k_small=8, k_large=108, trials=3):
    """Per-pass time of a non-foldable HBM read+write over x, measured
    differentially (subtracts fixed dispatch/tunnel cost; the result is
    pure on-chip streaming time).  Completion is forced by a host read of
    a scalar — block_until_ready alone does not synchronize on the
    tunneled axon platform."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def make(k):
        def body(i, b):
            return jnp.roll(b, 128) + jnp.bfloat16(1.0)
        return jax.jit(lambda a: lax.fori_loop(0, k, body, a).sum())

    def best_time(fn):
        float(fn(x))  # warm/compile
        best = None
        for _ in range(trials):
            t0 = time.monotonic()
            float(fn(x))
            dt = time.monotonic() - t0
            best = dt if best is None or dt < best else best
        return best

    d_small = best_time(make(k_small))
    d_large = best_time(make(k_large))
    return max(1e-9, (d_large - d_small) / (k_large - k_small)), d_small


def bench_streaming_echo(chunk_mb=64):
    """streaming_echo re-targeted at HBM: sustained throughput of the
    on-chip echo pipe over a 64MB chunk (payload read+written per pass)."""
    import jax.numpy as jnp

    n = chunk_mb * 1024 * 1024 // 2  # bf16 elements
    x = jnp.ones((n,), jnp.bfloat16)
    per_pass, dispatch = _per_pass_seconds(x)
    traffic = 2 * x.nbytes
    return {"gbps": round(traffic / per_pass / 1e9, 1),
            "chunk_mb": chunk_mb,
            "per_pass_us": round(per_pass * 1e6, 1),
            "dispatch_overhead_ms": round(dispatch * 1e3, 1)}


def bench_tensor_pipe(chunk_mb=8, n_chunks=8):
    """The TensorStream framework pipe itself (includes per-chunk dispatch;
    on the tunneled dev chip this is dominated by tunnel RTT)."""
    import jax
    import jax.numpy as jnp

    from brpc_tpu.ici import TensorStream

    dev = jax.devices()[0]
    n = chunk_mb * 1024 * 1024 // 2
    chunk = jnp.ones((n,), jnp.bfloat16)
    chunk.block_until_ready()
    outs = []
    # window = 4 chunks so transfers actually pipeline (a window equal to
    # one chunk would serialize them and measure nothing but turnaround)
    ts = TensorStream(dev, consumer=lambda a: outs.append(a),
                      window_bytes=4 * chunk.nbytes)
    ts.write(chunk)          # warmup: drainer thread + first dispatch
    deadline = time.monotonic() + 10
    while not outs and time.monotonic() < deadline:
        time.sleep(0.005)    # deterministic: wait until warmup delivered
    outs.clear()
    t0 = time.monotonic()
    for _ in range(n_chunks):
        ts.write(chunk)
    ts.close(wait=True)      # drainer has block_until_ready'd the tail;
    if outs:                 # sync again without compiling a gather op
        outs[-1].block_until_ready()
    wall = time.monotonic() - t0
    return {"gbps": round(n_chunks * chunk.nbytes / wall / 1e9, 3),
            "chunk_mb": chunk_mb, "chunks": len(outs)}


def bench_ici_ladder():
    """rdma_performance 64B-64MB ladder: per-size on-chip echo pass time
    (differential, dispatch excluded) + bandwidth."""
    import jax.numpy as jnp

    out = {}
    for size in (64, 4096, 65536, 1 << 20, 1 << 24, 1 << 26):
        x = jnp.ones((max(128, size // 2),), jnp.bfloat16)
        # scale pass count so the measured delta is well above clock
        # resolution even when per-pass cost is loop overhead (~µs)
        k_delta = max(50, min(20000, int(2e9 / max(x.nbytes, 1))))
        per_pass, _ = _per_pass_seconds(x, k_small=4, k_large=4 + k_delta,
                                        trials=2)
        out[f"{size}B"] = {"lat_us": round(per_pass * 1e6, 2),
                           "gbps": round(2 * x.nbytes / per_pass / 1e9, 3)}
    return out


def main():
    details = {}
    log("bench: unary echo (python service)...")
    details["echo"] = bench_unary_echo()
    log(f"  {details['echo']}")
    log("bench: native echo...")
    details["native_echo"] = bench_native_echo()
    log(f"  {details['native_echo']}")
    log("bench: streaming echo (on-chip)...")
    try:
        details["streaming"] = bench_streaming_echo()
        log(f"  {details['streaming']}")
        log("bench: tensor pipe (framework path incl. dispatch)...")
        details["tensor_pipe"] = bench_tensor_pipe(chunk_mb=64)
        log(f"  {details['tensor_pipe']}")
        log("bench: ici ladder...")
        details["ici_ladder"] = bench_ici_ladder()
        log(f"  {details['ici_ladder']}")
        headline = details["streaming"]["gbps"]
    except Exception as e:  # no usable accelerator: fall back to echo tput
        log(f"  streaming bench unavailable: {e}")
        headline = details["native_echo"]["qps"] * 128 / 1e9
        details["streaming"] = {"gbps": headline, "fallback": "native_echo"}
    import platform
    try:
        import jax
        details["platform"] = str(jax.devices()[0])
    except Exception:
        details["platform"] = platform.machine()
    print(json.dumps({
        "metric": "streaming_echo_throughput",
        "value": headline,
        "unit": "GB/s",
        "vs_baseline": round(headline / BASELINE_GBPS, 2),
        "details": details,
    }))


if __name__ == "__main__":
    main()
