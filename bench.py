"""Benchmark driver entry — prints ONE JSON line on stdout.

Headline metric: **tensor-pipe throughput through the framework transport**
(TensorStream -> IciEndpoint), where every chunk provably lands in a
distinct destination buffer (same-device sends go through a compiled copy
kernel; device_put-to-self would alias and move zero bytes).  This is the
streaming_echo config re-targeted at the TPU's native transport (ICI /
HBM), compared against the reference's best published transport number,
2.3 GB/s same-host multi-connection over 10GbE (docs/cn/benchmark.md:104)
— different link technologies, same "bytes through the framework's
streaming path" methodology.  Raw on-chip HBM read+write bandwidth is
reported separately as `hbm_stream` (a chip sanity number, NOT the
framework).

Every published number passes sanity gates: wall time must exceed timer
confidence, and bandwidth must be below a physical single-chip cap —
anything failing the gate is published as null with the reason.

Runs on whatever jax platform the environment provides (the real TPU chip
under the driver; CPU elsewhere).  All progress goes to stderr; stdout is
exactly one JSON object.
"""
import gc
import json
import math
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_GBPS = 2.3
# No single-chip HBM/ICI stream plausibly exceeds this (v5p HBM ~2.8TB/s);
# anything above is a measurement artifact and must not be published.
PHYS_BW_CAP_GBPS = 3000.0
# Published latencies below 100x timer resolution are noise.
_TIMER_CONFIDENCE_S = max(
    100 * time.get_clock_info("perf_counter").resolution, 2e-6)


def _gated(nbytes_moved, wall_s):
    """Return (gbps or None, issues list) applying the integrity gates."""
    issues = []
    if wall_s < _TIMER_CONFIDENCE_S:
        issues.append(
            f"wall {wall_s:.2e}s below timer confidence "
            f"{_TIMER_CONFIDENCE_S:.2e}s")
    gbps = nbytes_moved / wall_s / 1e9 if wall_s > 0 else float("inf")
    if gbps > PHYS_BW_CAP_GBPS:
        issues.append(f"{gbps:.3g} GB/s exceeds physical cap "
                      f"{PHYS_BW_CAP_GBPS} GB/s")
    return (None if issues else round(gbps, 3)), issues


# streaming_tensor's mid-batch liveness deadline, measured from the start
# of the CURRENT batch (ADVICE r5 — against the whole timed region's t0 a
# healthy late batch would be misflagged once the region outgrows it).
WEDGE_TIMEOUT_S = 120.0


def _batch_wedged(batch_t0, now, timeout_s=WEDGE_TIMEOUT_S):
    """True when the current batch has made no complete delivery for
    `timeout_s` — a per-batch bound, independent of how long the whole
    timed region has run."""
    return now - batch_t0 > timeout_s


# Native sockets hold raw pointers to ctypes trampolines; pin every callback
# for process lifetime (EOF callbacks fire after the bench function returns).
_KEEP = []


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def bench_unary_echo(duration_s=2.0, threads=4):
    """example/echo_c++ + multi_threaded_echo_c++ analog over loopback."""
    import brpc_tpu as brpc

    class Echo(brpc.Service):
        @brpc.method(request="raw", response="raw")
        def Echo(self, cntl, req):
            return req

    server = brpc.Server()
    server.add_service(Echo())
    server.start("127.0.0.1", 0)
    ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=5000)
    payload = b"x" * 128
    # warmup
    for _ in range(50):
        ch.call_sync("Echo", "Echo", payload, serializer="raw")
    counts = [0] * threads
    lats = []
    lat_lock = threading.Lock()
    stop = time.monotonic() + duration_s

    def worker(i):
        my_lats = []
        while time.monotonic() < stop:
            t0 = time.monotonic()
            ch.call_sync("Echo", "Echo", payload, serializer="raw")
            my_lats.append(time.monotonic() - t0)
            counts[i] += 1
        with lat_lock:
            lats.extend(my_lats)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    t0 = time.monotonic()
    [t.start() for t in ts]
    [t.join() for t in ts]
    wall = time.monotonic() - t0
    lats.sort()
    qps = sum(counts) / wall
    p99 = lats[int(len(lats) * 0.99)] * 1e6 if lats else 0
    p50 = lats[len(lats) // 2] * 1e6 if lats else 0
    server.stop()
    server.join()
    return {"qps": round(qps, 1), "p50_us": round(p50, 1),
            "p99_us": round(p99, 1), "threads": threads}


def bench_echo_scaling(conn_counts=(1, 4, 16, 64), per_conn_frames=15_000,
                       trials=3, budget_ms=3.0):
    """PYTHON-HANDLER scaling under the native C++ client pump — the
    reference's methodology (C++ client, docs/cn/benchmark.md:110-121)
    pointed at user handlers.  Each connection keeps one frame in flight,
    so N conns model N concurrent synchronous clients and the measured
    cost is the SERVER's dispatch + Python handler path only.

    Admission control (VERDICT r4 #4): the server runs with a usercode
    latency budget, so when the GIL lane's estimated wait exceeds
    `budget_ms` the excess load is shed natively with ELIMIT instead of
    queueing.  qps counts SUCCESSES only; sheds surface as err_frac.
    p50/p99 are success latencies.  Each rung runs `trials` times,
    median + spread reported (same jitter discipline as the native
    ladder)."""
    import ctypes

    import brpc_tpu as brpc
    from brpc_tpu._core import core, core_init

    class Echo(brpc.Service):
        NAME = "ScaleEcho"

        @brpc.method(request="raw", response="raw")
        def Echo(self, cntl, req):
            return req

    server = brpc.Server(brpc.ServerOptions(
        usercode_latency_budget_ms=budget_ms,
        # echo never blocks: run it on the dispatcher (single-threaded
        # event loop) — on a core-starved box the executor hop's
        # cross-thread GIL convoy dominated the tail
        usercode_inline=True))
    server.add_service(Echo())
    server.start("127.0.0.1", 0)
    core_init()
    out = {}
    try:
        for c in conn_counts:
            rs = []
            for _ in range(trials):
                qps = ctypes.c_double()
                p50 = ctypes.c_double()
                p99 = ctypes.c_double()
                ef = ctypes.c_double()
                rc = core.brpc_bench_pump(
                    server.port, b"ScaleEcho", b"Echo", c, 1,
                    per_conn_frames * c, 128,
                    ctypes.byref(qps), ctypes.byref(p50),
                    ctypes.byref(p99), ctypes.byref(ef))
                rs.append({"qps": qps.value, "p50_us": p50.value,
                           "p99_us": p99.value, "err_frac": ef.value,
                           "completed": rc == 0})
            qs = sorted(r["qps"] for r in rs)
            p50s = sorted(r["p50_us"] for r in rs)
            p99s = sorted(r["p99_us"] for r in rs)
            mid = len(rs) // 2
            out[f"{c}c"] = {
                "qps": round(qs[mid], 1), "p50_us": p50s[mid],
                "p99_us": p99s[mid],
                "qps_spread": [round(qs[0], 1), round(qs[-1], 1)],
                "p99_spread": [p99s[0], p99s[-1]],
                "shed_frac": round(
                    sorted(r["err_frac"] for r in rs)[mid], 4),
                "trials": trials,
                "completed": all(r["completed"] for r in rs)}
    finally:
        server.stop()
        server.join()
    base = out[f"{conn_counts[0]}c"]["qps"]
    peak = max(out[f"{c}c"]["qps"] for c in conn_counts)
    out["speedup_at_peak"] = round(peak / base, 2) if base else None
    out["usercode_budget_ms"] = budget_ms
    out["cpu_cores"] = os.cpu_count()
    out["note"] = ("native C++ client pump vs Python handlers; success-"
                   "qps only, ELIMIT sheds in shed_frac; handlers stay "
                   "GIL-bound so per-core saturation is the ceiling, but "
                   "added load must not DEGRADE throughput or tails")
    return out


def bench_grpc_echo(total=8000, inflight=32, payload_len=128,
                    stream_items=2000):
    """gRPC (h2) unary + server-streaming on the shared port.  Round 5
    moved the server data plane to C++ (src/cc/net/h2.cc: framing,
    HPACK, flow control, gRPC dispatch — the reference's native
    http2_rpc_protocol.cpp slot), so this rung now has three tiers:
    Python client end-to-end (interop proof; client-bound), native pump
    -> Python handler (bridge dispatch cost), and native pump -> native
    method — the pure-C++ path, target >= 100k qps on the 1-core box
    (measured ~235k vs ~9k for the round-4 all-Python plane)."""
    import time as _t
    from collections import deque

    import brpc_tpu as brpc
    from brpc_tpu.rpc.h2 import GrpcChannel

    class Echo(brpc.Service):
        NAME = "bench.Grpc"

        @brpc.method(request="raw", response="raw")
        def Echo(self, cntl, req):
            return bytes(req)

        @brpc.method(request="raw", response="raw")
        def Stream(self, cntl, req):
            n = int(bytes(req) or b"1")
            payload = b"s" * 128
            return (payload for _ in range(n))

    server = brpc.Server()
    server.add_service(Echo())
    server.start("127.0.0.1", 0)
    out = {}
    try:
        ch = GrpcChannel(f"127.0.0.1:{server.port}")
        payload = b"x" * payload_len
        for _ in range(100):
            ch.call("bench.Grpc", "Echo", payload)

        def one_trial():
            lat = []
            pend = deque()
            t0 = _t.perf_counter()
            for _ in range(total):
                pend.append((ch.acall("bench.Grpc", "Echo", payload),
                             _t.perf_counter()))
                if len(pend) >= inflight:
                    f, ts = pend.popleft()
                    f.result(30)
                    lat.append(_t.perf_counter() - ts)
            while pend:
                f, ts = pend.popleft()
                f.result(30)
                lat.append(_t.perf_counter() - ts)
            wall = _t.perf_counter() - t0
            lat.sort()
            return (total / wall, lat[len(lat) // 2] * 1e6,
                    lat[int(len(lat) * 0.99)] * 1e6)

        trials = sorted(one_trial() for _ in range(3))
        qps = trials[1][0]
        out["unary"] = {
            "qps": round(qps, 1), "inflight": inflight,
            "p50_us": round(trials[1][1], 1),
            "p99_us": round(trials[1][2], 1),
            "qps_spread": [round(trials[0][0], 1), round(trials[2][0], 1)],
            "target_qps": 4000,
            "met": qps >= 4000}
        # server-streaming: one call, many items (message throughput)
        got = 0
        t0 = _t.perf_counter()
        for item in ch.call_stream("bench.Grpc", "Stream",
                                   str(stream_items).encode()):
            got += 1
        wall = _t.perf_counter() - t0
        out["streaming"] = {"items": got,
                            "items_per_s": round(got / wall, 1)}
        ch.close()
        # Native-client pump tiers (round 5: the h2 data plane moved to
        # C++ — src/cc/net/h2.cc; the Python-client number above is now
        # CLIENT-bound).  Tier 1: pump -> Python handler through the
        # h2_native bridge (server dispatch cost only).  Tier 2: pump ->
        # native-registered method — the pure-C++ gRPC path, ZERO Python
        # per request (the reference's native h2, benchmark.md basis).
        import ctypes

        from brpc_tpu._core.lib import core as _core

        def pump(path, n):
            qps = ctypes.c_double()
            p50 = ctypes.c_double()
            p99 = ctypes.c_double()
            rc = _core.brpc_bench_pump_h2(server.port, path.encode(), 4, 32,
                                          n, payload_len, ctypes.byref(qps),
                                          ctypes.byref(p50),
                                          ctypes.byref(p99))
            return rc, qps.value, p50.value, p99.value

        trials = sorted(pump("/bench.Grpc/Echo", 30_000)
                        for _ in range(3))
        rc, q, p50v, p99v = trials[1]
        out["unary_pump_python"] = {
            "rc": rc, "qps": round(q, 1), "p50_us": round(p50v, 1),
            "p99_us": round(p99v, 1),
            "qps_spread": [round(trials[0][1], 1), round(trials[2][1], 1)]}
        _core.brpc_bench_register_native_echo(b"bench.NativeGrpc", b"Echo",
                                              1)
        try:
            trials = sorted(pump("/bench.NativeGrpc/Echo", 200_000)
                            for _ in range(3))
            rc, q, p50v, p99v = trials[1]
            out["unary_native"] = {
                "rc": rc, "qps": round(q, 1), "p50_us": round(p50v, 1),
                "p99_us": round(p99v, 1),
                "qps_spread": [round(trials[0][1], 1),
                               round(trials[2][1], 1)],
                "target_qps": 100_000, "met": q >= 100_000}
        finally:
            _core.brpc_unregister_method(b"bench.NativeGrpc", b"Echo")
    finally:
        server.stop()
        server.join()
    return out


def bench_native_echo_scaling(conn_counts=(1, 2, 4, 8, 16),
                              per_conn_frames=150_000, trials=3):
    """QPS vs connection count for the native unary hot path (the
    multi-connection half of the reference's same-host chart,
    docs/cn/benchmark.md:104).

    Jitter discipline (VERDICT r4 weak #3): each rung runs `trials` times
    and publishes the MEDIAN with the min-max spread alongside — on the
    shared 1-core driver box a single foreign process or 4ms OS stall can
    poison one trial's p99 by 100x, and a median over independent runs
    separates environment spikes from real queueing."""
    out = {}
    for c in conn_counts:
        rs = [bench_native_echo(conns=c, inflight=32,
                                total=per_conn_frames * c)
              for _ in range(trials)]
        qs = sorted(r["qps"] for r in rs)
        p50s = sorted(r["p50_us"] for r in rs)
        p99s = sorted(r["p99_us"] for r in rs)
        mid = len(rs) // 2
        out[f"{c}c"] = {"qps": qs[mid], "p50_us": p50s[mid],
                        "p99_us": p99s[mid],
                        "qps_spread": [qs[0], qs[-1]],
                        "p99_spread": [p99s[0], p99s[-1]],
                        "trials": trials,
                        "completed": all(r["completed"] for r in rs)}
    base = out[f"{conn_counts[0]}c"]["qps"]
    peak = max(out[f"{c}c"]["qps"] for c in conn_counts)
    out["speedup_at_peak"] = round(peak / base, 2) if base else None
    # the r3 gate, computed on medians: qps monotone non-decreasing (5%
    # tolerance for run-to-run noise) and p99 within 10x of p50 per rung
    out["monotone_qps"] = all(
        out[f"{b}c"]["qps"] >= out[f"{a}c"]["qps"] * 0.95
        for a, b in zip(conn_counts, conn_counts[1:]))
    out["tail_ok"] = all(
        out[f"{c}c"]["p99_us"] <= 10 * max(out[f"{c}c"]["p50_us"], 1)
        for c in conn_counts)
    # the curve is only as good as the cores under it: on a 1-core driver
    # box every config shares one CPU and the curve is flat by physics
    out["cpu_cores"] = os.cpu_count()
    return out


def bench_native_echo(conns=8, inflight=32, total=500_000, payload_len=128):
    """C++ client pump against the native unary hot path: meta parse,
    FlatMap method lookup, handler, response pack all in C++ (net/rpc.h,
    net/bench.cc).  p50/p99 from send-timestamp correlation ids.  Round 1's
    number timed a Python ctypes write loop — the client, not the server;
    this measures the framework's actual dispatch path."""
    import ctypes
    import os

    from brpc_tpu._core import core, core_init
    core_init()
    qps = ctypes.c_double()
    p50 = ctypes.c_double()
    p99 = ctypes.c_double()
    rc = core.brpc_bench_echo(conns, inflight, total, payload_len, 1,
                              ctypes.byref(qps), ctypes.byref(p50),
                              ctypes.byref(p99))
    return {"qps": round(qps.value, 1), "p50_us": p50.value,
            "p99_us": p99.value, "conns": conns, "inflight": inflight,
            "frames": total, "completed": rc == 0,
            "cpu_cores": os.cpu_count()}


def _per_pass_seconds(x, k_small=8, k_large=108, trials=3):
    """Per-pass time of a non-foldable HBM read+write over x, measured
    differentially (subtracts fixed dispatch/tunnel cost; the result is
    pure on-chip streaming time).  Completion is forced by a host read of
    a scalar — block_until_ready alone does not synchronize on the
    tunneled axon platform."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def make(k):
        def body(i, b):
            return jnp.roll(b, 128) + jnp.bfloat16(1.0)
        return jax.jit(lambda a: lax.fori_loop(0, k, body, a).sum())

    def best_time(fn):
        float(fn(x))  # warm/compile
        best = None
        for _ in range(trials):
            t0 = time.monotonic()
            float(fn(x))
            dt = time.monotonic() - t0
            best = dt if best is None or dt < best else best
        return best

    d_small = best_time(make(k_small))
    d_large = best_time(make(k_large))
    return max(1e-9, (d_large - d_small) / (k_large - k_small)), d_small


def bench_serving(batch_sizes=(1, 4, 16), threads_per_slot=3,
                  duration_s=1.0, trials=3):
    """Serving rung: dynamic-batcher qps and p99 queue delay vs
    max_batch_size through `brpc_tpu/serving` on jit scoring (a 2-layer
    MLP).  Same jitter discipline as the other rungs: `trials` runs per
    batch size, median + spread.  Runs on whatever jax platform the
    environment provides; the caller publishes {"skipped": true} when no
    device is reachable."""
    import threading

    import numpy as np
    import jax
    import jax.numpy as jnp

    from brpc_tpu.serving import DynamicBatcher

    D, H = 256, 4096
    rng = np.random.default_rng(0)
    w1 = jnp.asarray(rng.standard_normal((D, H)).astype(np.float32))
    w2 = jnp.asarray(rng.standard_normal((H, H)).astype(np.float32))
    w3 = jnp.asarray(rng.standard_normal((H, 1)).astype(np.float32))

    @jax.jit
    def score(x):
        return jnp.tanh(jnp.tanh(x @ w1) @ w2) @ w3

    item = np.ones((D,), np.float32)
    max_delay_us = 20_000

    def one_trial(bs: int, k: int):
        threads = max(4, threads_per_slot * bs)
        b = DynamicBatcher(score, max_batch_size=bs,
                           max_delay_us=max_delay_us,
                           batch_buckets=(bs,), length_buckets=(D,),
                           name=f"bench_bs{bs}_{k}")
        try:
            b.submit_wait(item, timeout_s=300)   # compile outside timing
            stop = time.monotonic() + duration_s
            counts = [0] * threads

            def worker(i):
                while time.monotonic() < stop:
                    b.submit_wait(item, timeout_s=60)
                    counts[i] += 1

            ts = [threading.Thread(target=worker, args=(i,))
                  for i in range(threads)]
            t0 = time.monotonic()
            [t.start() for t in ts]
            [t.join(120) for t in ts]
            wall = time.monotonic() - t0
            return (sum(counts) / wall,
                    b.queue_delay_rec.latency_percentile(0.99))
        finally:
            b.close()

    out = {}
    for bs in batch_sizes:
        rs = sorted(one_trial(bs, k) for k in range(trials))
        mid = len(rs) // 2
        out[f"bs{bs}"] = {
            "qps": round(rs[mid][0], 1),
            "queue_p99_us": round(rs[mid][1], 1),
            "qps_spread": [round(rs[0][0], 1), round(rs[-1][0], 1)],
            "trials": trials,
        }
    base = out[f"bs{batch_sizes[0]}"]["qps"]
    peak = max(out[f"bs{bs}"]["qps"] for bs in batch_sizes)
    out["speedup_at_peak"] = round(peak / base, 2) if base else None
    out["max_delay_us"] = max_delay_us
    out["note"] = ("dynamic-batcher rung (brpc_tpu/serving): per-item "
                   "qps through bucket-padded jit scoring vs "
                   "max_batch_size; queue_p99_us is time queued before "
                   "batch formation")
    return out


def bench_kvcache(shared_ratios=(0.0, 0.5, 0.9), n_requests=24,
                  prefix_tokens=32, suffix_tokens=16, new_tokens=8,
                  trials=3):
    """Paged-KV-cache rung: decode tokens/s and prefill-skip ratio vs
    shared-prefix ratio through `brpc_tpu/kvcache` + the DecodeEngine.

    Workload: `n_requests` prompts; a `shared_ratios` fraction open
    with ONE fixed `prefix_tokens`-token prefix (the shared-system-
    prompt shape) plus a unique suffix, the rest are fully distinct.
    The radix tree warms as early requests retire, so later admits of
    the shared prefix reuse its pages and skip that prefill — the
    prefill_skip ratio is the store's own hit-rate gauge, and tokens/s
    is end-to-end through admit/prefill/decode/retire.  Same jitter
    discipline as the other rungs: `trials` runs per ratio, median +
    spread.  The caller publishes {"skipped": true} when no device is
    reachable."""
    import threading

    import jax

    from brpc_tpu.kvcache import KVCacheStore
    from brpc_tpu.serving import DecodeEngine

    pt = 16

    @jax.jit
    def step(tokens, positions, pages):
        return tokens + 1

    @jax.jit
    def prefill(tokens, start):
        return tokens.sum()

    def one_trial(ratio: float, k: int):
        store = KVCacheStore(page_tokens=pt, page_bytes=pt * 64,
                             max_blocks=32,
                             name=f"bench_r{int(ratio * 100)}_{k}")
        eng = DecodeEngine(step, num_slots=4, store=store,
                           prefill_fn=prefill,
                           name=f"bench_kv_r{int(ratio * 100)}_{k}")
        shared = list(range(1000, 1000 + prefix_tokens))
        n_shared = int(n_requests * ratio)
        prompts = []
        for i in range(n_requests):
            suffix = [2000 + i * suffix_tokens + j
                      for j in range(suffix_tokens)]
            head = shared if i < n_shared else \
                [3000 + i * prefix_tokens + j
                 for j in range(prefix_tokens)]
            prompts.append(head + suffix)
        try:
            # warm the jit caches outside timing — with a THROWAWAY
            # prompt disjoint from the measured set, so the shared0
            # rung really sees 0% prefix reuse
            eng.submit([9_000_000 + j for j in range(prefix_tokens)],
                       1, lambda t: None)
            assert eng.join_idle(60)
            # measure the skip ratio over the TIMED workload only (the
            # warm-up request's tokens would dilute the denominator)
            h0 = store.hit_tokens.get_value()
            p0 = store.prompt_tokens.get_value()
            done = [threading.Event() for _ in prompts]
            t0 = time.monotonic()
            for i, p in enumerate(prompts):
                eng.submit(p, new_tokens, lambda t: None,
                           (lambda err, d=done[i]: d.set()))
            for d in done:
                assert d.wait(120), "kvcache bench request hung"
            wall = time.monotonic() - t0
            toks = n_requests * new_tokens
            dp = store.prompt_tokens.get_value() - p0
            skip = (store.hit_tokens.get_value() - h0) / dp if dp else 0.0
            return toks / wall, skip
        finally:
            eng.close()
            store.close()

    out = {}
    for ratio in shared_ratios:
        rs = sorted(one_trial(ratio, k) for k in range(trials))
        mid = len(rs) // 2
        out[f"shared{int(ratio * 100)}"] = {
            "tokens_per_s": round(rs[mid][0], 1),
            "prefill_skip_ratio": round(rs[mid][1], 4),
            "tokens_per_s_spread": [round(rs[0][0], 1),
                                    round(rs[-1][0], 1)],
            "trials": trials,
        }
    out["note"] = ("paged-KV rung (brpc_tpu/kvcache): decode tokens/s "
                   "and prefill-skip (radix hit-rate) vs shared-prefix "
                   "ratio; skip ratio climbs with sharing because "
                   "admits reuse cached pages instead of prefilling")
    return out


def bench_recovery(committed_ratios=(0.0, 0.5, 0.9), n_requests=6,
                   total_prompt_tokens=40, new_tokens=10, trials=3):
    """Recovery rung: supervised engine-crash failover through
    `brpc_tpu/serving/supervisor.py` + the paged KV cache.

    Workload: `n_requests` concurrent generations whose prompts share a
    COMMITTED prefix covering `committed_ratios` of the prompt (the
    prefix is committed to the radix tree by a clean completion before
    the wave; the rest of each prompt is unique).  A seeded
    `serving.step` fault crashes the engine mid-decode; the supervisor
    detects it, rebuilds against the surviving store, and re-admits
    every generation from its last emitted token.  Reported per ratio:

      * time-to-recover: crash detection -> first post-restart token
        (the supervisor's own detect_to_first_token_ms);
      * re-decoded-token ratio: (prompt tokens prefilled - cache-hit
        tokens) / prompt tokens over the wave+recovery window — 1.0
        means recovery replayed everything from scratch, lower means
        the committed prefix pages did their job.

    Same jitter discipline as the other rungs: `trials` runs per
    ratio, median + spread.  The caller publishes {"skipped": true}
    when no device is reachable."""
    import threading

    import jax

    from brpc_tpu import fault
    from brpc_tpu.kvcache import KVCacheStore
    from brpc_tpu.serving import DecodeEngine, EngineSupervisor

    pt = 8

    @jax.jit
    def step(tokens, positions, pages):
        return (tokens * 7 + positions) % 997

    calm = ({"queue_delay_us": float("inf"), "pool_ratio": 9.9,
             "queue_depth": 1e9},) * 3

    def one_trial(ratio: float, k: int):
        tag = f"rec_r{int(ratio * 100)}_{k}"
        store = KVCacheStore(page_tokens=pt, page_bytes=pt * 64,
                             max_blocks=64, name=f"bench_{tag}")
        sup = EngineSupervisor(
            lambda: DecodeEngine(step, num_slots=4, store=store,
                                 max_pages_per_slot=64,
                                 name=f"bench_{tag}_eng"),
            store=store, heartbeat_deadline_s=10.0,
            check_interval_s=0.01, ladder=calm, name=f"bench_{tag}_sup")
        # page-align the committed share so "committed" means whole
        # pages the radix tree can actually serve
        shared_n = int(total_prompt_tokens * ratio) // pt * pt
        shared = [5000 + k * 1000 + j for j in range(shared_n)]
        prompts = []
        for i in range(n_requests):
            uniq = [7000 + k * 1000 + i * total_prompt_tokens + j
                    for j in range(total_prompt_tokens - shared_n)]
            prompts.append(shared + uniq)
        try:
            # warm the jit cache AND commit the shared prefix
            done = threading.Event()
            warm = (shared + [9]) if shared else [9_000_000 + k, 1, 2]
            sup.submit(warm, 1, lambda t: None, lambda e: done.set())
            assert done.wait(120)
            assert sup.join_idle(60)
            h0 = store.hit_tokens.get_value()
            p0 = store.prompt_tokens.get_value()
            plan = fault.FaultPlan(900 + k).on(
                "serving.step", fault.ERROR, times=1, after=3)
            events = [threading.Event() for _ in prompts]
            with fault.injected(plan):
                for p, ev in zip(prompts, events):
                    sup.submit(p, new_tokens, lambda t: None,
                               (lambda err, d=ev: d.set()))
                for ev in events:
                    assert ev.wait(120), "recovery bench request hung"
            assert sup.stats()["restarts"] == 1, "crash never fired"
            rec = sup.stats()["last_recovery"] or {}
            ttr_ms = rec.get("detect_to_first_token_ms")
            dp = store.prompt_tokens.get_value() - p0
            dh = store.hit_tokens.get_value() - h0
            redecode = (dp - dh) / dp if dp else 1.0
            return ttr_ms, redecode
        finally:
            sup.close()
            store.clear()
            store.close()

    out = {}
    for ratio in committed_ratios:
        rs = []
        for k in range(trials):
            rs.append(one_trial(ratio, k))
        ttrs = sorted(r[0] for r in rs if r[0] is not None)
        reds = sorted(r[1] for r in rs)
        out[f"committed{int(ratio * 100)}"] = {
            "time_to_recover_ms": (round(ttrs[len(ttrs) // 2], 2)
                                   if ttrs else None),
            "time_to_recover_spread_ms": ([round(ttrs[0], 2),
                                           round(ttrs[-1], 2)]
                                          if ttrs else None),
            "redecoded_token_ratio": round(reds[len(reds) // 2], 4),
            "redecoded_token_ratio_spread": [round(reds[0], 4),
                                             round(reds[-1], 4)],
            "trials": trials,
        }
    out["note"] = ("recovery rung (brpc_tpu/serving/supervisor.py): "
                   "detect->first-post-restart-token latency and "
                   "re-decoded-token ratio vs committed-prefix share; "
                   "the ratio falls as committed pages turn recovery "
                   "prefill into cache hits")
    return out


def bench_trace_overhead(duration_s=1.0, threads=8, trials=3):
    """Tracing-overhead rung (ISSUE 5): serving qps through the dynamic
    batcher with rpcz OFF (the NULL_SPAN fast path every production
    default rides), ON at sample rate 1.0 (every trace kept), and ON at
    0.01 (per-trace head sampling).  Same jitter discipline as the
    other rungs: `trials` runs per mode, median + spread.  The claim
    under test: the disabled path costs nothing measurable, and
    sampling bounds the enabled cost."""
    import threading

    import numpy as np
    import jax
    import jax.numpy as jnp

    from brpc_tpu import rpcz
    from brpc_tpu.serving import DynamicBatcher

    D = 128
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((D, D)).astype(np.float32))

    @jax.jit
    def score(x):
        return jnp.tanh(x @ w).sum(axis=-1)

    item = np.ones((D,), np.float32)
    modes = (("off", False, 1.0), ("on_1.0", True, 1.0),
             ("on_0.01", True, 0.01))

    def one_trial(mode_k, on, rate, k):
        b = DynamicBatcher(score, max_batch_size=16, max_delay_us=500,
                           batch_buckets=(16,), length_buckets=(D,),
                           name=f"bench_trace_{mode_k}_{k}")
        try:
            b.submit_wait(item, timeout_s=300)   # compile outside timing
            rpcz.set_enabled(on, rate)
            stop = time.monotonic() + duration_s
            counts = [0] * threads

            def worker(i):
                while time.monotonic() < stop:
                    b.submit_wait(item, timeout_s=60)
                    counts[i] += 1

            ts = [threading.Thread(target=worker, args=(i,))
                  for i in range(threads)]
            t0 = time.monotonic()
            [t.start() for t in ts]
            [t.join(120) for t in ts]
            return sum(counts) / (time.monotonic() - t0)
        finally:
            rpcz.set_enabled(False)
            b.close()

    out = {}
    for mode_k, on, rate in modes:
        qps = sorted(one_trial(mode_k, on, rate, k) for k in range(trials))
        out[mode_k] = {
            "qps": round(qps[len(qps) // 2], 1),
            "qps_spread": [round(qps[0], 1), round(qps[-1], 1)],
            "trials": trials,
        }
    base = out["off"]["qps"]
    if base:
        for mode_k, _, _ in modes[1:]:
            out[mode_k]["overhead_pct_vs_off"] = round(
                (base - out[mode_k]["qps"]) / base * 100.0, 2)
    out["note"] = ("trace-overhead rung (brpc_tpu/rpcz): batcher qps "
                   "with rpcz off / on@1.0 / on@0.01; 'off' rides the "
                   "NULL_SPAN fast path — its spread vs the other "
                   "modes bounds the cost of shipping the tracing "
                   "hooks disabled")
    return out


def bench_hbm_stream(chunk_mb=64):
    """SECONDARY chip sanity number: raw on-chip HBM read+write bandwidth
    of a jitted roll+add loop.  No framework code runs here — this bounds
    what the transport could reach, it is not the transport."""
    import jax.numpy as jnp

    n = chunk_mb * 1024 * 1024 // 2  # bf16 elements
    x = jnp.ones((n,), jnp.bfloat16)
    per_pass, dispatch = _per_pass_seconds(x)
    traffic = 2 * x.nbytes
    gbps, issues = _gated(traffic, per_pass)
    return {"gbps": gbps, "chunk_mb": chunk_mb,
            "per_pass_us": round(per_pass * 1e6, 1),
            "dispatch_overhead_ms": round(dispatch * 1e3, 1),
            "note": "raw HBM loop, not framework code",
            **({"invalid": issues} if issues else {})}


def _readback_sync(arr):
    """Force true device completion: a scalar host readback.  On the
    tunneled axon platform block_until_ready returns before the device
    finishes (measured: 64 copies of 64MB 'complete' in 0.6ms); a gather
    to host cannot lie.  Warm the gather op first (same shape/dtype) so
    the timed call is cached."""
    return float(arr[0])


def _readback_baseline(arr, trials=9):
    """Fixed cost of a readback on an already-ready array (tunnel RTT);
    returns (median_s, spread_s).  Spread trims one outlier per side —
    the tunnel occasionally hiccups 20ms+ on a single RTT and a max-min
    spread would inflate the confidence floor past any measurable copy
    phase (4x21.9ms floor vs a 14ms copy phase on the r3 dev chip)."""
    _readback_sync(arr)  # warm the gather
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        _readback_sync(arr)
        times.append(time.perf_counter() - t0)
    times.sort()
    spread = (times[-2] - times[1]) if trials >= 4 else (times[-1] - times[0])
    return times[len(times) // 2], spread


def bench_tensor_pipe(chunk_mb=64, iter_chunks=80, max_total_gb=96):
    """HEADLINE: TensorStream -> IciEndpoint framework path.  Same-device
    chunks go through the endpoint's compiled copy kernel, so every chunk
    provably lands in a distinct destination buffer; cross-device
    (multi-chip) chunks ride device_put ICI DMA.

    Timing: ITERATIONS of `iter_chunks` chunks, each sized to fit the
    credit window (no mid-measurement stalls on completion observation —
    a tunnel RTT each) and each ending in a forced scalar readback; the
    copy phases (wall - readback baseline) are SUMMED across iterations
    until they clear a jitter floor that scales with sqrt(iterations).
    One iteration of 5GB finishes in ~15ms on the real chip — under the
    floor — so a single-shot measurement cannot resolve; accumulation
    keeps in-flight memory bounded by the window while moving enough
    total bytes to measure honestly (r3 first cut published null here)."""
    import jax
    import jax.numpy as jnp

    from brpc_tpu.ici import TensorStream
    from brpc_tpu.ici.endpoint import link_stats

    dev = jax.devices()[0]
    n = chunk_mb * 1024 * 1024 // 2
    chunk = jnp.ones((n,), jnp.bfloat16)
    _readback_sync(chunk)
    outs = []
    def consume(a):
        outs[:] = [a]
        consume.n += 1
    consume.n = 0
    # window covers ONE iteration; iterations drain (untimed) in between
    ts = TensorStream(dev, consumer=consume,
                      window_bytes=(iter_chunks + 2) * chunk.nbytes)
    stats0 = link_stats()
    # batch size bounded by per-dispatch live memory (in + out <= 512MB
    # total): 16x64MB batches kept 1GB live per dispatch and the
    # allocator churn depressed the measured bandwidth (r3 weak #4)
    bs = max(1, min(16, iter_chunks, (256 << 20) // chunk.nbytes))
    # warmup: drainer thread + EVERY batch arity the timed loop will use
    # (jit caches per arity — r3's first cut warmed one arity and then
    # paid a different-arity compile INSIDE the timed region, which is
    # seconds over the tunnel).  iter_chunks % bs != 0 means the loop's
    # final batch has a remainder arity: warm that too.
    warm_target = bs
    ts.write_many([chunk] * bs)
    rem = iter_chunks % bs
    if rem:
        ts.write_many([chunk] * rem)
        warm_target += rem
    deadline = time.monotonic() + 60
    while consume.n < warm_target and time.monotonic() < deadline:
        time.sleep(0.005)    # deterministic: wait until warmup delivered
    # the transfer must not alias the source — this is the "really moved
    # bytes" proof the r1 bench lacked.  Two proofs, strongest available:
    # (a) buffer pointers when the plugin exposes them; (b) a device-side
    # donation sentinel that works even over the axon tunnel (VERDICT r2
    # weak #4): copy a probe through the endpoint, then overwrite the
    # probe's buffer in place (donated jit) and re-read the destination —
    # if the "copy" had aliased the source, the destination would now
    # read the sentinel value.
    aliased = False
    alias_check = "unavailable"
    if outs:
        try:
            aliased = (outs[0].unsafe_buffer_pointer()
                       == chunk.unsafe_buffer_pointer())
            alias_check = "pointer-checked"
        except Exception:
            pass
    if alias_check == "unavailable":
        probe = jnp.full((1 << 20,), 3, jnp.bfloat16)
        probe.block_until_ready()
        dst = ts.endpoint.send(probe)
        dst.block_until_ready()
        overwrite = jax.jit(lambda v: v * 0 + 7, donate_argnums=0)
        sentinel = overwrite(probe)   # reuses probe's buffer on TPU
        sentinel.block_until_ready()
        if float(dst[0]) == 3.0:
            alias_check = "donation-sentinel-passed"
        else:
            aliased = True
            alias_check = "DONATION-SENTINEL-FAILED"
        del sentinel, dst, probe
    base, jitter = _readback_baseline(outs[0] if outs else chunk)
    delivered_before = consume.n
    copy_sum = 0.0
    wall_sum = 0.0
    moved = 0
    iters = 0
    # SNR grows with sqrt(iterations) (signal ~ n, noise ~ jitter*sqrt(n)),
    # so enough traffic ALWAYS resolves: n >= (4*jitter/copy_per_iter)^2.
    # 96GB covers tunnel jitter up to ~17ms at this chip's ~320GB/s.
    max_total = max_total_gb << 30
    issues = []
    while True:
        # untimed inter-iteration drain: the next timed run must start
        # with full window credit, or it measures stalls, not the pipe
        deadline = time.monotonic() + 120
        want = delivered_before + iters * iter_chunks
        while consume.n < want and time.monotonic() < deadline:
            time.sleep(0.002)
        if consume.n < want:
            # a timed run without full window credit measures stalls,
            # not the pipe — never publish that as a valid number
            issues.append(
                f"drainer wedged: {consume.n - delivered_before} of "
                f"{want - delivered_before} chunks delivered after 120s")
            break
        t0 = time.perf_counter()
        # batched dispatch: bs chunks per pre-compiled multi-copy program
        # (endpoint.send_batch) — one Python->PJRT call per <=256MB.  The
        # timed region ends when the LAST transfer provably completed
        # (scalar readback of the final destination buffer); consumer
        # delivery overlaps on the drainer thread.
        last = None
        for i in range(0, iter_chunks, bs):
            last = ts.write_many([chunk] * min(bs, iter_chunks - i))[-1]
        _readback_sync(last)
        wall = time.perf_counter() - t0
        copy_sum += wall - base
        wall_sum += wall
        moved += iter_chunks * chunk.nbytes
        iters += 1
        floor = max(0.010, 4 * jitter * math.sqrt(iters))
        if copy_sum >= floor:
            break
        if moved >= max_total:
            issues.append(
                f"copy phase {copy_sum * 1e3:.1f}ms not resolvable above "
                f"readback jitter ({jitter * 1e3:.1f}ms over {iters} "
                f"iters) at traffic cap {max_total_gb}GB")
            break
    ts.close(wait=True)
    stats1 = link_stats()
    gbps, gate_issues = _gated(moved, max(copy_sum, 1e-9))
    issues += gate_issues
    if aliased:
        issues.append("destination buffer aliased the source")
    if issues:
        gbps = None
    return {"gbps": gbps, "chunk_mb": chunk_mb,
            # hbm_stream counts READ+WRITE traffic; each pipe chunk also
            # reads the source and writes the destination, so the
            # traffic-basis number (2x moved bytes) is the one comparable
            # to hbm_stream.  Same-run measurement: 584 vs 715 GB/s = 82%
            # of raw HBM through the full framework pipe.
            "hbm_traffic_gbps": round(gbps * 2, 3) if gbps else None,
            "chunks": consume.n - delivered_before,   # timed deliveries
            "iterations": iters, "moved_gb": round(moved / (1 << 30), 2),
            "wall_s": round(wall_sum, 4),
            "copy_s": round(copy_sum, 4),
            "readback_baseline_ms": round(base * 1e3, 1),
            "alias_check": alias_check,
            "same_device_copies":
                stats1["same_device_copies"] - stats0["same_device_copies"],
            "cross_device_moves":
                stats1["cross_device_moves"] - stats0["cross_device_moves"],
            **({"invalid": issues} if issues else {})}


def bench_streaming_tensor(chunk_mb=4, iter_chunks=32, max_total_gb=32):
    """Unified StreamWrite carrying device tensors (VERDICT r3 #1): a
    REAL loopback RPC server accepts a stream on the chip, the client's
    stream.write() pushes device arrays, and each chunk rides the rail
    (stage -> IciEndpoint -> claim ticket on the socket -> unstage).
    Unlike tensor_pipe this pays the full framework cost per message:
    block staging, registry deposit/claim, control frames, CONSUMED
    feedback.  host_copy_count() is asserted unchanged — the number is
    only published if the path stayed zero-copy."""
    import jax
    import jax.numpy as jnp

    import brpc_tpu as brpc
    from brpc_tpu.ici import rail

    dev = jax.devices()[0]
    n = chunk_mb * 1024 * 1024 // 2
    chunk = jnp.ones((n,), jnp.bfloat16)
    _readback_sync(chunk)

    # count + most-recent only: retaining every delivered chunk would
    # pin up to max_total_gb of HBM for the whole run
    class _Sink:
        count = 0
        last = None
    def on_msg(stream, payload):
        _Sink.last = payload
        _Sink.count += 1

    class StreamSink(brpc.Service):
        @brpc.method(request="json", response="json")
        def Open(self, cntl, req):
            # 1GB window: the stream credit loop prices its releases at a
            # delivery round-trip, so the window must cover the link's
            # bandwidth-delay product or the writer stalls once per batch
            # (measured: 256MB capped the rung at 2 GB/s on a 64ms tunnel)
            cntl.accept_stream(on_msg, max_buf_size=1 << 30, device=dev)
            return {"ok": True}

    server = brpc.Server(brpc.ServerOptions(ici_device=dev))
    server.add_service(StreamSink())
    server.start("127.0.0.1", 0)
    ch = brpc.Channel(f"127.0.0.1:{server.port}", timeout_ms=120000)
    cntl = brpc.Controller()
    stream = brpc.stream_create(cntl, None, max_buf_size=1 << 30,
                                device=dev)
    issues = []
    try:
        ch.call_sync("StreamSink", "Open", {}, serializer="json", cntl=cntl)
        host_copies0 = rail.host_copy_count()
        # warmup: compile the stage/slice/unstage kernels
        stream.write(chunk)
        deadline = time.monotonic() + 120
        while _Sink.count == 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        if _Sink.count == 0:
            return {"error": "warmup chunk never delivered"}
        # warm the coalesced-dispatch programs: the stream sender batches
        # adjacent writes into power-of-2 send_batch arities, each a
        # distinct XLA program — compile them OUTSIDE the timed region
        # (VERDICT r4 #1a: warm every arity before measuring)
        for k in (2, 4, 8, 16, 32):
            for tk in rail.ship_many([chunk] * k, dev):
                rail.withdraw(tk)
        base, jitter = _readback_baseline(_Sink.last)
        warm = _Sink.count
        moved = 0
        iters = 0
        max_total = max_total_gb << 30
        # ONE timed region, ONE readback fence at the very end.  A fence
        # per batch made the confidence floor scale as jitter*sqrt(iters),
        # which a fast chip behind a noisy tunnel can never outrun (r5 dev
        # session: 1.4ms of copy per batch vs a 30ms tunnel hiccup
        # spread).  Between batches, delivery is confirmed by the
        # framework's own CONSUMED feedback (_Sink.count) — part of the
        # path being measured — and the elapsed check needs no fence.
        # at least 1s of timed streaming: the tunnel's throughput drifts
        # phase-to-phase (measured 10 vs 18 GB/s on back-to-back 0.2s
        # windows), and a longer region averages across phases as well as
        # clearing the jitter-confidence floor
        floor = max(1.0, 4 * jitter)
        t0 = time.perf_counter()
        while True:
            # the wedge deadline is PER BATCH (ADVICE r5): measured from
            # the start of the whole timed region, a healthy late batch
            # on a jittery link would be misflagged once the region
            # outgrows 120s (floor = 4*jitter can approach it)
            batch_t0 = time.perf_counter()
            for _ in range(iter_chunks):
                stream.write(chunk, timeout_s=120)
            # completion = delivery through the whole framework path
            want = warm + (iters + 1) * iter_chunks
            wedged = False
            while _Sink.count < want:
                if _batch_wedged(batch_t0, time.perf_counter()):
                    wedged = True
                    break
                time.sleep(0.001)
            if wedged:
                # a timed-out batch must invalidate the WHOLE result —
                # crediting its bytes would publish a bogus valid number
                issues.append(
                    f"stream wedged mid-batch: "
                    f"{_Sink.count - warm - iters * iter_chunks}"
                    f"/{iter_chunks} delivered")
                break
            moved += iter_chunks * chunk.nbytes
            iters += 1
            if time.perf_counter() - t0 - base >= floor:
                break
            if moved >= max_total:
                # byte cap first: fine (a fast link outruns the 1s
                # drift-averaging target) UNLESS the phase is still inside
                # the jitter-confidence floor — then the number is noise
                if time.perf_counter() - t0 - base < max(0.010, 4 * jitter):
                    issues.append(
                        f"copy phase {time.perf_counter() - t0 - base:.4f}s "
                        f"not resolvable above jitter "
                        f"({jitter * 1e3:.1f}ms, {iters} iters)")
                break
        if not any("wedged" in i for i in issues):
            _readback_sync(_Sink.last)
        copy_sum = time.perf_counter() - t0 - base
        host_copies = rail.host_copy_count() - host_copies0
        if host_copies:
            issues.append(f"{host_copies} host copies on the tensor path")
        gbps, gate_issues = _gated(moved, max(copy_sum, 1e-9))
        issues += gate_issues
        if issues:
            gbps = None
        return {"gbps": gbps, "chunk_mb": chunk_mb,
                "chunks": _Sink.count - warm, "iterations": iters,
                "moved_gb": round(moved / (1 << 30), 2),
                "copy_s": round(copy_sum, 4),
                "host_copies": host_copies,
                **({"invalid": issues} if issues else {})}
    finally:
        stream.close()
        server.stop()
        server.join()


def bench_ici_ladder(sizes=(64, 4096, 65536, 1 << 20, 1 << 24, 1 << 26)):
    """rdma_performance 64B-64MB ladder over the REAL endpoint path, now
    through the pre-compiled batched transfer program (send_batch: k copy
    HLOs in ONE XLA program, one dispatch) instead of k Python dispatches.
    Sizes are exact byte counts (uint8 payloads).  Each rung: m batched
    dispatches of k chunks ending in a forced scalar readback of the last
    batch's tail, minus the measured fixed readback cost.  Rungs whose
    copy phase is not resolvable above readback jitter are published as
    null — never as a fantasy number.

    r2's 65536B cliff (68us @4KB -> 1520us @64KB) was credit-window
    exhaustion: window_bytes=8*size meant batch 64 filled the window at
    64KB and every further send stalled on completion observation (~a
    tunnel RTT each).  Batched dispatch + a window sized for the whole
    trial removes the stall entirely."""
    import jax
    import jax.numpy as jnp

    from brpc_tpu.ici import IciEndpoint

    dev = jax.devices()[0]
    out = {}
    for size in sizes:
        x = jnp.ones((size,), jnp.uint8)     # exactly `size` bytes
        # chunks per dispatch: big enough to amortize the program call,
        # small enough to keep per-dispatch live memory <= 256MB in +
        # 256MB out AND the multi-copy program's arity compile-cheap —
        # arity-128 programs took ~a minute each to compile over the
        # tunnel and the small rungs are overhead-dominated either way.
        # NO floor above the memory cap: the old k floor of 8 made the
        # 64MB rung dispatch 512MB batches (1GB live each), and the
        # allocator churn showed up as the r3 "ladder dip".
        k = max(1, min(32, (256 << 20) // size))
        # the window bounds destination HBM held by unobserved transfers
        # (the drainer frees in bulk, one tunnel RTT per cycle); 6GB keeps
        # a comfortable margin on a 16GB chip while letting rungs push
        # enough traffic to clear the tunnel-RTT noise floor
        window = 6 << 30
        ep = IciEndpoint(dev, window_bytes=window)
        warm = ep.send_batch([x] * k)        # compile the k-copy program
        warm[-1].block_until_ready()
        base, jitter = _readback_baseline(warm[-1])
        # Total-traffic cap (24GB) — NOT in-flight memory: destinations
        # are freed as the trial proceeds.  A single timed run is bounded
        # by the WINDOW (m_window dispatches) so the writer never stalls
        # on a completion-observation tunnel RTT mid-measurement (r3's
        # first cut let the 64MB rung outrun the window and the stall
        # halved its published bandwidth — the "non-monotonic" artifact);
        # rungs needing more traffic than one window accumulate ITERATED
        # timed runs with untimed drains between, gated on a floor that
        # grows with sqrt(iterations).
        # total-traffic cap high enough that the sqrt(iterations) SNR
        # growth resolves even on high-jitter tunnel runs (see
        # bench_tensor_pipe)
        m_cap = max(1, (96 << 30) // (k * size))
        m_window = max(1, (window - k * size) // (k * size))

        def run_trial(m):
            """One timed trial of m dispatches, split into window-bounded
            iterations.  Returns (copy_sum, iters); copy_sum None on a
            wedged drainer."""
            iters = 0
            remaining = m
            copy_sum = 0.0
            while remaining > 0:
                mi = min(remaining, m_window)
                # untimed drain: start each timed run with full credit
                deadline = time.monotonic() + 120
                while ep.inflight_bytes > 0 and \
                        time.monotonic() < deadline:
                    time.sleep(0.002)
                if ep.inflight_bytes > 0:
                    return None, iters
                last = None
                t0 = time.perf_counter()
                for _ in range(mi):
                    last = ep.send_batch([x] * k)[-1]
                _readback_sync(last)
                copy_sum += time.perf_counter() - t0 - base
                remaining -= mi
                iters += 1
            return copy_sum, iters

        m = 1
        rung = None
        escalations = 0
        rung_deadline = time.monotonic() + 45
        while True:
            copy_sum, iters = run_trial(m)
            if copy_sum is None:
                rung = {"lat_us": None, "gbps": None, "batch": k,
                        "dispatches": m,
                        "invalid": ["drainer wedged: window credit not "
                                    "released within 120s"]}
                break
            floor = max(0.004, 4 * jitter * math.sqrt(iters))
            if copy_sum >= floor:
                # Re-measure at the accepted size.  A retrial BELOW the
                # floor is evidence the first trial only cleared it via a
                # one-off jitter spike (tunnel hiccup, allocator stall) —
                # the r4 64MB "dip" published 66 GB/s off exactly such a
                # spike while fresh trials measured 515.  In that case
                # the honest response is MORE TRAFFIC (double m), never
                # keeping the inflated number; when all trials clear the
                # floor, the minimum is the standard bandwidth estimator.
                # Escalation is BOUNDED (2 doublings + the rung budget)
                # so one noisy rung can't eat the whole bench window;
                # confirmation trials run only on the >=16MB rungs, where
                # a spike-induced dip would break the monotonic gate (the
                # sub-MB rungs are overhead-dominated and cheap to trust).
                trials = [copy_sum]
                spiked = False
                if size >= (1 << 24):
                    for _ in range(2):
                        if time.monotonic() > rung_deadline:
                            break
                        c2, _ = run_trial(m)
                        if c2 is None:
                            continue
                        if c2 < floor:
                            spiked = True
                        trials.append(c2)
                if spiked and m < m_cap and escalations < 2 \
                        and time.monotonic() < rung_deadline:
                    escalations += 1
                    m = min(m_cap, m * 2)
                    continue
                note = None
                copy_sum = min(trials)
                if copy_sum < floor:
                    # escalation exhausted with sub-floor trials: the
                    # MEDIAN is the low-bias estimator here (min would
                    # overstate bandwidth by up to the jitter)
                    copy_sum = sorted(trials)[len(trials) // 2]
                    note = "jitter-limited: median of trials"
                gbps, issues = _gated(m * k * size, max(copy_sum, 1e-9))
                rung = {"lat_us": round(copy_sum / (m * k) * 1e6, 2),
                        "gbps": gbps, "batch": k, "dispatches": m,
                        "iterations": iters,
                        **({"note": note} if note else {}),
                        **({"invalid": issues} if issues else {})}
                if issues:
                    rung["lat_us"] = None
                break
            if m >= m_cap or time.monotonic() > rung_deadline:
                rung = {"lat_us": None, "gbps": None, "batch": k,
                        "dispatches": m,
                        "invalid": [
                            f"copy phase {copy_sum * 1e3:.1f}ms below "
                            f"confidence floor {floor * 1e3:.1f}ms at "
                            f"dispatches {m} "
                            f"({'rung budget' if m < m_cap else 'cap'})"]}
                break
            m = min(m_cap, m * 2)
        ep.close()
        out[f"{size}B"] = rung
    # sanity gate (VERDICT r2 weak #3): the physical invariant of a
    # transfer ladder is BANDWIDTH monotone non-decreasing with size until
    # plateau — bigger chunks amortize fixed per-dispatch cost over more
    # bytes.  Per-chunk *latency* is NOT monotone in the overhead-
    # dominated regime (below ~1MB a rung's cost is Python dispatch +
    # tunnel scheduling, roughly flat per batch, so per-chunk latency
    # wobbles with batch geometry rather than byte count); gating on it
    # was the wrong invariant.  Tolerance 0.5: plateau rungs (>=16MB)
    # wobble +-40% run to run over the tunnel (measured 118-230 GB/s on
    # the same code), which is environment, not a framework artifact;
    # the gate still catches genuine cliffs — r2's 64KB credit stall was
    # 22x, and the r3 window-overrun stall halved the rung (0.48 < 0.5).
    bws = [(s, out[f"{s}B"].get("gbps")) for s in sizes]
    bad = [f"{a}B({ga}GB/s) > {b}B({gb}GB/s)"
           for (a, ga), (b, gb) in zip(bws, bws[1:])
           if ga is not None and gb is not None and gb < ga * 0.5]
    out["monotonic_bandwidth"] = not bad
    if bad:
        out["monotonic_violations"] = bad
    return out


_DCN_SERVER_SRC = """
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from brpc_tpu.ici.channel import register_device_service
from brpc_tpu.rpc.server import Server
register_device_service("Bench", "Echo", lambda x: x)
srv = Server(enable_dcn=True)
srv.start("127.0.0.1", 0)
print(f"PORT={{srv.port}}", flush=True)
srv.run_until_interrupt()
"""

_DCN_CLIENT_SRC = """
import json, sys, time
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from brpc_tpu.ici import dcn
ch = dcn.DcnChannel("ici://127.0.0.1:{port}/0")
topo = ch.handshake()
mode = "zero-copy" if topo.get("xfer") else "host-serialized"
mb = {mb}
x = np.random.default_rng(0).standard_normal(mb * 262144,
                                             dtype=np.float32)  # mb MiB
assert x.nbytes == mb * 1024 * 1024
import jax.numpy as jnp
xd = jnp.asarray(x)
out = ch.call_sync("Bench", "Echo", xd)       # warm both directions
best = None
for _ in range(5):
    t0 = time.perf_counter()
    out = ch.call_sync("Bench", "Echo", xd)
    jax.block_until_ready(out)   # async dispatch: force the pulled
    dt = time.perf_counter() - t0  # bytes to LAND inside the timing
    best = dt if best is None or dt < best else best
np.testing.assert_allclose(np.asarray(out)[:8], x[:8])
# request + response both move mb MB
print(json.dumps({{"mode": mode, "gbps": round(2 * mb / 1024 / best, 3),
                   "roundtrip_s": round(best, 4)}}))
"""


def bench_dcn(mb: int = 32) -> dict:
    """DCN data-plane rung (VERDICT r4 #10): two PROCESSES over loopback
    TCP, echoing a device array through the `_dcn` service — zero-copy
    fabric pull (jax.experimental.transfer) vs the host-serialized
    fallback (BRPC_DCN_DISABLE_XFER=1).  Both processes run forced-CPU:
    the rung measures the TRANSPORT path (control frames, fabric pulls,
    serializer), not HBM — chip-side numbers live in tensor_pipe.  The
    axon tunnel does not admit two clients, so CPU is also what keeps
    this rung runnable when the chip is."""
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    out = {"payload_mb": mb, "platform": "cpu (forced; transport-path rung)"}
    env_base = dict(os.environ)
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base.pop("BRPC_DCN_DISABLE_XFER", None)
    for label, extra in (("zero_copy", {}),
                         ("host_fallback", {"BRPC_DCN_DISABLE_XFER": "1"})):
        env = dict(env_base, **extra)
        server = subprocess.Popen(
            [sys.executable, "-c", _DCN_SERVER_SRC.format(repo=repo)],
            stdout=subprocess.PIPE, env=env, text=True)
        try:
            port = None
            deadline = time.monotonic() + 90
            import selectors
            sel = selectors.DefaultSelector()
            sel.register(server.stdout, selectors.EVENT_READ)
            while time.monotonic() < deadline and port is None:
                if server.poll() is not None:
                    break  # crashed before printing PORT=
                # bounded-wait poll: EOF would make readline() return ""
                # in a hot spin, a wedged-but-alive child would block it
                # past the deadline
                if not sel.select(timeout=1.0):
                    continue
                line = server.stdout.readline()
                if not line:
                    break
                if line.startswith("PORT="):
                    port = int(line.strip().split("=")[1])
            sel.close()
            if port is None:
                out[label] = {"error": "dcn server never came up"}
                continue
            r = subprocess.run(
                [sys.executable, "-c",
                 _DCN_CLIENT_SRC.format(repo=repo, port=port, mb=mb)],
                capture_output=True, text=True, env=env, timeout=240)
            if r.returncode != 0:
                tail = (r.stderr or "").strip().splitlines()[-1:]
                out[label] = {"error": tail[0] if tail else "client failed"}
            else:
                out[label] = json.loads(r.stdout.strip().splitlines()[-1])
        except subprocess.TimeoutExpired:
            out[label] = {"error": "dcn client timed out"}
        finally:
            server.terminate()
            try:
                server.wait(timeout=10)
            except subprocess.TimeoutExpired:
                # a child wedged in PJRT teardown must not discard the
                # measurements already collected
                server.kill()
                server.wait(timeout=10)
    zc = out.get("zero_copy", {})
    fb = out.get("host_fallback", {})
    if isinstance(zc, dict) and zc.get("gbps") and \
            isinstance(fb, dict) and fb.get("gbps"):
        out["zero_copy_speedup"] = round(zc["gbps"] / fb["gbps"], 2)
    return out


def _med_spread(vals, key: str, nd: int = 1) -> dict:
    """median + min/max spread over trials — the rung family's shared
    jitter discipline (and the shape tools/perf_diff.py gates on)."""
    vs = sorted(vals)
    return {key: round(vs[len(vs) // 2], nd),
            f"{key}_spread": [round(vs[0], nd), round(vs[-1], nd)],
            "trials": len(vs)}


def bench_microbench(trials=3, duration_s=0.4, quick=False):
    """Per-stage host micro-benchmark suite (ISSUE 6; in the spirit of
    PAPERS.md "Designing a Micro-Benchmark Suite to Evaluate gRPC for
    TensorFlow": attribute the RPC path's host overhead PER STAGE
    before optimizing any of it).  Each rung isolates ONE serving
    stage on the host:

      * frame_pump        — the native C++ client pump -> native echo
                            loop (the non-Python ceiling);
      * batch_assembly    — DynamicBatcher formation/scatter with a
                            trivial numpy batch_fn (no jit, no device);
      * radix_prefix_match — KVCacheStore.probe longest-prefix match
                            against a warmed radix tree;
      * page_alloc_release — store admit/retire cycles of uncached
                            prompts (page alloc, splice bookkeeping,
                            release);
      * emit_fanout       — emit-buffer push/pop through producer/
                            consumer pairs (the per-token delivery
                            path), plus a 4-pair concurrency probe;
      * span_submit       — rpcz span create/annotate/submit + drain to
                            the recent-span store;
      * host_us_per_token — serving_host_us_per_token over a real
                            DecodeEngine decode (the de-GIL headline);
      * sampler_overhead  — window-limited batcher qps with the
                            always-on profiler stopped vs running at its
                            default rate (the <2% always-on claim).

    Every number is CPU-valid by construction: no rung touches an
    accelerator (the kvcache rungs run on the jax CPU backend), so the
    suite publishes on every round and the de-GIL trajectory
    (ROADMAP item 4) never goes blind.  3-trial median + spread, like
    every other rung family.

    ISSUE 9: the de-GIL'd stages (batch_assembly, emit_fanout,
    span_submit, host_us_per_token) publish an explicit A/B — the
    headline metric rides the NATIVE path (the shipped configuration),
    with the pure-Python fallback (`native_hot_path_enabled` off)
    alongside as `*_python` and the per-round `native_speedup` interval
    ([min_native/max_python, max_native/min_python]): a lower bound
    above 1.0 is a beyond-spread win, no cross-round baseline needed."""
    import threading

    import numpy as np

    from brpc_tpu import flags as _flags, native_path, rpcz
    from brpc_tpu.serving import DynamicBatcher

    if quick:
        trials, duration_s = 2, 0.15
    out = {}
    have_native = native_path._core_lib() is not None

    def _with_flag(native, fn):
        was = _flags.get_flag("native_hot_path_enabled", True)
        _flags.set_flag("native_hot_path_enabled", bool(native))
        try:
            return fn()
        finally:
            _flags.set_flag("native_hot_path_enabled", was)

    def _ab(trial, unit):
        """The per-stage A/B: `trial(k, tag)` under the flag OFF
        (python fallback) and ON (native).  Headline `qps` = native
        median when the core is available, else the python median."""
        py = [_with_flag(False, lambda k=k: trial(k, "py"))
              for k in range(trials)]
        pm = _med_spread(py, "qps")
        entry = {}
        if have_native:
            nat = [_with_flag(True, lambda k=k: trial(k, "nat"))
                   for k in range(trials)]
            entry.update(_med_spread(nat, "qps"))
            entry["qps_python"] = pm["qps"]
            entry["qps_python_spread"] = pm["qps_spread"]
            if pm["qps"]:
                entry["native_speedup"] = round(
                    entry["qps"] / pm["qps"], 2)
                entry["native_speedup_spread"] = [
                    round(min(nat) / max(py), 2),
                    round(max(nat) / min(py), 2)]
        else:
            entry.update(pm)
            entry["note_native"] = ("native core unavailable: "
                                    "python path only")
        entry["unit"] = unit
        return entry

    # ---- frame_pump ----
    frames = 30_000 if quick else 100_000
    rs = []
    for _ in range(trials):
        r = bench_native_echo(conns=2, inflight=16, total=frames)
        if r["completed"]:
            rs.append(r["qps"])
    if rs:
        out["frame_pump"] = {**_med_spread(rs, "qps"),
                             "unit": "frames/s", "frames": frames}
    else:
        # the rung discipline: a rung that cannot run must SAY so —
        # a 0.0 wearing the metric's name would read as a real
        # collapse to perf_diff and poison the round as a baseline
        out["frame_pump"] = {"error": "native echo pump completed no "
                                      "trial", "frames": frames}

    # shared batcher-hammer: `threads` workers submit_wait against a
    # numpy-fn batcher for duration_s, returns items/s (used by the
    # batch_assembly and sampler_overhead rungs)
    def batcher_hammer(name, *, max_batch_size, max_delay_us, length,
                       threads):
        b = DynamicBatcher(lambda x: x.sum(axis=1),
                           max_batch_size=max_batch_size,
                           max_delay_us=max_delay_us,
                           batch_buckets=(max_batch_size,),
                           length_buckets=(length,), name=name)
        item = np.ones((length,), np.float32)
        try:
            b.submit_wait(item, timeout_s=30)
            stop = time.monotonic() + duration_s
            counts = [0] * threads

            def w(i):
                while time.monotonic() < stop:
                    b.submit_wait(item, timeout_s=30)
                    counts[i] += 1

            ts = [threading.Thread(target=w, args=(i,))
                  for i in range(threads)]
            t0 = time.monotonic()
            [t.start() for t in ts]
            [t.join(60) for t in ts]
            return sum(counts) / (time.monotonic() - t0)
        finally:
            b.close()

    # ---- batch_assembly (A/B: native GIL-released formation vs numpy
    # scatter loop, through the batcher's real _form_batch) ----
    #
    # The end-to-end batcher hammer above is WINDOW-bound (condvar
    # round-trips dominate at ~1ms/item), so formation cost is
    # invisible in it; this rung isolates the formation stage itself at
    # a prefill-realistic shape (64 prompts x 4k int32 tokens = 1MB of
    # scatter per formation).  4 concurrent formers is the headline —
    # the shipped shape is formation racing submitters for the GIL —
    # with the 1-thread A/B and the 4t/1t thread-scaling ratio
    # alongside (the speedup_at_peak plateau BENCH_r03-r05 tracked).
    from brpc_tpu.serving.batcher import _Pending

    ba_bs, ba_len = 64, 4096
    ba_live = [_Pending(np.arange(ba_len - (i % 129), dtype=np.int32),
                        ba_len - (i % 129), None,
                        lambda code, text, result: None)
               for i in range(ba_bs)]
    ba_b = DynamicBatcher(lambda x: x, max_batch_size=ba_bs,
                          max_delay_us=200, batch_buckets=(ba_bs,),
                          length_buckets=(ba_len,), dtype=np.int32,
                          name="microbench_ba_form")

    def ba_trial(k, tag, threads):
        iters = 40 if quick else 150
        barrier = threading.Barrier(threads + 1)

        def w():
            barrier.wait()
            for _ in range(iters):
                ba_b._form_batch(ba_live, ba_bs, ba_len)

        ts = [threading.Thread(target=w) for _ in range(threads)]
        [t.start() for t in ts]
        barrier.wait()
        t0 = time.monotonic()
        [t.join(120) for t in ts]
        return threads * iters / (time.monotonic() - t0)

    try:
        ba = _ab(lambda k, tag: ba_trial(k, tag, 4),
                 "batch formations/s (64x4096 int32 prompt scatter "
                 "through DynamicBatcher._form_batch, 4 concurrent "
                 "formers)")
        ba1 = _ab(lambda k, tag: ba_trial(k, tag, 1), "")
    finally:
        ba_b.close()
    ba["qps_1t"] = ba1["qps"]
    ba["qps_1t_spread"] = ba1.get("qps_spread")
    if ba1["qps"]:
        ba["speedup_at_peak"] = round(ba["qps"] / ba1["qps"], 2)
        lo1, hi1 = ba1.get("qps_spread", [ba1["qps"], ba1["qps"]])
        lo4, hi4 = ba.get("qps_spread", [ba["qps"], ba["qps"]])
        ba["speedup_at_peak_spread"] = [round(lo4 / hi1, 2),
                                        round(hi4 / lo1, 2)]
    if have_native and ba1.get("qps_python"):
        ba["qps_python_1t"] = ba1["qps_python"]
        ba["speedup_at_peak_python"] = round(
            ba["qps_python"] / ba1["qps_python"], 2)
    out["batch_assembly"] = ba

    # ---- radix_prefix_match + page_alloc_release (share a store) ----
    from brpc_tpu.kvcache import KVCacheStore

    def radix_trial(k):
        pt = 16
        store = KVCacheStore(page_tokens=pt, page_bytes=pt * 64,
                             max_blocks=64,
                             name=f"microbench_radix_{k}")
        try:
            # warm the tree with 8 cached prompts
            prompts = [[1000 * j + i for i in range(4 * pt)]
                       for j in range(8)]
            for p in prompts:
                store.retire(store.admit(p), cache=True)
            probe = np.asarray(prompts[3] + [7] * pt)
            n = 500 if quick else 3000
            t0 = time.monotonic()
            for _ in range(n):
                store.probe(probe)
            return n / (time.monotonic() - t0)
        finally:
            store.clear()
            store.close()

    out["radix_prefix_match"] = {
        **_med_spread([radix_trial(k) for k in range(trials)], "qps"),
        "unit": "longest-prefix probes/s (warm radix, 64-token prompts)"}

    def page_trial(k):
        pt = 16
        store = KVCacheStore(page_tokens=pt, page_bytes=pt * 64,
                             max_blocks=64,
                             name=f"microbench_page_{k}")
        try:
            n = 30 if quick else 120
            t0 = time.monotonic()
            for i in range(n):
                # unique prompts: every admit allocs+splices 2 fresh
                # pages, every retire releases them (cache=False)
                seq = store.admit([9_000_000 + i * 2 * pt + j
                                   for j in range(2 * pt)])
                store.retire(seq, cache=False)
            return n / (time.monotonic() - t0)
        finally:
            store.clear()
            store.close()

    out["page_alloc_release"] = {
        **_med_spread([page_trial(k) for k in range(trials)], "qps"),
        "unit": "admit+retire cycles/s (2 pages alloc/release each)"}

    # ---- emit_fanout (A/B: native token ring vs Python _EmitBuf) ----
    from brpc_tpu.serving.engine import _NativeEmitBuf, _make_emit_buf

    def emit_trial(k, pairs=1):
        # buffer type decided by the flag at construction, like the
        # engine's per-request choice
        bufs = [_make_emit_buf(1024) for _ in range(pairs)]
        n = 3000 if quick else 20_000
        drained = [0] * pairs

        def consume(i, buf):
            if isinstance(buf, _NativeEmitBuf):
                while True:
                    cnt, term, _err = buf.pop_batch(5.0)
                    drained[i] += cnt
                    if term:
                        return
            else:
                while True:
                    item = buf.pop(5.0)
                    if item is None or item[0] == "done":
                        return
                    drained[i] += 1

        def produce(buf):
            pushed = 0
            while pushed < n:
                if buf.push(pushed):
                    pushed += 1
                else:
                    time.sleep(0)   # full: yield instead of spinning
            buf.push_terminal(None)

        ts = []
        for i, buf in enumerate(bufs):
            ts.append(threading.Thread(target=consume, args=(i, buf)))
            ts.append(threading.Thread(target=produce, args=(buf,)))
        t0 = time.monotonic()
        [t.start() for t in ts]
        [t.join(120) for t in ts]
        return sum(drained) / (time.monotonic() - t0)

    out["emit_fanout"] = _ab(
        lambda k, tag: emit_trial(k),
        "tokens/s through one bounded emit buffer pair")

    # concurrency probe: 4 producer/consumer pairs side by side (4
    # concurrent token streams).  The Python _EmitBuf pays a GIL'd lock
    # round-trip per token so added pairs DEGRADE its aggregate; native
    # pairs hold aggregate flat — one sub-microsecond GIL-held push per
    # token (the ctypes-per-token variant collapsed 14x here: every
    # push's GIL release/reacquire became a handoff convoy under 4
    # producers, which is why tokring_push rides the C extension).
    # speedup_at_peak carries a spread so perf_diff gates a future
    # convoy regression.
    scaling = {"pairs": 4}
    py1 = out["emit_fanout"].get("qps_python",
                                 out["emit_fanout"]["qps"])
    py4m = _med_spread([_with_flag(False,
                                   lambda k=k: emit_trial(k, pairs=4))
                        for k in range(trials)], "qps_python_4p")
    scaling["qps_python_4p"] = py4m["qps_python_4p"]
    scaling["speedup_at_peak_python"] = (
        round(py4m["qps_python_4p"] / py1, 2) if py1 else None)
    if have_native:
        nat4 = _med_spread([_with_flag(True,
                                       lambda k=k: emit_trial(k, pairs=4))
                            for k in range(trials)], "qps_native_4p")
        scaling["qps_native_4p"] = nat4["qps_native_4p"]
        scaling["qps_native_4p_spread"] = nat4["qps_native_4p_spread"]
        nat1 = out["emit_fanout"]["qps"]
        n1lo, n1hi = out["emit_fanout"].get("qps_spread", [nat1, nat1])
        if nat1:
            n4lo, n4hi = nat4["qps_native_4p_spread"]
            scaling["speedup_at_peak"] = round(
                nat4["qps_native_4p"] / nat1, 2)
            scaling["speedup_at_peak_spread"] = [
                round(n4lo / n1hi, 2), round(n4hi / n1lo, 2)]
    out["emit_fanout_scaling"] = scaling

    # ---- span_submit (A/B: native MPSC queue vs collector submit) ----
    def span_trial(k, tag):
        was = (rpcz.enabled(), rpcz.sample_rate())
        rpcz.set_enabled(True, 1.0)
        try:
            n = 500 if quick else 2000
            t0 = time.monotonic()
            for i in range(n):
                sp = rpcz.new_span("client", "Micro", "Bench")
                sp.annotate("microbench span")
                rpcz.submit(sp)
            # land every span whichever path it took (native queue or
            # collector family) — submit-only would time pushes into an
            # unbounded queue and flatter the native number
            rpcz.flush()
            return n / (time.monotonic() - t0)
        finally:
            rpcz.set_enabled(*was)

    # cold-start warmup OUTSIDE the timed trials (span dataclass +
    # collector import + drainer-thread spinup land on the first call
    # and were making trial 1 read 3x slower than trials 2-3)
    _with_flag(False, lambda: span_trial(-1, "warm"))
    _with_flag(True, lambda: span_trial(-1, "warm"))
    out["span_submit"] = _ab(
        span_trial,
        "spans/s (create+annotate+submit+drain to the recent-span "
        "store; the 2000/s rpcz speed limit applies beyond it)")

    # ---- host_us_per_token (the de-GIL headline, ISSUE 9) ----
    from brpc_tpu.butil import hostcpu
    from brpc_tpu.serving import DecodeEngine

    def hupt_trial(k, tag):
        R, T = (4, 64) if quick else (8, 192)
        eng = DecodeEngine(lambda t, p: t + 1, num_slots=8,
                           kv_bytes_per_slot=256,
                           name=f"mb_hupt_{tag}_{k}")
        try:
            before = hostcpu.snapshot()
            dones = []
            for r in range(R):
                ev = threading.Event()
                dones.append(ev)
                eng.submit([r + 1], T, lambda tok: None,
                           lambda err, ev=ev: ev.set())
            for ev in dones:
                ev.wait(120)
        finally:
            eng.close()
        after = hostcpu.snapshot()
        toks = after["tokens"] - before["tokens"]
        host = sum(after["per_stage_us"][s] - before["per_stage_us"][s]
                   for s in hostcpu.HOST_STAGES)
        return host / max(1, toks)

    pm = _med_spread([_with_flag(False, lambda k=k: hupt_trial(k, "py"))
                      for k in range(trials)],
                     "serving_host_us_per_token_python", nd=2)
    hupt = {"serving_host_us_per_token_python":
            pm["serving_host_us_per_token_python"],
            "serving_host_us_per_token_python_spread":
            pm["serving_host_us_per_token_python_spread"]}
    if have_native:
        nm = _med_spread([_with_flag(True,
                                     lambda k=k: hupt_trial(k, "nat"))
                          for k in range(trials)],
                         "serving_host_us_per_token", nd=2)
        hupt["serving_host_us_per_token"] = \
            nm["serving_host_us_per_token"]
        hupt["serving_host_us_per_token_spread"] = \
            nm["serving_host_us_per_token_spread"]
        if pm["serving_host_us_per_token_python"]:
            hupt["reduction_pct"] = round(
                100.0 * (1 - nm["serving_host_us_per_token"]
                         / pm["serving_host_us_per_token_python"]), 1)
    else:
        hupt["serving_host_us_per_token"] = \
            hupt["serving_host_us_per_token_python"]
        hupt["serving_host_us_per_token_spread"] = \
            hupt["serving_host_us_per_token_python_spread"]
    hupt["unit"] = ("python-host CPU us per emitted token across the "
                    "serving stages (model_compute excluded), real "
                    "DecodeEngine decode, 8 concurrent requests")
    hupt["trials"] = trials
    out["host_us_per_token"] = hupt

    # ---- stream_scaling (the ≥1.5x thread-scaling criterion, ISSUE 9)
    #
    # The real shipped concurrency shape: ONE decode step loop fanning
    # tokens out to N concurrent streams, each with its own emitter.
    # Aggregate tokens/s at 4 streams over 1 stream is speedup_at_peak
    # — the number BENCH_r03–r05 watched plateau at 1.06–1.25x on the
    # GIL-bound path.  With native rings the emitters park OFF the GIL
    # (pop waits in native code) and the step loop pushes all slots in
    # one GIL-released call, so added streams stop convoying the loop.
    # (The synthetic per-stage rungs above can't carry this criterion
    # honestly: their producers are Python loops — GIL-serialized by
    # construction — and the 64x4096 formation shape saturates DRAM
    # bandwidth near 30 GB/s, capping ANY implementation's scaling.)
    def stream_trial(k, tag, streams):
        T = 400 if quick else 1500
        eng = DecodeEngine(lambda t, p: t + 1, num_slots=4,
                           kv_bytes_per_slot=256,
                           name=f"mb_ss_{tag}_{streams}_{k}")
        try:
            evs = []
            t0 = time.monotonic()
            for r in range(streams):
                ev = threading.Event()
                evs.append(ev)
                eng.submit([r + 1], T, lambda tok: None,
                           lambda err, ev=ev: ev.set())
            for ev in evs:
                ev.wait(300)
            return streams * T / (time.monotonic() - t0)
        finally:
            eng.close()

    ss4 = _ab(lambda k, tag: stream_trial(k, tag, 4),
              "aggregate tokens/s, 4 concurrent streams through one "
              "DecodeEngine step loop (trivial step_fn)")
    ss1 = _ab(lambda k, tag: stream_trial(k, tag, 1), "")
    ss4["streams"] = 4
    ss4["qps_1s"] = ss1["qps"]
    ss4["qps_1s_spread"] = ss1.get("qps_spread")
    if ss1["qps"]:
        ss4["speedup_at_peak"] = round(ss4["qps"] / ss1["qps"], 2)
        lo1, hi1 = ss1.get("qps_spread", [ss1["qps"], ss1["qps"]])
        lo4, hi4 = ss4.get("qps_spread", [ss4["qps"], ss4["qps"]])
        ss4["speedup_at_peak_spread"] = [round(lo4 / hi1, 2),
                                         round(hi4 / lo1, 2)]
    if have_native and ss1.get("qps_python"):
        ss4["speedup_at_peak_python"] = round(
            ss4["qps_python"] / ss1["qps_python"], 2)
    out["stream_scaling"] = ss4

    # ---- sampler_overhead ----
    from brpc_tpu.builtin.sampler import HotspotSampler

    def window_limited_qps(k, label):
        # threads << max_batch_size: every batch forms at WINDOW
        # expiry, so qps ~ threads/window — nearly deterministic, which
        # is what makes a small overhead measurable at all
        return batcher_hammer(f"microbench_so_{label}_{k}",
                              max_batch_size=64, max_delay_us=2000,
                              length=16, threads=4)

    samp = HotspotSampler.instance()
    was_running = samp.running
    samp.stop()
    off = [window_limited_qps(k, "off") for k in range(trials)]
    samp.start()
    try:
        on = [window_limited_qps(k, "on") for k in range(trials)]
    finally:
        if not was_running:
            samp.stop()
    off_med = sorted(off)[len(off) // 2]
    on_med = sorted(on)[len(on) // 2]
    out["sampler_overhead"] = {
        "qps_off": round(off_med, 1),
        "qps_off_spread": [round(min(off), 1), round(max(off), 1)],
        "qps_on": round(on_med, 1),
        "qps_on_spread": [round(min(on), 1), round(max(on), 1)],
        "overhead_pct": round((off_med - on_med) / off_med * 100.0, 2)
        if off_med else None,
        "trials": trials,
        "unit": "window-limited batcher qps, always-on sampler off vs "
                "on at its default rate",
    }

    # ---- flight_recorder overhead (ISSUE 15 acceptance) ----
    # The recorder is ALWAYS-ON; this rung proves it can be: the echo
    # pump (per-frame socket/executor events) and the emit fan-out
    # (per-batch TokenRing events) re-run with recording off, and the
    # on/off delta must stay within 2% beyond spread.
    from brpc_tpu.butil import flight as _flight
    if _flight.available():
        def _fl_ab(trial, unit):
            _flight.set_enabled(True)
            on = [trial(k) for k in range(trials)]
            _flight.set_enabled(False)
            try:
                off = [trial(k) for k in range(trials)]
            finally:
                _flight.set_enabled(True)
            on_m = _med_spread(on, "qps_on")
            off_m = _med_spread(off, "qps_off")
            entry = {**on_m, **off_m, "unit": unit}
            if off_m["qps_off"]:
                entry["overhead_pct"] = round(
                    (off_m["qps_off"] - on_m["qps_on"])
                    / off_m["qps_off"] * 100.0, 2)
            return entry

        fl = {}
        fl["emit_fanout"] = _fl_ab(
            lambda k: _with_flag(True, lambda: emit_trial(k)),
            "tokens/s through one native emit buffer pair, "
            "recorder on vs off")
        ec_frames = 10_000 if quick else 40_000
        def _echo_trial(k):
            r = bench_native_echo(conns=2, inflight=16, total=ec_frames)
            return r["qps"] if r["completed"] else 0.0
        fl["echo"] = _fl_ab(
            _echo_trial, "native echo frames/s, recorder on vs off")
        out["flight_recorder"] = fl

    out["cpu_valid"] = True
    out["note"] = ("per-stage host microbenches (ISSUE 6): every rung "
                   "isolates one serving stage on the host with no "
                   "accelerator dependency, so these numbers publish "
                   "on every round; 3-trial median+spread")
    return out


def _run_cpu_subcommand(name: str, timeout_s: float = 900) -> dict:
    """Run a CPU-valid rung family (`python bench.py <name>`) in a
    FRESH forced-CPU subprocess: these rungs import jax, and importing
    jax in the driver process on a wedged-tunnel box would hang the
    whole bench (the same reason _probe_device subprocesses)."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), name],
        capture_output=True, text=True, env=env, timeout=timeout_s)
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-1:] or ["no stderr"]
        return {"error": f"{name} subprocess rc={r.returncode}: "
                         f"{tail[0]}"}
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return {"error": f"{name} subprocess produced no JSON"}


def _run_microbench_subprocess(timeout_s: float = 900) -> dict:
    return _run_cpu_subcommand("microbench", timeout_s)


def bench_migrate(shared_ratios=(0.0, 0.5, 0.9), n_requests=12,
                  prompt_tokens=64, trials=3):
    """Migration rung (ISSUE 7): migrate-vs-recompute ADMIT latency and
    re-decoded-token ratio at 0/50/90% shared prefix, through the real
    ``_kvmig`` wire path (loopback server, host-serialized envelope —
    the in-process fallback data plane).

    Workload per ratio: the shared prefix is committed on a SOURCE
    store and migrated to a destination store behind a loopback
    migration service; then `n_requests` prompts opening with that
    prefix admit on the destination (migrated path) and on a COLD
    store (recompute path).  Reported per ratio:

      * migrated_admit_us / recompute_admit_us — mean per-admit wall
        time; at >=50% shared prefix the migrated path must win with
        NON-OVERLAPPING spread intervals (the ISSUE 7 acceptance gate,
        and perf_diff gates both series across rounds);
      * redecoded_token_ratio — (prompt tokens - cache-hit tokens) /
        prompt tokens at the destination: 1.0 means migration bought
        nothing, 1-ratio means every migrated page was a hit.

    CPU-valid by construction (page splices are jit CPU ops; no
    accelerator is touched), 3-trial median+spread."""
    import brpc_tpu as brpc
    from brpc_tpu.kvcache import KVCacheStore
    from brpc_tpu.migrate import PageMigrator, register_migration

    pt = 8

    def mk_store(tag):
        return KVCacheStore(page_tokens=pt, page_bytes=pt * 64,
                            max_blocks=128, name=tag)

    def admit_wave(store, reqs):
        # warm admit outside timing: the first splice compiles the
        # dynamic_update_slice shapes
        seq = store.admit([123456789, 2, 3])
        store.retire(seq, cache=False)
        t0 = time.monotonic()
        for p in reqs:
            seq = store.admit(p)
            store.retire(seq, cache=False)
        return (time.monotonic() - t0) / len(reqs) * 1e6

    def one_trial(ratio, k):
        tag = f"bench_mig_r{int(ratio * 100)}_{k}"
        shared_n = int(prompt_tokens * ratio) // pt * pt
        shared = [5000 + k * 7919 + j for j in range(shared_n)]

        def prompts(base):
            return [shared
                    + [base + i * prompt_tokens + j
                       for j in range(prompt_tokens - shared_n)]
                    for i in range(n_requests)]

        src = mk_store(f"{tag}_src")
        dst = mk_store(f"{tag}_dst")
        cold = mk_store(f"{tag}_cold")
        srv = brpc.Server(enable_dcn=True)
        register_migration(srv, dst)
        srv.start("127.0.0.1", 0)
        try:
            if shared_n:
                seq = src.admit(shared + [1])
                src.retire(seq, cache=True)
                m = PageMigrator(src, name=f"{tag}_m")
                pages = m.migrate(shared, f"127.0.0.1:{srv.port}")
                assert pages == shared_n // pt, (pages, shared_n)
            h0, p0 = dst.hit_tokens.get_value(), \
                dst.prompt_tokens.get_value()
            mig_us = admit_wave(dst, prompts(1_000_000))
            dp = dst.prompt_tokens.get_value() - p0
            dh = dst.hit_tokens.get_value() - h0
            redecode = (dp - dh) / dp if dp else 1.0
            rec_us = admit_wave(cold, prompts(2_000_000))
            return mig_us, rec_us, redecode
        finally:
            srv.stop()
            srv.join()
            for st in (src, dst, cold):
                st.clear()
                st.close()

    out = {}
    for ratio in shared_ratios:
        rs = [one_trial(ratio, k) for k in range(trials)]
        migs = sorted(r[0] for r in rs)
        recs = sorted(r[1] for r in rs)
        reds = sorted(r[2] for r in rs)
        out[f"shared{int(ratio * 100)}"] = {
            "migrated_admit_us": round(migs[len(migs) // 2], 1),
            "migrated_admit_us_spread": [round(migs[0], 1),
                                         round(migs[-1], 1)],
            "recompute_admit_us": round(recs[len(recs) // 2], 1),
            "recompute_admit_us_spread": [round(recs[0], 1),
                                          round(recs[-1], 1)],
            "redecoded_token_ratio": round(reds[len(reds) // 2], 4),
            "redecoded_token_ratio_spread": [round(reds[0], 4),
                                             round(reds[-1], 4)],
            "migrated_beats_recompute_beyond_spread":
                migs[-1] < recs[0],
            "trials": trials,
        }
    out["cpu_valid"] = True
    out["note"] = ("migration rung (brpc_tpu/migrate): per-admit "
                   "latency on a store that received the shared "
                   "prefix over the _kvmig wire vs a cold store that "
                   "recomputes, plus the re-decoded-token ratio; the "
                   "ISSUE 7 gate is migrated beating recompute beyond "
                   "spread at >=50% shared prefix")
    return out


def bench_model(shared_ratios=(0.0, 0.5, 0.9), n_requests=6,
                prompt_tokens=32, gen_tokens=12, trials=3):
    """Real-model serving rung (ISSUE 10): the TransformerRunner — a
    real transformer whose K/V live in the paged HBM layout and whose
    attention reads through the engine's page tables — vs the
    token-id HARNESS (the PR 2/3 stand-in step function) on the same
    engine/kvcache machinery, at 0/50/90% shared prefix.

    Per ratio, per mode:

      * tokens_per_s — generated tokens over the wave's wall time
        (3-trial median + spread; perf_diff gates both series);
      * prefill_skip_ratio — prompt tokens served by the radix cache /
        prompt tokens seen (higher = prefill compute actually skipped;
        for the REAL runner this is genuine attention-K/V reuse, not
        token bookkeeping — the prefill-skip savings ROADMAP item 1
        asked the bench to measure);
      * runner_vs_harness — runner/harness tokens_per_s (informational:
        the gap IS the model's FLOPs + kernel cost on this backend).

    CPU-valid by construction (the gather backend of the paged kernel
    is jax CPU ops); the full bench shells out here exactly like the
    microbench/migrate rungs."""
    import jax

    from brpc_tpu.models.runner import (TransformerConfig,
                                        TransformerRunner,
                                        init_runner_params,
                                        make_store_for)
    from brpc_tpu.kvcache import KVCacheStore
    from brpc_tpu.serving import DecodeEngine

    cfg = TransformerConfig()
    params = init_runner_params(cfg)
    pt = 8
    buckets = (16, 32, 64)

    def mk_real(tag):
        store = make_store_for(cfg, page_tokens=pt, max_blocks=64,
                               name=f"{tag}_rkv")
        runner = TransformerRunner(params, cfg, store=store,
                                   name=f"{tag}_m")
        eng = DecodeEngine(runner=runner, num_slots=4, store=store,
                           max_pages_per_slot=16,
                           prefill_buckets=buckets, name=f"{tag}_re")
        return store, eng

    def mk_harness(tag):
        store = KVCacheStore(page_tokens=pt, page_bytes=pt * 64,
                             max_blocks=64, name=f"{tag}_hkv")

        @jax.jit
        def step(tokens, positions, pages):
            return (tokens * 7 + positions) % 997

        eng = DecodeEngine(step, num_slots=4, store=store,
                           max_pages_per_slot=16,
                           prefill_buckets=buckets, name=f"{tag}_he")
        return store, eng

    def wave(eng, prompts):
        evs = []
        for p in prompts:
            ev = threading.Event()
            evs.append(ev)
            eng.submit(p, gen_tokens, lambda t: None,
                       lambda e, ev=ev: ev.set())
        for ev in evs:
            if not ev.wait(600):
                raise RuntimeError("model bench wave hung")

    def one_trial(ratio, k, mk):
        tag = f"bench_model_r{int(ratio * 100)}_{k}"
        shared_n = int(prompt_tokens * ratio) // pt * pt
        shared = [(5000 + k * 131 + j) % 997 for j in range(shared_n)]

        def prompts(base):
            return [shared
                    + [(base + i * prompt_tokens + j) % 997
                       for j in range(prompt_tokens - shared_n)]
                    for i in range(n_requests)]

        store, eng = mk(tag)
        try:
            # warm: compiles the bucket shapes AND seeds the radix
            # tree with the shared prefix (the steady-state the ratio
            # models), outside the timed window
            wave(eng, prompts(900_000)[:2])
            h0 = store.hit_tokens.get_value()
            p0 = store.prompt_tokens.get_value()
            t0 = time.monotonic()
            wave(eng, prompts(1_000_000))
            dt = time.monotonic() - t0
            dp = store.prompt_tokens.get_value() - p0
            dh = store.hit_tokens.get_value() - h0
            skip = dh / dp if dp else 0.0
            return n_requests * gen_tokens / dt, skip
        finally:
            eng.close()
            store.clear()
            store.close()

    def series(mk):
        out = {}
        for ratio in shared_ratios:
            rs = [one_trial(ratio, k, mk) for k in range(trials)]
            tps = sorted(r[0] for r in rs)
            skips = sorted(r[1] for r in rs)
            out[f"shared{int(ratio * 100)}"] = {
                "tokens_per_s": round(tps[len(tps) // 2], 1),
                "tokens_per_s_spread": [round(tps[0], 1),
                                        round(tps[-1], 1)],
                "prefill_skip_ratio": round(skips[len(skips) // 2], 4),
                "prefill_skip_ratio_spread": [round(skips[0], 4),
                                              round(skips[-1], 4)],
                "trials": trials,
            }
        return out

    out = {"runner": series(mk_real), "harness": series(mk_harness)}
    for key in out["runner"]:
        r = out["runner"][key]["tokens_per_s"]
        h = out["harness"][key]["tokens_per_s"]
        out["runner"][key]["runner_vs_harness"] = \
            round(r / h, 4) if h else None
    out["cpu_valid"] = True
    out["config"] = {"prompt_tokens": prompt_tokens,
                     "gen_tokens": gen_tokens,
                     "n_requests": n_requests,
                     "d_model": cfg.d_model, "n_layers": cfg.n_layers,
                     "n_heads": cfg.n_heads,
                     "kv_bytes_per_token": cfg.kv_bytes_per_token}
    out["note"] = ("real-model serving rung (ISSUE 10): tokens/s and "
                   "prefill-skip with the TransformerRunner's paged "
                   "attention over the HBM page tables vs the token-id "
                   "harness on identical machinery; CPU gather backend "
                   "— device rounds A/B the pallas kernel path")
    return out


def model_main(argv) -> None:
    """`python bench.py model`: run ONLY the real-model serving rung
    and print one JSON object on stdout (progress on stderr) — the
    `make model` bench entry and the subprocess the full bench run
    shells out to."""
    log("model: real-runner vs harness serving rung...")
    out = bench_model()
    for k, v in out.items():
        if isinstance(v, dict):
            log(f"  {k}: {json.dumps(v)}")
    print(json.dumps(out))


def bench_speculative(depths=(2, 4, 8), n_requests=4, prompt_tokens=16,
                      gen_tokens=48, trials=3):
    """Speculative-decoding rung (ISSUE 11): tokens/s of the
    TransformerRunner engine PLAIN vs SPECULATIVE at draft depths
    2/4/8, on the same store/engine machinery.

    Operating point: the draft is the host-side NGramProposer (prompt
    lookup) — draft cost ≪ target cost, the regime the ISSUE names —
    and the workload decodes long enough (``gen_tokens``) that the
    target's greedy output becomes self-repeating, so drafts actually
    accept (``accept_rate`` is published per depth; a rung whose
    drafts never accepted would be measuring nothing).  Per depth:
    tokens/s 3-trial median+spread (perf_diff gates the series) plus
    accept_rate / tokens_per_step medians and the ISSUE acceptance
    probe ``spec_beats_plain_beyond_spread`` (spread intervals
    disjoint in the faster direction at >=1 depth).

    CPU-valid by construction — the gather backend of the paged
    kernel; the full bench shells out here exactly like the
    microbench/migrate/model rungs."""
    from brpc_tpu.models.runner import (TransformerConfig,
                                        TransformerRunner,
                                        init_runner_params,
                                        make_store_for)
    from brpc_tpu.serving import DecodeEngine, NGramProposer
    from brpc_tpu.serving.engine import SPEC_ACCEPTED, SPEC_PROPOSED

    cfg = TransformerConfig()
    params = init_runner_params(cfg)
    pt = 8
    buckets = (16, 32)

    def prompts(k):
        return [[(100 + k * 131 + i * 37 + j) % 997
                 for j in range(prompt_tokens)]
                for i in range(n_requests)]

    def wave(eng, ps, n):
        evs = []
        errs: list = []
        for p in ps:
            ev = threading.Event()
            evs.append(ev)
            eng.submit(p, n, lambda t: None,
                       lambda e, ev=ev: (errs.append(e) if e is not None
                                         else None, ev.set()))
        for ev in evs:
            if not ev.wait(600):
                raise RuntimeError("speculative bench wave hung")
        if errs:
            # a failed generation must fail the TRIAL: counting its
            # full token budget over a shortened wall time would
            # inflate the series the acceptance gate reads
            raise RuntimeError(f"speculative bench wave errored: "
                               f"{errs[0]}")

    def one_trial(depth, k):
        tag = f"bench_spec_d{depth}_{k}"
        store = make_store_for(cfg, page_tokens=pt, max_blocks=64,
                               name=f"{tag}_kv")
        runner = TransformerRunner(params, cfg, store=store,
                                   name=f"{tag}_m")
        kw = {}
        if depth:
            kw = dict(draft_runner=NGramProposer(), draft_len=depth)
        eng = DecodeEngine(runner=runner, num_slots=n_requests,
                           store=store, max_pages_per_slot=24,
                           prefill_buckets=buckets, name=f"{tag}_e",
                           **kw)
        try:
            # full-length warm wave: the splice/verify jit shapes vary
            # with ACCEPT DEPTH (a kept-k commit splices k+1 rows), so
            # a short warm leaves compiles to fall inside the timing
            wave(eng, prompts(k), gen_tokens)
            a0, p0 = SPEC_ACCEPTED.get_value(), SPEC_PROPOSED.get_value()
            s0 = eng.steps.get_value()
            t0 = time.monotonic()
            wave(eng, prompts(k), gen_tokens)
            dt = time.monotonic() - t0
            da = SPEC_ACCEPTED.get_value() - a0
            dp = SPEC_PROPOSED.get_value() - p0
            ds = eng.steps.get_value() - s0
            # tokens_per_step is PER SLOT (emitted tokens per verify
            # iteration of one generation): the number the per-
            # generation span annotation carries, comparable across
            # slot counts
            return (n_requests * gen_tokens / dt,
                    da / dp if dp else 0.0,
                    gen_tokens / ds if ds else 0.0)
        finally:
            eng.close()
            store.clear()
            store.close()

    # trials INTERLEAVE across configs (round-robin plain/depths) so
    # load drift lands on every series instead of skewing whichever
    # config happened to run during the spike; one UNRECORDED warm
    # trial per config first retires every process-wide one-off
    # (arena growth, first-shape compiles) outside the measurement
    raw: dict = {0: []}
    for d in depths:
        raw[d] = []
    for d in raw:
        one_trial(d, 0)
    for k in range(trials):
        for d in raw:
            raw[d].append(one_trial(d, k))

    def series(depth):
        rs = raw[depth]
        tps = sorted(r[0] for r in rs)
        acc = sorted(r[1] for r in rs)
        tpstep = sorted(r[2] for r in rs)
        return {
            "tokens_per_s": round(tps[len(tps) // 2], 1),
            "tokens_per_s_spread": [round(tps[0], 1),
                                    round(tps[-1], 1)],
            "accept_rate": round(acc[len(acc) // 2], 4),
            "tokens_per_step": round(tpstep[len(tpstep) // 2], 2),
            "trials": trials,
        }

    out = {"plain": series(0)}
    plain_hi = out["plain"]["tokens_per_s_spread"][1]
    any_beyond = False
    for d in depths:
        s = series(d)
        s["speedup_vs_plain"] = round(
            s["tokens_per_s"] / out["plain"]["tokens_per_s"], 3) \
            if out["plain"]["tokens_per_s"] else None
        s["beats_plain_beyond_spread"] = \
            s["tokens_per_s_spread"][0] > plain_hi
        any_beyond = any_beyond or s["beats_plain_beyond_spread"]
        out[f"depth{d}"] = s
    out["spec_beats_plain_beyond_spread"] = any_beyond
    out["cpu_valid"] = True
    out["config"] = {"prompt_tokens": prompt_tokens,
                     "gen_tokens": gen_tokens,
                     "n_requests": n_requests, "draft": "ngram"}
    out["note"] = ("speculative decoding rung (ISSUE 11): plain vs "
                   "draft-tree verify tokens/s at depths 2/4/8 with a "
                   "host-side ngram draft (draft cost << target cost); "
                   "accept_rate/tokens_per_step medians ride along; "
                   "the acceptance gate is beyond-spread faster at "
                   ">=1 depth")
    return out


def speculative_main(argv) -> None:
    """`python bench.py speculative`: run ONLY the speculative-decoding
    rung and print one JSON object on stdout (progress on stderr) —
    the `make speculative` bench entry and the subprocess the full
    bench run shells out to."""
    log("speculative: plain vs draft-verify tokens/s rung...")
    out = bench_speculative()
    for k, v in out.items():
        if isinstance(v, dict):
            log(f"  {k}: {json.dumps(v)}")
        else:
            log(f"  {k}: {v}")
    print(json.dumps(out))


def bench_embedding(trials=3, duration_s=1.0, vocab=4096, dim=256,
                    n_keys=64, partitions=4, batch_size=32,
                    threads=64):
    """Sharded parameter-server rung (ISSUE 12), two questions:

    1. **Does batching pay?** lookups/s through the DynamicBatcher at
       max_batch_size=32 vs batch=1 issuance of the SAME jitted gather
       under the SAME offered load (equal thread counts — only the
       coalescing differs; the cleanest apples-to-apples form of the
       claim).  The coalescing win the service leans on; acceptance
       >= 3x.
    2. **What does the framework cost over raw collectives?** Per-
       lookup latency through the FULL stack (PSClient -> JSON RPC ->
       PartitionChannel fan-out -> server batcher -> jitted gather ->
       reassembly) vs the same keys through one compiled
       shard_map+psum program on the same mesh — the honest "framework
       tax" number PAPERS.md ("RPC Considered Harmful") demands,
       published with spread, not hidden.

    3-trial median+spread throughout; CPU-valid (the full bench runs it
    in a forced-CPU subprocess like microbench/migrate)."""
    import numpy as np

    from brpc_tpu.psserve import EmbeddingShardServer
    from brpc_tpu.serving import DynamicBatcher

    out = {"vocab": vocab, "dim": dim, "n_keys": n_keys}

    # ---- rung 1: batched-through-batcher vs unbatched issuance ----
    shard = EmbeddingShardServer(0, 1, vocab, dim, seed=0,
                                 key_buckets=(n_keys,),
                                 name="bench_emb")
    rng = np.random.default_rng(0)

    def one_trial(bs: int, k: int) -> float:
        nthreads = threads
        buckets = (bs,) if bs == 1 else (bs // 4, bs // 2, bs)
        b = DynamicBatcher(shard.lookup_batch_fn, max_batch_size=bs,
                           max_delay_us=20_000, batch_buckets=buckets,
                           length_buckets=(n_keys,), dtype=np.int64,
                           padded_output=True,
                           name=f"bench_emb_bs{bs}_{k}")
        keys = rng.integers(0, vocab, n_keys).astype(np.int64)
        try:
            b.submit_wait(keys, timeout_s=300)   # compile outside timing
            stop = time.monotonic() + duration_s
            counts = [0] * nthreads

            def worker(i):
                while time.monotonic() < stop:
                    b.submit_wait(keys, timeout_s=60)
                    counts[i] += 1

            ts = [threading.Thread(target=worker, args=(i,))
                  for i in range(nthreads)]
            t0 = time.monotonic()
            [t.start() for t in ts]
            [t.join(120) for t in ts]
            return sum(counts) / (time.monotonic() - t0)
        finally:
            b.close()

    un = [one_trial(1, k) for k in range(trials)]
    ba = [one_trial(batch_size, k) for k in range(trials)]
    rung1 = {}
    rung1.update(_med_spread(un, "unbatched_lookups_per_s"))
    rung1.update(_med_spread(ba, "batched_lookups_per_s"))
    rung1["batch_speedup"] = round(
        rung1["batched_lookups_per_s"]
        / max(rung1["unbatched_lookups_per_s"], 1e-9), 2)
    rung1["batch_size"] = batch_size
    out["batcher"] = rung1
    log(f"  batcher: {json.dumps(rung1)}")

    # ---- rung 2: framework vs raw collectives on the same mesh, with
    # the SERIALIZER AXIS (ISSUE 13: json vs tensorframe vs lowered) ----
    import jax
    if len(jax.devices()) < partitions:
        out["collective"] = {
            "skipped": True,
            "skip_reason": "no-mesh",
            "skip_detail": f"{len(jax.devices())} devices < "
                           f"{partitions} partitions",
        }
        return out
    from brpc_tpu.psserve import PSClient, ShardedEmbeddingTable
    from brpc_tpu.rpc import serialization as _ser
    from brpc_tpu.tools.rpc_press import (spin_up_psserve,
                                          tear_down_psserve)

    lowered = ShardedEmbeddingTable(vocab, dim, n_shards=partitions,
                                    seed=0, key_buckets=(n_keys,))
    servers, svcs, shards, pc = spin_up_psserve(
        partitions, vocab=vocab, dim=dim, max_delay_us=200,
        name_prefix="bench_emb")
    cli_j = PSClient(pc, vocab=vocab, dim=dim, serializer="json",
                     ici="off", name="bench_emb_cli_json")
    cli_t = PSClient(pc, vocab=vocab, dim=dim, serializer="tensorframe",
                     ici="off", name="bench_emb_cli_tf")
    try:
        keysets = [rng.integers(0, vocab, n_keys).astype(np.int64)
                   for _ in range(8)]
        # warm every path (compiles + negotiation) outside timing
        for ks in keysets[:2]:
            cli_j.lookup(ks)
            cli_t.lookup(ks)
            lowered.lookup(ks)

        def time_path(fn, k: int) -> tuple:
            """(median per-lookup us, lookups/s) over one trial window
            — the SAME closed-loop issuance for every serializer, so
            the axis compares equal offered load"""
            lats = []
            stop = time.monotonic() + duration_s
            i = 0
            t_start = time.monotonic()
            while time.monotonic() < stop:
                ks = keysets[(i + k) % len(keysets)]
                t0 = time.monotonic()
                fn(ks)
                lats.append((time.monotonic() - t0) * 1e6)
                i += 1
            elapsed = time.monotonic() - t_start
            return float(np.median(lats)), len(lats) / elapsed

        # the A/B axis runs 5 trials (vs 3 elsewhere): tax_reduction_x
        # is a RATIO OF PAIRINGS, so its spread is the most
        # noise-sensitive number the rung publishes and the ISSUE-13
        # acceptance gates on it.  INTERLEAVED json/tensorframe trials
        # so slow box drift (thermal, VM neighbors) hits both axes
        # equally instead of biasing whichever ran last.
        ab_trials = max(trials, 5)
        # the zero-copy claim, pinned: the tensorframe trials must not
        # grow the host-materializing tensor serializer's counters
        enc0 = _ser.tensor_host_encodes.get_value()
        dec0 = _ser.tensor_host_decodes.get_value()
        ft, fj = [], []
        for k in range(ab_trials):
            ft.append(time_path(cli_t.lookup, k))
            fj.append(time_path(cli_j.lookup, k))
        enc_delta = _ser.tensor_host_encodes.get_value() - enc0
        dec_delta = _ser.tensor_host_decodes.get_value() - dec0
        raw = [time_path(lambda ks: lowered.lookup(ks), k)
               for k in range(trials)]
        rung2 = {"partitions": partitions, "mode": lowered.mode}
        # framework_us continues the historical key: the DEFAULT wire
        # (tensorframe) through the full stack — its trajectory vs old
        # rounds IS the tax coming down
        rung2.update(_med_spread([x[0] for x in ft], "framework_us"))
        rung2.update(_med_spread([x[0] for x in fj],
                                 "framework_json_us"))
        rung2.update(_med_spread([x[0] for x in raw],
                                 "raw_collective_us"))
        rung2.update(_med_spread([x[1] for x in ft],
                                 "tensorframe_lookups_per_s"))
        rung2.update(_med_spread([x[1] for x in fj],
                                 "json_lookups_per_s"))
        rung2.update(_med_spread([x[1] for x in raw],
                                 "lowered_lookups_per_s"))
        # tax spreads from the worst/best pairings so the intervals are
        # honest about cross-path jitter, not just within-path
        def tax(nums, denoms):
            pairs = sorted(a / b for a, _ in nums for b, _ in denoms
                           if b > 0)
            med = round(np.median(pairs), 1)
            return med, [round(pairs[0], 1), round(pairs[-1], 1)]

        rung2["framework_tax_ratio"], rung2["framework_tax_spread"] = \
            tax(ft, raw)
        (rung2["framework_tax_ratio_json"],
         rung2["framework_tax_spread_json"]) = tax(fj, raw)
        # the acceptance number: how much the binary wire cut the tax
        # (raw cancels, so this is json-vs-tensorframe latency pairs);
        # >= 5x with a disjoint spread is the ISSUE-13 bar
        (rung2["tax_reduction_x"],
         rung2["tax_reduction_x_spread"]) = tax(fj, ft)
        rung2["tensor_host_encodes_delta"] = int(enc_delta)
        rung2["tensor_host_decodes_delta"] = int(dec_delta)
        # _med_spread stamps "trials" per call and the raw axis lands
        # last — record both counts explicitly so the published record
        # says what the gated A/B keys actually used
        rung2["trials"] = trials
        rung2["ab_trials"] = ab_trials
        out["collective"] = rung2
        log(f"  collective: {json.dumps(rung2)}")
    finally:
        tear_down_psserve(servers, svcs, pc)
        cli_j.close()
        cli_t.close()
    out["note"] = (
        "sharded parameter-server rung (ISSUE 12/13): batched-through-"
        "batcher vs batch=1 issuance of the same jitted gather "
        "(>=3x target), and per-lookup latency through the FULL RPC "
        "stack vs one compiled shard_map+psum collective on the same "
        "mesh, on BOTH wire formats — framework_tax_ratio (tensorframe,"
        " the default wire) and framework_tax_ratio_json are the honest"
        " overhead numbers; tax_reduction_x is the ISSUE-13 acceptance "
        "(json tax / tensorframe tax >= 5x beyond spread), and "
        "tensor_host_encodes_delta pins the zero-host-copy claim at 0 "
        "through transport on the binary path")
    return out


def embedding_main(argv) -> None:
    """`python bench.py embedding`: run ONLY the parameter-server rung
    and print one JSON object on stdout (progress on stderr) — the
    `make psserve` bench entry and the subprocess the full bench run
    shells out to.  Forces the virtual 8-device CPU mesh BEFORE jax
    loads so the collective rung has partitions to lower onto."""
    _force_virtual_mesh()
    log("embedding: sharded parameter-server rung...")
    out = bench_embedding()
    print(json.dumps(out))


def _force_virtual_mesh(n: int = 8) -> None:
    """Give this process n virtual CPU devices (no-op if jax already
    initialized with them)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _floor_spread(med, lo, hi, pad):
    """Widen a published [lo, hi] spread to at least ±``pad`` around
    the median (ISSUE 9 deflake): a deterministic workload's few-trial
    spread can collapse to ~0.2%, and perf_diff's disjoint-interval
    rule would then read sub-noise deltas as beyond-spread.  The floor
    encodes the known irreducible jitter the aggregate hides (for the
    cluster rung: engine admission quantization, ± half a step period
    per generation).  Rounds OUTWARD so publication can never narrow
    the interval back below the floor."""
    import math
    return [math.floor(min(lo, med - pad) * 100) / 100,
            math.ceil(max(hi, med + pad) * 100) / 100]


def bench_train(trials=3, vocab=65536, dim=32, n_shards=2,
                n_workers=4, wave_keys=2048, wave_duration_s=2.0,
                gen_vocab=512, gen_duration_s=1.0, gen_tokens=16):
    """Training-plane rung (ISSUE 17), two questions:

    1. **Does the co-located optimizer pay on the wire?** updates/s
       through the trainer's WAVE PATH (``_send_wave``: admit, retry,
       token discipline — the real machinery) from N worker threads
       against a BIG embedding table, mode="wire" (raw grads on the
       wire; the SHARD runs gradient scatter + slot step as ONE fused
       jitted program behind PS.Update, momentum never leaving the
       server) vs mode="pull_compute_push" (the classic loop: the
       HOST holds full-vocab adam slot tables and pays
       np.unique + unbuffered np.add.at + gather/slot-math/scatter
       into those tables per wave, shipping deltas back).  The big
       vocab is the point — co-location keeps slot state sharded
       device-side where the fused scatter absorbs it, while the
       host baseline's per-wave tax is row gather/scatter over
       vocab-sized host arrays.  The wave path is timed in isolation
       because everything else a training step does (dense pulls,
       lookups, grad compute) is byte-identical between modes and
       would only dilute the comparison.  Acceptance: wire >=
       baseline beyond spread.
    2. **What does a concurrent trainer cost serving?** decode
       tokens/s on a serving replica WITH vs WITHOUT a full trainer
       (grads and all) streaming waves against a PS fleet in the same
       process — the mixed-shape coexistence number the arbiter
       exists to protect (published as a ratio, not gated: the
       arbiter tests own the ordering proof).  Runs on its own small
       fleet (``gen_vocab``) so rung 1's big table does not inflate
       the trainer's grad compiles.

    3-trial median+spread throughout; jit compiles (trainer grad fn,
    shard fused apply) are warmed OUTSIDE timing so the rungs compare
    steady-state waves, not tracing.  CPU-valid (the full bench runs
    it in a forced-CPU subprocess like migrate/embedding)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import brpc_tpu as brpc
    from brpc_tpu.models.parameter_server import PSConfig
    from brpc_tpu.psserve import (EmbeddingShardServer, PSClient,
                                  register_psserve, unregister_psserve)
    from brpc_tpu.rpc.combo_channels import PartitionChannel
    from brpc_tpu.tools.rpc_press import (spin_up_replicas,
                                          tear_down_replicas)
    from brpc_tpu.train.optimizer import OptimizerSpec
    from brpc_tpu.train.trainer import DataParallelTrainer

    out = {"vocab": vocab, "dim": dim, "n_shards": n_shards,
           "workers": n_workers, "wave_keys": wave_keys}
    spec = OptimizerSpec("adam", lr=0.01)

    def mk_fleet(vocab_, buckets, table, prefix):
        servers, svcs, shards = [], [], []
        pc = PartitionChannel(n_shards)
        for i in range(n_shards):
            sh = EmbeddingShardServer(i, n_shards, vocab_, dim,
                                      seed=0, table=table,
                                      key_buckets=buckets,
                                      name=f"{prefix}_ps")
            shards.append(sh)
            s = brpc.Server()
            svcs.append(register_psserve(s, sh, name=f"{prefix}_{i}"))
            s.start("127.0.0.1", 0)
            servers.append(s)
            pc.add_partition(i, brpc.Channel(f"127.0.0.1:{s.port}",
                                             timeout_ms=10_000))
        cli = PSClient(pc, vocab=vocab_, dim=dim, name=f"{prefix}_cli")
        return servers, svcs, shards, pc, cli

    def tear_fleet(servers, svcs, pc):
        for svc in svcs:
            unregister_psserve(svc)
        for s in servers:
            try:
                s.stop()
                s.join()
            except Exception:
                pass
        pc.close()

    # ---- rung 1: wire-optimizer vs pull-compute-push wave-path
    # updates/s over the big table ----
    per_shard = wave_keys // n_shards
    servers, svcs, shards, pc, client = mk_fleet(
        vocab, (8, 32, 128, 512, per_shard), None, "bench_train")
    cfg1 = PSConfig(vocab=vocab, d_model=dim, d_ff=2 * dim,
                    n_layers=2, seq=16, batch=8)
    # fixed-size waves with a FIXED per-shard key count (equal draws
    # from each shard's contiguous ownership range), so every wave
    # pads to ONE bucket — a second bucket first seen mid-trial would
    # compile inside the timed window
    rng = np.random.default_rng(0)
    bounds = [(i * vocab // n_shards, (i + 1) * vocab // n_shards)
              for i in range(n_shards)]

    def mk_keys():
        ks = np.concatenate([rng.integers(lo, hi, per_shard)
                             for lo, hi in bounds]).astype(np.int64)
        return rng.permutation(ks)

    keysets = [mk_keys() for _ in range(8)]
    gradsets = [rng.standard_normal((per_shard * n_shards, dim))
                .astype(np.float32) for _ in range(4)]

    def wave_trial(mode: str, k: int) -> float:
        """updates/s of N worker threads driving ``_send_wave`` (the
        trainer's real wave path: per-worker client clones, retry +
        token discipline, and for pull_compute_push the host slot
        lock) for one timed window."""
        tr = DataParallelTrainer(
            client, cfg1, n_workers=n_workers, steps=1,
            optimizer=spec, mode=mode, seed=k,
            name=f"bench_wave_{mode}{k}")
        clis = [tr._clone_client(w) for w in range(n_workers)]
        # first wave outside timing: shard fused-apply/scatter compile
        # at this bucket, host slot allocation (pcp), negotiation on
        # the fresh clones
        tr._send_wave(clis[0], 0, 0, keysets[0], gradsets[0])
        stop_t = time.monotonic() + wave_duration_s
        counts = [0] * n_workers

        def worker(w):
            i = 0
            while time.monotonic() < stop_t:
                tr._send_wave(clis[w], w, i,
                              keysets[(w + i) % len(keysets)],
                              gradsets[(w + i) % len(gradsets)])
                counts[w] += 1
                i += 1

        ts = [threading.Thread(target=worker, args=(w,), daemon=True)
              for w in range(n_workers)]
        # GC paused for the window: a collection pass landing in one
        # mode's trial but not the other's is pure spread (pcp's host
        # slot tables are exactly the garbage that triggers one)
        gc.collect()
        gc.disable()
        try:
            t0 = time.monotonic()
            [t.start() for t in ts]
            [t.join(120) for t in ts]
            return sum(counts) / (time.monotonic() - t0)
        finally:
            gc.enable()

    try:
        # INTERLEAVED trials so box drift hits both modes equally
        wire, pcp = [], []
        for k in range(trials):
            wire.append(wave_trial("wire", k))
            pcp.append(wave_trial("pull_compute_push", k))
        rung1 = {"optimizer": "adam"}
        rung1.update(_med_spread(wire, "wire_updates_per_s"))
        rung1.update(_med_spread(pcp, "pcp_updates_per_s"))
        rung1["wire_speedup"] = round(
            rung1["wire_updates_per_s"]
            / max(rung1["pcp_updates_per_s"], 1e-9), 2)
        # the ISSUE 17 acceptance probe: disjoint spreads, wire above
        rung1["wire_beyond_spread"] = bool(
            rung1["wire_updates_per_s_spread"][0]
            > rung1["pcp_updates_per_s_spread"][1])
        out["optimizer_placement"] = rung1
        log(f"  optimizer_placement: {json.dumps(rung1)}")
    finally:
        tear_fleet(servers, svcs, pc)
        client.close()

    # ---- rung 2: serving tokens/s WITH vs WITHOUT a concurrent
    # trainer wave (one decode replica + a small PS fleet, same
    # process/CPUs) ----
    cfg2 = PSConfig(vocab=gen_vocab, d_model=dim, d_ff=2 * dim,
                    n_layers=2, seq=16, batch=8)
    embed0, dense0 = DataParallelTrainer.model_init(cfg2, seed=0)
    servers, svcs, shards, pc, client = mk_fleet(
        gen_vocab, (8, 32, 128, 512), embed0, "bench_mix")

    def make_trainer(steps_, seed):
        tr = DataParallelTrainer(
            client, cfg2, n_workers=n_workers, steps=steps_,
            optimizer=spec, mode="wire", seed=seed,
            name="bench_mix_trainer")
        tr.seed_dense(dense0)
        # warm the per-trainer jits (each trainer closes over its own
        # loss fn, so jax retraces per instance): compile outside the
        # timed window, exactly like the other rungs
        rows0 = jnp.zeros((cfg2.batch, cfg2.seq, cfg2.d_model),
                          jnp.float32)
        dense0j = {k: jnp.asarray(v) for k, v in dense0.items()}
        tr._grad_fn(rows0, dense0j, tr._eval_targets)
        tr._loss_fn(rows0, dense0j, tr._eval_targets)
        return tr

    # warm the small fleet's shard programs (fused apply + lookup at
    # the trainer's wave size) outside timing
    wk = rng.integers(0, gen_vocab, cfg2.batch * cfg2.seq).astype(
        np.int64)
    client.update(wk, rng.standard_normal(
        (wk.size, dim)).astype(np.float32), optimizer=spec)
    client.lookup(wk)

    replicas = spin_up_replicas(1, name_prefix="bench_train_srv")
    ch = brpc.Channel(replicas[0][3], timeout_ms=10_000)
    try:
        def gen_once(prompt) -> int:
            done = threading.Event()
            toks = []

            class _H(brpc.StreamHandler):
                def on_received_messages(self, stream, messages):
                    for m in messages:
                        d = json.loads(m)
                        if "token" in d:
                            toks.append(d["token"])
                        if d.get("done"):
                            done.set()

                def on_closed(self, stream):
                    done.set()

            cntl = brpc.Controller(timeout_ms=10_000)
            brpc.stream_create(cntl, _H())
            resp = ch.call_sync(
                "Serving", "Generate",
                {"prompt": prompt, "max_new_tokens": gen_tokens},
                serializer="json", cntl=cntl)
            if not resp.get("accepted") or not done.wait(30):
                return 0
            return len(toks)

        gen_once([1])        # warm the engine outside timing

        def gen_trial(k: int) -> float:
            stop = time.monotonic() + gen_duration_s
            tokens, t0 = 0, time.monotonic()
            while time.monotonic() < stop:
                tokens += gen_once([1 + k])
            return tokens / (time.monotonic() - t0)

        alone, mixed = [], []
        for k in range(trials):
            alone.append(gen_trial(k))
            # WITH: a long trainer streams waves for the whole
            # window; stop() drains it after the window closes
            tr = make_trainer(1_000_000, seed=100 + k)

            def bg_run(tr=tr):
                try:
                    tr.run()
                except Exception as e:
                    log(f"  bg trainer: {type(e).__name__}: {e}")

            bg = threading.Thread(
                target=bg_run,
                name=f"bench_train_bg{k}", daemon=True)
            bg.start()
            wait_s = time.monotonic() + 5
            while tr.n_waves == 0 and time.monotonic() < wait_s:
                time.sleep(0.005)
            mixed.append(gen_trial(k))
            tr.stop()
            bg.join(timeout=30)
        rung2 = {"gen_tokens": gen_tokens, "gen_vocab": gen_vocab}
        rung2.update(_med_spread(alone, "tokens_per_s_alone"))
        rung2.update(_med_spread(mixed, "tokens_per_s_mixed"))
        rung2["mixed_retention"] = round(
            rung2["tokens_per_s_mixed"]
            / max(rung2["tokens_per_s_alone"], 1e-9), 2)
        out["serving_coexistence"] = rung2
        log(f"  serving_coexistence: {json.dumps(rung2)}")
    finally:
        tear_down_replicas(replicas)
        tear_fleet(servers, svcs, pc)
        client.close()
    out["note"] = (
        "training-plane rung (ISSUE 17): wave-path updates/s with the "
        "optimizer CO-LOCATED on the shard (raw grads on the wire, "
        "fused scatter+slot-step jitted server-side over the sharded "
        "table) vs the pull-compute-push baseline (full-vocab adam "
        "slot tables at the host, np scatter-accumulate + slot math "
        "per wave, deltas on the wire) — wire_beyond_spread is the "
        "acceptance probe; plus decode tokens/s on a serving replica "
        "with vs without concurrent trainer waves in the same "
        "process (mixed_retention, published not gated — the arbiter "
        "tests own the shed-ordering proof)")
    return out


def train_main(argv) -> None:
    """`python bench.py train`: run ONLY the training-plane rung and
    print one JSON object on stdout (progress on stderr) — the
    `make train` bench entry and the subprocess the full bench run
    shells out to."""
    _force_virtual_mesh()
    log("train: training-plane rung...")
    out = bench_train()
    print(json.dumps(out))


def bench_cluster(n_replicas=2, trials=5, duration_s=2.0, threads=3,
                  step_delay_s=0.01, max_new=16):
    """Cluster front-door rung (ISSUE 8): generations/s DIRECT to one
    replica vs THROUGH the ClusterRouter, on a decode-bound workload
    (each step sleeps ``step_delay_s`` — 10ms is the realistic low end
    of an LLM decode step — so generation time is dominated by decode
    the way real serving is, and the router's extra hop reads as
    overhead against a realistic denominator; an instant-step workload
    would measure only the socket relay).

    Reported: direct_gens_per_s / router_gens_per_s (3-trial
    median+spread, both perf_diff-gated higher-is-better),
    router_overhead_pct (gated lower-is-better), TTFT through the
    router, and router_within_spread — the ISSUE 8 acceptance probe
    that at low load the router-vs-direct delta sits inside the
    measurement spread.  The probe compares PER-GENERATION latency
    interquartile ranges, not per-trial qps extremes: a deterministic
    workload's 3-trial qps spread collapses toward zero, which would
    read parity (~1-2ms fixed relay cost per generation, measured) as
    beyond-spread purely because the aggregate hides the real
    per-generation jitter (engine step-loop admission quantization,
    ±one step period).  CPU-valid by construction: the step function
    is plain numpy."""
    import threading as _threading

    import brpc_tpu as brpc
    from brpc_tpu.serving import RouterClient
    from brpc_tpu.tools.rpc_press import (spin_up_cluster,
                                          tear_down_cluster)

    PT = 8

    def drive(gen_fn, duration):
        """Run gen_fn in `threads` workers for `duration`; returns
        (gens_per_s, first-token latencies us, per-gen latencies us)."""
        stop = _threading.Event()
        mu = _threading.Lock()
        ok = [0]
        ttfts: list[int] = []
        lats: list[int] = []

        def worker(k):
            while not stop.is_set():
                t0 = time.monotonic()
                first = [None]

                def emit(tok, first=first):
                    if first[0] is None:
                        first[0] = time.monotonic()

                if not gen_fn(k, emit):
                    continue
                t1 = time.monotonic()
                with mu:
                    ok[0] += 1
                    lats.append(int((t1 - t0) * 1e6))
                    if first[0] is not None:
                        ttfts.append(int((first[0] - t0) * 1e6))

        ts = [_threading.Thread(target=worker, args=(k,), daemon=True)
              for k in range(threads)]
        t0 = time.monotonic()
        [t.start() for t in ts]
        time.sleep(duration)
        stop.set()
        [t.join(10) for t in ts]
        return ok[0] / (time.monotonic() - t0), ttfts, lats

    def one_trial(k):
        # replication deliberately OFF: the rung measures the router's
        # relay overhead, not page shipping (the press turns it on)
        replicas, router, rsrv, raddr = spin_up_cluster(
            n_replicas, page_tokens=PT, step_delay_s=step_delay_s,
            max_sessions=512, name_prefix=f"bench_cl_{k}")
        try:
            from brpc_tpu.migrate.disagg import _TokenCollector
            from brpc_tpu.rpc import Controller, stream_create

            def direct_gen(w, emit):
                # straight to replica 0's Serving.Generate stream —
                # the no-router baseline
                prompt = [w * 31 + j for j in range(PT)]
                col = _TokenCollector(emit)
                cntl = Controller(timeout_ms=20_000)
                stream_create(cntl, col)
                try:
                    dch = direct_chans[w % len(direct_chans)]
                    dch.call_sync(
                        "Serving", "Generate",
                        {"prompt": prompt, "max_new_tokens": max_new},
                        serializer="json", cntl=cntl)
                except brpc.RpcError:
                    return False
                return col.done.wait(20) and col.error is None

            direct_chans = [brpc.Channel(replicas[0][3],
                                         timeout_ms=20_000)
                            for _ in range(threads)]
            clients = [RouterClient(raddr, timeout_ms=20_000)
                       for _ in range(threads)]

            def router_gen(w, emit):
                prompt = [w * 31 + j for j in range(PT)]
                try:
                    res = clients[w % len(clients)].generate(
                        prompt, max_new, emit=emit, timeout_s=20)
                except brpc.RpcError:
                    return False
                return res["error"] is None

            # warm both paths (first-call setup outside timing)
            direct_gen(0, lambda t: None)
            router_gen(0, lambda t: None)
            d_qps, _, d_lats = drive(direct_gen, duration_s)
            r_qps, ttfts, r_lats = drive(router_gen, duration_s)
            resumes = router.resumes_total.get_value()
            return d_qps, r_qps, ttfts, resumes, d_lats, r_lats
        finally:
            tear_down_cluster(replicas, router, rsrv)

    rs = [one_trial(k) for k in range(trials)]
    ds = sorted(r[0] for r in rs)
    qs = sorted(r[1] for r in rs)
    all_ttft = sorted(t for r in rs for t in r[2])
    d_med, r_med = ds[len(ds) // 2], qs[len(qs) // 2]
    overheads = sorted((d - r) / d * 100.0
                       for d, r, _t, _n, _dl, _rl in rs if d > 0)
    d_lats = sorted(x for r in rs for x in r[4])
    r_lats = sorted(x for r in rs for x in r[5])

    def _iqr(xs):
        if not xs:
            return [0, 0]
        return [xs[len(xs) // 4], xs[(3 * len(xs)) // 4]]

    d_iqr, r_iqr = _iqr(d_lats), _iqr(r_lats)
    # minimum-spread floor (ISSUE 9 deflake): ± half a step period per
    # generation — the admission-quantization jitter a deterministic
    # workload's per-trial qps aggregate hides.  Without it, a ~0.2%
    # collapsed spread lets perf_diff flag a 5-6%-end run as a
    # beyond-spread regression (`make bench` crying wolf, PR 8 note).
    floor_frac = 1.0 / (2 * max_new)
    o_med = overheads[len(overheads) // 2] if overheads else None
    out = {
        "replicas": n_replicas,
        "threads": threads,
        "step_delay_ms": step_delay_s * 1e3,
        "direct_gens_per_s": round(d_med, 1),
        "direct_gens_per_s_spread": _floor_spread(
            d_med, ds[0], ds[-1], d_med * floor_frac),
        "router_gens_per_s": round(r_med, 1),
        "router_gens_per_s_spread": _floor_spread(
            r_med, qs[0], qs[-1], r_med * floor_frac),
        "router_overhead_pct": (round(o_med, 2)
                                if o_med is not None else None),
        "router_overhead_pct_spread": (
            _floor_spread(o_med, overheads[0], overheads[-1],
                          100.0 * floor_frac)
            if o_med is not None else None),
        "direct_gen_lat_p50_us": (d_lats[len(d_lats) // 2]
                                  if d_lats else None),
        "router_gen_lat_p50_us": (r_lats[len(r_lats) // 2]
                                  if r_lats else None),
        "direct_gen_lat_iqr_us": d_iqr,
        "router_gen_lat_iqr_us": r_iqr,
        # the ISSUE 8 acceptance probe: the router-vs-direct delta
        # sits inside the measurement spread at low load — compared at
        # per-generation latency granularity (IQR overlap), where the
        # real jitter lives; see the docstring
        "router_within_spread": bool(
            d_lats and r_lats and
            r_iqr[0] <= d_iqr[1] and d_iqr[0] <= r_iqr[1]),
        "router_ttft_p50_us": (all_ttft[len(all_ttft) // 2]
                               if all_ttft else None),
        "router_ttft_p99_us": (all_ttft[int(len(all_ttft) * 0.99)]
                               if all_ttft else None),
        "resumes": sum(r[3] for r in rs),
        "trials": trials,
        "cpu_valid": True,
        "note": ("cluster front-door rung (brpc_tpu/serving/router): "
                 "generations/s direct-to-replica vs through the "
                 "router on a decode-bound workload; perf_diff gates "
                 "direct/router gens_per_s (up) and "
                 "router_overhead_pct (down) on disjoint spread; "
                 f"{trials} trials with a ±{100 * floor_frac:.1f}% "
                 "minimum-spread floor (admission quantization) so a "
                 "collapsed deterministic spread cannot read noise as "
                 "beyond-spread"),
    }
    return out


def cluster_main(argv) -> None:
    """`python bench.py cluster`: run ONLY the cluster front-door rung
    and print one JSON object on stdout (progress on stderr) — the
    `make cluster`-adjacent bench entry and the subprocess the full
    bench run shells out to."""
    log("cluster: router-vs-direct generations rung...")
    out = bench_cluster()
    for k, v in out.items():
        if isinstance(v, (dict, list)):
            log(f"  {k}: {json.dumps(v)}")
        else:
            log(f"  {k}: {v}")
    print(json.dumps(out))


def bench_durable(n_replicas: int = 2, trials: int = 3,
                  duration_s: float = 2.0, threads: int = 3,
                  step_delay_s: float = 0.01, max_new: int = 16,
                  warm_fracs=(0.0, 0.5, 0.9)) -> dict:
    """Durable control-plane rung (ISSUE 16), two halves:

    A. **WAL tax** — generations/s through the router with the session
       WAL OFF vs ON, same decode-bound operating point as the cluster
       rung (step_delay dominates, so the WAL's file appends are the
       only delta).  Publishes ``wal_overhead_pct`` with the ISSUE 16
       acceptance claim ``wal_overhead_within_5pct``.

    B. **Crash -> first-token** — N generations stream over a
       WAL-backed router; the router AND the owner replica die
       mid-generation; a successor adopts the fleet from the WAL and
       every client resumes CONCURRENTLY (the adoption storm).  The
       latency to each session's first post-adoption token is taken at
       three buddy-warm operating points: 0% (replication off — every
       resume recomputes), 50% and 90% (that fraction of sessions had
       their pages shipped to the ring buddy, via the per-session
       ``Session.replicate`` opt-out, so the resume re-decodes only
       the unshipped tail).  All prompts share their first chunk so
       the affinity ring puts every session on ONE owner — killing it
       makes buddy warmth, not owner survival, the variable.

    Everything is CPU-valid: the step fn is plain numpy."""
    import tempfile as _tempfile
    import threading as _threading

    import brpc_tpu as brpc
    from brpc_tpu.serving import (ClusterRouter, ReplicaHandle,
                                  RouterClient, SessionTable,
                                  register_router)
    from brpc_tpu.tools.rpc_press import (spin_up_replicas,
                                          tear_down_replicas)

    PT = 8

    def handles(replicas, prefix):
        return [ReplicaHandle(addr, name=f"{prefix}_{i}", engine=eng,
                              store=store, server=srv)
                for i, (store, eng, srv, addr) in enumerate(replicas)]

    # ---- half A: WAL-off vs WAL-on generations/s ----

    def drive(raddr, duration):
        stop = _threading.Event()
        mu = _threading.Lock()
        ok = [0]
        clients = [RouterClient(raddr, timeout_ms=20_000)
                   for _ in range(threads)]

        def worker(w):
            while not stop.is_set():
                prompt = [w * 31 + j for j in range(PT)]
                try:
                    res = clients[w % len(clients)].generate(
                        prompt, max_new, timeout_s=20)
                except brpc.RpcError:
                    continue
                if res["error"] is None:
                    with mu:
                        ok[0] += 1

        ts = [_threading.Thread(target=worker, args=(w,), daemon=True)
              for w in range(threads)]
        t0 = time.monotonic()
        [t.start() for t in ts]
        time.sleep(duration)
        stop.set()
        [t.join(10) for t in ts]
        return ok[0] / (time.monotonic() - t0)

    def wal_trial(k):
        replicas = spin_up_replicas(
            n_replicas, page_tokens=PT, step_delay_s=step_delay_s,
            name_prefix=f"bench_dur_{k}")
        wal_dir = _tempfile.mkdtemp(prefix=f"bench_dur_{k}_")
        qps = {}
        wal_stats = None
        try:
            for mode, wal in (("off", None),
                              ("on", os.path.join(wal_dir, "s.wal"))):
                router = ClusterRouter(
                    handles(replicas, f"bd{k}{mode}"), wal=wal,
                    page_tokens=PT, max_sessions=512,
                    name=f"bench_dur_{k}_{mode}")
                rsrv = brpc.Server()
                register_router(rsrv, router)
                rsrv.start("127.0.0.1", 0)
                try:
                    raddr = f"127.0.0.1:{rsrv.port}"
                    drive(raddr, 0.2)            # warm both paths
                    qps[mode] = drive(raddr, duration_s)
                    if wal is not None:
                        wal_stats = router.sessions.wal.stats()
                finally:
                    router.close(timeout_s=3.0)
                    rsrv.stop()
                    rsrv.join()
        finally:
            tear_down_replicas(replicas)
            import shutil
            shutil.rmtree(wal_dir, ignore_errors=True)
        return qps["off"], qps["on"], wal_stats

    wal_rs = [wal_trial(k) for k in range(trials)]
    offs = sorted(r[0] for r in wal_rs)
    ons = sorted(r[1] for r in wal_rs)
    off_med, on_med = offs[len(offs) // 2], ons[len(ons) // 2]
    overheads = sorted((off - on) / off * 100.0
                       for off, on, _w in wal_rs if off > 0)
    o_med = overheads[len(overheads) // 2] if overheads else None
    last_wal = wal_rs[-1][2] or {}
    # same minimum-spread floor as the cluster rung: admission
    # quantization hides ±half a step period per generation
    floor_frac = 1.0 / (2 * max_new)

    # ---- half B: crash -> first post-adoption token ----

    N = 6
    budget = 28
    adopt_step = 0.02
    # real-model cost shape: prefill pays per uncached token, so a
    # buddy-warm resume (deep prefix hit) skips most of the re-decode
    # bill instead of re-paying one flat vectorized call
    prefill_cost = 0.003
    shared = [500 + j for j in range(PT)]    # one owner for the fleet

    def adoption_trial(frac, k):
        warm_n = int(round(frac * N))
        replicas = spin_up_replicas(
            2, page_tokens=PT, step_delay_s=adopt_step, num_slots=8,
            commit_live_pages=True, name_prefix=f"bench_ad{k}",
            prefill_cost_per_token_s=prefill_cost)
        addr_of = [addr for *_, addr in replicas]
        wal_dir = _tempfile.mkdtemp(prefix=f"bench_ad{k}_")
        wal_path = os.path.join(wal_dir, "s.wal")
        router = ClusterRouter(
            handles(replicas, f"ba{k}"), wal=wal_path,
            replicate_sessions=warm_n > 0, replication_factor=2,
            page_tokens=PT, chunk_tokens=PT, check_interval_s=0.02,
            name=f"bench_ad_{k}")
        rsrv = brpc.Server()
        register_router(rsrv, router)
        rsrv.start("127.0.0.1", 0)
        cli = RouterClient(f"127.0.0.1:{rsrv.port}", timeout_ms=20_000)
        successor = rsrv2 = None
        try:
            gens = []
            for i in range(N):
                prompt = shared + [600 + 17 * i + j for j in range(PT)]
                g = cli.start(prompt, budget)
                if i >= warm_n:
                    # cold: opt the session out before its first page
                    # commit (first token is >= one step away)
                    router.sessions.get(g.session_id).replicate = False
                gens.append(g)
            for g in gens:
                if not g.wait_tokens(16, timeout_s=30):
                    raise RuntimeError("bench_durable: no progress "
                                       "before the kill")
            rows = {r["session_id"]: r
                    for r in router.sessions.snapshot(limit=2 * N)}
            observed_warm = sum(
                1 for g in gens
                if rows[g.session_id]["replicated_pages"] > 2)
            owner = rows[gens[0].session_id]["replica"]
            sids = [g.session_id for g in gens]
            for g in gens:
                g.drop()

            # the crash: router and the one owner die together
            router.close(timeout_s=3.0)
            rsrv.stop()
            rsrv.join()
            vidx = addr_of.index(owner)
            vstore, veng, vsrv, _va = replicas[vidx]
            vsrv.stop()
            vsrv.join()
            veng.close(timeout_s=2.0)
            survivor = [replicas[i] for i in range(2) if i != vidx][0]

            t_adopt = time.monotonic()
            table = SessionTable.recover(wal_path)
            # resume at the DURABLE cursor: write-ahead means the
            # record is >= any client's view, so this is the
            # worst-case reconnect — zero replayed tokens, the first
            # emitted token is the first freshly RE-DECODED one (the
            # quantity buddy warmth actually moves)
            held = [(sid, table.get(sid).cursor) for sid in sids]
            successor = ClusterRouter(
                [ReplicaHandle(survivor[3], engine=survivor[1],
                               store=survivor[0], server=survivor[2])],
                sessions=table, page_tokens=PT, chunk_tokens=PT,
                check_interval_s=0.02, name=f"bench_ad_{k}_succ")
            rsrv2 = brpc.Server()
            register_router(rsrv2, successor)
            rsrv2.start("127.0.0.1", 0)
            adoption_ms = (time.monotonic() - t_adopt) * 1e3
            cli2 = RouterClient(f"127.0.0.1:{rsrv2.port}",
                                timeout_ms=30_000)

            # the adoption storm: every client resumes at once
            ttfts = []
            mu = _threading.Lock()

            def resume_one(sid, cursor):
                t0 = time.monotonic()
                first = [None]

                def emit(tok, first=first):
                    if first[0] is None:
                        first[0] = time.monotonic()

                g = cli2.resume(sid, cursor, emit=emit)
                g.wait(60)
                if g.error is None and first[0] is not None:
                    with mu:
                        ttfts.append((first[0] - t0) * 1e3)

            ts = [_threading.Thread(target=resume_one, args=h,
                                    daemon=True) for h in held]
            [t.start() for t in ts]
            [t.join(90) for t in ts]
            if len(ttfts) < N:
                raise RuntimeError(
                    f"bench_durable: only {len(ttfts)}/{N} resumes "
                    "produced a post-adoption token")
            ttfts.sort()
            return ttfts[len(ttfts) // 2], adoption_ms, observed_warm
        finally:
            if successor is not None:
                successor.close(timeout_s=3.0)
            if rsrv2 is not None:
                rsrv2.stop()
                rsrv2.join()
            tear_down_replicas(replicas)
            import shutil
            shutil.rmtree(wal_dir, ignore_errors=True)

    adopt = {}
    adoption_ms_all = []
    for frac in warm_fracs:
        meds = []
        warms = []
        for k in range(trials):
            med, ad_ms, ow = adoption_trial(frac, k)
            meds.append(med)
            warms.append(ow)
            adoption_ms_all.append(ad_ms)
        meds.sort()
        m = meds[len(meds) // 2]
        key = f"resume_ttft_warm{int(frac * 100)}_ms"
        adopt[key] = round(m, 1)
        # floor: first-token timing quantizes on a decode step plus
        # one prefill bucket (the suffix pads to 16-token buckets)
        adopt[key + "_spread"] = _floor_spread(
            m, meds[0], meds[-1], (adopt_step + 16 * prefill_cost) * 1e3)
        adopt[f"observed_warm_sessions_warm{int(frac * 100)}"] = (
            sorted(warms)[len(warms) // 2])
    adoption_ms_all.sort()
    ad_med = adoption_ms_all[len(adoption_ms_all) // 2]

    out = {
        "replicas": n_replicas,
        "threads": threads,
        "step_delay_ms": step_delay_s * 1e3,
        "wal_off_gens_per_s": round(off_med, 1),
        "wal_off_gens_per_s_spread": _floor_spread(
            off_med, offs[0], offs[-1], off_med * floor_frac),
        "wal_on_gens_per_s": round(on_med, 1),
        "wal_on_gens_per_s_spread": _floor_spread(
            on_med, ons[0], ons[-1], on_med * floor_frac),
        "wal_overhead_pct": (round(o_med, 2)
                             if o_med is not None else None),
        "wal_overhead_pct_spread": (
            _floor_spread(o_med, overheads[0], overheads[-1],
                          100.0 * floor_frac)
            if o_med is not None else None),
        # the ISSUE 16 acceptance claim: journaling every token
        # write-ahead costs <= 5% of WAL-off throughput at the median
        # (single trials swing ±3% on admission quantization alone —
        # the spread above says how much)
        "wal_overhead_within_5pct": bool(
            o_med is not None and o_med <= 5.0),
        "wal_appends": last_wal.get("appends"),
        "wal_size_bytes": last_wal.get("size_bytes"),
        "adopt_sessions": N,
        "adopt_step_delay_ms": adopt_step * 1e3,
        "adoption_ms": round(ad_med, 1),
        **adopt,
        "trials": trials,
        "cpu_valid": True,
        "note": ("durable control-plane rung (ISSUE 16): half A is "
                 "generations/s WAL-off vs WAL-on on the decode-bound "
                 "cluster operating point (wal_overhead_pct gated "
                 "down, <=5% acceptance); half B kills the router AND "
                 "the single owner replica mid-generation, adopts the "
                 "fleet from the WAL, and measures each session's "
                 "crash->first-token latency under a concurrent "
                 "resume storm at 0/50/90% buddy-warm (the warm "
                 "fraction had its pages on the ring buddy; resumes "
                 "re-decode only the unshipped tail, so the _ms "
                 "medians fall as warmth rises); "
                 f"{trials} trials, minimum-spread floors of "
                 f"±{100 * floor_frac:.1f}% (admission quantization) "
                 "and ±1 decode step (first-token quantization)"),
    }
    return out


def durable_main(argv) -> None:
    """`python bench.py durable`: run ONLY the durable control-plane
    rung and print one JSON object on stdout (progress on stderr) —
    the `make durable`-adjacent bench entry and the subprocess the
    full bench run shells out to."""
    log("durable: WAL tax + crash->first-token rung...")
    out = bench_durable()
    for k, v in out.items():
        if isinstance(v, (dict, list)):
            log(f"  {k}: {json.dumps(v)}")
        else:
            log(f"  {k}: {v}")
    print(json.dumps(out))


def bench_multimodel(n_replicas: int = 2, trials: int = 3,
                     duration_s: float = 2.0, threads: int = 3,
                     step_delay_s: float = 0.01, max_new: int = 16,
                     canary_sessions: int = 200) -> dict:
    """Multi-model plane rung (ISSUE 18), two halves:

    A. **Two-model tax** — generations/s through ONE router front door
       over the same replica fleet, single-deployment vs
       two-deployment (every request names its model; the only delta
       is the plane itself: catalog resolution, the (model, prefix)
       fingerprint fold, per-deployment engine dispatch).  Publishes
       ``two_model_overhead_pct`` with the ISSUE 18 acceptance claim
       ``two_model_overhead_within_5pct``.

    B. **Canary split** — one model_id behind two versioned
       deployments weighted 95/5; clients ask for the bare model_id
       and the router's smooth-WRR canary splitter picks the version.
       Publishes the observed v1 share with the acceptance claim
       ``canary_within_2pts`` (|observed - 95| <= 2 points; smooth WRR
       is deterministic to ±1 pick, so the band is generous).

    ``wrong_model_routes`` rides along and must be 0 — the plane's
    invariant, not a performance number.  CPU-valid: numpy step fns."""
    import threading as _threading

    import brpc_tpu as brpc
    from brpc_tpu.serving import RouterClient
    from brpc_tpu.serving.modelplane import WARM
    from brpc_tpu.tools.rpc_press import (spin_up_multimodel_cluster,
                                          tear_down_multimodel_cluster)

    PT = 8

    # ---- half A: single-deployment vs two-deployment gens/s ----

    def drive(raddr, duration, models):
        stop = _threading.Event()
        mu = _threading.Lock()
        ok = [0]
        clients = [RouterClient(raddr, timeout_ms=20_000)
                   for _ in range(threads)]

        def worker(w):
            n = 0
            while not stop.is_set():
                prompt = [w * 31 + j for j in range(PT)]
                m = models[(w + n) % len(models)]
                n += 1
                try:
                    res = clients[w % len(clients)].generate(
                        prompt, max_new, timeout_s=20, model=m)
                except brpc.RpcError:
                    continue
                if res["error"] is None:
                    with mu:
                        ok[0] += 1

        ts = [_threading.Thread(target=worker, args=(w,), daemon=True)
              for w in range(threads)]
        t0 = time.monotonic()
        [t.start() for t in ts]
        time.sleep(duration)
        stop.set()
        [t.join(10) for t in ts]
        return ok[0] / (time.monotonic() - t0)

    def tax_trial(k):
        qps = {}
        wrong = 0
        for mode, models in (("single", ["modela"]),
                             ("dual", ["modela", "modelb"])):
            replicas, _mults, router, rsrv, raddr = \
                spin_up_multimodel_cluster(
                    n_replicas, models, page_tokens=PT,
                    step_delay_s=step_delay_s, max_sessions=512,
                    name_prefix=f"bench_mm_{k}_{mode}")
            try:
                drive(raddr, 0.2, models)        # warm both paths
                qps[mode] = drive(raddr, duration_s, models)
                wrong += router.stats()["wrong_model_routes"]
            finally:
                tear_down_multimodel_cluster(replicas, router, rsrv)
        return qps["single"], qps["dual"], wrong

    tax_rs = [tax_trial(k) for k in range(trials)]
    singles = sorted(r[0] for r in tax_rs)
    duals = sorted(r[1] for r in tax_rs)
    s_med = singles[len(singles) // 2]
    d_med = duals[len(duals) // 2]
    overheads = sorted((s - d) / s * 100.0
                       for s, d, _w in tax_rs if s > 0)
    o_med = overheads[len(overheads) // 2] if overheads else None
    wrong_routes = sum(r[2] for r in tax_rs)
    # same minimum-spread floor as the cluster/durable rungs:
    # admission quantization hides ± half a step period per generation
    floor_frac = 1.0 / (2 * max_new)

    # ---- half B: 95/5 canary split over one model_id ----

    def canary_trial(k):
        replicas, _mults, router, rsrv, raddr = \
            spin_up_multimodel_cluster(
                1, ["orca@v1", "orca@v2"], page_tokens=PT,
                step_delay_s=0.0, max_sessions=1024,
                name_prefix=f"bench_can_{k}")
        try:
            # the canary weights: v1 holds 95, v2 holds 5
            replicas[0]["deps"].deploy("orca@v1", weight=95, state=WARM)
            replicas[0]["deps"].deploy("orca@v2", weight=5, state=WARM)
            router.catalog.note(replicas[0]["addr"],
                                replicas[0]["deps"].snapshot())
            cli = RouterClient(raddr, timeout_ms=20_000)
            for i in range(canary_sessions):
                prompt = [900 + 7 * i + j for j in range(PT)]
                res = cli.generate(prompt, 2, timeout_s=20,
                                   model="orca")
                if res["error"] is not None:
                    raise RuntimeError(
                        f"bench_multimodel: canary generation failed "
                        f"E{res['error']}")
            picks = router.stats()["canary"].get("orca", {})
            v1 = picks.get("orca@v1", 0)
            total = sum(picks.values())
            return 100.0 * v1 / total if total else 0.0
        finally:
            tear_down_multimodel_cluster(replicas, router, rsrv)

    shares = sorted(canary_trial(k) for k in range(trials))
    share_med = shares[len(shares) // 2]

    return {
        "replicas": n_replicas,
        "threads": threads,
        "step_delay_ms": step_delay_s * 1e3,
        "single_model_gens_per_s": round(s_med, 1),
        "single_model_gens_per_s_spread": _floor_spread(
            s_med, singles[0], singles[-1], s_med * floor_frac),
        "two_model_gens_per_s": round(d_med, 1),
        "two_model_gens_per_s_spread": _floor_spread(
            d_med, duals[0], duals[-1], d_med * floor_frac),
        "two_model_overhead_pct": (round(o_med, 2)
                                   if o_med is not None else None),
        "two_model_overhead_pct_spread": (
            _floor_spread(o_med, overheads[0], overheads[-1],
                          100.0 * floor_frac)
            if o_med is not None else None),
        # the ISSUE 18 acceptance claim: naming models costs <= 5% of
        # anonymous single-model throughput at the median
        "two_model_overhead_within_5pct": bool(
            o_med is not None and o_med <= 5.0),
        "canary_sessions": canary_sessions,
        "canary_v1_share_pct": round(share_med, 2),
        "canary_v1_share_pct_spread": _floor_spread(
            share_med, shares[0], shares[-1],
            100.0 / canary_sessions),
        "canary_within_2pts": bool(abs(share_med - 95.0) <= 2.0),
        "wrong_model_routes": wrong_routes,
        "trials": trials,
        "cpu_valid": True,
        "note": ("multi-model plane rung (ISSUE 18): half A is "
                 "generations/s through one router front door, "
                 "single- vs two-deployment on the same fleet and the "
                 "same decode-bound operating point (the plane's "
                 "catalog/fingerprint/dispatch cost is the only "
                 "delta; <=5% acceptance), half B drives one model_id "
                 "behind 95/5-weighted versioned deployments and "
                 "reads the router's smooth-WRR canary scoreboard "
                 "(±2-point acceptance; the splitter is deterministic "
                 f"to ±1 pick); {trials} trials, minimum-spread "
                 f"floors of ±{100.0 / (2 * max_new):.1f}% "
                 "(admission quantization) / ±1 pick (canary); "
                 "wrong_model_routes must read 0"),
    }


def bench_telemetry(n_replicas: int = 2, trials: int = 3,
                    duration_s: float = 2.0, threads: int = 3,
                    step_delay_s: float = 0.01,
                    max_new: int = 16) -> dict:
    """Fleet telemetry plane rung (ISSUE 20): generations/s through
    one router front door with the telemetry plane OFF
    (``telemetry_collect=False``: no collector, no pulls, no SLO
    engine) vs ON (every 20 Hz tick samples the router scoreboard into
    fleet series and runs an attached burn-rate SLO engine; every
    replica's ``_telemetry`` increment is pulled over the control
    channel on its own ``telemetry_pull_interval_s`` cadence).  Same
    fleet, same decode-bound operating point — the collection pass is
    the only delta.

    Publishes ``telemetry_overhead_pct`` with the ISSUE 20 acceptance
    claim ``telemetry_overhead_within_2pct``, plus the collection
    evidence that makes a ~0% result meaningful rather than vacuous:
    ``collector_pulls``/``slo_evaluations`` must be well above 0 and
    ``bytes_per_pull`` bounds the per-tick wire increment (the
    cursor-based Pull ships deltas, not whole snapshots).  CPU-valid:
    numpy step fns."""
    import threading as _threading

    import brpc_tpu as brpc
    from brpc_tpu.serving import RouterClient
    from brpc_tpu.serving.slo import Objective, SLOEngine
    from brpc_tpu.tools.rpc_press import (spin_up_multimodel_cluster,
                                          tear_down_multimodel_cluster)

    PT = 8
    MODELS = ["orca@v1", "orca@v2"]

    def drive(raddr, duration):
        stop = _threading.Event()
        mu = _threading.Lock()
        ok = [0]
        clients = [RouterClient(raddr, timeout_ms=20_000)
                   for _ in range(threads)]

        def worker(w):
            n = 0
            while not stop.is_set():
                prompt = [w * 31 + j for j in range(PT)]
                m = MODELS[(w + n) % len(MODELS)]
                n += 1
                try:
                    res = clients[w % len(clients)].generate(
                        prompt, max_new, timeout_s=20, model=m)
                except brpc.RpcError:
                    continue
                if res["error"] is None:
                    with mu:
                        ok[0] += 1

        ts = [_threading.Thread(target=worker, args=(w,), daemon=True)
              for w in range(threads)]
        t0 = time.monotonic()
        [t.start() for t in ts]
        time.sleep(duration)
        stop.set()
        [t.join(10) for t in ts]
        return ok[0] / (time.monotonic() - t0)

    def trial(k):
        out = {}
        evidence = {}
        for mode in ("off", "on"):
            replicas, _mults, router, rsrv, raddr = \
                spin_up_multimodel_cluster(
                    n_replicas, MODELS, page_tokens=PT,
                    step_delay_s=step_delay_s, max_sessions=512,
                    name_prefix=f"bench_tel_{k}_{mode}",
                    router_kw={"telemetry_collect": mode == "on"})
            try:
                if mode == "on":
                    # a real burn-rate engine in the loop: targets are
                    # unreachable and clean_windows is effectively
                    # infinite, so it evaluates every tick but never
                    # re-weights — the full observe cost, zero plane
                    # mutations mid-measurement
                    router.attach_slo(SLOEngine(
                        "orca", "orca@v1", "orca@v2",
                        [Objective("itl_p99_ms", 60_000.0),
                         Objective("ttft_p99_ms", 60_000.0)],
                        short_window_s=0.5, long_window_s=1.5,
                        clean_windows=10**9))
                drive(raddr, 0.2)            # warm both paths
                out[mode] = drive(raddr, duration_s)
                if mode == "on":
                    cs = router.collector.stats()
                    evidence = {
                        "pulls": cs["pulls"],
                        "pull_bytes": cs["pull_bytes"],
                        "pull_errors": cs["pull_errors"],
                        "slo_evaluations":
                            router.slo.snapshot()["evaluations"],
                    }
            finally:
                tear_down_multimodel_cluster(replicas, router, rsrv)
        return out["off"], out["on"], evidence

    rs = [trial(k) for k in range(trials)]
    offs = sorted(r[0] for r in rs)
    ons = sorted(r[1] for r in rs)
    off_med = offs[len(offs) // 2]
    on_med = ons[len(ons) // 2]
    overheads = sorted((off - on) / off * 100.0
                       for off, on, _e in rs if off > 0)
    o_med = overheads[len(overheads) // 2] if overheads else None
    pulls = sum(r[2].get("pulls", 0) for r in rs)
    pull_bytes = sum(r[2].get("pull_bytes", 0) for r in rs)
    pull_errors = sum(r[2].get("pull_errors", 0) for r in rs)
    slo_evals = sum(r[2].get("slo_evaluations", 0) for r in rs)
    # same minimum-spread floor as the cluster/multimodel rungs:
    # admission quantization hides ± half a step period per generation
    floor_frac = 1.0 / (2 * max_new)

    return {
        "replicas": n_replicas,
        "threads": threads,
        "step_delay_ms": step_delay_s * 1e3,
        "telemetry_off_gens_per_s": round(off_med, 1),
        "telemetry_off_gens_per_s_spread": _floor_spread(
            off_med, offs[0], offs[-1], off_med * floor_frac),
        "telemetry_on_gens_per_s": round(on_med, 1),
        "telemetry_on_gens_per_s_spread": _floor_spread(
            on_med, ons[0], ons[-1], on_med * floor_frac),
        "telemetry_overhead_pct": (round(o_med, 2)
                                   if o_med is not None else None),
        "telemetry_overhead_pct_spread": (
            _floor_spread(o_med, overheads[0], overheads[-1],
                          100.0 * floor_frac)
            if o_med is not None else None),
        # the ISSUE 20 acceptance claim: the whole plane — fleet
        # sampling + per-replica pulls + SLO burn evaluation — costs
        # <= 2% of front-door throughput at the median
        "telemetry_overhead_within_2pct": bool(
            o_med is not None and o_med <= 2.0),
        # collection evidence: a 0% overhead claim over a collector
        # that never pulled would be vacuous
        "collector_pulls": pulls,
        "collector_pull_bytes": pull_bytes,
        "collector_pull_errors": pull_errors,
        "bytes_per_pull": (round(pull_bytes / pulls, 1)
                           if pulls else None),
        "slo_evaluations": slo_evals,
        "telemetry_actually_collected": bool(pulls > 0
                                             and slo_evals > 0),
        "trials": trials,
        "cpu_valid": True,
        "note": ("fleet telemetry plane rung (ISSUE 20): "
                 "generations/s through one router front door with "
                 "the collection pass (20 Hz fleet series sampling + "
                 "SLO burn-rate evaluation, incremental per-replica "
                 "_telemetry pulls on their own cadence) off vs on "
                 "over the same fleet and operating point; <=2% "
                 "acceptance at the "
                 f"median over {trials} trials, minimum-spread floor "
                 f"of ±{100.0 / (2 * max_new):.1f}% (admission "
                 "quantization); collector_pulls/slo_evaluations "
                 "must be > 0 or the claim is vacuous, and "
                 "bytes_per_pull bounds the cursor-based wire "
                 "increment"),
    }


def telemetry_main(argv) -> None:
    """`python bench.py telemetry`: run ONLY the fleet telemetry
    overhead rung and print one JSON object on stdout (progress on
    stderr) — the `make telemetry`-adjacent bench entry and the
    subprocess the full bench run shells out to."""
    log("telemetry: fleet collection on/off overhead rung...")
    out = bench_telemetry()
    for k, v in out.items():
        if isinstance(v, (dict, list)):
            log(f"  {k}: {json.dumps(v)}")
        else:
            log(f"  {k}: {v}")
    print(json.dumps(out))


def multimodel_main(argv) -> None:
    """`python bench.py multimodel`: run ONLY the multi-model plane
    rung and print one JSON object on stdout (progress on stderr) —
    the `make multimodel`-adjacent bench entry and the subprocess the
    full bench run shells out to."""
    log("multimodel: two-model tax + canary split rung...")
    out = bench_multimodel()
    for k, v in out.items():
        if isinstance(v, (dict, list)):
            log(f"  {k}: {json.dumps(v)}")
        else:
            log(f"  {k}: {v}")
    print(json.dumps(out))


def migrate_main(argv) -> None:
    """`python bench.py migrate`: run ONLY the migration rung and
    print one JSON object on stdout (progress on stderr) — the
    `make migrate`-adjacent bench entry and the subprocess the full
    bench run shells out to."""
    log("migrate: migrate-vs-recompute admit rung...")
    out = bench_migrate()
    for k, v in out.items():
        if isinstance(v, dict):
            log(f"  {k}: {json.dumps(v)}")
    print(json.dumps(out))


def _classify_probe_failure(stderr: str, timed_out: bool,
                            phase: str) -> tuple[str, str]:
    """Map one probe attempt's outcome to a skip_reason KIND (ISSUE 6
    bench hygiene: a skipped rung must say WHY — "no device" is a very
    different trajectory signal from "device present but hung").

      * wedge-deadline — the probe subprocess blew its hard timeout
        (enumeration hung = wedged tunnel; compute hung = device
        present but its data path is wedged);
      * no-device     — jax answered cleanly that there is no usable
        accelerator (backend init failure, zero devices);
      * exception     — anything else (missing jax, import error, a
        crash that isn't a backend-absence message).
    """
    if timed_out:
        return "wedge-deadline", (
            f"device {'enumeration' if phase == 'enum' else 'compute'} "
            f"hung past the deadline "
            f"({'wedged tunnel?' if phase == 'enum' else 'device present but hung'})")
    tail = (stderr or "").strip().splitlines()[-1:] or ["no stderr"]
    msg = tail[0]
    lowered = msg.lower()
    if ("unable to initialize backend" in lowered
            or "no devices" in lowered
            or "failed to get device" in lowered
            or "no visible device" in lowered):
        return "no-device", msg
    return "exception", msg


def _skip_entry(kind: str, detail: str) -> dict:
    """The honest-skip publication shape every device rung uses: a
    machine-readable skip_reason kind plus the human detail (the old
    `reason` key is kept so earlier-round tooling still parses)."""
    return {"skipped": True, "skip_reason": kind, "skip_detail": detail,
            "reason": detail}


def _probe_device(timeouts_s: tuple = (60, 90, 150)) -> tuple[bool, str, str]:
    """Probe jax device init in a SUBPROCESS with a hard timeout.  A
    wedged tunnel makes jax.devices() block forever inside the PJRT
    client constructor — in-process there is no way back, so a bench run
    must discover it out-of-process or hang the whole driver.

    TWO PHASES per attempt (ISSUE 6): device ENUMERATION first, then a
    tiny COMPUTATION — init can succeed while the data path is wedged,
    and the two failures must publish differently ("no device" vs
    "device present but hung").  Bounded retries in FRESH subprocesses:
    a transiently flaky tunnel often recovers between attempts, and each
    attempt starts a clean PJRT client.  Timeouts ESCALATE (60/90/150s)
    so a cold-but-working tunnel whose init+first-compile legitimately
    takes >60s still passes on a later attempt, while a wedged tunnel
    costs a bounded ~5 min total.

    Returns ``(ok, skip_kind, cause)`` — skip_kind one of
    "no-device" / "wedge-deadline" / "exception" when not ok."""
    import subprocess
    import sys
    kind = cause = ""
    n = len(timeouts_s)
    for i, timeout_s in enumerate(timeouts_s):
        # phase 1: enumeration only — distinguishes "tunnel wedged at
        # init" from "no device" without paying a compile
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s, capture_output=True, text=True)
            timed_out = False
        except subprocess.TimeoutExpired:
            r, timed_out = None, True
        if timed_out or r.returncode != 0:
            kind, msg = _classify_probe_failure(
                r.stderr if r is not None else "", timed_out, "enum")
            cause = (f"jax device probe ({kind}): {msg} after "
                     f"{timeout_s}s budget, attempt {i + 1}/{n}")
            log(f"  {cause}")
            continue
        # phase 2: a tiny computation through the data path
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp; "
                 "jnp.ones((8,)).block_until_ready()"],
                timeout=timeout_s, capture_output=True, text=True)
            timed_out = False
        except subprocess.TimeoutExpired:
            r, timed_out = None, True
        if timed_out or r.returncode != 0:
            kind, msg = _classify_probe_failure(
                r.stderr if r is not None else "", timed_out, "compute")
            cause = (f"jax compute probe ({kind}): {msg} after "
                     f"{timeout_s}s budget, attempt {i + 1}/{n}")
            log(f"  {cause}")
            continue
        return True, "", ""
    return False, kind, cause


def main():
    details = {}
    log("bench: unary echo (python service)...")
    details["echo"] = bench_unary_echo()
    log(f"  {details['echo']}")
    log("bench: native echo...")
    details["native_echo"] = bench_native_echo()
    log(f"  {details['native_echo']}")
    log("bench: echo thread-scaling (python service)...")
    details["echo_scaling"] = bench_echo_scaling()
    log(f"  {details['echo_scaling']}")
    log("bench: native echo connection-scaling...")
    details["native_echo_scaling"] = bench_native_echo_scaling()
    log(f"  {details['native_echo_scaling']}")
    log("bench: grpc echo (h2 python data plane)...")
    try:
        details["grpc_echo"] = bench_grpc_echo()
    except Exception as e:
        details["grpc_echo"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['grpc_echo']}")
    log("bench: dcn data plane (two processes, loopback)...")
    try:
        details["dcn"] = bench_dcn()
    except Exception as e:
        details["dcn"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['dcn']}")
    log("bench: per-stage host microbenches (subprocess, forced CPU)...")
    try:
        details["microbench"] = _run_microbench_subprocess()
    except Exception as e:
        details["microbench"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['microbench']}")
    log("bench: kv page migration (subprocess, forced CPU)...")
    try:
        details["migrate"] = _run_cpu_subcommand("migrate")
    except Exception as e:
        details["migrate"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['migrate']}")
    log("bench: cluster front door (subprocess, forced CPU)...")
    try:
        details["cluster"] = _run_cpu_subcommand("cluster")
    except Exception as e:
        details["cluster"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['cluster']}")
    log("bench: durable control plane (subprocess, forced CPU)...")
    try:
        details["durable"] = _run_cpu_subcommand("durable")
    except Exception as e:
        details["durable"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['durable']}")
    log("bench: multi-model plane (subprocess, forced CPU)...")
    try:
        details["multimodel"] = _run_cpu_subcommand("multimodel")
    except Exception as e:
        details["multimodel"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['multimodel']}")
    log("bench: fleet telemetry plane (subprocess, forced CPU)...")
    try:
        details["telemetry"] = _run_cpu_subcommand("telemetry")
    except Exception as e:
        details["telemetry"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['telemetry']}")
    log("bench: real-model serving (subprocess, forced CPU)...")
    try:
        details["model"] = _run_cpu_subcommand("model")
    except Exception as e:
        details["model"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['model']}")
    log("bench: speculative decoding (subprocess, forced CPU)...")
    try:
        details["speculative"] = _run_cpu_subcommand("speculative")
    except Exception as e:
        details["speculative"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['speculative']}")
    log("bench: sharded parameter server (subprocess, forced CPU)...")
    try:
        details["embedding"] = _run_cpu_subcommand("embedding")
    except Exception as e:
        details["embedding"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['embedding']}")
    log("bench: training plane (subprocess, forced CPU)...")
    try:
        details["train"] = _run_cpu_subcommand("train")
    except Exception as e:
        details["train"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['train']}")
    log("bench: probing device reachability...")
    device_ok, skip_kind, device_err = _probe_device()
    if not device_ok:
        log(f"  {device_err}; skipping device benches")
    log("bench: serving dynamic batcher...")
    if not device_ok:
        # r5 bench discipline: a rung that cannot run must SAY so —
        # never publish a fallback wearing the metric's name; ISSUE 6
        # adds the skip_reason KIND (no-device / wedge-deadline /
        # exception) so the trajectory records WHY
        details["serving"] = _skip_entry(skip_kind, device_err)
    else:
        try:
            details["serving"] = bench_serving()
        except Exception as e:
            details["serving"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['serving']}")
    log("bench: paged kv cache...")
    if not device_ok:
        details["kvcache"] = _skip_entry(skip_kind, device_err)
    else:
        try:
            details["kvcache"] = bench_kvcache()
        except Exception as e:
            details["kvcache"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['kvcache']}")
    log("bench: engine crash recovery...")
    if not device_ok:
        details["recovery"] = _skip_entry(skip_kind, device_err)
    else:
        try:
            details["recovery"] = bench_recovery()
        except Exception as e:
            details["recovery"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['recovery']}")
    log("bench: rpcz trace overhead...")
    if not device_ok:
        details["trace_overhead"] = _skip_entry(skip_kind, device_err)
    else:
        try:
            details["trace_overhead"] = bench_trace_overhead()
        except Exception as e:
            details["trace_overhead"] = {"error": f"{type(e).__name__}: {e}"}
    log(f"  {details['trace_overhead']}")
    # each bench is isolated: a failure in one must not clobber another's
    # already-valid result
    for name, fn in (("tensor_pipe", lambda: bench_tensor_pipe(chunk_mb=64)),
                     ("streaming_tensor", bench_streaming_tensor),
                     ("hbm_stream", bench_hbm_stream),
                     ("ici_ladder", bench_ici_ladder)):
        if not device_ok:
            details[name] = {"error": device_err,
                             **_skip_entry(skip_kind, device_err)}
            continue
        log(f"bench: {name}...")
        try:
            details[name] = fn()
            log(f"  {details[name]}")
        except Exception as e:
            log(f"  {name} unavailable: {e}")
            details[name] = {"error": f"{type(e).__name__}: {e}"}
    headline = details["tensor_pipe"].get("gbps")
    # VERDICT r4 weak #1: a skipped device bench must SAY "skipped" — never
    # publish a fallback value wearing the device metric's name.  The
    # native-echo figure rides along under its own explicit key.
    skipped = headline is None
    if skipped:
        details["headline_skip_reason"] = details["tensor_pipe"].get(
            "error") or "tensor_pipe gated/failed"
    import platform
    try:
        if not device_ok:
            raise RuntimeError("device unreachable")
        import jax
        details["platform"] = str(jax.devices()[0])
    except Exception:
        details["platform"] = platform.machine()
    # Details are deliberately NOT on stdout: round 3's single giant JSON
    # line outgrew the driver's tail buffer and the headline was lost
    # (BENCH_r03 parsed: null).  Per-bench results go to stderr line by
    # line plus a sidecar file; the LAST stdout line is the compact
    # machine-readable headline only.
    for name, d in details.items():
        log(f"detail {name}: {json.dumps(d)}")
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_DETAILS.json"), "w") as f:
            json.dump(details, f, indent=1)
    except OSError as e:
        log(f"could not write BENCH_DETAILS.json: {e}")
    line = {
        "metric": "tensor_pipe_throughput",
        "value": headline,
        "unit": "GB/s",
        "vs_baseline": (round(headline / BASELINE_GBPS, 2)
                        if headline is not None else None),
    }
    if skipped:
        line["skipped"] = True
        line["skip_reason"] = details["headline_skip_reason"]
        line["fallback_native_echo_gbps"] = round(
            details["native_echo"]["qps"] * 128 / 1e9, 6)
    print(json.dumps(line))


def microbench_main(argv) -> None:
    """`python bench.py microbench [--quick]`: run ONLY the per-stage
    host microbench suite and print one JSON object on stdout (progress
    on stderr) — the `make microbench` entry and the subprocess the
    full bench run shells out to."""
    quick = "--quick" in argv
    log(f"microbench: per-stage host suite{' (quick)' if quick else ''}...")
    out = bench_microbench(quick=quick)
    for k, v in out.items():
        if isinstance(v, dict):
            log(f"  {k}: {json.dumps(v)}")
    print(json.dumps(out))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "microbench":
        microbench_main(sys.argv[2:])
    elif len(sys.argv) > 1 and sys.argv[1] == "migrate":
        migrate_main(sys.argv[2:])
    elif len(sys.argv) > 1 and sys.argv[1] == "cluster":
        cluster_main(sys.argv[2:])
    elif len(sys.argv) > 1 and sys.argv[1] == "durable":
        durable_main(sys.argv[2:])
    elif len(sys.argv) > 1 and sys.argv[1] == "multimodel":
        multimodel_main(sys.argv[2:])
    elif len(sys.argv) > 1 and sys.argv[1] == "telemetry":
        telemetry_main(sys.argv[2:])
    elif len(sys.argv) > 1 and sys.argv[1] == "model":
        model_main(sys.argv[2:])
    elif len(sys.argv) > 1 and sys.argv[1] == "speculative":
        speculative_main(sys.argv[2:])
    elif len(sys.argv) > 1 and sys.argv[1] == "embedding":
        embedding_main(sys.argv[2:])
    elif len(sys.argv) > 1 and sys.argv[1] == "train":
        train_main(sys.argv[2:])
    else:
        main()
