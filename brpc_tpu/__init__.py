"""tpu-rpc: a TPU-native RPC framework with the capabilities of Apache bRPC.

Built from scratch against the structural analysis in SURVEY.md:
  * native C++ host core (src/cc/): zero-copy IOBuf, work-stealing executor,
    timer thread, epoll socket core with wait-free writes, wire framing
  * Python protocol/API layer: Channel/Controller/Server, combo channels,
    load balancing, naming, health checking, circuit breaking, streaming,
    bvar metrics, builtin HTTP console
  * TPU-native transport (brpc_tpu.ici): IOBuf blocks in HBM, chip-to-chip
    streaming via XLA collectives, fan-out lowered to ppermute/all_gather
"""
__version__ = "0.1.0"

from brpc_tpu import errors  # noqa: F401
from brpc_tpu.errors import RpcError  # noqa: F401
from brpc_tpu.rpc import (  # noqa: F401
    Authenticator, CallManager, CallMapper, Channel, ChannelOptions,
    Controller, DynamicPartitionChannel, GrpcChannel, HmacAuthenticator,
    MethodStatus, ParallelChannel, PartitionChannel, PartitionParser,
    DataFactory, HttpChannel, HttpResponse, HttpStreamReader,
    MemcacheChannel, MemcacheError, MemcacheService, MemoryMemcacheService,
    MemoryRedisService, MongoClient, MongoService, ProgressiveAttachment,
    ProgressiveResponse, RedisChannel, RedisError, RedisPipeline,
    RedisService, ResponseMerger, RetryPolicy, SelectiveChannel, Server,
    ServerOptions, Service, SimpleDataPool, SocketMap, Stream,
    StreamHandler, SubCall, SumMerger, TField, ThriftChannel, ThriftError,
    ThriftService, TokenAuthenticator, method, stream_accept,
    stream_create,
)
from brpc_tpu.rpc.service import MethodSpec  # noqa: F401
from brpc_tpu.butil.endpoint import EndPoint, str2endpoint  # noqa: F401
from brpc_tpu import bvar  # noqa: F401
from brpc_tpu import fault  # noqa: F401
from brpc_tpu import flags  # noqa: F401
from brpc_tpu import rpcz  # noqa: F401
