from brpc_tpu._core.lib import (  # noqa: F401
    core,
    core_init,
    core_shutdown,
    IOBuf,
    MESSAGE_CB,
    FAILED_CB,
    ACCEPTED_CB,
    TASK_CB,
    MSG_TRPC,
    MSG_HTTP,
)
