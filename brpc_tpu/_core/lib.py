"""ctypes bindings to libbrpc_core.so — the native host core.

The native core owns the transport hot path (epoll dispatchers, wait-free
socket writes, frame parsing, IOBuf block management, work-stealing executor,
timer thread); Python is the protocol/API layer above it, mirroring how the
reference layers generated protobuf stubs over its C++ core.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

MSG_TRPC = 0
MSG_HTTP = 1
MSG_REDIS = 2
MSG_MEMCACHE = 3
MSG_THRIFT = 4
MSG_MONGO = 5
MSG_H2 = 6
MSG_RAW = 7
MSG_NSHEAD = 8
MSG_FILTERED = 9   # transport-filter ciphertext (in-socket TLS)

_here = os.path.dirname(os.path.abspath(__file__))
_libpath = os.path.join(_here, "libbrpc_core.so")


def _build_if_needed() -> None:
    if os.path.exists(_libpath) and \
            os.path.exists(os.path.join(_here, "_fastrpc.so")):
        return
    repo = os.path.dirname(os.path.dirname(_here))
    subprocess.run(["make", "-j8"], cwd=repo, check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


_build_if_needed()
core = ctypes.CDLL(_libpath)

# Callback signatures (see src/cc/capi.cc).
# meta is c_void_p, NOT c_char_p: meta is opaque binary (may contain NULs) and
# ctypes would strlen-truncate a c_char_p argument.  Read it with
# ctypes.string_at(meta, meta_len).
MESSAGE_CB = ctypes.CFUNCTYPE(None, ctypes.c_uint64, ctypes.c_int,
                              ctypes.c_void_p, ctypes.c_size_t,
                              ctypes.c_void_p, ctypes.c_void_p)
FAILED_CB = ctypes.CFUNCTYPE(None, ctypes.c_uint64, ctypes.c_int,
                             ctypes.c_void_p)
ACCEPTED_CB = ctypes.CFUNCTYPE(None, ctypes.c_uint64, ctypes.c_uint64,
                               ctypes.c_void_p)
TASK_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
DELETER_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p)


class RequestHeader(ctypes.Structure):
    """Mirror of brpc::RequestHeader (src/cc/net/rpc.h) — a natively
    pre-parsed TRPC meta.  Pointer fields alias the native meta buffer and
    are only valid during the callback."""
    _fields_ = [
        ("cid", ctypes.c_uint64),
        ("timeout_ms", ctypes.c_uint32),
        ("present_mask", ctypes.c_uint32),
        ("service", ctypes.c_void_p),
        ("service_len", ctypes.c_uint32),
        ("method", ctypes.c_void_p),
        ("method_len", ctypes.c_uint32),
        ("attempt", ctypes.c_uint16),
        ("compress", ctypes.c_uint8),
        ("msg_type", ctypes.c_uint8),
        ("content_type", ctypes.c_void_p),
        ("content_type_len", ctypes.c_uint32),
        ("error_code", ctypes.c_int32),
        ("error_text", ctypes.c_void_p),
        ("error_text_len", ctypes.c_uint32),
        ("attachment_size", ctypes.c_uint64),
    ]


REQUEST_CB = ctypes.CFUNCTYPE(None, ctypes.c_uint64,
                              ctypes.POINTER(RequestHeader), ctypes.c_void_p,
                              ctypes.c_void_p)
RESPONSE_CB = ctypes.CFUNCTYPE(None, ctypes.c_uint64,
                               ctypes.POINTER(RequestHeader), ctypes.c_void_p,
                               ctypes.c_void_p)
NATIVE_METHOD_FN = ctypes.CFUNCTYPE(ctypes.c_int32, ctypes.c_uint64,
                                    ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_void_p)
# Native h2 session event (src/cc/net/h2.h H2EventCallback): sid,
# stream_id, kind, service/len, method/len, headers/len ("k\0v\0" pairs),
# body IOBuf* (owned by callee; may be NULL), grpc message flags, user.
H2_EVENT_CB = ctypes.CFUNCTYPE(None, ctypes.c_uint64, ctypes.c_uint32,
                               ctypes.c_int, ctypes.c_void_p,
                               ctypes.c_size_t, ctypes.c_void_p,
                               ctypes.c_size_t, ctypes.c_void_p,
                               ctypes.c_size_t, ctypes.c_void_p,
                               ctypes.c_int, ctypes.c_void_p)

_sigs = {
    "brpc_core_init": (None, [ctypes.c_int, ctypes.c_int]),
    "brpc_core_shutdown": (None, []),
    "brpc_set_min_log_level": (None, [ctypes.c_int]),
    "brpc_crc32c": (ctypes.c_uint32, [ctypes.c_char_p, ctypes.c_size_t,
                                      ctypes.c_uint32]),
    # snappy block-format codec (butil/snappy.cc)
    "brpc_snappy_max_compressed_length": (ctypes.c_size_t,
                                          [ctypes.c_size_t]),
    "brpc_snappy_compress": (ctypes.c_size_t,
                             [ctypes.c_char_p, ctypes.c_size_t,
                              ctypes.c_void_p]),
    "brpc_snappy_uncompressed_length": (ctypes.c_int64,
                                        [ctypes.c_char_p, ctypes.c_size_t]),
    "brpc_snappy_decompress": (ctypes.c_int,
                               [ctypes.c_char_p, ctypes.c_size_t,
                                ctypes.c_void_p, ctypes.c_size_t]),
    # native CPU profiler (butil/profiler.cc)
    "brpc_prof_start": (ctypes.c_int, [ctypes.c_int]),
    "brpc_prof_stop": (ctypes.c_int, []),
    "brpc_prof_dump": (ctypes.c_int, [ctypes.c_char_p]),
    "brpc_prof_folded": (ctypes.c_int, [ctypes.c_char_p, ctypes.c_size_t]),
    "brpc_prof_samples": (ctypes.c_int64, []),
    "brpc_iobuf_new": (ctypes.c_void_p, []),
    "brpc_iobuf_free": (None, [ctypes.c_void_p]),
    "brpc_iobuf_clear": (None, [ctypes.c_void_p]),
    "brpc_iobuf_size": (ctypes.c_size_t, [ctypes.c_void_p]),
    "brpc_iobuf_block_num": (ctypes.c_size_t, [ctypes.c_void_p]),
    "brpc_iobuf_append": (None, [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]),
    "brpc_iobuf_append_iobuf": (None, [ctypes.c_void_p, ctypes.c_void_p]),
    "brpc_iobuf_copy_to": (ctypes.c_size_t, [ctypes.c_void_p, ctypes.c_void_p,
                                             ctypes.c_size_t, ctypes.c_size_t]),
    "brpc_iobuf_cutn": (ctypes.c_size_t, [ctypes.c_void_p, ctypes.c_void_p,
                                          ctypes.c_size_t]),
    "brpc_iobuf_pop_front": (ctypes.c_size_t, [ctypes.c_void_p, ctypes.c_size_t]),
    "brpc_iobuf_append_user_data": (None, [ctypes.c_void_p, ctypes.c_void_p,
                                           ctypes.c_size_t, DELETER_CB,
                                           ctypes.c_void_p]),
    "brpc_iobuf_live_blocks": (ctypes.c_int64, []),
    "brpc_executor_submit": (None, [TASK_CB, ctypes.c_void_p]),
    "brpc_executor_tasks_executed": (ctypes.c_int64, []),
    "brpc_executor_steals": (ctypes.c_int64, []),
    "brpc_fiber_counters": (None, [ctypes.POINTER(ctypes.c_int64),
                                   ctypes.POINTER(ctypes.c_int64),
                                   ctypes.POINTER(ctypes.c_int64),
                                   ctypes.POINTER(ctypes.c_int64)]),
    "brpc_executor_num_workers": (ctypes.c_int, []),
    "brpc_timer_add": (ctypes.c_uint64, [TASK_CB, ctypes.c_void_p, ctypes.c_int64]),
    "brpc_timer_cancel": (ctypes.c_int, [ctypes.c_uint64]),
    "brpc_timer_fired": (ctypes.c_int64, []),
    "brpc_now_us": (ctypes.c_int64, []),
    "brpc_listen": (ctypes.c_int, [ctypes.c_char_p, ctypes.c_int, MESSAGE_CB,
                                   FAILED_CB, ACCEPTED_CB, ctypes.c_void_p,
                                   ctypes.c_int, ctypes.POINTER(ctypes.c_uint64),
                                   ctypes.POINTER(ctypes.c_int)]),
    "brpc_connect": (ctypes.c_int, [ctypes.c_char_p, ctypes.c_int, MESSAGE_CB,
                                    FAILED_CB, ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_uint64)]),
    "brpc_socket_write_frame": (ctypes.c_int, [ctypes.c_uint64, ctypes.c_char_p,
                                               ctypes.c_size_t, ctypes.c_char_p,
                                               ctypes.c_size_t, ctypes.c_void_p]),
    "brpc_socket_write_raw": (ctypes.c_int, [ctypes.c_uint64, ctypes.c_char_p,
                                             ctypes.c_size_t, ctypes.c_void_p]),
    "brpc_socket_set_protocol": (ctypes.c_int, [ctypes.c_uint64, ctypes.c_int]),
    # transport filter (in-socket TLS)
    "brpc_socket_set_filter": (ctypes.c_int, [ctypes.c_uint64, ctypes.c_int]),
    "brpc_socket_inject": (ctypes.c_int, [ctypes.c_uint64, ctypes.c_char_p,
                                          ctypes.c_size_t]),
    "brpc_socket_set_failed": (ctypes.c_int, [ctypes.c_uint64, ctypes.c_int]),
    "brpc_socket_alive": (ctypes.c_int, [ctypes.c_uint64]),
    "brpc_socket_stats": (ctypes.c_int, [ctypes.c_uint64,
                                         ctypes.POINTER(ctypes.c_int64),
                                         ctypes.POINTER(ctypes.c_int64),
                                         ctypes.POINTER(ctypes.c_int64),
                                         ctypes.c_char_p, ctypes.c_int,
                                         ctypes.POINTER(ctypes.c_int)]),
    "brpc_socket_active_count": (ctypes.c_int64, []),
    "brpc_socket_traffic": (None, [ctypes.POINTER(ctypes.c_int64),
                                   ctypes.POINTER(ctypes.c_int64),
                                   ctypes.POINTER(ctypes.c_int64)]),
    # bvar combiners (per-thread cells, src/cc/bvar/combiner.h)
    "brpc_atomic_new": (ctypes.c_void_p, []),
    "brpc_atomic_free": (None, [ctypes.c_void_p]),
    "brpc_atomic_incr": (ctypes.c_int64, [ctypes.c_void_p, ctypes.c_int64]),
    "brpc_atomic_get": (ctypes.c_int64, [ctypes.c_void_p]),
    "brpc_adder_new": (ctypes.c_void_p, []),
    "brpc_adder_free": (None, [ctypes.c_void_p]),
    "brpc_adder_add": (None, [ctypes.c_void_p, ctypes.c_int64]),
    "brpc_adder_get": (ctypes.c_int64, [ctypes.c_void_p]),
    "brpc_latency_new": (ctypes.c_void_p, []),
    "brpc_latency_free": (None, [ctypes.c_void_p]),
    "brpc_latency_record": (None, [ctypes.c_void_p, ctypes.c_int64]),
    "brpc_latency_stats": (None, [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_int64),
                                  ctypes.POINTER(ctypes.c_int64),
                                  ctypes.POINTER(ctypes.c_int64)]),
    "brpc_latency_percentile": (ctypes.c_double, [ctypes.c_void_p,
                                                  ctypes.c_double]),
    "brpc_socket_set_overcrowded_limit": (None, [ctypes.c_int64]),
    "brpc_socket_overcrowded_limit": (ctypes.c_int64, []),
    "brpc_socket_pending_write": (ctypes.c_int64, [ctypes.c_uint64]),
    # native unary RPC hot path
    "brpc_register_python_method": (None, [ctypes.c_char_p, ctypes.c_char_p]),
    "brpc_register_native_method": (None, [ctypes.c_char_p, ctypes.c_char_p,
                                           NATIVE_METHOD_FN, ctypes.c_void_p,
                                           ctypes.c_int]),
    "brpc_unregister_method": (ctypes.c_int, [ctypes.c_char_p,
                                              ctypes.c_char_p]),
    "brpc_set_request_callback": (None, [REQUEST_CB, ctypes.c_void_p]),
    "brpc_rpc_dropped_responses": (ctypes.c_int64, []),
    "brpc_rpc_counters": (None, [ctypes.POINTER(ctypes.c_int64),
                                 ctypes.POINTER(ctypes.c_int64)]),
    "brpc_send_response": (ctypes.c_int, [ctypes.c_uint64, ctypes.c_uint64,
                                          ctypes.c_uint16, ctypes.c_int32,
                                          ctypes.c_char_p, ctypes.c_char_p,
                                          ctypes.c_char_p, ctypes.c_size_t,
                                          ctypes.c_void_p]),
    "brpc_send_request": (ctypes.c_int, [ctypes.c_uint64, ctypes.c_uint64,
                                         ctypes.c_uint16, ctypes.c_char_p,
                                         ctypes.c_char_p, ctypes.c_uint32,
                                         ctypes.c_uint8, ctypes.c_char_p,
                                         ctypes.c_char_p, ctypes.c_size_t,
                                         ctypes.c_void_p]),
    "brpc_listen_rpc": (ctypes.c_int, [ctypes.c_char_p, ctypes.c_int,
                                       MESSAGE_CB, FAILED_CB, ACCEPTED_CB,
                                       ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_uint64),
                                       ctypes.POINTER(ctypes.c_int)]),
    "brpc_connect_rpc": (ctypes.c_int, [ctypes.c_char_p, ctypes.c_int,
                                        MESSAGE_CB, FAILED_CB, RESPONSE_CB,
                                        ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_uint64)]),
    # native h2/gRPC server data plane (src/cc/net/h2.h)
    "brpc_listen_rpc_h2": (ctypes.c_int, [ctypes.c_char_p, ctypes.c_int,
                                          MESSAGE_CB, FAILED_CB, ACCEPTED_CB,
                                          ctypes.c_void_p,
                                          ctypes.POINTER(ctypes.c_uint64),
                                          ctypes.POINTER(ctypes.c_int)]),
    "brpc_h2_set_event_cb": (None, [H2_EVENT_CB, ctypes.c_void_p]),
    "brpc_h2_respond_unary": (ctypes.c_int, [ctypes.c_uint64,
                                             ctypes.c_uint32, ctypes.c_int,
                                             ctypes.c_char_p,
                                             ctypes.c_size_t,
                                             ctypes.c_char_p,
                                             ctypes.c_size_t,
                                             ctypes.c_char_p,
                                             ctypes.c_size_t]),
    "brpc_h2_send_response_headers": (ctypes.c_int, [ctypes.c_uint64,
                                                     ctypes.c_uint32,
                                                     ctypes.c_char_p,
                                                     ctypes.c_size_t]),
    "brpc_h2_send_message": (ctypes.c_int, [ctypes.c_uint64,
                                            ctypes.c_uint32,
                                            ctypes.c_char_p, ctypes.c_size_t,
                                            ctypes.c_int]),
    "brpc_h2_send_trailers": (ctypes.c_int, [ctypes.c_uint64,
                                             ctypes.c_uint32, ctypes.c_int,
                                             ctypes.c_char_p,
                                             ctypes.c_size_t,
                                             ctypes.c_char_p,
                                             ctypes.c_size_t]),
    "brpc_h2_native_stats": (None, [ctypes.POINTER(ctypes.c_int64),
                                    ctypes.POINTER(ctypes.c_int64),
                                    ctypes.POINTER(ctypes.c_int64)]),
    # gRPC unary pump against an existing server's NATIVE h2 plane
    "brpc_bench_register_native_echo": (None, [ctypes.c_char_p,
                                               ctypes.c_char_p,
                                               ctypes.c_int]),
    "brpc_bench_pump_h2": (ctypes.c_int, [ctypes.c_int, ctypes.c_char_p,
                                          ctypes.c_int, ctypes.c_int,
                                          ctypes.c_uint64, ctypes.c_int,
                                          ctypes.POINTER(ctypes.c_double),
                                          ctypes.POINTER(ctypes.c_double),
                                          ctypes.POINTER(ctypes.c_double)]),
    "brpc_bench_echo": (ctypes.c_int, [ctypes.c_int, ctypes.c_int,
                                       ctypes.c_uint64, ctypes.c_int,
                                       ctypes.c_int,
                                       ctypes.POINTER(ctypes.c_double),
                                       ctypes.POINTER(ctypes.c_double),
                                       ctypes.POINTER(ctypes.c_double)]),
    # native client pump against an EXISTING server (Python handlers):
    # port, service, method, conns, inflight, total, payload_len,
    # out: success qps, p50, p99, err_frac (sheds/errors; nullable)
    "brpc_bench_pump": (ctypes.c_int, [ctypes.c_int, ctypes.c_char_p,
                                       ctypes.c_char_p, ctypes.c_int,
                                       ctypes.c_int, ctypes.c_uint64,
                                       ctypes.c_int,
                                       ctypes.POINTER(ctypes.c_double),
                                       ctypes.POINTER(ctypes.c_double),
                                       ctypes.POINTER(ctypes.c_double),
                                       ctypes.POINTER(ctypes.c_double)]),
    # usercode admission control (net/rpc.h; latency-budget ELIMIT sheds)
    "brpc_set_usercode_budget_us": (None, [ctypes.c_int64]),
    "brpc_usercode_budget_us": (ctypes.c_int64, []),
    "brpc_usercode_shed_count": (ctypes.c_int64, []),
    "brpc_usercode_pending": (ctypes.c_int64, []),
    "brpc_usercode_ema_us": (ctypes.c_double, []),
    "brpc_set_usercode_inline": (None, [ctypes.c_int]),
    "brpc_usercode_inline": (ctypes.c_int, []),
    # contention sampler (per-site stacks on contended FiberMutex locks)
    "brpc_contention_folded": (ctypes.c_int, [ctypes.c_char_p,
                                              ctypes.c_size_t]),
    "brpc_contention_events": (ctypes.c_int64, []),
    "brpc_contention_samples": (ctypes.c_int64, []),
    "brpc_contention_reset": (None, []),
    "brpc_contention_selftest": (ctypes.c_int, [ctypes.c_int, ctypes.c_int,
                                                ctypes.c_int]),
    # IOBuf block-allocation-site sampler (/memory)
    "brpc_iobuf_alloc_folded": (ctypes.c_int, [ctypes.c_char_p,
                                               ctypes.c_size_t]),
    "brpc_iobuf_alloc_events": (ctypes.c_int64, []),
    "brpc_iobuf_alloc_reset": (None, []),
    # fiber / butex (coroutine M:N runtime, src/cc/bthread/fiber.h)
    "brpc_fiber_demo_start": (ctypes.c_void_p, [ctypes.c_int]),
    "brpc_fiber_demo_blocked": (ctypes.c_int, [ctypes.c_void_p]),
    "brpc_fiber_demo_started": (ctypes.c_int64, [ctypes.c_void_p]),
    "brpc_fiber_demo_release": (None, [ctypes.c_void_p]),
    "brpc_fiber_demo_join": (ctypes.c_int, [ctypes.c_void_p, ctypes.c_int]),
    "brpc_fiber_demo_free": (None, [ctypes.c_void_p]),
    "brpc_fiber_pingpong": (ctypes.c_int, [ctypes.c_int, ctypes.c_int]),
    "brpc_fiber_mutex_stress": (ctypes.c_int64, [ctypes.c_int, ctypes.c_int,
                                                 ctypes.c_int]),
    "brpc_fiber_sleep_probe": (ctypes.c_int64, [ctypes.c_int64,
                                                ctypes.c_int]),
    "brpc_fiber_cond_stress": (ctypes.c_int64, [ctypes.c_int64,
                                                ctypes.c_int]),
    # CallId (bthread_id analog, src/cc/bthread/id.h)
    # fd wait (net/fd_wait.h): events bit1=read, bit2=write
    "brpc_fd_wait": (ctypes.c_int, [ctypes.c_int, ctypes.c_uint32,
                                    ctypes.c_int]),
    "brpc_fiber_fd_wait_probe": (ctypes.c_int, [ctypes.c_int,
                                                ctypes.c_uint32,
                                                ctypes.c_int]),
    "brpc_id_create": (ctypes.c_uint64, [ctypes.c_uint32]),
    "brpc_id_valid": (ctypes.c_int, [ctypes.c_uint64]),
    "brpc_id_trylock": (ctypes.c_int, [ctypes.c_uint64]),
    "brpc_id_unlock": (ctypes.c_int, [ctypes.c_uint64]),
    "brpc_id_unlock_and_destroy": (ctypes.c_int, [ctypes.c_uint64]),
    "brpc_id_join": (ctypes.c_int, [ctypes.c_uint64, ctypes.c_int]),
    "brpc_id_live_count": (ctypes.c_int64, []),
    "brpc_id_lock_stress": (ctypes.c_int64, [ctypes.c_int, ctypes.c_int,
                                             ctypes.c_int]),
    "brpc_id_destroy_stress": (ctypes.c_int64, [ctypes.c_int,
                                                ctypes.c_int]),
    "brpc_fiber_sem_stress": (ctypes.c_int, [ctypes.c_int, ctypes.c_int,
                                             ctypes.c_int, ctypes.c_int]),
    "brpc_fiber_rw_stress": (ctypes.c_int64, [ctypes.c_int, ctypes.c_int,
                                              ctypes.c_int]),
    # native serving hot path (ISSUE 9; src/cc/serving_hotpath.cc):
    # bounded emit token rings with batch push/pop, batch-formation
    # pad, page-table gather — ctypes releases the GIL for each call
    "brpc_tokring_new": (ctypes.c_void_p, [ctypes.c_int]),
    "brpc_tokring_free": (None, [ctypes.c_void_p]),
    "brpc_tokring_live": (ctypes.c_int64, []),
    "brpc_tokring_push": (ctypes.c_int, [ctypes.c_void_p, ctypes.c_int32]),
    "brpc_tokring_push_many": (ctypes.c_int,
                               [ctypes.POINTER(ctypes.c_void_p),
                                ctypes.POINTER(ctypes.c_int32),
                                ctypes.c_int,
                                ctypes.POINTER(ctypes.c_uint8)]),
    "brpc_tokring_push_terminal": (ctypes.c_int, [ctypes.c_void_p,
                                                  ctypes.c_int32]),
    "brpc_tokring_pop_many": (ctypes.c_int,
                              [ctypes.c_void_p,
                               ctypes.POINTER(ctypes.c_int32),
                               ctypes.c_int, ctypes.c_int64,
                               ctypes.POINTER(ctypes.c_int),
                               ctypes.POINTER(ctypes.c_int32)]),
    "brpc_tokring_size": (ctypes.c_int64, [ctypes.c_void_p]),
    # native flight recorder (ISSUE 15; src/cc/butil/flight.h):
    # always-on per-thread event rings in the C++ core — merged dump,
    # per-thread last-event table, stats, and the forced-stall probe
    "brpc_flight_enable": (None, [ctypes.c_int]),
    "brpc_flight_enabled": (ctypes.c_int, []),
    "brpc_flight_dump": (ctypes.c_int, [ctypes.c_char_p, ctypes.c_size_t,
                                        ctypes.c_int]),
    "brpc_flight_threads": (ctypes.c_int, [ctypes.c_char_p,
                                           ctypes.c_size_t]),
    "brpc_flight_stats": (None, [ctypes.POINTER(ctypes.c_int64),
                                 ctypes.POINTER(ctypes.c_int64),
                                 ctypes.POINTER(ctypes.c_int64)]),
    "brpc_flight_selftest_emit": (None, [ctypes.c_int, ctypes.c_uint64]),
    "brpc_flight_stall_probe": (ctypes.c_int, [ctypes.c_int]),
    # syscall attribution (ISSUE 15 satellite; ROADMAP 1(e))
    "brpc_syscall_counters": (None, [ctypes.POINTER(ctypes.c_int64),
                                     ctypes.POINTER(ctypes.c_int64),
                                     ctypes.POINTER(ctypes.c_int64),
                                     ctypes.POINTER(ctypes.c_int64)]),
    "brpc_write_size_hist": (ctypes.c_int, [ctypes.POINTER(ctypes.c_int64),
                                            ctypes.c_int]),
    "brpc_socket_syscalls": (ctypes.c_int, [ctypes.c_uint64,
                                            ctypes.POINTER(ctypes.c_int64),
                                            ctypes.POINTER(ctypes.c_int64)]),
    "brpc_batch_pad": (None, [ctypes.POINTER(ctypes.c_void_p),
                              ctypes.POINTER(ctypes.c_int64),
                              ctypes.c_int, ctypes.c_void_p,
                              ctypes.c_int64, ctypes.c_int64]),
    "brpc_page_table_fill": (None, [ctypes.POINTER(ctypes.c_void_p),
                                    ctypes.POINTER(ctypes.c_int64),
                                    ctypes.POINTER(ctypes.c_int32),
                                    ctypes.c_int, ctypes.c_void_p,
                                    ctypes.c_int, ctypes.c_int]),
}
for _name, (_res, _args) in _sigs.items():
    fn = getattr(core, _name)
    fn.restype = _res
    fn.argtypes = _args

_init_lock = threading.Lock()
_initialized = False


_fastrpc_cache = None
_fastrpc_attempts = 0


def _fastrpc_mod():
    """The _fastrpc C extension, or None while it is still being built
    (lazy: importing it at module scope would recurse through the
    build-on-import path).  Permanent failure is cached after a few
    tries — failed imports aren't in sys.modules, and paying the import
    machinery + ImportError on every to_bytes would tax the very hot
    path this accelerates."""
    global _fastrpc_cache, _fastrpc_attempts
    if _fastrpc_cache is None and _fastrpc_attempts < 3:
        _fastrpc_attempts += 1
        try:
            from brpc_tpu._core import _fastrpc as fb
            _fastrpc_cache = fb
        except Exception:
            return None
    return _fastrpc_cache


def core_init(num_workers: int = 0, num_dispatchers: int = 0) -> None:
    """Start the native executor, dispatchers and timer thread (idempotent).
    num_dispatchers=0 lets the native core size the epoll pool by CPU
    count (1 on small hosts — extra epoll threads only time-slice and
    inflate the p99 tail by whole scheduler quanta)."""
    global _initialized
    with _init_lock:
        if not _initialized:
            core.brpc_core_init(num_workers, num_dispatchers)
            _initialized = True


def core_shutdown() -> None:
    global _initialized
    with _init_lock:
        if _initialized:
            core.brpc_core_shutdown()
            _initialized = False


class IOBuf:
    """Python view of a native zero-copy chained buffer.

    Wraps the native butil::IOBuf (src/cc/butil/iobuf.h).  Appending shares
    or copies into refcounted 8KB blocks; moving data between IOBufs
    (``append_iobuf``, ``cutn``) never copies payload bytes.
    """

    __slots__ = ("handle", "_owned")

    def __init__(self, data: bytes | None = None, *, handle: int | None = None):
        if handle is not None:
            self.handle = handle
            self._owned = True
        else:
            self.handle = core.brpc_iobuf_new()
            self._owned = True
        if data:
            self.append(data)

    def __del__(self):
        h = getattr(self, "handle", None)
        if h and self._owned:
            core.brpc_iobuf_free(h)
            self.handle = None

    def __len__(self) -> int:
        return core.brpc_iobuf_size(self.handle)

    @property
    def block_count(self) -> int:
        return core.brpc_iobuf_block_num(self.handle)

    def append(self, data: bytes) -> None:
        core.brpc_iobuf_append(self.handle, data, len(data))

    def append_iobuf(self, other: "IOBuf") -> None:
        core.brpc_iobuf_append_iobuf(self.handle, other.handle)

    def cutn(self, n: int) -> "IOBuf":
        out = IOBuf()
        core.brpc_iobuf_cutn(self.handle, out.handle, n)
        return out

    def pop_front(self, n: int) -> int:
        return core.brpc_iobuf_pop_front(self.handle, n)

    def to_bytes(self, n: int | None = None, pos: int = 0) -> bytes:
        fb = _fastrpc_mod()
        if fb is not None:
            # single copy straight into the bytes object (the ctypes
            # fallback below pays two copies plus a zero-init)
            return fb.iobuf_bytes(self.handle, pos, -1 if n is None else n)
        size = len(self)
        if n is None:
            n = size - pos
        n = max(0, min(n, size - pos))
        buf = ctypes.create_string_buffer(n)
        got = core.brpc_iobuf_copy_to(self.handle, buf, n, pos)
        return buf.raw[:got]

    def clear(self) -> None:
        core.brpc_iobuf_clear(self.handle)


class TokenRing:
    """Python handle on one native bounded emit ring (ISSUE 9;
    src/cc/serving_hotpath.cc).  The hot calls — batch push from the
    decode step loop, batch pop from the emitter — run with the GIL
    released for the call's duration; the terminal marker's Python
    error OBJECT rides a wrapper slot whose exactly-once owner is
    decided by the native ring (first push_terminal wins), so native
    and Python state can never disagree about which error a consumer
    observes."""

    __slots__ = ("handle", "cap", "_terminal_obj", "_terminal_set",
                 "_tmu")

    def __init__(self, cap: int):
        self.cap = int(cap)
        self.handle = core.brpc_tokring_new(self.cap)
        self._terminal_obj = None
        self._terminal_set = False
        # terminal is once-per-request (cold): a tiny Python lock keeps
        # the error OBJECT slot and the native marker exactly-once
        # together; the per-token path never touches it
        self._tmu = threading.Lock()

    def __del__(self):
        h = getattr(self, "handle", None)
        if h:
            core.brpc_tokring_free(h)
            self.handle = None

    def push(self, tok: int) -> bool:
        # prefer the C-extension entry: it HOLDS the GIL (the ring
        # mutex is held for nanoseconds, so a ctypes GIL drop/reacquire
        # per token costs more than the push — and under N producer
        # threads becomes a handoff convoy)
        fb = _fastrpc_mod()
        if fb is not None:
            return bool(fb.tokring_push(self.handle, tok))
        return bool(core.brpc_tokring_push(self.handle, tok))

    def push_terminal(self, err) -> None:
        with self._tmu:
            if self._terminal_set:
                return
            # object BEFORE the native marker: a consumer that observes
            # the native terminal must find the winner's object in place
            self._terminal_obj = err
            self._terminal_set = True
            core.brpc_tokring_push_terminal(
                self.handle, getattr(err, "code", 0) or 0)

    def pop_many(self, out, timeout_s: float):
        """Drain into the caller's ctypes int32 array `out`; returns
        ``(count, terminal_seen, err_obj)``."""
        term = ctypes.c_int(0)
        errc = ctypes.c_int32(0)
        n = core.brpc_tokring_pop_many(
            self.handle, out, len(out), int(timeout_s * 1e6),
            ctypes.byref(term), ctypes.byref(errc))
        return n, bool(term.value), self._terminal_obj

    def __len__(self) -> int:
        return core.brpc_tokring_size(self.handle)


def tokring_live() -> int:
    """Globally live native emit rings (chaos-suite leak baseline)."""
    return core.brpc_tokring_live()
