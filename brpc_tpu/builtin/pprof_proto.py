"""pprof protobuf profile encoder (the /pprof wire format; reference
serves it via builtin/pprof_service.* so any server is a remote pprof
target — SURVEY.md §2.7, hotspots_service.cpp:488-510).

Hand-rolled protobuf wire encoding of the public profile.proto schema
(github.com/google/pprof/proto/profile.proto) — no protoc dependency:

  Profile:  sample_type=1  sample=2  location=4  function=5
            string_table=6  duration_nanos=10  period_type=11  period=12
  ValueType: type=1 unit=2         (string-table indices)
  Sample:    location_id=1 value=2 (location ids LEAF FIRST)
  Location:  id=1 line=4
  Line:      function_id=1 line=2
  Function:  id=1 name=2

Input is the profiler's collapsed-stack Counter ("frameA;frameB;..."
root->leaf, sample counts); every distinct frame string becomes one
Function+Location.  Output is gzip-compressed, which is what pprof
fetches over HTTP (`go tool pprof http://host:port/pprof/profile`).
"""
from __future__ import annotations

import gzip



def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _uint(field: int, n: int) -> bytes:
    return _key(field, 0) + _varint(n)


def _blob(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _packed_uints(field: int, values) -> bytes:
    body = b"".join(_varint(v) for v in values)
    return _blob(field, body)


def encode_profile(stacks: dict[str, int], period_ns: int,
                   duration_ns: int) -> bytes:
    """collapsed-stack counts -> gzipped profile.proto bytes."""
    strtab: list[bytes] = [b""]          # index 0 must be ""
    index: dict[str, int] = {"": 0}

    def sid(s: str) -> int:
        i = index.get(s)
        if i is None:
            i = index[s] = len(strtab)
            strtab.append(s.encode("utf-8", "replace"))
        return i

    func_ids: dict[str, int] = {}
    functions: list[bytes] = []
    locations: list[bytes] = []

    def loc_id(frame: str) -> int:
        fid = func_ids.get(frame)
        if fid is None:
            fid = func_ids[frame] = len(functions) + 1
            functions.append(_uint(1, fid) + _uint(2, sid(frame)))
            line = _uint(1, fid)                      # Line.function_id
            locations.append(_uint(1, fid) + _blob(4, line))
        return fid

    samples: list[bytes] = []
    for collapsed, count in stacks.items():
        frames = [f for f in collapsed.split(";") if f]
        if not frames:
            continue
        ids = [loc_id(f) for f in reversed(frames)]    # leaf first
        samples.append(_packed_uints(1, ids) +
                       _packed_uints(2, [count]))

    sample_type = _uint(1, sid("samples")) + _uint(2, sid("count"))
    period_type = _uint(1, sid("cpu")) + _uint(2, sid("nanoseconds"))

    out = [_blob(1, sample_type)]
    out += [_blob(2, s) for s in samples]
    out += [_blob(4, loc) for loc in locations]
    out += [_blob(5, fn) for fn in functions]
    out += [_blob(6, s) for s in strtab]
    out.append(_uint(10, max(0, duration_ns)))
    out.append(_blob(11, period_type))
    out.append(_uint(12, max(1, period_ns)))
    return gzip.compress(b"".join(out), compresslevel=6)


# ---- minimal decoder (tests + /pprof self-checks) ----

def _read_varint(buf: bytes, off: int) -> tuple[int, int]:
    v = 0
    shift = 0
    while True:
        if off >= len(buf) or shift > 63:
            raise ValueError("truncated or oversized varint")
        b = buf[off]
        off += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, off
        shift += 7


def decode_profile(data: bytes) -> dict:
    """Gzipped profile.proto -> {string_table, samples:[(loc_ids,[v])],
    functions:{id:name_idx}, period}.  Enough structure to assert on."""
    buf = gzip.decompress(data)
    out = {"string_table": [], "samples": [], "functions": {},
           "locations": {}, "period": 0}
    off = 0
    while off < len(buf):
        key, off = _read_varint(buf, off)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, off = _read_varint(buf, off)
            if field == 12:
                out["period"] = v
        elif wire == 2:
            ln, off = _read_varint(buf, off)
            if off + ln > len(buf):
                raise ValueError("length-delimited field overruns buffer")
            payload = buf[off:off + ln]
            off += ln
            if field == 6:
                out["string_table"].append(payload.decode("utf-8"))
            elif field == 2:
                out["samples"].append(_decode_sample(payload))
            elif field == 5:
                fid, name = _decode_function(payload)
                out["functions"][fid] = name
            elif field == 4:
                lid, fid = _decode_location(payload)
                out["locations"][lid] = fid
        else:
            raise ValueError(f"unexpected wire type {wire}")
    return out


def _decode_sample(p: bytes):
    locs, vals = [], []
    off = 0
    while off < len(p):
        key, off = _read_varint(p, off)
        field, wire = key >> 3, key & 7
        if wire == 2:
            ln, off = _read_varint(p, off)
            end = off + ln
            while off < end:
                v, off = _read_varint(p, off)
                (locs if field == 1 else vals).append(v)
        else:
            v, off = _read_varint(p, off)
            (locs if field == 1 else vals).append(v)
    return locs, vals


def _decode_function(p: bytes):
    fid = name = 0
    off = 0
    while off < len(p):
        key, off = _read_varint(p, off)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, off = _read_varint(p, off)
            if field == 1:
                fid = v
            elif field == 2:
                name = v
        else:
            ln, off = _read_varint(p, off)
            off += ln
    return fid, name


def _decode_location(p: bytes):
    lid = fid = 0
    off = 0
    while off < len(p):
        key, off = _read_varint(p, off)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, off = _read_varint(p, off)
            if field == 1:
                lid = v
        elif wire == 2:
            ln, off = _read_varint(p, off)
            inner = p[off:off + ln]
            off += ln
            if field == 4:
                ioff = 0
                while ioff < len(inner):
                    k2, ioff = _read_varint(inner, ioff)
                    if k2 & 7 == 0:
                        v2, ioff = _read_varint(inner, ioff)
                        if k2 >> 3 == 1:
                            fid = v2
                    else:
                        ln2, ioff = _read_varint(inner, ioff)
                        ioff += ln2
    return lid, fid
