"""Profilers behind the /hotspots console pages
(reference builtin/hotspots_service.cpp driving four profilers: CPU,
heap, growth, contention — §5.2).  The TPU-build analogs:

  * CPU       — a sampling profiler over sys._current_frames(): stacks of
                every Python thread at ~100Hz for N seconds, reported in
                pprof-text and collapsed-flamegraph formats.  This covers
                the host-side Python layer; native executor/dispatcher
                threads show up at their Python entry points (callbacks).
  * heap      — tracemalloc snapshot: top allocation sites.
  * growth    — tracemalloc diff between the profile start and end.
  * contention — stacks filtered to lock waits (threading acquire/wait
                frames), the Python analog of sampled mutex contention
                (bthread/mutex.cpp:62-107).

All are on-demand (nothing runs until the page is hit), like the
reference's profilers.
"""
from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter

_WAIT_MARKERS = (
    ("threading", "wait"), ("threading", "acquire"), ("threading", "join"),
    ("threading", "_wait_for_tstate_lock"), ("queue", "get"),
    # a thread blocked inside an instrumented hot lock (butil/lockprof)
    # is a lock wait like any other
    ("lockprof", "acquire"), ("lockprof", "_acquire_restore"),
)


def _collect_stacks(duration_s: float, hz: int = 100,
                    contention_only: bool = False) -> Counter:
    """Sample all thread stacks for duration_s; returns
    Counter{collapsed_stack: samples}."""
    stacks: Counter = Counter()
    me = threading.get_ident()
    interval = 1.0 / hz
    end = time.monotonic() + duration_s
    while time.monotonic() < end:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            entries = traceback.extract_stack(frame)
            if not entries:
                continue
            if contention_only and not _is_waiting(entries):
                continue
            collapsed = ";".join(
                f"{_short(e.filename)}:{e.name}" for e in entries)
            stacks[collapsed] += 1
        time.sleep(interval)
    return stacks


def _is_waiting(entries) -> bool:
    tail = entries[-1]
    mod = _short(tail.filename).rsplit("/", 1)[-1].removesuffix(".py")
    for m, fn in _WAIT_MARKERS:
        if mod == m and tail.name == fn:
            return True
    return False


def _short(path: str) -> str:
    for marker in ("/site-packages/", "/python3.", "/brpc_tpu/"):
        i = path.find(marker)
        if i >= 0:
            return ("brpc_tpu/" + path[i + len(marker):]
                    if marker == "/brpc_tpu/" else path[i + 1:])
    return path


def _render(stacks: Counter, title: str, fmt: str) -> str:
    total = sum(stacks.values())
    if fmt == "collapsed":
        # flamegraph.pl / speedscope input format
        return "".join(f"{s} {n}\n" for s, n in stacks.most_common())
    lines = [f"--- {title}: {total} samples, {len(stacks)} unique stacks ---",
             ""]
    # leaf-function flat profile (pprof --text style)
    leafs: Counter = Counter()
    for s, n in stacks.items():
        leafs[s.rsplit(";", 1)[-1]] += n
    lines.append(f"{'samples':>8}  {'%':>6}  leaf function")
    for fn_name, n in leafs.most_common(30):
        lines.append(f"{n:>8}  {100.0 * n / max(1, total):>5.1f}%  {fn_name}")
    lines.append("")
    lines.append("hottest stacks:")
    for s, n in stacks.most_common(10):
        lines.append(f"  [{n} samples]")
        for fr in s.split(";"):
            lines.append(f"    {fr}")
    return "\n".join(lines) + "\n"


def cpu_profile(duration_s: float = 1.0, fmt: str = "text") -> str:
    return _render(_collect_stacks(duration_s), "cpu profile", fmt)


def cpu_profile_pb(duration_s: float = 1.0, hz: int = 100,
                   contention_only: bool = False) -> bytes:
    """Gzipped profile.proto — the wire format `go tool pprof` fetches
    from /pprof/profile (builtin/pprof_proto.py)."""
    from brpc_tpu.builtin.pprof_proto import encode_profile
    stacks = _collect_stacks(duration_s, hz, contention_only)
    return encode_profile(stacks, period_ns=int(1e9 / hz),
                          duration_ns=int(duration_s * 1e9))


def contention_profile(duration_s: float = 1.0, fmt: str = "text") -> str:
    """Two views on one page (reference bthread/mutex.cpp
    ContentionProfiler): NATIVE per-site folded stacks captured on
    contended FiberMutex locks (event-driven, rate-bounded 1/ms —
    answers "WHICH lock"; unresolved coroutine frames print as
    module+0xoffset, addr2line-able), then the Python-side sampling of
    threads sitting in lock/queue waits."""
    out = []
    try:
        import ctypes

        from brpc_tpu._core import core
        buf = ctypes.create_string_buffer(1 << 20)
        n = core.brpc_contention_folded(buf, len(buf))
        events = core.brpc_contention_events()
        out.append(f"--- native FiberMutex contention sites "
                   f"({events} events since start; folded stacks, "
                   f"addr2line -e libbrpc_core.so <offset> for local "
                   f"frames) ---")
        out.append(buf.value.decode("utf-8", "replace")
                   if n > 0 else "(no contention recorded)")
        out.append("")
    except Exception as e:  # native core absent: python view still works
        out.append(f"(native contention sampler unavailable: {e})")
    out.append(_render(_collect_stacks(duration_s, contention_only=True),
                       "python threads in lock/queue waits", fmt))
    return "\n".join(out)


def heap_profile(top: int = 30) -> str:
    import tracemalloc
    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return ("tracemalloc was off — tracing enabled now; "
                "hit this page again to see allocations.\n")
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")
    total = sum(s.size for s in stats)
    lines = [f"--- heap profile: {total / 1e6:.1f} MB tracked, "
             f"{len(stats)} sites ---", ""]
    for s in stats[:top]:
        fr = s.traceback[0]
        lines.append(f"{s.size / 1024:>10.1f} KB  {s.count:>7} blocks  "
                     f"{_short(fr.filename)}:{fr.lineno}")
    return "\n".join(lines) + "\n"


def growth_profile(duration_s: float = 1.0, top: int = 30) -> str:
    import tracemalloc
    if not tracemalloc.is_tracing():
        tracemalloc.start()
    before = tracemalloc.take_snapshot()
    time.sleep(duration_s)
    after = tracemalloc.take_snapshot()
    diff = after.compare_to(before, "lineno")
    lines = [f"--- heap growth over {duration_s}s ---", ""]
    shown = 0
    for s in diff:
        if s.size_diff <= 0:
            continue
        fr = s.traceback[0]
        lines.append(f"{s.size_diff / 1024:>+10.1f} KB  "
                     f"{s.count_diff:>+7} blocks  "
                     f"{_short(fr.filename)}:{fr.lineno}")
        shown += 1
        if shown >= top:
            break
    if shown == 0:
        lines.append("(no growth)")
    return "\n".join(lines) + "\n"


def native_cpu_profile(duration_s: float = 1.0, fmt: str = "folded",
                       hz: int = 100):
    """Native-thread CPU profile (butil/profiler.cc): SIGPROF sampling
    across ALL threads — dispatchers, executor workers, drainers — which
    the Python-frame profiler cannot see (VERDICT r2 weak #7).

    fmt="folded": flamegraph-input text (root;..;leaf count).
    fmt="pprof": legacy pprof CPU profile binary + /proc/self/maps —
    feed it to `pprof <python-binary> <file>` or `pprof -http`.
    """
    import ctypes
    import os
    import tempfile
    import time as _time

    from brpc_tpu._core import core
    if core.brpc_prof_start(hz) != 0:
        return "profiler already running\n"
    _time.sleep(min(60.0, max(0.05, duration_s)))
    n = core.brpc_prof_stop()
    if fmt == "pprof":
        fd, path = tempfile.mkstemp(prefix="brpc_prof_")
        os.close(fd)
        try:
            core.brpc_prof_dump(path.encode())
            with open(path, "rb") as f:
                data = f.read()
        finally:
            os.unlink(path)
        return data, "application/octet-stream"
    buf = ctypes.create_string_buffer(4 * 1024 * 1024)
    core.brpc_prof_folded(buf, len(buf))
    text = buf.value.decode("utf-8", "replace")
    return (f"--- native cpu profile: {n} samples @ {hz}Hz over "
            f"{duration_s}s (all threads) ---\n{text}")
