"""Builtin HTTP console router (reference src/brpc/builtin/*; SURVEY.md §2.7).

Serves the observability pages on the SAME port as RPC traffic (the native
core detects HTTP and hands raw requests here).  Endpoints are registered in
builtin/services.py; this module parses requests and frames responses.
"""
from __future__ import annotations

import traceback
from urllib.parse import parse_qs, urlparse

from brpc_tpu.butil.containers import CaseIgnoredDict, MRUCache
from brpc_tpu.rpc.transport import Transport


class HttpRequest:
    def __init__(self, raw: bytes):
        head, _, self.body = raw.partition(b"\r\n\r\n")
        lines = head.split(b"\r\n")
        parts = lines[0].decode("latin1").split(" ")
        self.method = parts[0]
        target = parts[1] if len(parts) > 1 else "/"
        u = urlparse(target)
        self.path = u.path
        self.query = {k: v[0] for k, v in parse_qs(u.query).items()}
        # case-insensitive lookup, original casing preserved (the
        # case_ignored_flat_map slot backing the reference's HttpHeader)
        self.headers = CaseIgnoredDict()
        for ln in lines[1:]:
            k, _, v = ln.decode("latin1").partition(":")
            self.headers[k.strip()] = v.strip()


def http_response(status: int, body: bytes | str,
                  content_type: str = "text/plain; charset=utf-8",
                  extra_headers: dict | None = None) -> bytes:
    if isinstance(body, str):
        body = body.encode()
    reason = {200: "OK", 404: "Not Found", 500: "Internal Server Error",
              400: "Bad Request", 302: "Found"}.get(status, "OK")
    hdr = [f"HTTP/1.1 {status} {reason}",
           f"Content-Type: {content_type}",
           f"Content-Length: {len(body)}"]
    for k, v in (extra_headers or {}).items():
        hdr.append(f"{k}: {v}")
    hdr.append("\r\n")
    return "\r\n".join(hdr).encode() + body


class HttpRouter:
    _MISS = object()   # sentinel: "path not yet resolved" (None is a
                       # valid, cacheable "no prefix route" outcome)

    def __init__(self, server):
        self.server = server
        from brpc_tpu.builtin.services import build_routes
        self.routes = build_routes(server)
        # longest-prefix resolution is a linear scan over every route;
        # console paths repeat heavily (sparkline polls, pprof subpaths),
        # so memoize path -> prefix handler.  self.routes is immutable
        # after build, which is what makes the cache sound.
        self._prefix_cache = MRUCache(capacity=256)

    def handle(self, sid: int, raw: bytes) -> None:
        t = Transport.instance()
        try:
            req = HttpRequest(raw)
        except Exception:
            t.write_raw(sid, http_response(400, "bad request"))
            return
        # user handlers first, then builtins: exact match, then longest
        # prefix (pprof-style subpaths)
        handler = self.server._http_handlers.get(req.path) or \
            self.routes.get(req.path)
        if handler is None:
            handler = self._prefix_cache.get(req.path, self._MISS)
            if handler is self._MISS:
                handler, best = None, ""
                for prefix, h in self.routes.items():
                    if len(prefix) > 1 and prefix.endswith("/") and \
                            req.path.startswith(prefix) and \
                            len(prefix) > len(best):
                        handler, best = h, prefix
                self._prefix_cache.put(req.path, handler)
            if handler is None and req.path.startswith("/"):
                # RESTful RPC access: /ServiceName/Method
                handler = self._try_rpc(req)
        if handler is None:
            t.write_raw(sid, http_response(
                404, f"no handler for {req.path!r}\n"))
            return
        try:
            resp = handler(req) if callable(handler) else handler
            from brpc_tpu.rpc.progressive import (ProgressiveAttachment,
                                                  ProgressiveResponse)
            if isinstance(resp, ProgressiveResponse):
                # chunked server push (progressive_attachment.h)
                hdr = [f"HTTP/1.1 {resp.status} OK",
                       f"Content-Type: {resp.content_type}",
                       "Transfer-Encoding: chunked"]
                for k, v in resp.extra_headers.items():
                    hdr.append(f"{k}: {v}")
                hdr.append("\r\n")
                t.write_raw(sid, "\r\n".join(hdr).encode())
                resp.writer(ProgressiveAttachment(sid))
            elif isinstance(resp, bytes) and resp.startswith(b"HTTP/1."):
                t.write_raw(sid, resp)
            else:
                body, ctype = resp if isinstance(resp, tuple) else \
                    (resp, "text/plain; charset=utf-8")
                t.write_raw(sid, http_response(200, body, ctype))
        except Exception:
            t.write_raw(sid, http_response(500, traceback.format_exc()))

    def _try_rpc(self, req: HttpRequest):
        """RESTful bridge: POST /Service/Method with a JSON body calls the
        RPC method (the json2pb RESTful path of the reference, restful.cpp)."""
        parts = [p for p in req.path.split("/") if p]
        if len(parts) != 2:
            return None
        key = (parts[0], parts[1])
        spec = self.server._methods.get(key)
        if spec is None:
            return None

        def call(req_: HttpRequest):
            import json
            from brpc_tpu import errors
            try:
                payload = json.loads(req_.body) if req_.body.strip() else None
            except json.JSONDecodeError as e:
                return http_response(
                    400, json.dumps({"error": errors.EREQUEST,
                                     "text": f"bad JSON body: {e}"}),
                    "application/json")
            try:
                result = self.server.invoke_restful(parts[0], parts[1],
                                                    payload)
            except errors.RpcError as e:
                status = 401 if e.code == errors.ERPCAUTH else \
                    503 if e.code in (errors.ELIMIT, errors.ELOGOFF) else 500
                return http_response(
                    status, json.dumps({"error": e.code, "text": e.text}),
                    "application/json")
            return json.dumps(result, default=str), "application/json"

        return call
