"""Always-on background sampling profiler (ISSUE 6).

The on-demand profilers in builtin/profiler.py answer "what is hot
RIGHT NOW, while I watch".  This module answers the question the bench
trajectory keeps raising after the fact — "where did the host CPU go
over the last minutes?" — with a low-Hz wall-clock sampler over
``sys._current_frames()`` that runs for the life of the process:

  * each sampled thread stack is FOLDED (root;..;leaf) and tagged with
    its serving STAGE (butil/stagetag.py: frame pump, batch formation,
    prefill, decode step, emit fan-out, span submit, ...) as the
    root frame, so one folded profile attributes CPU per stage;
  * each sample is classified RUNNING vs WAITING — a leaf frame inside
    threading/queue acquire/wait is a thread parked on a lock (in
    CPython, equivalently, a thread NOT holding the GIL); the ratio of
    waiting samples over all samples is the headline
    ``gil_wait_ratio`` bvar (wait-classified samples / all samples);
  * samples land in a bounded RING of time windows, so the /hotspots
    console can show "the last N minutes" without unbounded memory and
    a stall that ended an hour ago ages out.

Default rate is 10 Hz (flag ``hotspot_sampler_hz``): ~10 stack walks
per second across all threads, measured <2% batcher qps overhead by
tests/test_hotspots.py (the tier-1 gate for shipping it always-on).
``hotspot_sampler_enabled`` (reloadable via /flags) flips it live;
Server.start() brings it up by default.

``burst()`` is the synchronous high-rate variant behind
``/hotspots?seconds=N`` — same stage tagging, 100 Hz, bounded
duration — and feeds the existing pprof-pb encoder for `go tool
pprof`.
"""
from __future__ import annotations

import sys
import threading
import time
from collections import Counter, deque

from brpc_tpu.butil import stagetag
from brpc_tpu.flags import define_flag, get_flag

define_flag("hotspot_sampler_enabled", True,
            "run the always-on low-Hz stage-tagged sampling profiler "
            "(flip live on /flags)", reloadable=True)
define_flag("hotspot_sampler_hz", 10.0,
            "sampling rate of the always-on profiler", reloadable=True)

# leaf frames that mean "parked on a lock/queue, not running" — the
# lockprof entries matter most: a thread blocked inside an
# InstrumentedLock acquire is parked on exactly the hot locks this
# layer ledgers, and counting it as running would undercount
# gil_wait_ratio where it matters
_WAIT_MARKERS = frozenset([
    ("threading", "wait"), ("threading", "acquire"), ("threading", "join"),
    ("threading", "_wait_for_tstate_lock"), ("threading", "wait_for"),
    ("queue", "get"), ("queue", "put"),
    ("lockprof", "acquire"), ("lockprof", "_acquire_restore"),
])

# leaf frames that mean "inside a GIL-released native call" (ISSUE 9):
# a ctypes foreign call adds NO Python frame, so a thread spending its
# time in the de-GIL'd hot path samples at the binding-layer call site.
# Without this class those stacks would read as Python "run" time —
# exactly the time the rewrite moved OFF Python — so they fold into a
# `;[native]` leaf and count as their own column: not GIL-bound run
# time, not lock-wait.
_NATIVE_LEAF_PREFIXES = ("brpc_tpu/_core/", "brpc_tpu/native_path")
# native calls issued directly from hot-path frames (the engine's
# batched token push runs the foreign call from its own frame)
_NATIVE_MARKERS = frozenset([
    ("engine", "_push_token_runs"),
])
# binding-layer call sites that deliberately HOLD the GIL (the
# _fastrpc fast entries: a per-token ctypes GIL drop/reacquire costs
# more than the push) — a thread sampled here is GIL-bound Python run
# time, and classing it "native" would overstate gil_wait_ratio's
# de-GIL story exactly where this measurement judges it
_GIL_HELD_BINDING = frozenset([
    ("lib", "push"),            # TokenRing.push -> fb.tokring_push
    ("lib", "push_terminal"),   # cold, Python-mutex-held
])


def _is_native_leaf(leaf_code) -> bool:
    key = (_modname(leaf_code.co_filename), leaf_code.co_name)
    if key in _NATIVE_MARKERS:
        return True
    if key in _GIL_HELD_BINDING:
        return False
    return _short(leaf_code.co_filename).startswith(_NATIVE_LEAF_PREFIXES)


def _modname(filename: str) -> str:
    base = filename.rsplit("/", 1)[-1]
    return base[:-3] if base.endswith(".py") else base


def _short(path: str) -> str:
    for marker in ("/site-packages/", "/python3.", "/brpc_tpu/"):
        i = path.find(marker)
        if i >= 0:
            return ("brpc_tpu/" + path[i + len(marker):]
                    if marker == "/brpc_tpu/" else path[i + 1:])
    return path


def _fold(frame, skip_tids=None) -> tuple[str, str]:
    """(folded root;..;leaf stack, class) for one thread frame — a raw
    f_back walk: no linecache, no source IO, cheap enough for an
    always-on path.  class is one of "run" (executing Python), "wait"
    (parked on a lock/queue) or "native" (inside a GIL-released
    foreign call in the de-GIL'd hot path)."""
    parts: list[str] = []
    f = frame
    while f is not None:
        code = f.f_code
        parts.append(f"{_short(code.co_filename)}:{code.co_name}")
        f = f.f_back
    parts.reverse()
    leaf = frame.f_code
    if (_modname(leaf.co_filename), leaf.co_name) in _WAIT_MARKERS:
        cls = "wait"
    elif _is_native_leaf(leaf):
        cls = "native"
    else:
        cls = "run"
    return ";".join(parts), cls


_CLS_SUFFIX = {"run": "", "wait": ";[lock-wait]", "native": ";[native]"}


def sample_once(exclude: frozenset = frozenset()) -> list[tuple]:
    """One pass over every live thread: [(stage, folded, class)].
    ``exclude`` filters thread idents (the sampler excludes itself)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        if tid in exclude:
            continue
        folded, cls = _fold(frame)
        stage_name = stagetag.stage_of(tid, names.get(tid, ""))
        out.append((stage_name, folded, cls))
    return out


class _Window:
    __slots__ = ("start", "samples", "run", "wait", "native",
                 "stage_run", "stage_wait", "stage_native")

    def __init__(self, start: float):
        self.start = start
        self.samples: Counter = Counter()   # "stage;folded[;class]"
        self.run = 0
        self.wait = 0
        self.native = 0
        self.stage_run: Counter = Counter()
        self.stage_wait: Counter = Counter()
        self.stage_native: Counter = Counter()


class HotspotSampler:
    """The always-on profiler singleton (see module docstring)."""

    _instance: "HotspotSampler | None" = None
    _instance_mu = threading.Lock()

    def __init__(self, window_s: float = 15.0, ring: int = 40):
        self.window_s = window_s
        self._ring: deque = deque(maxlen=ring)   # closed windows
        self._win = _Window(time.monotonic())
        self._mu = threading.Lock()   # guards ring/window swap + reads
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.samples_total = 0

    # ---- lifecycle ----

    @classmethod
    def instance(cls) -> "HotspotSampler":
        inst = cls._instance
        if inst is None:
            with cls._instance_mu:
                if cls._instance is None:
                    cls._instance = cls()
                inst = cls._instance
        return inst

    @classmethod
    def ensure_started(cls) -> "HotspotSampler":
        """Start (or restart) the sampler if the flag allows it."""
        inst = cls.instance()
        if get_flag("hotspot_sampler_enabled", True):
            inst.start()
        return inst

    def start(self) -> None:
        with self._mu:
            if self._thread is not None and self._thread.is_alive():
                return
            # a FRESH stop event per thread: a racing stop() can only
            # ever set the event of the thread it actually swapped out,
            # never strand or double-start a sampler
            self._stop = stop_ev = threading.Event()
            self._thread = threading.Thread(
                target=self._run, args=(stop_ev,), daemon=True,
                name="hotspot-sampler")
            self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        """Stop and JOIN the sampler thread (clean removal — the
        disable path must leave no thread behind)."""
        with self._mu:
            self._stop.set()
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout_s)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # ---- the sampling loop ----

    def _run(self, stop_ev: threading.Event) -> None:
        me = frozenset((threading.get_ident(),))
        while not stop_ev.is_set():
            hz = max(0.2, min(200.0, float(
                get_flag("hotspot_sampler_hz", 10.0) or 10.0)))
            if not get_flag("hotspot_sampler_enabled", True):
                # flag flipped off under us: exit; the /flags side
                # effect (or the next Server.start) restarts us
                return
            t0 = time.monotonic()
            try:
                observed = sample_once(exclude=me)
            except Exception:
                observed = []   # a torn frame walk must not kill the loop
            with self._mu:
                win = self._win
                if t0 - win.start >= self.window_s:
                    self._ring.append(win)
                    win = self._win = _Window(t0)
                for stage_name, folded, cls in observed:
                    win.samples[
                        f"{stage_name};{folded}{_CLS_SUFFIX[cls]}"] += 1
                    if cls == "wait":
                        win.wait += 1
                        win.stage_wait[stage_name] += 1
                    elif cls == "native":
                        win.native += 1
                        win.stage_native[stage_name] += 1
                    else:
                        win.run += 1
                        win.stage_run[stage_name] += 1
                self.samples_total += len(observed)
            stop_ev.wait(max(0.0, 1.0 / hz - (time.monotonic() - t0)))

    # ---- reads ----

    def _windows(self) -> list[_Window]:
        with self._mu:
            return list(self._ring) + [self._win]

    def folded(self, last_s: float | None = None) -> Counter:
        """Merged stage-tagged folded stacks over the ring (or the last
        `last_s` seconds of it)."""
        now = time.monotonic()
        merged: Counter = Counter()
        for w in self._windows():
            if last_s is not None and now - w.start > last_s + self.window_s:
                continue
            merged.update(w.samples)
        return merged

    def gil_wait_ratio(self) -> float:
        # native samples stay in the denominator: a thread inside a
        # GIL-released foreign call is making progress WITHOUT the GIL,
        # and dropping it would inflate the ratio exactly where the
        # de-GIL rewrite (ISSUE 9) succeeded
        run = wait = 0
        for w in self._windows():
            run += w.run + w.native
            wait += w.wait
        total = run + wait
        return round(wait / total, 4) if total else 0.0

    def stage_table(self) -> dict[str, dict]:
        run: Counter = Counter()
        wait: Counter = Counter()
        native: Counter = Counter()
        for w in self._windows():
            run.update(w.stage_run)
            wait.update(w.stage_wait)
            native.update(w.stage_native)
        out = {}
        for stage_name in sorted(set(run) | set(wait) | set(native)):
            r, wt, nv = run[stage_name], wait[stage_name], \
                native[stage_name]
            total = r + wt + nv
            out[stage_name] = {
                "run": r, "wait": wt, "native": nv,
                "wait_ratio": round(wt / total, 4) if total else 0.0,
            }
        return out

    def snapshot(self) -> dict:
        return {
            "running": self.running,
            "hz": float(get_flag("hotspot_sampler_hz", 10.0) or 10.0),
            "window_s": self.window_s,
            "windows": len(self._windows()),
            "samples": self.samples_total,
            "gil_wait_ratio": self.gil_wait_ratio(),
            "stages": self.stage_table(),
        }


def burst(duration_s: float, hz: int = 100) -> Counter:
    """Synchronous high-rate stage-tagged collection (the
    ``/hotspots?seconds=N`` burst mode).  Returns the same folded
    Counter shape as :meth:`HotspotSampler.folded`."""
    me = frozenset((threading.get_ident(),))
    stacks: Counter = Counter()
    interval = 1.0 / max(1, hz)
    end = time.monotonic() + min(60.0, max(0.05, duration_s))
    while time.monotonic() < end:
        for stage_name, folded, cls in sample_once(exclude=me):
            stacks[f"{stage_name};{folded}{_CLS_SUFFIX[cls]}"] += 1
        time.sleep(interval)
    return stacks


def render_folded(stacks: Counter, title: str, top: int = 25) -> str:
    """Human view of a stage-tagged folded profile: per-stage totals
    then the hottest stacks."""
    total = sum(stacks.values())
    by_stage: Counter = Counter()
    wait_by_stage: Counter = Counter()
    native_by_stage: Counter = Counter()
    for s, n in stacks.items():
        stage_name = s.split(";", 1)[0]
        by_stage[stage_name] += n
        if s.endswith(";[lock-wait]"):
            wait_by_stage[stage_name] += n
        elif s.endswith(";[native]"):
            native_by_stage[stage_name] += n
    lines = [f"--- {title}: {total} samples, {len(stacks)} unique "
             f"stage-tagged stacks ---", "",
             f"{'samples':>8}  {'%':>6}  {'lock-wait%':>10}  "
             f"{'native%':>7}  stage"]
    for stage_name, n in by_stage.most_common():
        w = wait_by_stage[stage_name]
        nv = native_by_stage[stage_name]
        lines.append(f"{n:>8}  {100.0 * n / max(1, total):>5.1f}%  "
                     f"{100.0 * w / max(1, n):>9.1f}%  "
                     f"{100.0 * nv / max(1, n):>6.1f}%  {stage_name}")
    lines.append("")
    lines.append("hottest stacks (stage;root;..;leaf):")
    for s, n in stacks.most_common(top):
        lines.append(f"  [{n} samples]")
        for fr in s.split(";"):
            lines.append(f"    {fr}")
    return "\n".join(lines) + "\n"


# headline bvar: appears on /vars and /brpc_metrics as `gil_wait_ratio`
from brpc_tpu.bvar.reducer import PassiveStatus  # noqa: E402

PassiveStatus(
    lambda: HotspotSampler.instance().gil_wait_ratio(),
).expose("gil_wait_ratio")
