"""Builtin console pages (reference src/brpc/builtin/, 27 services
auto-registered at server.cpp:484-586; SURVEY.md §2.7).

Implemented: /index (dashboard), /status (per-method qps/latency via
MethodStatus), /vars (+ wildcard filter), /flags (live edit with ?setvalue=),
/health, /version, /connections, /sockets, /bthreads (executor stats),
/rpcz (recent spans, ?trace_id= filter), /brpc_metrics (Prometheus text),
/services (method inventory — /protobufs analog), /memory, /ici (link
stats of the ICI transport), /serving (dynamic-batcher occupancy +
decode slot map + supervisor state/restart/recovery stats,
brpc_tpu/serving), /kvcache (paged-KV hit-rate, page
occupancy, radix-tree size, eviction counters, brpc_tpu/kvcache),
/flightrecorder (the native core's always-on per-thread event rings:
merged tail, per-thread state, syscall attribution — ISSUE 15).
"""
from __future__ import annotations

import html
import json
import os
import threading
import time

from brpc_tpu import rpcz
from brpc_tpu.bvar import dump_exposed
from brpc_tpu.flags import define_flag, list_flags, set_flag
from brpc_tpu.builtin.router import HttpRequest, http_response
from brpc_tpu._core import core

# filesystem browsing is an explicit operator opt-in (reference
# -enable_dir_service, a process-start gflag, off by default).  NOT
# reloadable: a live-flippable gate would let anyone with console access
# turn on arbitrary-file reads via /flags, so the flag guards nothing.
define_flag("enable_dir_service", False,
            "allow /dir to browse the server's filesystem (start-time only)",
            reloadable=False)


def build_routes(server) -> dict:
    def index(req):
        rows = "".join(
            f'<li><a href="{p}">{p}</a></li>'
            for p in sorted(routes) if p not in ("/", "/index"))
        return (f"<html><head><title>{server.options.server_info_name}"
                f"</title></head><body><h1>"
                f"{server.options.server_info_name}</h1>"
                f"<p>uptime {server.uptime_s:.0f}s · port {server.port} · "
                f"{server.connection_count} connections · "
                f'<a href="/dashboard">dashboard</a></p>'
                f"<ul>{rows}</ul></body></html>", "text/html")

    # ---- /dashboard (see module-level _DashHistory/_spark) ----
    hist = _dash_history_for(server)

    def dashboard(req):
        hist.ensure()
        samples = list(hist.samples)
        blocks = []
        for key, st in sorted(server.method_statuses.items()):
            svc, m = key
            qps, lat = [], []
            for (t0, s0), (t1, s1) in zip(samples, samples[1:]):
                c0, sum0 = s0.get(key, (0, 0))
                c1, sum1 = s1.get(key, (0, 0))
                dt = max(1e-6, t1 - t0)
                dc = max(0, c1 - c0)
                qps.append(dc / dt)
                lat.append((sum1 - sum0) / dc if dc else 0.0)
            r = st.latency_rec
            blocks.append(
                f"<tr><td>{svc}.{m}</td>"
                f"<td>{r.qps():.1f}</td>"
                f"<td>{_spark(qps)}</td>"
                f"<td>{r.latency():.0f}us / "
                f"p99 {r.latency_percentile(0.99):.0f}us</td>"
                f"<td>{_spark(lat)}</td>"
                f"<td>{st.nerror.get_value()}</td></tr>")
        note = ("" if len(samples) > 2 else
                "<p>(collecting history — refresh in a few seconds)</p>")
        return (f"<html><head><title>dashboard</title>"
                f"<meta http-equiv='refresh' content='5'></head><body>"
                f"<h1>{server.options.server_info_name} dashboard</h1>"
                f"<p>last {len(samples)}s · auto-refreshes</p>{note}"
                f"<table border='0' cellpadding='4'>"
                f"<tr><th>method</th><th>qps</th><th>qps (2m)</th>"
                f"<th>latency</th><th>avg latency (2m)</th>"
                f"<th>errors</th></tr>"
                f"{''.join(blocks)}</table></body></html>", "text/html")

    def status(req):
        lines = [f"server: {server.options.server_info_name}",
                 f"uptime_s: {server.uptime_s:.0f}",
                 f"port: {server.port}",
                 f"connections: {server.connection_count}", ""]
        for (svc, m), st in sorted(server.method_statuses.items()):
            r = st.latency_rec
            lines.append(
                f"{svc}.{m}: count={r.count()} error={st.nerror.get_value()} "
                f"qps={r.qps():.1f} concurrency={st.concurrency} "
                f"latency_avg_us={r.latency():.0f} "
                f"p99_us={r.latency_percentile(0.99):.0f} "
                f"max_us={r.max_latency()}")
        return "\n".join(lines) + "\n"

    def vars_page(req):
        pattern = req.query.get("filter", "*")
        data = dump_exposed(pattern)
        return "".join(f"{k} : {_fmt(v)}\n" for k, v in sorted(data.items()))

    def flags_page(req):
        name = req.query.get("setvalue")
        if name is not None:
            val = req.query.get(name, req.query.get("value", ""))
            ok = set_flag(name, val)
            _apply_flag_side_effects(name)
            return ("ok\n" if ok else
                    http_response(400, f"cannot set flag {name!r}\n"))
        out = []
        for f in list_flags():
            mark = " (R)" if f.reloadable else ""
            out.append(f"{f.name}={f.value}{mark}  # {f.help} "
                       f"(default {f.default})")
        return "\n".join(out) + "\n"

    def health(req):
        return ("OK\n" if server.running else
                http_response(500, "server stopping\n"))

    def version(req):
        from brpc_tpu import __version__
        return f"tpu-rpc/{__version__}\n"

    def connections(req):
        from brpc_tpu.rpc.transport import Transport
        t = Transport.instance()
        lines = [f"{'socket_id':>12} {'remote':>22} {'read':>12} "
                 f"{'written':>12} {'msgs':>8}"]
        for sid in server.connections():
            s = t.socket_stats(sid)
            if s:
                lines.append(f"{sid:>12} {s['remote']:>22} "
                             f"{s['bytes_read']:>12} {s['bytes_written']:>12} "
                             f"{s['messages_read']:>8}")
        return "\n".join(lines) + "\n"

    def sockets(req):
        return (f"active_sockets: {core.brpc_socket_active_count()}\n"
                f"live_iobuf_blocks: {core.brpc_iobuf_live_blocks()}\n")

    def bthreads(req):
        import ctypes
        w = ctypes.c_int64()
        k = ctypes.c_int64()
        t = ctypes.c_int64()
        m = ctypes.c_int64()
        core.brpc_fiber_counters(ctypes.byref(w), ctypes.byref(k),
                                 ctypes.byref(t), ctypes.byref(m))
        return (f"workers: {core.brpc_executor_num_workers()}\n"
                f"tasks_executed: {core.brpc_executor_tasks_executed()}\n"
                f"steals: {core.brpc_executor_steals()}\n"
                f"timers_fired: {core.brpc_timer_fired()}\n"
                f"butex_waits: {w.value}\n"
                f"butex_wakes: {k.value}\n"
                f"butex_timeouts: {t.value}\n"
                f"fiber_mutex_contended: {m.value}\n")

    def rpcz_page(req):
        tid = req.query.get("trace_id")
        limit = int(req.query.get("limit", "50"))
        if tid:
            # TIMELINE view (ISSUE 5): every collected span of ONE
            # trace, tree-ordered with relative offsets — the
            # generation-tracing read path (ingress -> batch -> prefill
            # -> decode -> kv annotations -> post-crash continuation)
            spans = rpcz.recent_spans(2048, int(tid))
            if not spans:
                spans = rpcz.load_disk_spans(2048, int(tid))
            # CROSS-PROCESS STITCHING (ISSUE 20): on a router, fan the
            # query out through the fleet collector — replica and
            # PS-shard spans of the same trace join the local tree
            import sys as _sys
            if "brpc_tpu.serving" in _sys.modules:
                try:
                    from brpc_tpu.serving import fleet_trace_spans
                    seen = {(s.trace_id, s.span_id, s.kind, s.start_us)
                            for s in spans}
                    for s in fleet_trace_spans(int(tid)):
                        key = (s.trace_id, s.span_id, s.kind, s.start_us)
                        if key not in seen:
                            seen.add(key)
                            spans.append(s)
                except Exception:
                    pass   # a dead peer must not 500 the local view
            if not spans:
                return f"no spans collected for trace {tid}\n"
            # span ids are pid-salted (top bits), so distinct processes
            # in the merged tree are countable without a pid field
            pids = {s.span_id >> 40 for s in spans}
            head = (f"(stitched across {len(pids)} processes)\n"
                    if len(pids) > 1 else "")
            return head + rpcz.format_trace(spans)
        spans = rpcz.recent_spans(limit)
        lines = []
        for s in reversed(spans):
            lines.append(
                f"{time.strftime('%H:%M:%S', time.localtime(s.start_us/1e6))}"
                f" trace={s.trace_id} span={s.span_id} "
                f"parent={s.parent_span_id} {s.kind} "
                f"{s.service}.{s.method} peer={s.remote_side} "
                f"lat={s.latency_us}us req={s.request_size}B "
                f"res={s.response_size}B err={s.error_code}"
                + (f" recovered_from={s.recovered_from}"
                   if s.recovered_from else "")
                + (f" migrated_from={s.migrated_from}"
                   if getattr(s, "migrated_from", 0) else "")
                + ("".join(f"\n    @{t} {html.escape(m)}"
                           for t, m in s.annotations)))
        lines.append("")
        lines.append("(append ?trace_id=<id> for the tree-ordered "
                     "timeline of one trace)")
        return "\n".join(lines) + "\n"

    def metrics(req):
        # Prometheus text format (builtin/prometheus_metrics_service.cpp
        # role) with honest TYPEs (ISSUE 6): LatencyRecorders export as
        # quantile-labeled SUMMARY families, Adders (monotonic event
        # counters throughout this codebase) as `counter`, the rest as
        # `gauge`; every family gets a # HELP line.  MultiDimension
        # variables render with their REAL label names —
        # name{method="Echo",code="0"} — the mbvar contract.
        from brpc_tpu.bvar.multi_dimension import MultiDimension
        from brpc_tpu.bvar.recorder import IntRecorder, LatencyRecorder
        from brpc_tpu.bvar.reducer import Adder, PassiveStatus
        from brpc_tpu.bvar.variable import exposed_variables

        def esc(v):
            # exposition-format label escaping: one bad value must not
            # invalidate the whole scrape
            return (str(v).replace("\\", "\\\\")
                    .replace('"', '\\"').replace("\n", "\\n"))

        def mangle(k):
            return k.replace("-", "_").replace(".", "_").replace("/", "_")

        all_vars = sorted(exposed_variables("*").items())
        # a recorder registers <base>_latency (itself) plus satellite
        # percentile/count gauges; the summary family subsumes those —
        # emitting both would publish two TYPEs for one family
        recorders = {}
        suppress = set()
        for k, var in all_vars:
            if isinstance(var, LatencyRecorder) and k.endswith("_latency"):
                base = k[: -len("_latency")]
                recorders[base] = var
                suppress.add(k)
                suppress.add(base + "_count")
                for q in ("50", "90", "99", "999", "9999"):
                    suppress.add(f"{base}_latency_{q}")
        out = []
        for base, rec in sorted(recorders.items()):
            name = mangle(base)
            try:
                c, s, _m = rec.snapshot()
                quants = [(q, rec.latency_percentile(q))
                          for q in (0.5, 0.9, 0.99, 0.999)]
            except Exception:
                continue
            out.append(f"# HELP {name} latency recorder (microseconds)")
            out.append(f"# TYPE {name} summary")
            for q, v in quants:
                out.append(f'{name}{{quantile="{q}"}} {v}')
            out.append(f"{name}_sum {s}")
            out.append(f"{name}_count {c}")
        for k, var in all_vars:
            if k in suppress:
                continue
            name = mangle(k)
            try:
                if isinstance(var, MultiDimension):
                    out.append(f"# HELP {name} bvar MultiDimension")
                    out.append(f"# TYPE {name} gauge")
                    label_names = var.labels
                    for key, lvar in var.items():
                        lv = lvar.get_value()
                        if isinstance(lv, bool):
                            lv = int(lv)
                        if not isinstance(lv, (int, float)):
                            continue
                        pairs = ",".join(
                            f'{ln}="{esc(kv)}"'
                            for ln, kv in zip(label_names, key))
                        out.append(f"{name}{{{pairs}}} {lv}")
                    continue
                v = var.get_value()
            except Exception:
                # one throwing variable (torn-down PassiveStatus callback)
                # must not 500 the whole scrape
                continue
            if isinstance(v, bool):
                v = int(v)
            if isinstance(v, (int, float)):
                if isinstance(var, Adder):
                    kind, what = "counter", "monotonic event counter"
                elif isinstance(var, IntRecorder):
                    kind, what = "gauge", "average of recorded values"
                elif isinstance(var, PassiveStatus):
                    kind, what = "gauge", "pull-callback status"
                else:
                    kind, what = "gauge", type(var).__name__
                out.append(f"# HELP {name} bvar {what}")
                out.append(f"# TYPE {name} {kind}")
                out.append(f"{name} {v}")
        # fleet families (ISSUE 20): on a router, every collected
        # series' last sample exports as ONE aggregated family with a
        # replica label — the cross-process scrape a per-process /vars
        # cannot answer
        import sys as _sys
        if "brpc_tpu.serving" in _sys.modules:
            try:
                from brpc_tpu.serving import fleet_snapshot
                snap = fleet_snapshot(points=1)
                rows, dead, slos = [], [], []
                for fs in snap["routers"].values():
                    for rep, models in (fs.get("series") or {}).items():
                        for mod, mets in models.items():
                            for met, vals in mets.items():
                                if vals:
                                    rows.append((rep, mod, met,
                                                 vals[-1]))
                    for r in (fs.get("collector") or {}).get(
                            "replicas", []):
                        dead.append((r.get("addr", ""),
                                     1 if r.get("tombstoned") else 0))
                    if fs.get("slo"):
                        slos.append(fs["slo"])
                if rows:
                    out.append("# HELP brpc_fleet_metric last collected "
                               "fleet series sample")
                    out.append("# TYPE brpc_fleet_metric gauge")
                    for rep, mod, met, v in sorted(rows):
                        out.append(
                            f'brpc_fleet_metric{{replica="{esc(rep)}",'
                            f'model="{esc(mod)}",metric="{esc(met)}"}}'
                            f' {v}')
                if dead:
                    out.append("# HELP brpc_fleet_tombstoned replica "
                               "tombstoned by the fleet collector")
                    out.append("# TYPE brpc_fleet_tombstoned gauge")
                    for rep, v in sorted(dead):
                        out.append(
                            f'brpc_fleet_tombstoned{{replica='
                            f'"{esc(rep)}"}} {v}')
                if slos:
                    out.append("# HELP brpc_fleet_slo_state SLO "
                               "engine ramp state (1 = current)")
                    out.append("# TYPE brpc_fleet_slo_state gauge")
                    for s in slos:
                        out.append(
                            f'brpc_fleet_slo_state{{model='
                            f'"{esc(s.get("model_id", ""))}",state='
                            f'"{esc(s.get("state", ""))}"}} 1')
                    for fam, key, what in (
                            ("brpc_fleet_slo_floor", "floor",
                             "advisory overload floor while burning"),
                            ("brpc_fleet_slo_evaluations",
                             "evaluations", "burn evaluations run"),
                            ("brpc_fleet_slo_holds", "holds",
                             "ramp holds during fleet disruption")):
                        out.append(f"# HELP {fam} {what}")
                        out.append(f"# TYPE {fam} gauge")
                        for s in slos:
                            out.append(
                                f'{fam}{{model='
                                f'"{esc(s.get("model_id", ""))}"}}'
                                f' {int(s.get(key, 0) or 0)}')
            except Exception:
                pass   # fleet families are additive, never 500 a scrape
        return "\n".join(out) + "\n", "text/plain; version=0.0.4"

    def services_page(req):
        out = {}
        for name, svc in server.services.items():
            out[name] = {m: {
                "request": spec.request_serializer.name,
                "response": spec.response_serializer.name,
            } for m, spec in svc.rpc_methods().items()}
        return json.dumps(out, indent=1), "application/json"

    def memory(req):
        import ctypes
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF)
        buf = ctypes.create_string_buffer(1 << 18)
        n = core.brpc_iobuf_alloc_folded(buf, len(buf))
        sites = buf.value.decode("utf-8", "replace") if n > 0 else ""
        return (f"max_rss_kb: {ru.ru_maxrss}\n"
                f"live_iobuf_blocks: {core.brpc_iobuf_live_blocks()}\n"
                f"iobuf_block_handouts: {core.brpc_iobuf_alloc_events()}\n"
                f"\n--- iobuf block allocation sites (sampled 1/ms; "
                f"reference iobuf_profiler analog; addr2line -e "
                f"libbrpc_core.so <offset> for local frames) ---\n"
                f"{sites}")

    def ici(req):
        try:
            from brpc_tpu.ici.endpoint import link_stats
            return json.dumps(link_stats(), indent=1), "application/json"
        except Exception:
            return "ici transport not active\n"

    def serving_page(req):
        # inference-serving introspection (brpc_tpu/serving): batch
        # occupancy, decode slot map, shed/pad stats.  Import lazily —
        # the serving layer (and its jax dependency chain) loads only
        # when something registered a batcher/engine or the operator
        # asks for the page.
        import sys
        if "brpc_tpu.serving" not in sys.modules:
            return "no serving components registered\n"
        from brpc_tpu.serving import serving_snapshot
        snap = serving_snapshot()
        if not snap["batchers"] and not snap["engines"] \
                and not snap.get("supervisors"):
            return "no serving components registered\n"
        return json.dumps(snap, indent=1), "application/json"

    def serving_generations_page(req):
        # per-request generation console (ISSUE 5): recent generations
        # (TTFT, inter-token latency, prefill-skip, restart count, and
        # the trace_id to paste into /rpcz?trace_id=) plus the aggregate
        # serving_ttft_us / serving_itl_us percentiles.  Same lazy-
        # import discipline as /serving.
        import sys
        if "brpc_tpu.serving" not in sys.modules:
            return "no serving components registered\n"
        from brpc_tpu.serving import generations_snapshot
        limit = int(req.query.get("limit", "50"))
        return json.dumps(generations_snapshot(limit), indent=1), \
            "application/json"

    def kvcache_page(req):
        # paged-KV-cache introspection (brpc_tpu/kvcache): hit-rate,
        # page occupancy, radix-tree size, eviction/COW counters.
        # Lazy import, same discipline as /serving: the kvcache layer
        # loads only when something created a store or the operator
        # asks for the page.
        import sys
        if "brpc_tpu.kvcache" not in sys.modules:
            return "no kv-cache stores registered\n"
        from brpc_tpu.kvcache import kvcache_snapshot
        snap = kvcache_snapshot()
        if not snap["stores"]:
            return "no kv-cache stores registered\n"
        return json.dumps(snap, indent=1), "application/json"

    def cluster_page(req):
        # cluster front door introspection (ISSUE 8): per router the
        # replica table (health / breaker isolation / quarantine /
        # ladder level), session counts + resume stats, and the
        # overload gradient's per-level fire counters.  Lazy import,
        # same discipline as /serving.
        import sys
        if "brpc_tpu.serving" not in sys.modules:
            return "no cluster routers registered\n"
        from brpc_tpu.serving import cluster_snapshot
        snap = cluster_snapshot()
        if not snap["routers"]:
            return "no cluster routers registered\n"
        return json.dumps(snap, indent=1), "application/json"

    def psserve_page(req):
        # sharded parameter-server introspection (brpc_tpu/psserve,
        # ISSUE 12): per-shard row ranges + version counters + hot-key
        # histograms, the Lookup/Update batchers' coalescing stats, and
        # client routing/retry/stale-read counters.  Lazy import, same
        # discipline as /serving.
        import sys
        if "brpc_tpu.psserve" not in sys.modules:
            return "no parameter-server components registered\n"
        from brpc_tpu.psserve import psserve_snapshot
        snap = psserve_snapshot()
        if not snap["shards"] and not snap["clients"] \
                and not snap["lowered"]:
            return "no parameter-server components registered\n"
        # PR 15 syscall attribution alongside the shard tables: a PS
        # process is the fleet's I/O hot spot, and the same counters
        # ride every _telemetry Pull (ISSUE 20)
        from brpc_tpu.butil import flight
        snap["syscalls"] = flight.syscall_counters()
        return json.dumps(snap, indent=1), "application/json"

    def fleet_page(req):
        # fleet telemetry console (ISSUE 20): per router the collector's
        # replica table (pulls / bytes / tombstones), the per-model
        # scoreboard, sparkline series, canary ramp state and the SLO
        # engine's burn rates + decision trail.  Lazy import, same
        # discipline as /serving; ?fmt=json for the raw snapshot,
        # ?points=N sizes the sparklines.
        import sys
        if "brpc_tpu.serving" not in sys.modules:
            return "no cluster routers registered\n"
        from brpc_tpu.serving import fleet_snapshot
        try:
            points = min(128, max(2, int(req.query.get("points", "32"))))
        except ValueError:
            points = 32
        snap = fleet_snapshot(points)
        if not snap["routers"]:
            return "no cluster routers registered\n"
        if req.query.get("fmt") == "json":
            return json.dumps(snap, indent=1), "application/json"
        out = ["<html><body><style>td,th{padding:2px 8px;"
               "font:12px monospace}table{border-collapse:collapse}"
               "th{text-align:left;border-bottom:1px solid #999}"
               "</style>"]
        for rname, fs in sorted(snap["routers"].items()):
            col = fs.get("collector") or {}
            out.append(f"<h2>fleet: {html.escape(rname)}</h2>")
            out.append(
                f"<p>pulls={col.get('pulls', 0)} "
                f"bytes={col.get('pull_bytes', 0)} "
                f"errors={col.get('pull_errors', 0)} "
                f"tombstones={col.get('tombstones', 0)} "
                f"series={col.get('series', 0)} "
                f"fleet_spans={col.get('fleet_spans', 0)}</p>")
            rows = col.get("replicas") or []
            if rows:
                out.append("<h3>replicas</h3><table><tr>"
                           "<th>addr</th><th>name</th><th>pid</th>"
                           "<th>pulls</th><th>errors</th><th>state</th>"
                           "<th>bytes</th><th>age_s</th>"
                           "<th>write_syscalls</th></tr>")
                for r in rows:
                    state = ("TOMBSTONED" if r.get("tombstoned")
                             else "no-telemetry" if r.get("unsupported")
                             else "live")
                    sc = (r.get("syscalls") or {}).get("write_syscalls",
                                                       "")
                    out.append(
                        f"<tr><td>{html.escape(str(r.get('addr')))}</td>"
                        f"<td>{html.escape(str(r.get('name') or ''))}</td>"
                        f"<td>{r.get('pid') or ''}</td>"
                        f"<td>{r.get('pulls', 0)}</td>"
                        f"<td>{r.get('errors', 0)}</td>"
                        f"<td>{state}</td>"
                        f"<td>{r.get('last_bytes', 0)}</td>"
                        f"<td>{r.get('pull_age_s') or ''}</td>"
                        f"<td>{sc}</td></tr>")
                out.append("</table>")
            models = fs.get("models") or {}
            if models:
                out.append("<h3>models</h3><table><tr><th>key</th>"
                           "<th>sessions</th><th>sheds</th>"
                           "<th>finished</th><th>failed</th>"
                           "<th>ttft_p99_ms</th><th>itl_p99_ms</th>"
                           "</tr>")
                for key, row in sorted(models.items()):
                    out.append(
                        f"<tr><td>{html.escape(key)}</td>"
                        f"<td>{row.get('sessions', 0)}</td>"
                        f"<td>{row.get('sheds', 0)}</td>"
                        f"<td>{row.get('finished', 0)}</td>"
                        f"<td>{row.get('failed', 0)}</td>"
                        f"<td>{(row.get('ttft') or {}).get('p99_ms')}"
                        f"</td>"
                        f"<td>{(row.get('itl') or {}).get('p99_ms')}"
                        f"</td></tr>")
                out.append("</table>")
            canary = fs.get("canary") or {}
            if canary:
                out.append("<h3>canary picks</h3><table>"
                           "<tr><th>model</th><th>splits</th></tr>")
                for m, picks in sorted(canary.items()):
                    split = " ".join(f"{html.escape(k)}={v}"
                                     for k, v in sorted(picks.items()))
                    out.append(f"<tr><td>{html.escape(m)}</td>"
                               f"<td>{split}</td></tr>")
                out.append("</table>")
            slo = fs.get("slo")
            if slo:
                cw = slo.get("clean_windows") or {}
                out.append(
                    f"<h3>slo: {html.escape(slo.get('model_id', ''))} "
                    f"— {html.escape(slo.get('state', ''))}</h3>"
                    f"<p>canary={html.escape(slo.get('canary', ''))} "
                    f"baseline={html.escape(slo.get('baseline', ''))} "
                    f"clean_windows={cw.get('streak', 0)}/"
                    f"{cw.get('required', 0)} "
                    f"holds={slo.get('holds', 0)} "
                    f"floor={slo.get('floor', 0)}</p>")
                last = slo.get("last_eval") or {}
                for side in ("canary", "baseline"):
                    ev = last.get(side) or {}
                    burns = ev.get("burns") or {}
                    if not burns:
                        continue
                    out.append(f"<h4>{side}: "
                               f"{html.escape(str(ev.get('verdict')))}"
                               f"</h4><table><tr><th>metric</th>"
                               f"<th>target</th><th>burn_short</th>"
                               f"<th>burn_long</th></tr>")
                    for met, b in sorted(burns.items()):
                        flag = " &#x1F525;" if b.get("burning") else ""
                        out.append(
                            f"<tr><td>{html.escape(met)}{flag}</td>"
                            f"<td>{b.get('target')}</td>"
                            f"<td>{b.get('short')}</td>"
                            f"<td>{b.get('long')}</td></tr>")
                    out.append("</table>")
                trail = slo.get("trail") or []
                if trail:
                    out.append("<h4>decision trail</h4><table>"
                               "<tr><th>t</th><th>verdict</th>"
                               "<th>action</th><th>detail</th></tr>")
                    for ev in trail[-20:]:
                        t = time.strftime(
                            "%H:%M:%S", time.localtime(ev.get("t", 0)))
                        out.append(
                            f"<tr><td>{t}</td>"
                            f"<td>{html.escape(ev.get('verdict', ''))}"
                            f"</td>"
                            f"<td>{html.escape(ev.get('action', ''))}"
                            f"</td>"
                            f"<td>{html.escape(ev.get('detail', ''))}"
                            f"</td></tr>")
                    out.append("</table>")
            series = fs.get("series") or {}
            if series:
                out.append("<h3>series</h3><table><tr><th>replica</th>"
                           "<th>model</th><th>metric</th><th>last</th>"
                           "<th>spark</th></tr>")
                for rep, models_ in sorted(series.items()):
                    for mod, mets in sorted(models_.items()):
                        for met, vals in sorted(mets.items()):
                            out.append(
                                f"<tr><td>{html.escape(rep)}</td>"
                                f"<td>{html.escape(mod)}</td>"
                                f"<td>{html.escape(met)}</td>"
                                f"<td>{vals[-1] if vals else ''}</td>"
                                f"<td>{_spark(vals)}</td></tr>")
                out.append("</table>")
        out.append("<p>args: ?fmt=json ?points=N</p></body></html>")
        return "\n".join(out), "text/html"

    def migration_page(req):
        # cross-host KV data plane introspection (brpc_tpu/migrate):
        # global migrate counters, outbound/inbound route matrices,
        # standby sync state, and the live offer-table size (idles at
        # zero under the ack-on-pull discipline).  Lazy import, same
        # discipline as /serving and /kvcache.
        import sys
        if "brpc_tpu.migrate" not in sys.modules:
            return "no migration components registered\n"
        from brpc_tpu.migrate import migration_snapshot
        snap = migration_snapshot()
        if not snap["outbound"] and not snap["inbound"] \
                and not snap["standby"]:
            return "no migration components registered\n"
        return json.dumps(snap, indent=1), "application/json"

    # /hotspots (hotspots_service.cpp; §5.2): the landing page now
    # serves the ALWAYS-ON stage-tagged sampling profiler's ring
    # (ISSUE 6) — folded stacks rooted at their serving stage, the
    # gil_wait_ratio headline, and a per-stage run/wait table.
    # ?seconds=N switches to a synchronous 100Hz burst resample;
    # ?fmt=collapsed emits flamegraph input, ?fmt=pb the pprof proto
    # (reusing the cpu_profile_pb encoder).  The on-demand profilers
    # stay at /hotspots/{cpu,native,contention,heap,growth}.
    def hotspots_index(req):
        from brpc_tpu.builtin import sampler as _sampler
        fmt = req.query.get("fmt", "text")
        if "seconds" in req.query:
            seconds = _seconds(req)
            hz = 100
            stacks = _sampler.burst(seconds, hz)
            if fmt in ("pb", "proto"):
                from brpc_tpu.builtin.pprof_proto import encode_profile
                return (encode_profile(stacks, period_ns=int(1e9 / hz),
                                       duration_ns=int(seconds * 1e9)),
                        "application/octet-stream")
            if fmt == "collapsed":
                return "".join(f"{s} {n}\n"
                               for s, n in stacks.most_common())
            return _sampler.render_folded(
                stacks, f"hotspot burst: {seconds}s @ {hz}Hz, "
                        f"stage-tagged")
        samp = _sampler.HotspotSampler.instance()
        stacks = samp.folded()
        if fmt in ("pb", "proto"):
            from brpc_tpu.builtin.pprof_proto import encode_profile
            hz = float(samp.snapshot()["hz"]) or 10.0
            return (encode_profile(stacks, period_ns=int(1e9 / hz),
                                   duration_ns=int(
                                       samp.window_s * len(samp._windows())
                                       * 1e9)),
                    "application/octet-stream")
        if fmt == "collapsed":
            return "".join(f"{s} {n}\n" for s, n in stacks.most_common())
        snap = samp.snapshot()
        lines = [
            f"--- always-on hotspot sampler: "
            f"{'RUNNING' if snap['running'] else 'STOPPED'} "
            f"@ {snap['hz']:g}Hz, {snap['windows']} windows x "
            f"{snap['window_s']:g}s, {snap['samples']} samples ---",
            f"gil_wait_ratio: {snap['gil_wait_ratio']} "
            f"(lock/queue-wait samples / all samples; also a bvar on "
            f"/brpc_metrics)",
            "",
        ]
        body = _sampler.render_folded(stacks,
                                      "ring profile (stage-tagged)") \
            if stacks else ("(no samples yet — sampler disabled or "
                            "just started; flip hotspot_sampler_enabled "
                            "on /flags)\n")
        tail = ("\nargs: ?seconds=N (synchronous 100Hz burst) "
                "?fmt=collapsed|pb\n"
                "locks: /hotspots/locks (contention ledger)\n"
                "on-demand profilers: /hotspots/cpu /hotspots/native "
                "/hotspots/contention /hotspots/heap /hotspots/growth\n")
        return "\n".join(lines) + body + tail

    def hotspots_locks(req):
        # the lock-contention ledger (ISSUE 6; butil/lockprof.py):
        # per-named-lock acquisitions, contended acquisitions, wait and
        # hold latencies, and the last holder's serving stage — plus
        # the lock-order WITNESS (ISSUE 14): live held sets per thread
        # and any ABBA cycles the observed acquisition orders close
        from brpc_tpu.butil import lockprof
        from brpc_tpu.butil.lockprof import locks_snapshot
        snap = locks_snapshot()
        if req.query.get("fmt") == "json":
            return json.dumps({
                "ledger": snap,
                "witness": {
                    "enabled": lockprof.witness_enabled(),
                    "held": lockprof.held_locks_snapshot(),
                    "edges": lockprof.lock_order_edges(),
                    "violations": [
                        {k: v for k, v in viol.items() if k != "stack"}
                        for viol in lockprof.order_violations()],
                },
            }, indent=1), "application/json"
        if not snap:
            return ("no instrumented locks registered yet\n\n"
                    + lockprof.witness_report())
        cols = ("acquisitions", "contentions", "contention_ratio",
                "wait_avg_us", "wait_p99_us", "wait_max_us",
                "hold_avg_us", "hold_p99_us", "hold_max_us")
        lines = ["--- lock-contention ledger (named hot locks; "
                 "wait/hold recorders also on /brpc_metrics as "
                 "lock_<name>_{wait,hold}_us) ---", "",
                 f"{'lock':<18}" + "".join(f"{c:>18}" for c in cols)
                 + f"  {'last_holder_stage'}"]
        for name, st in snap.items():
            lines.append(
                f"{name:<18}"
                + "".join(f"{st[c]:>18}" for c in cols)
                + f"  {st['last_holder_stage']}")
        return "\n".join(lines) + "\n\n" + lockprof.witness_report()

    def flightrecorder_page(req):
        # native flight recorder (ISSUE 15; src/cc/butil/flight.h):
        # the always-on per-thread event rings inside the C++ core —
        # merged time-ordered tail, per-thread "what is every native
        # thread doing RIGHT NOW" table, recorder stats, and the
        # syscall-attribution counters (ROADMAP 1(e)).  ?limit=N sizes
        # the tail; ?fmt=json returns the structured snapshot.
        from brpc_tpu.butil import flight
        try:
            limit = min(4096, max(1, int(req.query.get("limit", "200"))))
        except ValueError:
            limit = 200
        if not flight.available():
            body = ("native flight recorder unavailable "
                    "(native core not built)\n")
            if req.query.get("fmt") == "json":
                return json.dumps({"available": False}), "application/json"
            return body
        if req.query.get("fmt") == "json":
            return json.dumps({
                "available": True,
                "enabled": flight.enabled(),
                "stats": flight.stats(),
                "syscalls": flight.syscall_counters(),
                "bytes_per_write": flight.write_size_hist(),
                "threads": flight.threads(),
                "events": flight.events(limit),
            }, indent=1), "application/json"
        hist = flight.write_size_hist()
        hist_line = "  ".join(f"le_{k}={v}" for k, v in hist.items()
                              if v) or "(no writes yet)"
        return (flight.report(limit)
                + f"\nbytes_per_write: {hist_line}\n"
                + "\nargs: ?limit=N (tail size) ?fmt=json\n"
                + "flip recording live: /flags?setvalue="
                + "flight_recorder_enabled&value=false\n")

    def _seconds(req, default=1.0):
        try:
            return min(60.0, max(0.05, float(req.query.get("seconds",
                                                           default))))
        except ValueError:
            return default

    def _cpu_profile(req, default_fmt):
        from brpc_tpu.builtin import profiler
        fmt = req.query.get("fmt", default_fmt)
        if fmt in ("pb", "proto"):
            return (profiler.cpu_profile_pb(_seconds(req)),
                    "application/octet-stream")
        return profiler.cpu_profile(_seconds(req), fmt)

    def hotspots_cpu(req):
        return _cpu_profile(req, "text")

    def pprof_profile(req):
        # `go tool pprof http://host:port/pprof/profile` expects a
        # gzipped profile.proto by default (pprof_service.* role);
        # ?fmt=text keeps the human view
        return _cpu_profile(req, "pb")

    def hotspots_native(req):
        # native-thread sampler (dispatchers/executor/drainers);
        # ?fmt=pprof returns the legacy pprof binary for pprof tooling
        from brpc_tpu.builtin import profiler
        return profiler.native_cpu_profile(_seconds(req),
                                           req.query.get("fmt", "folded"))

    def hotspots_contention(req):
        from brpc_tpu.builtin import profiler
        return profiler.contention_profile(_seconds(req),
                                           req.query.get("fmt", "text"))

    def hotspots_heap(req):
        from brpc_tpu.builtin import profiler
        return profiler.heap_profile()

    def hotspots_growth(req):
        from brpc_tpu.builtin import profiler
        return profiler.growth_profile(_seconds(req))

    def vlog_page(req):
        """Verbose-logging control (reference /vlog lists VLOG callsites
        with their verbosity, index_service.cpp:159).  The TPU build's
        log sites are Python loggers plus the native core's min level;
        both are listed and LIVE-SETTABLE: ?set=<logger>=<level> (logger
        '<native>' adjusts the C++ core's sink threshold)."""
        import logging as _logging

        from brpc_tpu._core import core
        msg = ""
        if "set" in req.query:
            name, _, level = req.query["set"].partition("=")
            try:
                lv = int(level) if level.lstrip("-").isdigit() \
                    else getattr(_logging, level.upper())
                if name == "<native>":
                    core.brpc_set_min_log_level(int(lv))
                else:
                    _logging.getLogger(name or None).setLevel(lv)
                msg = f"set {name or 'root'} to {lv}"
            except (AttributeError, ValueError, TypeError) as e:
                msg = f"bad set request: {e}"
        lines = [msg, "logger                               level", "-" * 44]
        root = _logging.getLogger()
        lines.append(f"{'root':36} {_logging.getLevelName(root.level)}")
        for name in sorted(_logging.Logger.manager.loggerDict):
            lg = _logging.Logger.manager.loggerDict[name]
            if isinstance(lg, _logging.Logger):
                lines.append(
                    f"{name:36} "
                    f"{_logging.getLevelName(lg.level)}"
                    f"{' (inherits)' if lg.level == 0 else ''}")
        lines.append(f"{'<native>':36} (set via ?set=<native>=<int>)")
        lines.append("")
        lines.append("usage: /vlog?set=<logger>=<level>   e.g. "
                     "?set=brpc_tpu=DEBUG or ?set=<native>=2")
        return "\n".join(filter(None, lines)) + "\n"

    def dir_page(req):
        """Filesystem browser (reference dir_service.cpp): directories
        list entries as links, regular files stream back (bounded).
        GATED like the reference's -enable_dir_service (off by default):
        unauthenticated whole-filesystem read must be an explicit
        operator choice — flip it live on /flags."""
        import html as _html
        import os as _os
        import stat as _stat
        from urllib.parse import quote as _q, unquote as _unq

        from brpc_tpu import flags as _f
        if not _f.get_flag("enable_dir_service"):
            return ("/dir is disabled; set enable_dir_service=true on "
                    "/flags to allow filesystem browsing "
                    "(reference -enable_dir_service)\n")
        target = _unq(req.path[len("/dir"):]) or "/"
        target = _os.path.normpath(target)
        if not target.startswith("/"):
            target = "/" + target
        try:
            if _os.path.isdir(target):
                entries = sorted(_os.listdir(target))
                rows = []
                parent = _os.path.dirname(target.rstrip("/")) or "/"
                rows.append(f'<li><a href="/dir{_q(parent)}">..</a></li>')
                for e in entries:
                    p = _os.path.join(target, e)
                    mark = "/" if _os.path.isdir(p) else ""
                    rows.append(f'<li><a href="/dir{_q(p)}">'
                                f'{_html.escape(e)}{mark}</a></li>')
                return (f"<html><body><h3>{_html.escape(target)}</h3>"
                        f"<ul>{''.join(rows)}</ul></body></html>",
                        "text/html")
            # regular files only: an open() on a FIFO would park this
            # console worker forever
            st_ = _os.stat(target)
            if not _stat.S_ISREG(st_.st_mode):
                return f"not a regular file: {target}\n"
            with open(target, "rb") as f:
                data = f.read(1 << 20)   # bounded: first 1MB
            return data, "application/octet-stream"
        except OSError as e:
            return f"cannot read {target}: {e}\n"

    routes = {
        "/": index, "/index": index,
        "/dashboard": dashboard,
        "/status": status,
        "/vars": vars_page,
        "/flags": flags_page,
        "/health": health,
        "/version": version,
        "/connections": connections,
        "/sockets": sockets,
        "/bthreads": bthreads,
        "/rpcz": rpcz_page,
        "/brpc_metrics": metrics,
        "/services": services_page,
        "/protobufs": services_page,
        "/memory": memory,
        "/ici": ici,
        "/serving": serving_page,
        "/serving/generations": serving_generations_page,
        "/kvcache": kvcache_page,
        "/migration": migration_page,
        "/cluster": cluster_page,
        "/fleet": fleet_page,
        "/psserve": psserve_page,
        "/flightrecorder": flightrecorder_page,
        "/hotspots": hotspots_index,
        "/hotspots/locks": hotspots_locks,
        "/hotspots/cpu": hotspots_cpu,
        "/hotspots/native": hotspots_native,
        "/hotspots/contention": hotspots_contention,
        "/hotspots/heap": hotspots_heap,
        "/hotspots/growth": hotspots_growth,
        # remote-pprof style aliases (pprof_service.*): same data under the
        # /pprof prefix so generic tooling can scrape it
        "/pprof/profile": pprof_profile,
        "/pprof/profile_native": hotspots_native,
        "/pprof/contention": hotspots_contention,
        "/pprof/heap": hotspots_heap,
        "/pprof/growth": hotspots_growth,
        "/vlog": vlog_page,
        "/dir": dir_page,
        "/dir/": dir_page,     # prefix route: /dir/<abs path>
    }
    return routes


class _DashHistory:
    """2-minute per-second (count, sum_us) history per method — the data
    behind /dashboard's sparklines (the reference /index embeds
    jquery+flot charts; ours are dependency-free inline SVG)."""

    def __init__(self, server):
        from collections import deque
        self._server = server
        self.samples = deque(maxlen=120)   # (ts, {key: (count, sum_us)})
        self._started = False
        self._mu = threading.Lock()

    def ensure(self):
        with self._mu:
            if self._started:
                return
            self._started = True
            threading.Thread(target=self._run, daemon=True,
                             name="console-dashboard").start()

    def _run(self):
        while self._server.running:
            snap = {}
            for key, st in self._server.method_statuses.items():
                c, s_us, _ = st.latency_rec.snapshot()  # one native call
                snap[key] = (c, s_us)
            self.samples.append((time.time(), snap))
            time.sleep(1.0)


def _dash_history_for(server) -> _DashHistory:
    """One history (and one sampler thread) per Server instance, however
    many routers are built for it."""
    h = getattr(server, "_dash_history", None)
    if h is None or h._server is not server:
        h = _DashHistory(server)
        server._dash_history = h
    return h


def _spark(points, width=240, height=36):
    if len(points) < 2:
        return "<svg width='240' height='36'></svg>"
    top = max(points) or 1
    n = len(points)
    coords = " ".join(
        f"{i * width / (n - 1):.1f},"
        f"{height - 2 - (v / top) * (height - 6):.1f}"
        for i, v in enumerate(points))
    return (f"<svg width='{width}' height='{height}'>"
            f"<polyline points='{coords}' fill='none' "
            f"stroke='#36c' stroke-width='1.5'/>"
            f"<text x='{width - 4}' y='10' text-anchor='end' "
            f"font-size='9' fill='#666'>{top:.4g}</text></svg>")


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.6g}"
    return v


def _apply_flag_side_effects(name: str) -> None:
    from brpc_tpu.flags import get_flag
    if name == "rpcz_enabled" or name == "rpcz_sample_rate":
        rpcz.set_enabled(get_flag("rpcz_enabled", True),
                         get_flag("rpcz_sample_rate", 1.0))
    elif name == "rpcz_database_dir":
        rpcz.set_database_dir(get_flag("rpcz_database_dir", "") or None)
    elif name == "health_check_interval_s":
        from brpc_tpu.policy import health_check
        health_check.health_check_interval_s = \
            get_flag("health_check_interval_s", 1.0)
    elif name == "hotspot_sampler_enabled":
        from brpc_tpu.builtin.sampler import HotspotSampler
        if get_flag("hotspot_sampler_enabled", True):
            HotspotSampler.ensure_started()
        else:
            HotspotSampler.instance().stop()
    elif name == "flight_recorder_enabled":
        from brpc_tpu.butil import flight
        flight.apply_flag()
