from brpc_tpu.butil.endpoint import EndPoint, str2endpoint  # noqa: F401
from brpc_tpu.butil.doubly_buffered import DoublyBufferedData  # noqa: F401
from brpc_tpu.butil.containers import CaseIgnoredDict, MRUCache  # noqa: F401
