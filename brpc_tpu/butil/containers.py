"""Container utilities (SURVEY.md §2.1 "other containers" row).

CaseIgnoredDict — the case_ignored_flat_map analog (reference
    butil/containers/case_ignored_flat_map.h, used by HttpHeader): a
    mapping with case-insensitive lookup that PRESERVES the original key
    casing on iteration, so proxied HTTP headers go back out the way they
    came in instead of lower-cased.

MRUCache — most-recently-used cache (reference butil/containers/
    mru_cache.h): bounded mapping evicting the least-recently-used entry.
    Backs the console router's route-resolution cache.
"""
from __future__ import annotations

from collections import OrderedDict
from collections.abc import MutableMapping


class CaseIgnoredDict(MutableMapping):
    """dict with case-insensitive str keys, original casing preserved.

    Non-string keys are passed through untouched (so it can hold e.g.
    pseudo-header tuples without surprises).
    """

    __slots__ = ("_data",)

    def __init__(self, items=None, **kw):
        # _data: canonical(lower) key -> (original_key, value)
        self._data = {}
        if items is not None:
            self.update(items)
        if kw:
            self.update(kw)

    @staticmethod
    def _canon(key):
        return key.lower() if isinstance(key, str) else key

    def __setitem__(self, key, value):
        self._data[self._canon(key)] = (key, value)

    def __getitem__(self, key):
        return self._data[self._canon(key)][1]

    def __delitem__(self, key):
        del self._data[self._canon(key)]

    def __contains__(self, key):
        return self._canon(key) in self._data

    def __iter__(self):
        for orig, _ in self._data.values():
            yield orig

    def __len__(self):
        return len(self._data)

    def __repr__(self):
        return f"CaseIgnoredDict({dict(self.items())!r})"

    def copy(self):
        return CaseIgnoredDict(self.items())


class MRUCache:
    """Bounded most-recently-used cache.

    get() refreshes recency; put() evicts the least-recently-used entry
    once `capacity` is exceeded.  Not thread-safe on its own — callers in
    concurrent contexts wrap operations or tolerate racy refreshes (the
    router's cache does: a stale miss just redoes the prefix scan).
    """

    __slots__ = ("capacity", "_data", "hits", "misses")

    _MISSING = object()

    def __init__(self, capacity: int = 128):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._data: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key, default=None):
        try:
            value = self._data[key]
        except KeyError:
            self.misses += 1
            return default
        # refresh recency (move_to_end may race with an eviction from
        # another thread; a KeyError there means the entry just fell out)
        try:
            self._data.move_to_end(key)
        except KeyError:
            pass
        self.hits += 1
        return value

    def put(self, key, value) -> None:
        self._data[key] = value
        try:
            self._data.move_to_end(key)
        except KeyError:
            pass
        while len(self._data) > self.capacity:
            try:
                self._data.popitem(last=False)
            except KeyError:
                break

    def __contains__(self, key):
        return key in self._data

    def __len__(self):
        return len(self._data)

    def clear(self) -> None:
        self._data.clear()
