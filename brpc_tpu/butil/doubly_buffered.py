"""DoublyBufferedData — read-mostly data with wait-free reads.

Reference: src/butil/containers/doubly_buffered_data.h:38-75 — readers take a
thread-local lock on the foreground copy; a writer modifies the background
copy, atomically flips, then takes every reader lock once to ensure no reader
still uses the old foreground.  Backs every load balancer's server list.

Python build keeps the same contract with simpler machinery: reads are a
single attribute load of an immutable snapshot (atomic under the GIL and under
free-threading, since snapshots are never mutated); writes copy-modify-flip
under a writer mutex.  Same wait-free read property, idiomatic substrate.
"""
from __future__ import annotations

import threading
from typing import Callable, Generic, TypeVar

T = TypeVar("T")


class DoublyBufferedData(Generic[T]):
    def __init__(self, initial: T):
        self._fg: T = initial
        self._mu = threading.Lock()

    def read(self) -> T:
        """Wait-free: returns the current immutable snapshot."""
        return self._fg

    def modify(self, fn: Callable[[T], T]) -> T:
        """Apply fn to a copy of the current value and flip.  fn must treat
        its input as read-only and return the new snapshot."""
        with self._mu:
            new = fn(self._fg)
            self._fg = new
            return new
