"""EndPoint — address value type (reference src/butil/endpoint.{h,cpp}).

Extends the reference's ip:port model with the TPU fabric: an endpoint is
either a host address ("10.0.0.3:8000", "[::1]:8000", "unix:/tmp/s.sock")
or an ICI device address ("ici://slice0/4" = chip 4 in slice0), so channels
can target either the DCN (TCP) transport or the in-pod ICI transport with
one address grammar.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class EndPoint:
    host: str
    port: int = 0
    scheme: str = "tcp"   # tcp | unix | ici

    def __str__(self) -> str:
        if self.scheme == "ici":
            return f"ici://{self.host}/{self.port}"
        if self.scheme == "unix":
            return f"unix:{self.host}"
        if ":" in self.host:  # ipv6
            return f"[{self.host}]:{self.port}"
        return f"{self.host}:{self.port}"

    @property
    def is_ici(self) -> bool:
        return self.scheme == "ici"


def str2endpoint(s: str) -> EndPoint:
    """Parse "host:port", "[v6]:port", "unix:/path", "ici://slice/chip"."""
    s = s.strip()
    if s.startswith("ici://"):
        rest = s[6:]
        if "/" in rest:
            slice_name, chip = rest.rsplit("/", 1)
            return EndPoint(slice_name, int(chip), "ici")
        return EndPoint(rest, 0, "ici")
    if s.startswith("unix:"):
        return EndPoint(s[5:], 0, "unix")
    if s.startswith("["):  # [v6]:port
        close = s.index("]")
        host = s[1:close]
        port = int(s[close + 2 :]) if close + 2 <= len(s) - 1 else 0
        return EndPoint(host, port)
    if ":" in s:
        host, port = s.rsplit(":", 1)
        return EndPoint(host or "127.0.0.1", int(port))
    return EndPoint(s, 0)
