"""Fiber/task-local storage — the bthread_key_create/getspecific analog.

Reference: src/bthread/key.cpp:49 (bthread keys: per-bthread slots that
travel with the bthread across workers, with destructors at bthread
exit).  TPU build: the scheduling unit user code rides here is a Python
callable hopping between threads/executors, so fiber-locals are built on
``contextvars`` — the host-runtime mechanism whose Context object
travels with scheduled work exactly the way a bthread's key table
travels with the bthread.

API shape mirrors the reference:

    key = fiber_local.key_create(destructor=close_it)   # bthread_key_create
    fiber_local.set_specific(key, value)                # bthread_setspecific
    v = fiber_local.get_specific(key)                   # bthread_getspecific
    fiber_local.key_delete(key)                         # bthread_key_delete

and the hop primitive that makes them FIBER-locals rather than
thread-locals:

    fn2 = fiber_local.wrap(fn)      # captures the caller's context
    fiber_local.spawn(fn, *args)    # run fn on the executor IN that
                                    # context (locals + rpcz span travel)

rpcz's current-span propagation rides the same mechanism
(brpc_tpu/rpcz.py), so a span set in a handler follows work the handler
spawns — the span-propagation-through-a-fiber-hop contract.
"""
from __future__ import annotations

import contextvars
import itertools
import os
import threading
from typing import Any, Callable, Optional

_key_ids = itertools.count(1)


# distinguishes "never set" from an explicitly stored None (bthread
# keys distinguish NULL-set from unset via the key table)
_UNSET = object()


class FiberLocalKey:
    """One fiber-local slot (bthread_key_t).  The optional destructor
    runs for values the FIBER ITSELF set when the hop exits — inherited
    values belong to the parent (bthread-exit destructor semantics,
    key.cpp: a bthread destroys only its own key table)."""

    __slots__ = ("id", "_var", "destructor", "deleted")

    def __init__(self, destructor: Optional[Callable[[Any], None]] = None):
        self.id = next(_key_ids)
        self._var = contextvars.ContextVar(f"fiber_local_{self.id}",
                                           default=_UNSET)
        self.destructor = destructor
        self.deleted = False


_keys_mu = threading.Lock()
_live_keys: dict[int, FiberLocalKey] = {}


def key_create(destructor: Optional[Callable[[Any], None]] = None
               ) -> FiberLocalKey:
    key = FiberLocalKey(destructor)
    with _keys_mu:
        _live_keys[key.id] = key
    return key


def key_delete(key: FiberLocalKey) -> None:
    """Invalidate the key: subsequent get/set raise (the reference's
    versioned-key invalidation; key.cpp reuses slots by version)."""
    key.deleted = True
    with _keys_mu:
        _live_keys.pop(key.id, None)


def set_specific(key: FiberLocalKey, value) -> None:
    if key.deleted:
        raise KeyError("fiber-local key was deleted")
    key._var.set(value)


def get_specific(key: FiberLocalKey, default=None):
    if key.deleted:
        raise KeyError("fiber-local key was deleted")
    v = key._var.get()
    return default if v is _UNSET else v


def _snapshot() -> dict:
    with _keys_mu:
        keys = list(_live_keys.values())
    return {k.id: k._var.get() for k in keys}


def run_destructors(entry_snapshot: Optional[dict] = None) -> None:
    """Run destructors for values THIS fiber set (bthread-exit
    semantics; invoked automatically by wrap()).  With an entry
    snapshot, values inherited unchanged from the parent context are
    SKIPPED — destroying a parent's live resource from a side hop (and
    once per hop) is exactly what bthread keys don't do."""
    with _keys_mu:
        keys = list(_live_keys.values())
    for key in keys:
        v = key._var.get()
        if v is _UNSET or v is None:
            continue
        if entry_snapshot is not None and \
                v is entry_snapshot.get(key.id, _UNSET):
            continue            # inherited, not ours to destroy
        if key.destructor is not None:
            try:
                key.destructor(v)
            except Exception:
                import logging
                logging.exception("fiber-local destructor raised")
        key._var.set(_UNSET)


def wrap(fn: Callable, *, destructors: bool = True) -> Callable:
    """Bind `fn` to the CALLER's context: wherever the returned callable
    later runs (another thread, the executor, a timer), every
    fiber-local — and the rpcz current span — reads as it did here."""
    ctx = contextvars.copy_context()

    def bound(*args, **kwargs):
        def _run():
            snap = _snapshot() if destructors else None
            try:
                return fn(*args, **kwargs)
            finally:
                if destructors:
                    run_destructors(snap)
        return ctx.copy().run(_run)

    return bound


_spawn_pool = None
_spawn_mu = threading.Lock()


def _pool():
    # Elastic: spawn()'s advertised use is offloading BLOCKING work, so a
    # fixed tiny pool lets 8 parked spawns starve every later done().
    # ThreadPoolExecutor only grows on demand, so a generous max costs
    # nothing while idle; mirror usercode_backup_pool's grow-on-demand.
    global _spawn_pool
    with _spawn_mu:
        if _spawn_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            workers = max(32, 4 * (os.cpu_count() or 1))
            _spawn_pool = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="fiber-spawn")
        return _spawn_pool


def spawn(fn: Callable, *args, **kwargs):
    """bthread_start_background analog for Python callables: run `fn` on
    a worker IN the caller's context (fiber-locals + rpcz span travel
    with it).  Returns a Future."""
    return _pool().submit(wrap(fn), *args, **kwargs)
