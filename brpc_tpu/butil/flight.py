"""Python surface of the native flight recorder (ISSUE 15;
src/cc/butil/flight.h).

The C++ core records every load-bearing transition — executor
task-begin/end, steal, park/unpark, butex wait/wake/timeout, timer
fire/cancel, socket lifecycle + read/write syscalls, TokenRing batch
push/pop/terminal — into always-on per-thread overwrite-oldest rings.
This module parses the native text dumps into structured events, feeds
the ``/flightrecorder`` console page, exposes the recorder + syscall
attribution counters on ``/vars`` / ``/brpc_metrics``, and renders the
wedge-autopsy report ``tests/wedge_guard.py`` prints on every deadline
miss.

Everything degrades to empty results when the native core is
unavailable — the recorder is an observability surface, never a
dependency.
"""
from __future__ import annotations

import ctypes

from brpc_tpu.flags import define_flag, get_flag

define_flag("flight_recorder_enabled", True,
            "record native-core transitions (executor/butex/timer/"
            "socket/token-ring) into the always-on per-thread flight "
            "rings; off = the record hook is a single relaxed-load "
            "no-op", reloadable=True)

# bytes-per-write histogram bucket labels (log2 from 64B; the last
# bucket is open-ended) — must match Socket::kWriteHistBuckets.
WRITE_HIST_BUCKETS = 16
WRITE_HIST_LABELS = tuple(
    str(64 << i) for i in range(WRITE_HIST_BUCKETS - 1)) + ("+inf",)


def _core():
    """The raw CDLL, or None when the native build is unavailable."""
    from brpc_tpu import native_path
    lib = native_path._core_lib()
    return lib.core if lib is not None else None


def available() -> bool:
    return _core() is not None


def enabled() -> bool:
    c = _core()
    return bool(c.brpc_flight_enabled()) if c is not None else False


def set_enabled(on: bool) -> None:
    c = _core()
    if c is not None:
        c.brpc_flight_enable(1 if on else 0)


def apply_flag() -> None:
    """Push the reloadable flag's value into the native core (the
    /flags side-effect hook in builtin/services.py)."""
    set_enabled(bool(get_flag("flight_recorder_enabled", True)))


def stats() -> dict:
    c = _core()
    if c is None:
        return {"events": 0, "threads": 0, "dropped": 0}
    ev, th, dr = (ctypes.c_int64(), ctypes.c_int64(), ctypes.c_int64())
    c.brpc_flight_stats(ctypes.byref(ev), ctypes.byref(th),
                        ctypes.byref(dr))
    return {"events": ev.value, "threads": th.value, "dropped": dr.value}


def events(limit: int = 512) -> list[dict]:
    """Merged time-ordered tail across every native thread's ring,
    oldest first."""
    c = _core()
    if c is None:
        return []
    buf = ctypes.create_string_buffer(1 << 20)
    n = c.brpc_flight_dump(buf, len(buf), int(limit))
    out = []
    if n <= 0:
        return out
    for line in buf.value.decode("utf-8", "replace").splitlines():
        parts = line.split()
        # <ts_us> <tid> <name> <kind> a=0x<hex> b=<dec>
        if len(parts) != 6:
            continue
        try:
            out.append({
                "ts_us": int(parts[0]),
                "tid": int(parts[1]),
                "thread": parts[2],
                "kind": parts[3],
                "a": int(parts[4][2:], 16),
                "b": int(parts[5][2:]),
            })
        except ValueError:
            continue
    return out


def threads() -> list[dict]:
    """Per-thread state table: what every native thread last did and
    how long ago."""
    c = _core()
    if c is None:
        return []
    buf = ctypes.create_string_buffer(1 << 18)
    n = c.brpc_flight_threads(buf, len(buf))
    out = []
    if n <= 0:
        return out
    for line in buf.value.decode("utf-8", "replace").splitlines():
        parts = line.split()
        # <tid> <name> <live|exited> events= dropped= last= age_us=
        if len(parts) != 7:
            continue
        try:
            kv = dict(p.split("=", 1) for p in parts[3:])
            out.append({
                "tid": int(parts[0]),
                "thread": parts[1],
                "live": parts[2] == "live",
                "events": int(kv["events"]),
                "dropped": int(kv["dropped"]),
                "last": kv["last"],
                "age_us": int(kv["age_us"]),
            })
        except (ValueError, KeyError):
            continue
    return out


def syscall_counters() -> dict:
    """Process-wide read/write syscall counts + the dispatch write
    batch's coalescing hit/miss counters (ROADMAP 1(e): the
    frame-coalescing before/after metric)."""
    c = _core()
    if c is None:
        return {"read_syscalls": 0, "write_syscalls": 0,
                "batch_hits": 0, "batch_misses": 0}
    vals = [ctypes.c_int64() for _ in range(4)]
    c.brpc_syscall_counters(*[ctypes.byref(v) for v in vals])
    return {"read_syscalls": vals[0].value,
            "write_syscalls": vals[1].value,
            "batch_hits": vals[2].value,
            "batch_misses": vals[3].value}


def write_size_hist() -> dict:
    """bytes-per-write histogram: {bucket_upper_bound_label: count}."""
    c = _core()
    if c is None:
        return {}
    arr = (ctypes.c_int64 * WRITE_HIST_BUCKETS)()
    n = c.brpc_write_size_hist(arr, WRITE_HIST_BUCKETS)
    return {WRITE_HIST_LABELS[i]: arr[i] for i in range(n)}


def socket_syscalls(sid: int) -> dict | None:
    """Per-socket syscall attribution, or None for a stale/failed id."""
    c = _core()
    if c is None:
        return None
    rd, wr = ctypes.c_int64(), ctypes.c_int64()
    if c.brpc_socket_syscalls(ctypes.c_uint64(sid), ctypes.byref(rd),
                              ctypes.byref(wr)) != 0:
        return None
    return {"read_syscalls": rd.value, "write_syscalls": wr.value}


def report(limit: int = 120) -> str:
    """The wedge-autopsy text: recorder stats, the per-thread table
    (every native thread's LAST event and its age), then the merged
    event tail — what wedge_guard prints to stderr on a deadline miss
    so the next tier-1 wedge names which worker/socket/butex stopped
    advancing and what it last did."""
    if not available():
        return "native flight recorder unavailable (no native core)\n"
    st = stats()
    sc = syscall_counters()
    lines = [
        f"flight recorder: {'ENABLED' if enabled() else 'DISABLED'} · "
        f"{st['threads']} threads · {st['events']} events recorded "
        f"({st['dropped']} overwritten)",
        f"syscalls: read={sc['read_syscalls']} "
        f"write={sc['write_syscalls']} "
        f"batch_hits={sc['batch_hits']} "
        f"batch_misses={sc['batch_misses']}",
        "",
        "--- per-thread state (last event of every native thread) ---",
    ]
    for t in threads():
        lines.append(
            f"  tid={t['tid']:<8} {t['thread']:<12} "
            f"{'live' if t['live'] else 'exited':<7} "
            f"last={t['last']:<14} age_us={t['age_us']:<12} "
            f"events={t['events']} dropped={t['dropped']}")
    lines.append("")
    lines.append(f"--- merged event tail (oldest first, "
                 f"last {limit}) ---")
    for e in events(limit):
        lines.append(f"  {e['ts_us']} {e['thread']:<12} "
                     f"{e['kind']:<14} a=0x{e['a']:x} b={e['b']}")
    return "\n".join(lines) + "\n"


_exposed = False


def expose_flight_variables() -> None:
    """Recorder + syscall-attribution counters on /vars and
    /brpc_metrics (idempotent; called from Server.start next to
    expose_default_variables).  The PassiveStatus getters read the
    native counters directly and return zeros when the core is absent,
    so exposure is always safe."""
    global _exposed
    if _exposed:
        return
    _exposed = True
    from brpc_tpu.bvar.multi_dimension import MultiDimension
    from brpc_tpu.bvar.reducer import PassiveStatus

    PassiveStatus(lambda: stats()["events"]) \
        .expose("flight_events_recorded")
    PassiveStatus(lambda: stats()["threads"]) \
        .expose("flight_threads_tracked")
    PassiveStatus(lambda: stats()["dropped"]) \
        .expose("flight_events_overwritten")
    PassiveStatus(lambda: int(enabled())).expose("flight_enabled")
    PassiveStatus(lambda: syscall_counters()["read_syscalls"]) \
        .expose("socket_read_syscalls")
    PassiveStatus(lambda: syscall_counters()["write_syscalls"]) \
        .expose("socket_write_syscalls")
    PassiveStatus(lambda: syscall_counters()["batch_hits"]) \
        .expose("socket_write_batch_hits")
    PassiveStatus(lambda: syscall_counters()["batch_misses"]) \
        .expose("socket_write_batch_misses")

    # bytes-per-write histogram as an mbvar: renders on /brpc_metrics
    # as socket_bytes_per_write{le="64"} ... — Prometheus-histogram
    # shaped without a new exporter branch
    md = MultiDimension(["le"], lambda: None,
                        name="socket_bytes_per_write")
    for label in WRITE_HIST_LABELS:
        cell = PassiveStatus(
            (lambda lb: lambda: write_size_hist().get(lb, 0))(label))
        md._stats[(label,)] = cell
    md.expose("socket_bytes_per_write")
