"""Per-stage host-CPU accounting (ISSUE 6).

``time.thread_time()`` deltas recorded by the serving hot paths —
batch formation (batcher drainer), prefill and decode-step bookkeeping
(engine thread, MINUS the model-fn calls, which are accounted
separately under ``model_compute``), emit fan-out (per-request emitter
threads), span submit (the bvar collector drainer) — accumulate into
per-stage Adders, and roll up into ONE honest headline:

    serving_host_us_per_token = python-host CPU microseconds spent
        across all serving stages / tokens emitted

The native frame pump runs no Python and cannot be thread_time()'d
from here; its cost is measured by the ``frame_pump`` microbench rung
(bench.py microbench) instead.  ``model_compute`` (the jit'd
prefill/step calls) is deliberately EXCLUDED from the per-token
rollup: the metric exists to size the de-GIL prize (ROADMAP item 4),
which is host bookkeeping, not model math.
"""
from __future__ import annotations

from brpc_tpu.bvar.reducer import Adder, PassiveStatus

# stages that are python-host work (counted in the per-token rollup)
HOST_STAGES = ("batch_formation", "prefill", "decode_step",
               "emit_fanout", "span_submit")
# informational: CPU burned inside the user model fns (jit'd compute)
MODEL_STAGE = "model_compute"

_adders: dict[str, Adder] = {
    s: Adder(f"serving_host_cpu_{s}_us")
    for s in HOST_STAGES + (MODEL_STAGE,)
}

# total tokens emitted by every engine (the rollup's denominator)
tokens_total = Adder("serving_tokens_total")


def add(stage: str, us: float) -> None:
    """Record `us` microseconds of host CPU attributed to `stage`."""
    if us > 0:
        _adders[stage].add(int(us))


def stage_us(stage: str) -> int:
    return _adders[stage].get_value()


def host_us_per_token() -> float:
    toks = tokens_total.get_value()
    if not toks:
        return 0.0
    host = sum(_adders[s].get_value() for s in HOST_STAGES)
    return round(host / toks, 2)


def snapshot() -> dict:
    return {
        "per_stage_us": {s: _adders[s].get_value()
                         for s in HOST_STAGES + (MODEL_STAGE,)},
        "tokens": tokens_total.get_value(),
        "host_us_per_token": host_us_per_token(),
    }


PassiveStatus(host_us_per_token).expose("serving_host_us_per_token")
