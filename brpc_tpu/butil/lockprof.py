"""Lock-contention ledger (ISSUE 6) — instrumented lock wrappers for
the NAMED hot locks of the serving path.

The reference profiles mutex contention by sampling contended
pthread/bthread mutex acquisitions into folded stacks
(bthread/mutex.cpp ContentionProfiler).  The Python-layer analog here
is a LEDGER, not a sampler: each named hot lock (batcher queue,
KVCacheStore, engine slot map, per-request emit buffers, rpcz submit)
is wrapped in an :class:`InstrumentedLock` that records

  * acquisitions and CONTENDED acquisitions (the fast try-acquire hit
    means zero cost beyond one C call when uncontended),
  * wait time per contended acquisition (LatencyRecorder — avg/p99/max
    ride the existing /brpc_metrics scrape as a summary),
  * hold time per critical section,
  * the last holder's serving stage (butil/stagetag.py) — when a lock
    is hot, "who holds it" is the actionable half of the answer.

Stats are shared PER NAME, not per instance: a thousand per-request
emit buffers aggregate into one "serving.emit_buf" ledger row, so the
native LatencyRecorder slot pool is never exhausted by lock churn.

The wrapper satisfies the ``threading.Condition`` lock protocol
(acquire/release/_release_save/_acquire_restore/_is_owned), so a
Condition built over it keeps correct semantics while every reacquire
after ``wait()`` is accounted like any other acquisition.
"""
from __future__ import annotations

import threading
import time

from brpc_tpu.butil import stagetag

_registry: dict[str, "LockStats"] = {}
_registry_mu = threading.Lock()


class LockStats:
    """Aggregated ledger entry for one named lock (class)."""

    __slots__ = ("name", "wait_rec", "hold_rec", "acquisitions",
                 "contentions", "last_holder_stage")

    def __init__(self, name: str):
        # import here, not at module top: bvar's LatencyRecorder binds
        # the native core, and this module must stay importable for
        # stage tagging alone
        from brpc_tpu.bvar import Adder, LatencyRecorder
        self.name = name
        safe = name.replace(".", "_").replace("-", "_")
        self.wait_rec = LatencyRecorder(f"lock_{safe}_wait_us")
        self.hold_rec = LatencyRecorder(f"lock_{safe}_hold_us")
        self.acquisitions = Adder(f"lock_{safe}_acquisitions")
        self.contentions = Adder(f"lock_{safe}_contentions")
        self.last_holder_stage = ""

    def snapshot(self) -> dict:
        acq = self.acquisitions.get_value()
        con = self.contentions.get_value()
        return {
            "acquisitions": acq,
            "contentions": con,
            "contention_ratio": round(con / acq, 4) if acq else 0.0,
            "wait_avg_us": round(self.wait_rec.latency(), 1),
            "wait_p99_us": round(self.wait_rec.latency_percentile(0.99), 1),
            "wait_max_us": self.wait_rec.max_latency(),
            "hold_avg_us": round(self.hold_rec.latency(), 1),
            "hold_p99_us": round(self.hold_rec.latency_percentile(0.99), 1),
            "hold_max_us": self.hold_rec.max_latency(),
            "last_holder_stage": self.last_holder_stage,
        }


def lock_stats(name: str) -> LockStats:
    """Get-or-create the shared ledger entry for `name`."""
    st = _registry.get(name)
    if st is None:
        with _registry_mu:
            st = _registry.get(name)
            if st is None:
                st = _registry[name] = LockStats(name)
    return st


def locks_snapshot() -> dict[str, dict]:
    """All ledger rows — the /hotspots/locks console page's data."""
    with _registry_mu:
        entries = dict(_registry)
    return {name: st.snapshot() for name, st in sorted(entries.items())}


class InstrumentedLock:
    """A Lock/RLock wrapper feeding the shared ledger entry `name`.

    ``inner`` defaults to a plain ``threading.Lock``; pass
    ``threading.RLock()`` for reentrant use.  Multiple wrapper
    instances may (and for per-request locks, should) share one name.
    """

    __slots__ = ("_inner", "_is_rlock", "stats", "_depth", "_t_hold")

    def __init__(self, name: str, inner=None):
        self._inner = inner if inner is not None else threading.Lock()
        # RLocks carry the Condition protocol natively; plain Locks
        # need our emulation below
        self._is_rlock = hasattr(self._inner, "_is_owned")
        self.stats = lock_stats(name)
        self._depth = 0          # touched only while holding the lock
        self._t_hold = 0.0

    # ---- core protocol ----

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._inner.acquire(False):
            got = True
        elif not blocking:
            return False
        else:
            st = self.stats
            st.contentions.add(1)
            t0 = time.monotonic()
            got = self._inner.acquire(True, timeout)
            if got:
                st.wait_rec.add(int((time.monotonic() - t0) * 1e6))
        if got:
            self._begin_hold()
        return got

    def release(self) -> None:
        self._end_hold()
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return locked() if locked is not None else self._depth > 0

    # ---- hold accounting (caller holds the lock at both sites) ----

    def _begin_hold(self) -> None:
        self._depth += 1
        if self._depth == 1:
            self._t_hold = time.monotonic()
            st = self.stats
            st.acquisitions.add(1)
            st.last_holder_stage = stagetag.current_stage()

    def _end_hold(self) -> None:
        self._depth -= 1
        if self._depth == 0:
            self.stats.hold_rec.add(
                int((time.monotonic() - self._t_hold) * 1e6))

    # ---- threading.Condition protocol ----

    def _release_save(self):
        """Full release (all recursion levels) for Condition.wait."""
        depth, self._depth = self._depth, 1
        self._end_hold()
        if self._is_rlock:
            return (self._inner._release_save(), depth)
        self._inner.release()
        return (None, depth)

    def _acquire_restore(self, state) -> None:
        inner_state, depth = state
        st = self.stats
        t0 = time.monotonic()
        if self._is_rlock:
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        waited = time.monotonic() - t0
        # a reacquire that had to park behind another holder is real
        # contention; an immediate reacquire is not worth a record
        if waited >= 50e-6:
            st.contentions.add(1)
            st.wait_rec.add(int(waited * 1e6))
        self._begin_hold()
        self._depth = depth

    def _is_owned(self) -> bool:
        if self._is_rlock:
            return self._inner._is_owned()
        # plain-Lock emulation (mirrors threading.Condition's fallback),
        # on the INNER lock so the probe never pollutes the ledger
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self) -> str:
        return (f"<InstrumentedLock {self.stats.name!r} "
                f"depth={self._depth}>")
