"""Lock-contention ledger (ISSUE 6) — instrumented lock wrappers for
the NAMED hot locks of the serving path.

The reference profiles mutex contention by sampling contended
pthread/bthread mutex acquisitions into folded stacks
(bthread/mutex.cpp ContentionProfiler).  The Python-layer analog here
is a LEDGER, not a sampler: each named hot lock (batcher queue,
KVCacheStore, engine slot map, per-request emit buffers, rpcz submit)
is wrapped in an :class:`InstrumentedLock` that records

  * acquisitions and CONTENDED acquisitions (the fast try-acquire hit
    means zero cost beyond one C call when uncontended),
  * wait time per contended acquisition (LatencyRecorder — avg/p99/max
    ride the existing /brpc_metrics scrape as a summary),
  * hold time per critical section,
  * the last holder's serving stage (butil/stagetag.py) — when a lock
    is hot, "who holds it" is the actionable half of the answer.

Stats are shared PER NAME, not per instance: a thousand per-request
emit buffers aggregate into one "serving.emit_buf" ledger row, so the
native LatencyRecorder slot pool is never exhausted by lock churn.

The wrapper satisfies the ``threading.Condition`` lock protocol
(acquire/release/_release_save/_acquire_restore/_is_owned), so a
Condition built over it keeps correct semantics while every reacquire
after ``wait()`` is accounted like any other acquisition.
"""
from __future__ import annotations

import os
import sys
import threading
import time
import traceback

from brpc_tpu.butil import stagetag

_registry: dict[str, "LockStats"] = {}
_registry_mu = threading.Lock()


class LockStats:
    """Aggregated ledger entry for one named lock (class)."""

    __slots__ = ("name", "wait_rec", "hold_rec", "acquisitions",
                 "contentions", "last_holder_stage")

    def __init__(self, name: str):
        # import here, not at module top: bvar's LatencyRecorder binds
        # the native core, and this module must stay importable for
        # stage tagging alone
        from brpc_tpu.bvar import Adder, LatencyRecorder
        self.name = name
        safe = name.replace(".", "_").replace("-", "_")
        self.wait_rec = LatencyRecorder(f"lock_{safe}_wait_us")
        self.hold_rec = LatencyRecorder(f"lock_{safe}_hold_us")
        self.acquisitions = Adder(f"lock_{safe}_acquisitions")
        self.contentions = Adder(f"lock_{safe}_contentions")
        self.last_holder_stage = ""

    def snapshot(self) -> dict:
        acq = self.acquisitions.get_value()
        con = self.contentions.get_value()
        return {
            "acquisitions": acq,
            "contentions": con,
            "contention_ratio": round(con / acq, 4) if acq else 0.0,
            "wait_avg_us": round(self.wait_rec.latency(), 1),
            "wait_p99_us": round(self.wait_rec.latency_percentile(0.99), 1),
            "wait_max_us": self.wait_rec.max_latency(),
            "hold_avg_us": round(self.hold_rec.latency(), 1),
            "hold_p99_us": round(self.hold_rec.latency_percentile(0.99), 1),
            "hold_max_us": self.hold_rec.max_latency(),
            "last_holder_stage": self.last_holder_stage,
        }


def lock_stats(name: str) -> LockStats:
    """Get-or-create the shared ledger entry for `name`."""
    st = _registry.get(name)
    if st is None:
        with _registry_mu:
            st = _registry.get(name)
            if st is None:
                st = _registry[name] = LockStats(name)
    return st


def locks_snapshot() -> dict[str, dict]:
    """All ledger rows — the /hotspots/locks console page's data."""
    with _registry_mu:
        entries = dict(_registry)
    return {name: st.snapshot() for name, st in sorted(entries.items())}


class InstrumentedLock:
    """A Lock/RLock wrapper feeding the shared ledger entry `name`.

    ``inner`` defaults to a plain ``threading.Lock``; pass
    ``threading.RLock()`` for reentrant use.  Multiple wrapper
    instances may (and for per-request locks, should) share one name.
    """

    __slots__ = ("_inner", "_is_rlock", "stats", "_depth", "_t_hold")

    def __init__(self, name: str, inner=None):
        self._inner = inner if inner is not None else threading.Lock()
        # RLocks carry the Condition protocol natively; plain Locks
        # need our emulation below
        self._is_rlock = hasattr(self._inner, "_is_owned")
        self.stats = lock_stats(name)
        self._depth = 0          # touched only while holding the lock
        self._t_hold = 0.0

    # ---- core protocol ----

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _witness_on:
            # order edges are recorded at acquire ATTEMPT, not success:
            # a genuine ABBA deadlock never completes its second
            # acquire, and the attempt is exactly the evidence we need
            _witness_attempt(self.stats.name)
        if self._inner.acquire(False):
            got = True
        elif not blocking:
            return False
        else:
            st = self.stats
            st.contentions.add(1)
            t0 = time.monotonic()
            if _witness_on:
                _witness_waiting(st.name)
            try:
                got = self._inner.acquire(True, timeout)
            finally:
                if _witness_on:
                    _witness_waiting(None)
            if got:
                st.wait_rec.add(int((time.monotonic() - t0) * 1e6))
        if got:
            self._begin_hold()
        return got

    def release(self) -> None:
        self._end_hold()
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return locked() if locked is not None else self._depth > 0

    # ---- hold accounting (caller holds the lock at both sites) ----

    def _begin_hold(self) -> None:
        self._depth += 1
        if self._depth == 1:
            self._t_hold = time.monotonic()
            st = self.stats
            st.acquisitions.add(1)
            st.last_holder_stage = stagetag.current_stage()
            if _witness_on:
                _witness_acquired(st.name)

    def _end_hold(self) -> None:
        self._depth -= 1
        if self._depth == 0:
            self.stats.hold_rec.add(
                int((time.monotonic() - self._t_hold) * 1e6))
            if _witness_on:
                _witness_released(self.stats.name)

    # ---- threading.Condition protocol ----

    def _release_save(self):
        """Full release (all recursion levels) for Condition.wait."""
        depth, self._depth = self._depth, 1
        self._end_hold()
        if self._is_rlock:
            return (self._inner._release_save(), depth)
        self._inner.release()
        return (None, depth)

    def _acquire_restore(self, state) -> None:
        inner_state, depth = state
        st = self.stats
        if _witness_on:
            _witness_attempt(st.name)
        t0 = time.monotonic()
        if self._is_rlock:
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        waited = time.monotonic() - t0
        # a reacquire that had to park behind another holder is real
        # contention; an immediate reacquire is not worth a record
        if waited >= 50e-6:
            st.contentions.add(1)
            st.wait_rec.add(int(waited * 1e6))
        self._begin_hold()
        self._depth = depth

    def _is_owned(self) -> bool:
        if self._is_rlock:
            return self._inner._is_owned()
        # plain-Lock emulation (mirrors threading.Condition's fallback),
        # on the INNER lock so the probe never pollutes the ledger
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self) -> str:
        return (f"<InstrumentedLock {self.stats.name!r} "
                f"depth={self._depth}>")


# ---------------------------------------------------------------------------
# Runtime lock-order witness (ISSUE 14)
#
# The ledger above answers "which lock is hot"; the witness answers
# "which locks can DEADLOCK".  Every InstrumentedLock acquisition
# records, per thread, the set of named locks already held; the first
# time lock B is acquired while A is held, the ordered edge A->B enters
# a process-global lock-order graph.  A new edge that closes a cycle
# (some path B->...->A already exists) is an ABBA violation: a
# POTENTIAL deadlock, reported the first time the two orders are ever
# observed -- no actual hang is needed, which is the whole point (the
# PR 11 tier-1 wedge produced a silent hang and zero evidence).
#
# Cost discipline: the steady-state per-acquisition work is one module
# flag read, one thread-local lookup, a list append/pop and -- only
# while other locks are held -- a dict membership probe per held lock.
# The witness lock (_wit_mu) is taken only when a NEVER-SEEN edge
# appears, which happens a bounded number of times per process
# (distinct name pairs), so the hot path never serializes on it.
#
# The held-set tables are also the WEDGE DUMP substrate:
# ``held_locks_snapshot()`` shows every thread's held names and, for a
# thread parked in a contended acquire, the name it is waiting for --
# tests/wedge_guard.py prints this when a native call blows its
# deadline, and /hotspots/locks renders it live.
# ---------------------------------------------------------------------------

_witness_on = os.environ.get("BRPC_LOCK_WITNESS", "1") not in ("0", "", "off")
_wit_mu = threading.Lock()
_wit_tls = threading.local()
_wit_edges: dict[tuple, dict] = {}        # (a, b) -> {"site", "count"}
_wit_adj: dict[str, set] = {}             # a -> {b, ...}
_wit_violations: list = []
_wit_seen_cycles: set = set()
_wit_threads: dict[int, list] = {}        # ident -> held-name list
_wit_waiting: dict[int, str] = {}         # ident -> name being waited on
_wit_viol_adder = None                    # lazy bvar Adder
MAX_WITNESS_EDGES = 4096
MAX_WITNESS_VIOLATIONS = 64


def set_witness_enabled(on: bool) -> None:
    global _witness_on
    _witness_on = bool(on)


def witness_enabled() -> bool:
    return _witness_on


def _wit_held() -> list:
    held = getattr(_wit_tls, "held", None)
    if held is None:
        held = _wit_tls.held = []
    ident = threading.get_ident()
    # re-register whenever the table lost us — reset_witness() clears
    # it, and a thread whose TLS list predates the reset must come
    # back, or every post-reset wedge dump reads "(none held)".  The
    # steady-state cost is one dict hit.
    if _wit_threads.get(ident) is not held:
        with _wit_mu:
            if len(_wit_threads) > 512:
                alive = {t.ident for t in threading.enumerate()}
                for k in [k for k, v in _wit_threads.items()
                          if not v and k not in alive]:
                    del _wit_threads[k]
            _wit_threads[ident] = held
    return held


def _wit_site() -> str:
    f = sys._getframe(2)
    here = __file__
    while f is not None and f.f_code.co_filename == here:
        f = f.f_back
    if f is None:
        return "?"
    return f"{os.path.relpath(f.f_code.co_filename)}:{f.f_lineno}"


def _witness_waiting(name) -> None:
    ident = threading.get_ident()
    if name is None:
        _wit_waiting.pop(ident, None)
    else:
        _wit_waiting[ident] = name


def _witness_attempt(name: str) -> None:
    """Record order edges held->name the first time each is seen."""
    held = _wit_held()
    if held:
        for h in held:
            if h != name and (h, name) not in _wit_edges:
                _wit_new_edge(h, name)


def _witness_acquired(name: str) -> None:
    _wit_held().append(name)


def _witness_released(name: str) -> None:
    held = getattr(_wit_tls, "held", None)
    if held:
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break


def _wit_new_edge(a: str, b: str) -> None:
    """First observation of order a->b: insert, then cycle-check."""
    with _wit_mu:
        if (a, b) in _wit_edges or len(_wit_edges) >= MAX_WITNESS_EDGES:
            return
        site = _wit_site()
        _wit_edges[(a, b)] = {"site": site, "count": 1}
        _wit_adj.setdefault(a, set()).add(b)
        # does a path b -> ... -> a already exist?  (iterative DFS with
        # parent links so the violation report carries the cycle path)
        parent = {b: None}
        stack = [b]
        found = False
        while stack and not found:
            n = stack.pop()
            for m in _wit_adj.get(n, ()):
                if m not in parent:
                    parent[m] = n
                    if m == a:
                        found = True
                        break
                    stack.append(m)
        if not found:
            return
        path = [a]
        n = parent[a]
        while n is not None:
            path.append(n)
            n = parent[n]
        path.reverse()               # b ... a
        cycle = path + [b]           # b ... a -> b closes it
        key = frozenset(cycle)
        if key in _wit_seen_cycles:
            return
        _wit_seen_cycles.add(key)
        if len(_wit_violations) >= MAX_WITNESS_VIOLATIONS:
            return
        edge_sites = {
            f"{x}->{y}": _wit_edges.get((x, y), {}).get("site", "?")
            for x, y in zip(cycle, cycle[1:])}
        _wit_violations.append({
            "cycle": cycle,
            "edge": [a, b],
            "site": site,
            "thread": threading.current_thread().name,
            "stage": stagetag.current_stage(),
            "edge_sites": edge_sites,
            "stack": "".join(traceback.format_stack(
                sys._getframe(1), limit=12)),
        })
    global _wit_viol_adder
    try:
        if _wit_viol_adder is None:
            from brpc_tpu.bvar import Adder
            _wit_viol_adder = Adder("lock_order_violations")
        _wit_viol_adder.add(1)
    except Exception:
        pass


def held_locks_snapshot() -> dict:
    """Every tracked thread's held named locks (+ the lock it is
    blocked acquiring, when contended) -- the wedge dump's payload."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    with _wit_mu:
        rows = [(i, list(h)) for i, h in _wit_threads.items()]
    waiting = dict(_wit_waiting)
    for ident, held in rows:
        wait = waiting.get(ident)
        if not held and wait is None:
            continue
        label = names.get(ident, f"thread-{ident}")
        out[label] = {"held": held, "waiting_for": wait}
    return out


def lock_order_edges() -> dict:
    """The observed order graph: {'a->b': {'site': ..}}."""
    with _wit_mu:
        return {f"{a}->{b}": dict(info)
                for (a, b), info in sorted(_wit_edges.items())}


def order_violations() -> list:
    """ABBA cycles observed so far (potential deadlocks)."""
    with _wit_mu:
        return [dict(v) for v in _wit_violations]


def reset_witness() -> None:
    """Drop the graph, violations and held-set tables (tests)."""
    with _wit_mu:
        _wit_edges.clear()
        _wit_adj.clear()
        _wit_violations.clear()
        _wit_seen_cycles.clear()
        _wit_threads.clear()
    _wit_waiting.clear()
    _wit_tls.held = []


def witness_report() -> str:
    """Human-readable dump: held sets per thread, the order graph's
    size, and every ABBA cycle with its edge sites.  Wired into
    tests/wedge_guard.py deadline misses and /hotspots/locks."""
    lines = ["--- lock-order witness ---"]
    snap = held_locks_snapshot()
    if snap:
        lines.append("held locks by thread:")
        for tname, row in sorted(snap.items()):
            wait = (f"  (BLOCKED acquiring {row['waiting_for']!r})"
                    if row["waiting_for"] else "")
            lines.append(f"  {tname}: {row['held'] or '[]'}{wait}")
    else:
        lines.append("held locks by thread: (none held)")
    with _wit_mu:
        n_edges = len(_wit_edges)
        viols = [dict(v) for v in _wit_violations]
    lines.append(f"order graph: {n_edges} edge(s)")
    if viols:
        lines.append(f"ABBA violations: {len(viols)}")
        for v in viols:
            lines.append("  cycle: " + " -> ".join(v["cycle"]))
            for edge, site in sorted(v["edge_sites"].items()):
                lines.append(f"    {edge} first seen at {site}")
            lines.append(f"    closing thread: {v['thread']} "
                         f"(stage {v['stage'] or '-'})")
    else:
        lines.append("ABBA violations: none")
    return "\n".join(lines) + "\n"
