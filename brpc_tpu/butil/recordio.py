"""recordio — length-prefixed, checksummed record files
(reference butil/recordio.{h,cc}; used by rpc_dump §5.5).

Record layout (little-endian):
  u32 magic "RIO1" | u32 meta_len | u64 body_len | u32 crc32(meta+body)
  meta bytes | body bytes

Readers skip to the next magic on corruption, so a truncated tail or a
damaged record loses only itself.
"""
from __future__ import annotations

import struct
import zlib
from typing import BinaryIO, Iterator, Optional

MAGIC = b"RIO1"
_HDR = struct.Struct("<4sIQI")


class RecordWriter:
    def __init__(self, fp: BinaryIO):
        self._fp = fp

    def write(self, body: bytes, meta: bytes = b"") -> None:
        crc = zlib.crc32(meta) & 0xFFFFFFFF
        crc = zlib.crc32(body, crc) & 0xFFFFFFFF
        self._fp.write(_HDR.pack(MAGIC, len(meta), len(body), crc))
        if meta:
            self._fp.write(meta)
        if body:
            self._fp.write(body)

    def flush(self) -> None:
        self._fp.flush()


class RecordReader:
    def __init__(self, fp: BinaryIO):
        self._fp = fp

    def read(self) -> Optional[tuple[bytes, bytes]]:
        """Returns (meta, body) or None at EOF.  Corrupt records are
        skipped by scanning forward to the next magic.

        A damaged record must lose ONLY itself: if its length fields are
        the corrupted part, trusting them would either swallow the next
        record (crc fails, but the file position is already past it) or
        hit EOF and drop everything after the damage.  So any failed
        record rewinds to just past its own magic and rescans — the scan
        lands on the NEXT record's magic (fuzz-proven in
        test_fuzz_recordio_reader_recovers)."""
        while True:
            start = self._fp.tell()
            hdr = self._fp.read(_HDR.size)
            if len(hdr) < _HDR.size:
                return None
            magic, meta_len, body_len, crc = _HDR.unpack(hdr)
            if magic != MAGIC:
                # resync: find the next magic in this chunk + what follows
                if not self._resync(hdr):
                    return None
                continue
            try:
                meta = self._fp.read(meta_len)
                body = self._fp.read(body_len)
            except (OverflowError, MemoryError):
                # a corrupted u64 length can exceed Py_ssize_t: damage,
                # not a record (found by the recordio fuzz target)
                if not self._recover(start):
                    return None
                continue
            if len(meta) < meta_len or len(body) < body_len:
                # short read: EITHER a truncated tail or a lying length —
                # rescan past this magic; a true tail yields no further
                # magic and ends the stream
                if not self._recover(start):
                    return None
                continue
            got = zlib.crc32(meta) & 0xFFFFFFFF
            got = zlib.crc32(body, got) & 0xFFFFFFFF
            if got != crc:
                # Damaged record.  If the frame still LINES UP (the next
                # bytes are a magic, or this was the last record), the
                # lengths were intact and the damage is body bit-rot:
                # trust them and skip in O(1).  Rescanning from inside a
                # well-framed record would let MAGIC bytes embedded in
                # its payload (rpc_dump bodies are raw network bytes)
                # surface as a fabricated top-level record.  Only when
                # the frame does NOT line up — the lengths themselves are
                # the damage — rewind past this magic and rescan.
                nxt = self._fp.read(len(MAGIC))
                if nxt == MAGIC:
                    self._fp.seek(-len(MAGIC), 1)
                    continue
                if len(nxt) < len(MAGIC):
                    # damaged record was the tail (ADVICE r5): a short
                    # non-empty lookahead (1-3 trailing bytes at EOF) is
                    # the same situation as nxt == b"" — too few bytes
                    # left for another record to exist.  Rescanning from
                    # inside this record's payload would let embedded
                    # MAGIC bytes (rpc_dump bodies are raw network bytes)
                    # fabricate a top-level record.
                    return None
                if not self._recover(start):
                    return None
            else:
                return meta, body

    def _recover(self, start: int) -> bool:
        """Shared damaged-record recovery: rewind to just past the failed
        record's magic and scan for the next one, so a record whose
        LENGTH fields are the corrupted part loses only itself (trusting
        a lying length would swallow the following record, or hit EOF
        and drop everything after the damage)."""
        self._fp.seek(start + len(MAGIC))
        return self._resync(b"")

    def _resync(self, tail: bytes) -> bool:
        """Scan forward for the next magic.  Every caller guarantees the
        scan cannot re-find the record it just failed on: the bad-header
        path's `tail` does not begin with MAGIC (that's why it's here),
        and the damaged-record paths seek past their own magic before
        calling.  Scanning from 0 also catches a magic that STARTS in
        the 3-byte carry spanning two chunk reads."""
        buf = tail
        while True:
            idx = buf.find(MAGIC)
            if idx >= 0:
                rest = buf[idx:]
                # rewind so the next read starts at the magic
                self._fp.seek(-len(rest), 1)
                return True
            chunk = self._fp.read(65536)
            if not chunk:
                return False
            buf = buf[-3:] + chunk

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        while True:
            r = self.read()
            if r is None:
                return
            yield r
