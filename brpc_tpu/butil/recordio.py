"""recordio — length-prefixed, checksummed record files
(reference butil/recordio.{h,cc}; used by rpc_dump §5.5).

Record layout (little-endian):
  u32 magic "RIO1" | u32 meta_len | u64 body_len | u32 crc32(meta+body)
  meta bytes | body bytes

Readers skip to the next magic on corruption, so a truncated tail or a
damaged record loses only itself.
"""
from __future__ import annotations

import struct
import zlib
from typing import BinaryIO, Iterator, Optional

MAGIC = b"RIO1"
_HDR = struct.Struct("<4sIQI")


class RecordWriter:
    def __init__(self, fp: BinaryIO):
        self._fp = fp

    def write(self, body: bytes, meta: bytes = b"") -> None:
        crc = zlib.crc32(meta) & 0xFFFFFFFF
        crc = zlib.crc32(body, crc) & 0xFFFFFFFF
        self._fp.write(_HDR.pack(MAGIC, len(meta), len(body), crc))
        if meta:
            self._fp.write(meta)
        if body:
            self._fp.write(body)

    def flush(self) -> None:
        self._fp.flush()


class RecordReader:
    def __init__(self, fp: BinaryIO):
        self._fp = fp

    def read(self) -> Optional[tuple[bytes, bytes]]:
        """Returns (meta, body) or None at EOF.  Corrupt records are
        skipped by scanning forward to the next magic."""
        while True:
            hdr = self._fp.read(_HDR.size)
            if len(hdr) < _HDR.size:
                return None
            magic, meta_len, body_len, crc = _HDR.unpack(hdr)
            if magic != MAGIC:
                # resync: find the next magic in this chunk + what follows
                if not self._resync(hdr):
                    return None
                continue
            meta = self._fp.read(meta_len)
            body = self._fp.read(body_len)
            if len(meta) < meta_len or len(body) < body_len:
                return None  # truncated tail
            got = zlib.crc32(meta) & 0xFFFFFFFF
            got = zlib.crc32(body, got) & 0xFFFFFFFF
            if got != crc:
                continue  # damaged record — drop it, keep reading
            return meta, body

    def _resync(self, tail: bytes) -> bool:
        buf = tail
        while True:
            idx = buf.find(MAGIC, 1)
            if idx >= 0:
                rest = buf[idx:]
                # rewind so the next read starts at the magic
                self._fp.seek(-len(rest), 1)
                return True
            chunk = self._fp.read(65536)
            if not chunk:
                return False
            buf = buf[-3:] + chunk

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        while True:
            r = self.read()
            if r is None:
                return
            yield r
