"""Serving-stage tags for host hot-path attribution (ISSUE 6).

Every thread in the serving hot path belongs to a STAGE — frame pump,
batch formation, prefill, decode step, emit fan-out, span submit — and
both the always-on sampling profiler (builtin/sampler.py) and the
lock-contention ledger (butil/lockprof.py) label what they observe
with it, so a folded stack or a lock-wait spike reads as "which stage
burned the CPU / held the lock", not just "which thread id".

Two sources, explicit beats implicit:

  * explicit — code that KNOWS its stage marks a region with the
    ``stage("prefill")`` context manager (the engine thread runs
    admit/prefill/decode on one thread, so the thread name alone
    cannot split them);
  * implicit — the thread-name prefix map below.  Threads the runtime
    names (serving-batcher-*, serving-emit-*, bvar-collector) resolve
    without any marking; foreign threads the native core registers on
    their first Python callback show up as Dummy-N and are the frame
    pump's Python entry points.

Lookups are GIL-atomic dict reads — no lock on any hot path.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

# thread ident -> explicitly marked stage (only the owning thread
# writes its slot; single dict ops are GIL-atomic)
_explicit: dict[int, str] = {}

# thread-name prefix -> stage (first match wins)
_NAME_STAGES = (
    ("serving-batcher", "batch_formation"),
    ("serving-engine", "decode_step"),
    ("serving-supervisor", "decode_step"),
    ("serving-emit", "emit_fanout"),
    ("kv-migrate", "migrate"),
    ("bvar-collector", "span_submit"),
    ("rpcz-spanq", "span_submit"),
    ("bvar-sampler", "bvar_sampler"),
    ("hotspot-sampler", "hotspot_sampler"),
    # native executor/dispatcher threads (the C++ frame pump) have no
    # Python-side Thread object; threading registers them as Dummy-N
    # the first time a callback runs Python on them
    ("Dummy", "frame_pump"),
    ("svc-tag-", "rpc_handler"),
    ("usercode", "rpc_handler"),
    ("grpc-", "rpc_handler"),
    ("console-dashboard", "console"),
    ("MainThread", "main"),
)


def stage_of(tid: int, thread_name: str = "") -> str:
    """Stage of thread `tid` (explicit mark wins over the name map)."""
    s = _explicit.get(tid)
    if s is not None:
        return s
    for prefix, stage_name in _NAME_STAGES:
        if thread_name.startswith(prefix):
            return stage_name
    return "other"


def current_stage() -> str:
    t = threading.current_thread()
    return stage_of(t.ident or 0, t.name)


@contextmanager
def stage(name: str):
    """Mark the calling thread as running `name` for the duration."""
    tid = threading.get_ident()
    prev = _explicit.get(tid)
    _explicit[tid] = name
    try:
        yield
    finally:
        if prev is None:
            _explicit.pop(tid, None)
        else:
            _explicit[tid] = prev
