from brpc_tpu.bvar.variable import (  # noqa: F401
    Variable, expose, dump_exposed, describe_exposed, find_exposed,
)
from brpc_tpu.bvar.reducer import Adder, Maxer, Miner, PassiveStatus, Status  # noqa: F401
from brpc_tpu.bvar.window import Window, PerSecond  # noqa: F401
from brpc_tpu.bvar.recorder import IntRecorder, Percentile, LatencyRecorder  # noqa: F401
from brpc_tpu.bvar.multi_dimension import MultiDimension  # noqa: F401
