"""Collector — shared, speed-limited sampling infrastructure (reference
src/bvar/collector.{h,cpp}; SURVEY.md §2.7 "Collector" row).

The reference funnels every "sampled heavyweight record" — rpcz spans,
mutex-contention samples, rpc_dump captures — through one global collector:
submission is a cheap, speed-limited handoff on the hot path, and the
expensive part (serialization, file IO, indexing) runs on a background
thread over batches.  This is that design:

  * `Collected` — base class for sample objects; `dump_and_destroy()` runs
    on the collector thread, never on the submitter.
  * `CollectorSpeedLimit` — per-family token bucket (default 1000
    samples/s, the reference's collector_max_sampling_overhead spirit):
    `grab()` is one lock + two int ops; beyond the budget samples are
    dropped, counted, and serving is unaffected.
  * `Collector` — global pending list + one daemon drainer; `flush()`
    drains synchronously for readers that need everything submitted so
    far (the /rpcz page, dump-file close).

Consumers here: rpcz spans (brpc_tpu/rpcz.py) and rpc_dump captures
(brpc_tpu/rpc/rpc_dump.py) — file IO for dumps moved off the dispatch
path onto the collector thread.
"""
from __future__ import annotations

import threading
import time

from brpc_tpu.bvar.reducer import Adder


class Collected:
    """A sample.  Subclasses implement dump_and_destroy(); it runs on the
    collector thread (or inside flush()), exactly once."""

    def dump_and_destroy(self) -> None:
        raise NotImplementedError


class CollectorSpeedLimit:
    """Token bucket: at most `max_per_second` grabs per rolling second.

    The reference adapts a sampling probability instead
    (collector.h:30-60 _sampling_range); a bucket gives the same property
    — bounded collection overhead under load — with simpler, testable
    state.
    """

    def __init__(self, name: str, max_per_second: int = 1000):
        self.name = name
        self.max_per_second = max_per_second
        self._mu = threading.Lock()
        self._window_start = time.monotonic()
        self._in_window = 0
        self.grabbed = Adder(f"collector_{name}_grabbed")
        self.denied = Adder(f"collector_{name}_denied")

    def grab(self) -> bool:
        now = time.monotonic()
        with self._mu:
            if now - self._window_start >= 1.0:
                self._window_start = now
                self._in_window = 0
            if self._in_window >= self.max_per_second:
                self.denied.add(1)
                return False
            self._in_window += 1
        self.grabbed.add(1)
        return True


class Collector:
    _instance = None
    _instance_lock = threading.Lock()

    GRAB_INTERVAL_S = 0.1   # drain cadence (reference COLLECTOR_GRAB_...)

    @classmethod
    def instance(cls) -> "Collector":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def __init__(self):
        self._mu = threading.Lock()
        self._drain_mu = threading.Lock()  # serializes drains so flush()
        self._pending: list[Collected] = []  # waits out an in-flight batch
        self._wake = threading.Event()
        self._stopped = False
        self._thread: threading.Thread | None = None

    def submit(self, sample: Collected,
               limit: CollectorSpeedLimit | None = None) -> bool:
        """Hot-path handoff.  Returns False when the speed limit dropped
        the sample (dump_and_destroy will never run for it)."""
        if limit is not None and not limit.grab():
            return False
        with self._mu:
            self._pending.append(sample)
            if self._thread is None and not self._stopped:
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="bvar-collector")
                self._thread.start()
        self._wake.set()
        return True

    def flush(self) -> None:
        """Drain everything submitted so far on THIS thread.  Readers that
        must observe all prior submissions (the /rpcz page, dump close)
        call this instead of sleeping a drain interval."""
        self._drain()

    def _drain(self) -> None:
        with self._drain_mu:
            with self._mu:
                batch, self._pending = self._pending, []
            for s in batch:
                try:
                    s.dump_and_destroy()
                except Exception:
                    pass  # a broken sample must never kill the drainer

    def _run(self) -> None:
        while not self._stopped:
            self._wake.wait(self.GRAB_INTERVAL_S)
            self._wake.clear()
            self._drain()

    def stop(self) -> None:
        self._stopped = True
        self._wake.set()
        self._drain()
