"""Collector — shared, speed-limited sampling infrastructure (reference
src/bvar/collector.{h,cpp}; SURVEY.md §2.7 "Collector" row).

The reference funnels every "sampled heavyweight record" — rpcz spans,
mutex-contention samples, rpc_dump captures — through one global collector:
submission is a cheap, speed-limited handoff on the hot path, and the
expensive part (serialization, file IO, indexing) runs on a background
thread over batches.  This is that design:

  * `Collected` — base class for sample objects; `dump_and_destroy()` runs
    on the collector thread, never on the submitter.
  * `CollectorSpeedLimit` — per-family budget: at most max_per_second
    grabs per FIXED one-second window (a window boundary therefore admits
    a burst of up to 2x in a short instant — bounded overhead is the
    contract, not smoothness; the reference's adaptive sampling_range is
    approximate the same way).  `grab()` is one small lock + two int ops.
  * `Collector` — pending samples bucketed per family + one daemon
    drainer; `flush(family)` drains ONE family synchronously so a reader
    (the /rpcz page, dump-file close) observes its own prior submissions
    without doing other families' heavyweight work (a console thread must
    never end up writing rpc_dump files).

Consumers here: rpcz spans (brpc_tpu/rpcz.py) and rpc_dump captures
(brpc_tpu/rpc/rpc_dump.py) — file IO for dumps moved off the dispatch
path onto the collector thread.
"""
from __future__ import annotations

import threading
import time

from brpc_tpu.bvar.reducer import Adder


class Collected:
    """A sample.  Subclasses implement dump_and_destroy(); it runs on the
    collector thread (or inside flush()), exactly once."""

    def dump_and_destroy(self) -> None:
        raise NotImplementedError


class CollectorSpeedLimit:
    """Fixed-window budget: at most `max_per_second` grabs per window.

    `clock` is injectable for deterministic tests.
    """

    def __init__(self, name: str, max_per_second: int = 1000,
                 clock=time.monotonic):
        self.name = name
        self.max_per_second = max_per_second
        self._clock = clock
        self._mu = threading.Lock()
        self._window_start = clock()
        self._in_window = 0
        self.grabbed = Adder(f"collector_{name}_grabbed")
        self.denied = Adder(f"collector_{name}_denied")

    def grab(self) -> bool:
        return self.grab_n(1) == 1

    def grab_n(self, n: int) -> int:
        """Grab up to `n` budget slots in ONE window check; returns how
        many were granted.  The batch-drain path (ISSUE 9: the rpcz
        spanq drainer) uses this so a 2000-span drain costs one lock
        round-trip and one clock read instead of 2000 — per-span grab()
        under the GIL was the drainer's whole cost, and it stole the
        GIL from the very token path the queue exists to protect."""
        now = self._clock()
        with self._mu:
            if now - self._window_start >= 1.0:
                self._window_start = now
                self._in_window = 0
            granted = max(0, min(n, self.max_per_second
                                 - self._in_window))
            self._in_window += granted
        if granted:
            self.grabbed.add(granted)
        if n > granted:
            self.denied.add(n - granted)
        return granted


_limits: dict[str, CollectorSpeedLimit] = {}
_limits_lock = threading.Lock()


def get_or_create_limit(name: str,
                        max_per_second: int = 1000) -> CollectorSpeedLimit:
    """Shared per-family limit registry — one place for the init-race
    handling instead of double-checked-locking boilerplate per consumer."""
    limit = _limits.get(name)
    if limit is None:
        with _limits_lock:
            limit = _limits.get(name)
            if limit is None:
                limit = CollectorSpeedLimit(name, max_per_second)
                _limits[name] = limit
    return limit


class Collector:
    _instance = None
    _instance_lock = threading.Lock()

    GRAB_INTERVAL_S = 0.1   # drain cadence (reference COLLECTOR_GRAB_...)

    @classmethod
    def instance(cls) -> "Collector":
        # lock-free fast path: this runs on every submission
        inst = cls._instance
        if inst is not None:
            return inst
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def __init__(self):
        self._mu = threading.Lock()
        # per-family drain locks: flush("rpcz") must neither perform NOR
        # wait on another family's in-flight IO (a console thread parked
        # behind a disk-stalled rpc_dump batch is the same outage as
        # doing the writes itself)
        self._drain_locks: dict[str, threading.Lock] = {}
        self._pending: dict[str, list[Collected]] = {}
        self._wake = threading.Event()
        self._stopped = False
        self._thread: threading.Thread | None = None

    def submit(self, sample: Collected,
               limit: CollectorSpeedLimit | None = None,
               family: str = "default") -> bool:
        """Hot-path handoff.  Returns False when the speed limit dropped
        the sample (dump_and_destroy will never run for it)."""
        if limit is not None and not limit.grab():
            return False
        with self._mu:
            # the stopped check must be under the lock: stop()'s final
            # drain holds it too, so a sample either lands before that
            # drain (and is consumed by it) or observes _stopped here
            stopped = self._stopped
            if not stopped:
                self._pending.setdefault(family, []).append(sample)
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._run, daemon=True,
                        name="bvar-collector")
                    self._thread.start()
        if stopped:
            # no drainer will ever run again; honor the accept contract
            # inline rather than stranding the sample
            try:
                sample.dump_and_destroy()
            except Exception:
                pass
            return True
        self._wake.set()
        return True

    def flush(self, family: str | None = None) -> None:
        """Drain one family (or all, family=None) on THIS thread.  Readers
        that must observe their own prior submissions (the /rpcz page,
        dump close) flush their family only — never another consumer's
        pending IO."""
        self._drain(family)

    def _drain_lock(self, family: str) -> threading.Lock:
        with self._mu:
            lock = self._drain_locks.get(family)
            if lock is None:
                lock = self._drain_locks[family] = threading.Lock()
            return lock

    def _drain(self, family: str | None = None) -> None:
        if family is None:
            with self._mu:
                families = list(self._pending.keys())
            for f in families:
                self._drain(f)
            return
        with self._drain_lock(family):
            with self._mu:
                batch = self._pending.pop(family, None)
            t_cpu0 = time.thread_time()
            for s in batch or ():
                try:
                    s.dump_and_destroy()
                except Exception:
                    pass  # a broken sample must never kill the drainer
            if batch and family == "rpcz":
                # span-submit host-CPU accounting (ISSUE 6): the
                # heavyweight half of rpcz submission runs here
                from brpc_tpu.butil import hostcpu
                hostcpu.add("span_submit",
                            (time.thread_time() - t_cpu0) * 1e6)

    def _run(self) -> None:
        while not self._stopped:
            self._wake.wait(self.GRAB_INTERVAL_S)
            self._wake.clear()
            self._drain()

    def stop(self) -> None:
        with self._mu:          # order against submit's locked check
            self._stopped = True
        self._wake.set()
        self._drain()
