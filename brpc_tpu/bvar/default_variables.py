"""Process/system default variables — cpu, rss, fds, threads, io.

Reference: bvar/default_variables.cpp (process_cpu_usage, process_memory,
process_fd_count, system loadavg …, exported on every server's /vars).
Importing this module exposes the set once; the server imports it at
start so /vars and /brpc_metrics always carry process health.
"""
from __future__ import annotations

import os
import resource
import threading
import time

from brpc_tpu.bvar.reducer import PassiveStatus

_exposed = False
_expose_lock = threading.Lock()
_start_time = time.time()

_last_cpu: tuple[float, float] | None = None  # (wall, cpu_seconds)


def _cpu_seconds() -> float:
    ru = resource.getrusage(resource.RUSAGE_SELF)
    return ru.ru_utime + ru.ru_stime


def _cpu_usage() -> float:
    """Fraction of one core used since the last sample (process_cpu_usage
    semantics: windowed, not lifetime-average)."""
    global _last_cpu
    now = time.monotonic()
    cpu = _cpu_seconds()
    if _last_cpu is None:
        _last_cpu = (now, cpu)
        return 0.0
    dw, dc = now - _last_cpu[0], cpu - _last_cpu[1]
    if dw >= 1.0:
        _last_cpu = (now, cpu)
    return round(dc / dw, 4) if dw > 0 else 0.0


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        # ru_maxrss is KB on Linux — peak, not current, but better than 0
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def _fd_count() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def _thread_count() -> int:
    return threading.active_count()


def _loadavg() -> float:
    try:
        return round(os.getloadavg()[0], 2)
    except OSError:
        return 0.0


def _io_read_bytes() -> int:
    return _proc_io("read_bytes")


def _io_write_bytes() -> int:
    return _proc_io("write_bytes")


def _proc_io(field: str) -> int:
    try:
        with open("/proc/self/io") as f:
            for line in f:
                k, _, v = line.partition(":")
                if k == field:
                    return int(v)
    except (OSError, ValueError):
        pass
    return -1


def expose_default_variables() -> None:
    """Idempotent; called by Server.start (and importable standalone)."""
    global _exposed
    with _expose_lock:
        if _exposed:
            return
        _exposed = True
        PassiveStatus(_cpu_usage).expose("process_cpu_usage")
        PassiveStatus(_cpu_seconds).expose("process_cpu_seconds")
        PassiveStatus(_rss_bytes).expose("process_memory_resident_bytes")
        PassiveStatus(_fd_count).expose("process_fd_count")
        PassiveStatus(_thread_count).expose("process_thread_count")
        PassiveStatus(os.getpid).expose("process_pid")
        PassiveStatus(lambda: round(time.time() - _start_time, 1)) \
            .expose("process_uptime_seconds")
        PassiveStatus(_loadavg).expose("system_loadavg_1m")
        PassiveStatus(_io_read_bytes).expose("process_io_read_bytes")
        PassiveStatus(_io_write_bytes).expose("process_io_write_bytes")
