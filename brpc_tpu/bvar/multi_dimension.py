"""MultiDimension — labelled metrics (reference multi_dimension{,_inl}.h).

Maps label-value tuples to an underlying bvar (Adder/LatencyRecorder/...),
the Prometheus-label surface of mbvar (SURVEY.md §2.7)."""
from __future__ import annotations

import threading
from typing import Callable

from brpc_tpu.bvar.variable import Variable


class MultiDimension(Variable):
    def __init__(self, labels: list[str], make: Callable[[], Variable],
                 name: str = ""):
        self._labels = list(labels)
        self._make = make
        self._stats: dict[tuple, Variable] = {}
        self._mu = threading.Lock()
        super().__init__(name)

    def get_stats(self, *label_values) -> Variable:
        if len(label_values) != len(self._labels):
            raise ValueError(f"expected {len(self._labels)} labels")
        key = tuple(str(v) for v in label_values)
        with self._mu:
            v = self._stats.get(key)
            if v is None:
                v = self._make()
                self._stats[key] = v
            return v

    def delete_stats(self, *label_values) -> None:
        with self._mu:
            self._stats.pop(tuple(str(v) for v in label_values), None)

    def has_stats(self, *label_values) -> bool:
        with self._mu:
            return tuple(str(v) for v in label_values) in self._stats

    def count_stats(self) -> int:
        with self._mu:
            return len(self._stats)

    @property
    def labels(self):
        return list(self._labels)

    def items(self):
        with self._mu:
            return list(self._stats.items())

    def get_value(self):
        return {"/".join(k): v.get_value() for k, v in self.items()}
