"""IntRecorder / Percentile / LatencyRecorder.

Reference: compressed-histogram percentiles sampled per second
(detail/percentile.{h,cpp}) feeding the LatencyRecorder bundle —
latency avg/max/qps/p50..p99.99 (latency_recorder.h:49-75).

Implementation: log-bucketed histogram (1ns..100s in ~4% steps) — O(1)
insert, percentile by bucket walk; per-second windows via the sampler
thread.  Not a port: bucket math chosen for numpy-free speed in Python.
"""
from __future__ import annotations

import math
import threading

from brpc_tpu.bvar.reducer import Adder
from brpc_tpu.bvar.variable import Variable
from brpc_tpu.bvar.window import PerSecond, Window

# log-spaced buckets: value -> bucket index
_BUCKETS = 512
_MIN_V = 1.0
_MAX_V = 1e11      # 100s in us is 1e8; headroom
_LOG_MIN = math.log(_MIN_V)
_LOG_RANGE = math.log(_MAX_V) - _LOG_MIN


def _bucket_of(v: float) -> int:
    if v <= _MIN_V:
        return 0
    i = int((math.log(v) - _LOG_MIN) / _LOG_RANGE * (_BUCKETS - 1))
    return min(_BUCKETS - 1, max(0, i))


def _bucket_value(i: int) -> float:
    return math.exp(_LOG_MIN + (i + 0.5) / (_BUCKETS - 1) * _LOG_RANGE)


class Percentile:
    """Log-bucket histogram with per-thread write cells (combiner design,
    reference detail/combiner.h): adds touch only the caller's own cell —
    no shared lock on the per-request path — and reads merge cells."""

    def __init__(self):
        self._tls = threading.local()
        self._cells: list = []
        self._mu = threading.Lock()  # guards the cell list only

    def _cell(self):
        c = getattr(self._tls, "c", None)
        if c is None:
            c = [0] * (_BUCKETS + 1)  # [-1] slot holds the count
            self._tls.c = c
            with self._mu:
                self._cells.append(c)
        return c

    def add(self, v: float) -> None:
        c = self._cell()
        c[_bucket_of(v)] += 1
        c[_BUCKETS] += 1

    def snapshot(self) -> tuple[list[int], int]:
        with self._mu:
            cells = list(self._cells)
        counts = [0] * _BUCKETS
        n = 0
        for c in cells:
            for i in range(_BUCKETS):
                if c[i]:
                    counts[i] += c[i]
            n += c[_BUCKETS]
        return counts, n

    def get_number(self, ratio: float) -> float:
        counts, n = self.snapshot()
        if n == 0:
            return 0.0
        target = ratio * n
        acc = 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= target:
                return _bucket_value(i)
        return _bucket_value(_BUCKETS - 1)


class IntRecorder(Variable):
    """Average of recorded values (reference int_recorder.h)."""

    def __init__(self, name: str = ""):
        self._sum = Adder()
        self._count = Adder()
        super().__init__(name)

    def add(self, v) -> "IntRecorder":
        self._sum.add(v)
        self._count.add(1)
        return self

    def __lshift__(self, v):
        return self.add(v)

    def get_value(self):
        c = self._count.get_value()
        return self._sum.get_value() / c if c else 0

    @property
    def count(self):
        return self._count.get_value()


class _NativeStat:
    """Variable-shaped view of one field of a native latency recorder —
    lets Window/PerSecond sample native combiner state like any reducer.
    The stats C function is cached at init: get_value runs once a second
    per sampler for the life of the recorder, and per-call module
    imports would be pure overhead."""

    __slots__ = ("_handle", "_field", "_stats")

    def __init__(self, handle, field: str):
        from brpc_tpu._core import core
        self._handle = handle
        self._field = field
        self._stats = core.brpc_latency_stats

    def get_value(self):
        import ctypes
        c = ctypes.c_int64()
        s = ctypes.c_int64()
        m = ctypes.c_int64()
        self._stats(self._handle, ctypes.byref(c), ctypes.byref(s),
                    ctypes.byref(m))
        return {"count": c.value, "sum": s.value, "max": m.value}[self._field]


class LatencyRecorder(Variable):
    """The standard per-method bundle: << latency_us records one call.

    Backed by the NATIVE combiner (src/cc/bvar/combiner.h): add() is one C
    call writing the calling thread's own cells — count, sum, max and a
    log-bucket histogram — with no Python-level lock and no shared
    cacheline (VERDICT r2 task 5; reference latency_recorder.h:49-75 over
    detail/combiner.h).  Reads merge cells across threads natively.

    Exposes (when named): <name>_latency (avg us, windowed),
    <name>_max_latency, <name>_qps, <name>_count, and percentiles via
    latency_percentile(p).
    """

    def __init__(self, name: str = "", window_size: int = 10):
        from brpc_tpu._core import core
        self._h = core.brpc_latency_new()
        self._record = core.brpc_latency_record  # bound-method lookup once
        self._free = core.brpc_latency_free      # cached for __del__ (the
        # module globals may be torn down before late GC runs)
        self._percentile = core.brpc_latency_percentile
        self._num = _NativeStat(self._h, "count")
        self._sum = _NativeStat(self._h, "sum")
        self._max = _NativeStat(self._h, "max")
        self._win_sum = Window(self._sum, window_size)
        self._win_num = Window(self._num, window_size)
        self._qps = PerSecond(self._num, window_size)
        super().__init__(name)

    def expose(self, name: str):
        super().expose(name + "_latency")
        from brpc_tpu.bvar.reducer import PassiveStatus
        PassiveStatus(lambda: self.max_latency()).expose(name + "_max_latency")
        PassiveStatus(lambda: round(self._qps.get_value(), 1)).expose(name + "_qps")
        PassiveStatus(lambda: self._num.get_value()).expose(name + "_count")
        for p, label in ((0.5, "50"), (0.9, "90"), (0.99, "99"),
                         (0.999, "999"), (0.9999, "9999")):
            PassiveStatus(lambda p=p: round(self.latency_percentile(p), 1)) \
                .expose(f"{name}_latency_{label}")
        return self

    def add(self, latency_us) -> "LatencyRecorder":
        self._record(self._h, int(latency_us))
        return self

    def __lshift__(self, latency_us):
        return self.add(latency_us)

    def get_value(self):
        """Windowed average latency in us."""
        n = self._win_num.get_value()
        return self._win_sum.get_value() / n if n else 0

    def latency(self) -> float:
        return self.get_value()

    def latency_percentile(self, ratio: float) -> float:
        return self._percentile(self._h, float(ratio))

    def max_latency(self):
        return self._max.get_value()

    def snapshot(self):
        """(count, sum_us, max_us) in ONE native stats call — for pollers
        (the console dashboard samples every method once a second)."""
        import ctypes
        from brpc_tpu._core import core
        c = ctypes.c_int64()
        s = ctypes.c_int64()
        m = ctypes.c_int64()
        core.brpc_latency_stats(self._h, ctypes.byref(c), ctypes.byref(s),
                                ctypes.byref(m))
        return c.value, s.value, m.value

    def __del__(self):
        # release the native slot (512 process-wide): leaking recorders
        # would silently dead-end new ones once the pool exhausts
        h = getattr(self, "_h", None)
        if h:
            try:
                self._free(h)
            except Exception:
                pass
            self._h = None

    def qps(self) -> float:
        return self._qps.get_value()

    def count(self):
        return self._num.get_value()
