"""Reducers — write-local, combine-on-read counters.

Reference design (reducer.h:35-40, detail/combiner.h:71-156): each writing
thread owns an agent cell; << is an uncontended thread-local write; reads
merge all agents.  Kept here with per-thread cells in a threading.local —
the write path is a plain attribute add on the caller's own cell (no shared
mutable state), reads sum the live cells.
"""
from __future__ import annotations

import threading
from typing import Callable

from brpc_tpu.bvar.variable import Variable


class _AgentGroup:
    """Tracks all thread cells of one reducer for combine-on-read."""

    def __init__(self):
        self._tls = threading.local()
        self._cells: list = []
        self._lock = threading.Lock()
        # sum of cells from dead threads is folded here lazily? cells are
        # kept alive by the registry; thread death leaves the cell in place
        # (bounded by thread count, as in the reference's agent list).

    def cell(self, make):
        c = getattr(self._tls, "cell", None)
        if c is None:
            c = make()
            self._tls.cell = c
            with self._lock:
                self._cells.append(c)
        return c

    def cells(self):
        with self._lock:
            return list(self._cells)


class _Cell:
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v


class Adder(Variable):
    """adder << n — thread-local add, combined sum on read."""

    def __init__(self, name: str = "", initial=0):
        self._agents = _AgentGroup()
        self._zero = initial
        super().__init__(name)

    def add(self, n=1):
        self._agents.cell(lambda: _Cell(self._zero)).v += n
        return self

    def __lshift__(self, n):
        return self.add(n)

    def get_value(self):
        total = self._zero
        for c in self._agents.cells():
            total += c.v
        return total

    def reset(self):
        value = self.get_value()
        for c in self._agents.cells():
            c.v = self._zero
        return value


class Maxer(Variable):
    def __init__(self, name: str = ""):
        self._agents = _AgentGroup()
        super().__init__(name)

    def add(self, n):
        c = self._agents.cell(lambda: _Cell(None))
        if c.v is None or n > c.v:
            c.v = n
        return self

    def __lshift__(self, n):
        return self.add(n)

    def get_value(self):
        vals = [c.v for c in self._agents.cells() if c.v is not None]
        return max(vals) if vals else 0

    def reset(self):
        v = self.get_value()
        for c in self._agents.cells():
            c.v = None
        return v


class Miner(Variable):
    def __init__(self, name: str = ""):
        self._agents = _AgentGroup()
        super().__init__(name)

    def add(self, n):
        c = self._agents.cell(lambda: _Cell(None))
        if c.v is None or n < c.v:
            c.v = n
        return self

    def __lshift__(self, n):
        return self.add(n)

    def get_value(self):
        vals = [c.v for c in self._agents.cells() if c.v is not None]
        return min(vals) if vals else 0

    def reset(self):
        v = self.get_value()
        for c in self._agents.cells():
            c.v = None
        return v


class PassiveStatus(Variable):
    """Pull-callback variable (reference passive_status.h)."""

    def __init__(self, fn: Callable[[], object], name: str = ""):
        self._fn = fn
        super().__init__(name)

    def get_value(self):
        return self._fn()


class Status(Variable):
    """Directly-set value (reference status.h)."""

    def __init__(self, value=None, name: str = ""):
        self._value = value
        super().__init__(name)

    def set_value(self, v):
        self._value = v

    def get_value(self):
        return self._value
