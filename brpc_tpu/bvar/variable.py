"""bvar variable registry (reference src/bvar/variable.{h,cpp}).

Named, exposable variables with wildcard dump — the backbone every subsystem
self-reports through (SURVEY.md §2.7, §5.6).  Export paths: /vars builtin,
Prometheus text (builtin/prometheus_metrics_service in the reference), and
periodic file dump.
"""
from __future__ import annotations

import fnmatch
import threading
from typing import Callable, Optional

_registry: dict[str, "Variable"] = {}
_registry_lock = threading.Lock()


class Variable:
    """Base of every metric.  Subclasses implement get_value()."""

    def __init__(self, name: str = ""):
        self._name = ""
        if name:
            self.expose(name)

    # ---- registry ----

    def expose(self, name: str) -> "Variable":
        name = name.strip().replace(" ", "_")
        with _registry_lock:
            if self._name:
                _registry.pop(self._name, None)
            self._name = name
            _registry[name] = self
        return self

    def hide(self) -> None:
        with _registry_lock:
            if self._name:
                _registry.pop(self._name, None)
                self._name = ""

    @property
    def name(self) -> str:
        return self._name

    # ---- value access ----

    def get_value(self):
        raise NotImplementedError

    def describe(self) -> str:
        v = self.get_value()
        if isinstance(v, float):
            return f"{v:.6g}"
        return str(v)


def exposed_variables(pattern: str = "*") -> dict:
    """Variable OBJECTS by name (dump_exposed gives values) — exporters
    that need type information (e.g. Prometheus label rendering for
    MultiDimension) go through this."""
    with _registry_lock:
        return {k: v for k, v in _registry.items()
                if fnmatch.fnmatch(k, pattern)}


def expose(name: str, fn: Callable[[], object]) -> Variable:
    """Expose a pull-callback as a variable (PassiveStatus shorthand)."""
    from brpc_tpu.bvar.reducer import PassiveStatus
    return PassiveStatus(fn).expose(name)


def find_exposed(name: str) -> Optional[Variable]:
    with _registry_lock:
        return _registry.get(name)


def dump_exposed(pattern: str = "*") -> dict[str, object]:
    """Snapshot of {name: value} for names matching the wildcard."""
    with _registry_lock:
        items = list(_registry.items())
    out = {}
    for name, var in items:
        if fnmatch.fnmatch(name, pattern):
            try:
                out[name] = var.get_value()
            except Exception as e:  # pragma: no cover
                out[name] = f"<error: {e}>"
    return out


def describe_exposed(pattern: str = "*") -> str:
    return "\n".join(f"{k} : {v}" for k, v in sorted(dump_exposed(pattern).items()))
