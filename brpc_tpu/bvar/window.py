"""Window / PerSecond — periodic sampling of reducers.

Reference: one sampler thread per process snapshots every reducer once a
second into a ring; Window<V,N> reports the delta over the last N seconds
(detail/sampler.h:44-102).  Same design: a singleton daemon thread samples
registered variables each second.
"""
from __future__ import annotations

import threading
import time
import weakref

from brpc_tpu.bvar.variable import Variable


class _SamplerThread:
    _instance = None
    _lock = threading.Lock()

    @classmethod
    def instance(cls):
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def __init__(self):
        self._samplers: list = []
        self._mu = threading.Lock()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="bvar-sampler")
        self._thread.start()

    def add(self, sampler) -> None:
        # weakref: a Window whose owner was replaced in the bvar registry
        # (same-name re-expose) must become collectable — a strong ref
        # here would pin every recorder a process ever created and leak
        # its native combiner slot forever
        with self._mu:
            self._samplers.append(weakref.ref(sampler))

    def _run(self):
        while True:
            start = time.monotonic()
            with self._mu:
                refs = list(self._samplers)
            dead = []
            for ref in refs:
                s = ref()
                if s is None:
                    dead.append(ref)
                    continue
                try:
                    s.take_sample()
                except Exception:  # pragma: no cover
                    pass
            if dead:
                with self._mu:
                    self._samplers = [r for r in self._samplers
                                      if r not in dead]
            time.sleep(max(0.0, 1.0 - (time.monotonic() - start)))


class Window(Variable):
    """Value delta over the last `window_size` seconds of a reducer with
    get_value() (Adder) — max kept samples bound memory like the reference's
    ring."""

    def __init__(self, var, window_size: int = 10, name: str = ""):
        self._var = var
        self._window = max(1, window_size)
        self._samples: list[tuple[float, object]] = []
        self._mu = threading.Lock()
        _SamplerThread.instance().add(self)
        super().__init__(name)

    def take_sample(self):
        now = time.monotonic()
        v = self._var.get_value()
        with self._mu:
            self._samples.append((now, v))
            horizon = now - self._window - 2
            while self._samples and self._samples[0][0] < horizon:
                self._samples.pop(0)

    def get_value(self):
        with self._mu:
            if not self._samples:
                return 0
            newest_t, newest_v = self._samples[-1]
            target = newest_t - self._window
            oldest_v = None
            for t, v in self._samples:
                if t >= target:
                    oldest_v = v
                    break
            if oldest_v is None:
                oldest_v = self._samples[0][1]
            try:
                return newest_v - oldest_v
            except TypeError:
                return newest_v

    def get_span(self) -> float:
        with self._mu:
            if len(self._samples) < 2:
                return 1.0
            newest_t = self._samples[-1][0]
            target = newest_t - self._window
            for t, _ in self._samples:
                if t >= target:
                    return max(1e-9, newest_t - t)
            return max(1e-9, newest_t - self._samples[0][0])


class PerSecond(Window):
    """Windowed delta divided by the window span — qps/throughput."""

    def get_value(self):
        delta = super().get_value()
        span = self.get_span()
        try:
            return delta / span
        except TypeError:
            return 0.0
