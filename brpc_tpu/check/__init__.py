"""brpc-check (ISSUE 14) — the repo-invariant static-analysis suite.

bRPC ships its own correctness tooling beside the runtime (contention
profiler, rpcz, builtin diagnostics); this package is that idea turned
on the REPO: six AST passes encode the load-bearing conventions the
tree has grown — the static lock-order graph must be acyclic
(lock-order), wire parsers bounds-check before sizing
(bounded-decode), jit programs compile once per bucket (jit-hot-path),
every fault site is registered and test-referenced (fault-sites), hot
modules use the InstrumentedLock ledger (lock-hygiene), and tests
bound their joins/native entries (wedge-hygiene).  `make check` runs
them all against the committed CHECK_BASELINE.json: frozen findings
pass, new ones exit 1.

CLI: ``python tools/brpc_check.py`` (``--json`` for machine output,
``--write-baseline`` / ``--write-fault-registry`` to regenerate the
committed artifacts).  The runtime complement — the lock-order
WITNESS that observes executed acquisition orders and flags ABBA
cycles live — is butil/lockprof.py.
"""
from brpc_tpu.check.base import Finding, Repo  # noqa: F401
from brpc_tpu.check.runner import all_passes, run_checks  # noqa: F401
