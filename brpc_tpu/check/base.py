"""brpc-check infrastructure (ISSUE 14) — findings, source cache,
suppression comments.

The suite is AST-based and repo-local: every pass walks parsed Python
sources under a repo root and returns :class:`Finding`s.  A finding's
``key`` is its BASELINE IDENTITY — built from the pass id plus stable
symbols (paths, qualnames, lock/site names), never line numbers, so a
committed baseline survives unrelated edits while a genuinely new
violation of the same kind in the same function still matches its
frozen twin (one finding per (pass, symbol) is the granularity the
baseline freezes; the messages carry lines for humans).

Suppressions: a ``# brpc-check: allow(<pass-id>)`` comment on the
flagged line or the line above waives that pass there — for the rare
case where the invariant is deliberately broken and a comment
explaining why belongs in the source anyway.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re

ALLOW_RE = re.compile(r"#\s*brpc-check:\s*allow\(([a-z0-9_,\- ]+)\)")


@dataclasses.dataclass
class Finding:
    pass_id: str
    path: str          # repo-relative, forward slashes
    line: int
    key: str           # stable baseline identity (no line numbers)
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"[{self.pass_id}] {self.path}:{self.line}: {self.message}"


class SourceFile:
    """One parsed source file; parse errors surface as a finding from
    the runner, not an exception (a syntax-broken tree must fail the
    check, not crash it)."""

    def __init__(self, root: str, rel: str):
        self.rel = rel.replace(os.sep, "/")
        self.path = os.path.join(root, rel)
        with open(self.path, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree: ast.Module | None = None
        self.parse_error: str | None = None
        try:
            self.tree = ast.parse(self.text, filename=self.rel)
        except SyntaxError as e:
            self.parse_error = f"syntax error: {e}"

    def allowed(self, line: int, pass_id: str) -> bool:
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                m = ALLOW_RE.search(self.lines[ln - 1])
                if m and pass_id in [s.strip()
                                     for s in m.group(1).split(",")]:
                    return True
        return False


class Repo:
    """Root + cached parsed sources.  Passes share one parse per file
    so the whole six-pass suite stays well under the 30s budget."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._cache: dict[str, SourceFile] = {}

    def file(self, rel: str) -> SourceFile | None:
        rel = rel.replace(os.sep, "/")
        sf = self._cache.get(rel)
        if sf is None:
            if not os.path.isfile(os.path.join(self.root, rel)):
                return None
            sf = self._cache[rel] = SourceFile(self.root, rel)
        return sf

    def files(self, subdirs=("brpc_tpu",)) -> list[SourceFile]:
        out = []
        for sub in subdirs:
            base = os.path.join(self.root, sub)
            if os.path.isfile(base) and sub.endswith(".py"):
                sf = self.file(sub)
                if sf is not None:
                    out.append(sf)
                continue
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          self.root)
                    sf = self.file(rel)
                    if sf is not None:
                        out.append(sf)
        return out


def last_segment(func: ast.expr) -> str | None:
    """The trailing name of a call target: jax.jit -> 'jit'."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def base_name(func: ast.expr) -> str | None:
    """The leading name of a dotted call target: jax.jit -> 'jax'."""
    node = func
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def qualname_stack(stack: list[str]) -> str:
    return ".".join(stack) if stack else "<module>"


class FuncIndexer(ast.NodeVisitor):
    """Yields (qualname, class_name, FunctionDef) for every function in
    a module, tracking the lexical class/function stack."""

    def __init__(self):
        self.out: list[tuple[str, str | None, ast.AST]] = []
        self._stack: list[tuple[str, str]] = []  # (kind, name)

    def _cls(self) -> str | None:
        for kind, name in reversed(self._stack):
            if kind == "class":
                return name
            return None          # nested inside a function: no class
        return None

    def visit_ClassDef(self, node: ast.ClassDef):
        self._stack.append(("class", node.name))
        self.generic_visit(node)
        self._stack.pop()

    def _visit_func(self, node):
        qual = ".".join(n for _, n in self._stack + [("func", node.name)])
        self.out.append((qual, self._cls(), node))
        self._stack.append(("func", node.name))
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


def iter_functions(tree: ast.Module):
    ix = FuncIndexer()
    ix.visit(tree)
    return ix.out
