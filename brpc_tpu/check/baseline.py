"""Baseline handling for brpc-check (ISSUE 14).

The suite runs on every PR; pre-existing violations must not block
unrelated work, but NEW ones must exit 1.  The committed baseline
(CHECK_BASELINE.json at the repo root) freezes each known finding by
its stable key; `tools/brpc_check.py` reports

  * NEW findings (not in the baseline)        -> exit 1
  * SUPPRESSED findings (frozen)              -> counted, exit 0
  * STALE baseline entries (no longer firing) -> nagged, exit 0 —
    burn them out with --write-baseline so the frozen set only ever
    shrinks.
"""
from __future__ import annotations

import json
import os

BASELINE_REL = "CHECK_BASELINE.json"


def load_baseline(path: str) -> dict[str, dict]:
    if not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return dict(data.get("findings", {}))


def write_baseline(path: str, findings) -> None:
    data = {
        "comment": ("brpc-check frozen findings (ISSUE 14). "
                    "Pre-existing violations only — new findings fail "
                    "`make check`. Regenerate (shrink-only, please) "
                    "with `python tools/brpc_check.py --write-baseline`."),
        "findings": {
            f.key: {"path": f.path, "message": f.message}
            for f in sorted(findings, key=lambda f: f.key)
        },
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")


def split_findings(findings, baseline: dict):
    """(new, suppressed, stale_keys)."""
    new, suppressed = [], []
    fired = set()
    for f in findings:
        fired.add(f.key)
        (suppressed if f.key in baseline else new).append(f)
    stale = sorted(k for k in baseline if k not in fired)
    return new, suppressed, stale
