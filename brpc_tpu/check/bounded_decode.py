"""Pass 2: bounded-decode discipline in wire-parser modules (ISSUE 14).

The repo's parser contract (rpc/compact.py, rpc/tensorframe.py and the
protocol codecs): every integer read off the wire is BOUNDS-CHECKED in
exact Python ints before it sizes anything — a slice, a frombuffer, an
allocation.  A hostile peer otherwise drives `bytearray(length_field)`
to an 8 EiB allocation or a silent short-read.  This pass flags, per
function, any sizing use of a wire-read integer with no preceding
check.

Taint, intraprocedurally: a variable is wire-read when assigned from
``struct.unpack/unpack_from`` (or a subscript of one),
``int.from_bytes``, or a reader-shaped call (``u8/u16/u32/u64``,
``varint``, ``read_*``/``_read*``); arithmetic on tainted stays
tainted.  A check is any ``if``/``while``/``assert`` whose test
compares the tainted name (the `if n > len(buf): raise` idiom), or
passing it to a ``*check*/*need*/*require*/*bound*/*expect*`` helper;
``min(n, CAP)`` launders the taint by construction.  Sized sinks:
slice bounds, ``frombuffer(count=n)``, ``bytearray/bytes/zeros/empty/
full`` allocation args, and ``seq * n`` repetition.

Intraprocedural by design: a helper like ``take(n)`` that does its own
bounds check inside is the SANCTIONED pattern, and flagging its call
sites would punish exactly the discipline we want.
"""
from __future__ import annotations

import ast
import re

from brpc_tpu.check.base import Finding, Repo, iter_functions, last_segment

PASS_ID = "bounded-decode"

# the wire-parser modules under the contract (rpc/compact.py's Reader
# is the exemplar; tensorframe's decode is the newest adopter)
PARSER_MODULES = (
    "brpc_tpu/rpc/compact.py",
    "brpc_tpu/rpc/tensorframe.py",
    "brpc_tpu/rpc/hpack.py",
    "brpc_tpu/rpc/h2.py",
    "brpc_tpu/rpc/redis.py",
    "brpc_tpu/rpc/memcache.py",
    "brpc_tpu/rpc/mongo.py",
)

_READER_RE = re.compile(
    r"^(u|i)(8|16|32|64)$|^(read_|_read|peek_)|^(varint|unpack|"
    r"unpack_from|from_bytes)$")
_CHECK_RE = re.compile(r"check|need|require|bound|expect|validate|_fits")
_ALLOC_NAMES = {"bytearray", "bytes", "zeros", "empty", "full", "ones"}


def _is_reader_call(node: ast.expr) -> bool:
    if isinstance(node, ast.Subscript):
        return _is_reader_call(node.value)
    if not isinstance(node, ast.Call):
        return False
    seg = last_segment(node.func)
    return bool(seg and _READER_RE.search(seg))


class _TaintState:
    def __init__(self):
        self.tainted: set[str] = set()
        self.checked: set[str] = set()

    def expr_tainted(self, node: ast.expr) -> set[str]:
        """Names through which `node` is tainted-and-unchecked; a
        direct reader call reports the pseudo-name '<wire-read>'."""
        if isinstance(node, ast.Call):
            if _is_reader_call(node):
                return {"<wire-read>"}
            seg = last_segment(node.func)
            if seg in ("min", "len"):
                # min() bounds by construction; len() is host-side
                # truth — either one laundering the expression is the
                # sanctioned fix this pass points at
                return set()
            out: set[str] = set()
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                out |= self.expr_tainted(a)
            return out
        if isinstance(node, ast.Name):
            if node.id in self.tainted and node.id not in self.checked:
                return {node.id}
            return set()
        out = set()
        for child in ast.iter_child_nodes(node):
            out |= self.expr_tainted(child)
        return out


class BoundedDecodePass:
    pass_id = PASS_ID
    title = "wire-read integers are bounds-checked before sizing"

    def __init__(self, modules=PARSER_MODULES):
        self.modules = modules

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for rel in self.modules:
            sf = repo.file(rel)
            if sf is None or sf.tree is None:
                continue
            for qual, _cls, fn in iter_functions(sf.tree):
                out.extend(self._scan_function(sf, qual, fn))
        return out

    # ---- per-function scan ----

    def _scan_function(self, sf, qual, fn) -> list[Finding]:
        st = _TaintState()
        findings: dict[str, Finding] = {}

        def flag(node, names, what):
            name = sorted(names)[0]
            key = f"{PASS_ID}:{sf.rel}:{qual}:{name}"
            if key in findings or sf.allowed(node.lineno, PASS_ID):
                return
            findings[key] = Finding(
                pass_id=PASS_ID, path=sf.rel, line=node.lineno, key=key,
                message=(f"{what} sized by wire-read integer "
                         f"{name!r} with no preceding bounds check "
                         f"(in {qual})"))

        def mark_checked(test):
            for sub in ast.walk(test):
                if isinstance(sub, ast.Name) and sub.id in st.tainted:
                    st.checked.add(sub.id)

        def scan_sinks(node):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Subscript) and \
                        isinstance(sub.slice, ast.Slice):
                    names = set()
                    for bound in (sub.slice.lower, sub.slice.upper):
                        if bound is not None:
                            names |= st.expr_tainted(bound)
                    if names:
                        flag(sub, names, "slice")
                elif isinstance(sub, ast.Call):
                    seg = last_segment(sub.func)
                    if seg == "frombuffer":
                        for kw in sub.keywords:
                            if kw.arg == "count":
                                names = st.expr_tainted(kw.value)
                                if names:
                                    flag(sub, names, "frombuffer")
                    elif seg in _ALLOC_NAMES:
                        for a in sub.args:
                            names = st.expr_tainted(a)
                            if names:
                                flag(sub, names, f"{seg}() allocation")
                elif isinstance(sub, ast.BinOp) and \
                        isinstance(sub.op, ast.Mult):
                    # b"\x00" * n / [0] * n repetition
                    for side, other in ((sub.left, sub.right),
                                        (sub.right, sub.left)):
                        if isinstance(other, (ast.Constant, ast.List,
                                              ast.Tuple)):
                            names = st.expr_tainted(side)
                            if names:
                                flag(sub, names, "sequence repetition")

        def visit(stmts):
            for s in stmts:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                    continue
                if isinstance(s, (ast.If, ast.While)):
                    scan_sinks(s.test)
                    mark_checked(s.test)
                    visit(s.body)
                    visit(s.orelse)
                    continue
                if isinstance(s, ast.Assert):
                    mark_checked(s.test)
                    continue
                if isinstance(s, ast.Assign) and len(s.targets) >= 1:
                    scan_sinks(s.value)
                    tainted_by = st.expr_tainted(s.value) or \
                        ({"<wire-read>"} if _is_reader_call(s.value)
                         else set())
                    for t in s.targets:
                        names = [n.id for n in ast.walk(t)
                                 if isinstance(n, ast.Name)]
                        for n in names:
                            if tainted_by:
                                st.tainted.add(n)
                                st.checked.discard(n)
                            else:
                                st.tainted.discard(n)
                                st.checked.discard(n)
                    continue
                if isinstance(s, ast.AugAssign):
                    scan_sinks(s.value)
                    continue
                if isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
                    seg = last_segment(s.value.func) or ""
                    if _CHECK_RE.search(seg):
                        for a in s.value.args:
                            for sub in ast.walk(a):
                                if isinstance(sub, ast.Name) and \
                                        sub.id in st.tainted:
                                    st.checked.add(sub.id)
                        continue
                    scan_sinks(s)
                    continue
                scan_sinks(s)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(s, attr, None)
                    if sub:
                        visit(sub)
                for h in getattr(s, "handlers", []):
                    visit(h.body)

        visit(fn.body)
        return list(findings.values())
