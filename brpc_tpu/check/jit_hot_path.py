"""Pass 3: one-compile-per-bucket jit discipline (ISSUE 14).

The serving stack's compile-count invariants (trace-counter-pinned in
the batcher/engine tests) all flow from one convention: ``jax.jit`` /
``shard_map`` / ``pjit`` / ``pmap`` programs are constructed ONCE — at
module level, in ``__init__`` (per bucket), or in an explicitly-cached
builder — never inside a per-call function, where every request would
pay a retrace (and the jit cache grows without bound when shapes
vary).  This pass flags jit construction inside function bodies unless
the enclosing function is constructor-shaped (``__init__``,
``make_*``/``build_*``/``*compile*``) or wrapped in
``functools.lru_cache``/``cache``.
"""
from __future__ import annotations

import ast
import re

from brpc_tpu.check.base import (Finding, Repo, base_name, last_segment,
                                 qualname_stack)

PASS_ID = "jit-hot-path"

_JIT_NAMES = {"jit", "pjit", "pmap", "shard_map"}
_SETUP_RE = re.compile(r"^(__init__|__init_subclass__|make|build|_make|"
                       r"_build|_?jit)|compile")
_CACHE_DECOS = {"lru_cache", "cache", "cached_property"}


def _decorated_cached(fn) -> bool:
    for d in fn.decorator_list:
        seg = last_segment(d.func if isinstance(d, ast.Call) else d)
        if seg in _CACHE_DECOS:
            return True
    return False


class JitHotPathPass:
    pass_id = PASS_ID
    title = "jit/shard_map constructed at module level, not per call"

    def __init__(self, subdirs=("brpc_tpu",)):
        self.subdirs = subdirs

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for sf in repo.files(self.subdirs):
            if sf.tree is None:
                continue
            imports_jax = any(
                (isinstance(n, ast.Import)
                 and any(a.name.split(".")[0] == "jax" for a in n.names))
                or (isinstance(n, ast.ImportFrom) and n.module
                    and n.module.split(".")[0] == "jax")
                for n in ast.walk(sf.tree))
            if not imports_jax:
                continue
            out.extend(self._scan(sf))
        return out

    def _scan(self, sf) -> list[Finding]:
        found: dict[str, Finding] = {}

        def walk(node, name_stack, in_func, exempt):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    child_exempt = exempt or \
                        bool(_SETUP_RE.search(child.name)) or \
                        _decorated_cached(child)
                    # decorators evaluate in the ENCLOSING scope
                    for d in child.decorator_list:
                        walk(d, name_stack, in_func, exempt)
                    walk(child, name_stack + [child.name], True,
                         child_exempt)
                    continue
                if isinstance(child, ast.ClassDef):
                    # class bodies execute at import time: the name
                    # rides the qualname, per-call-ness does not
                    walk(child, name_stack + [child.name], in_func,
                         exempt)
                    continue
                if isinstance(child, ast.Call) and not exempt \
                        and in_func:
                    seg = last_segment(child.func)
                    base = base_name(child.func)
                    if seg in _JIT_NAMES and base in (
                            "jax", "pjit", "jit", "pmap", "shard_map",
                            "shmap", None):
                        qual = qualname_stack(name_stack)
                        key = f"{PASS_ID}:{sf.rel}:{qual}:{seg}"
                        if key not in found and \
                                not sf.allowed(child.lineno, PASS_ID):
                            found[key] = Finding(
                                pass_id=PASS_ID, path=sf.rel,
                                line=child.lineno, key=key,
                                message=(
                                    f"{seg}(...) constructed inside "
                                    f"per-call function {qual} — hoist "
                                    f"to module level or a bucketed "
                                    f"__init__ cache (one compile per "
                                    f"bucket)"))
                walk(child, name_stack, in_func, exempt)

        walk(sf.tree, [], False, False)
        return list(found.values())
