"""Pass 5: InstrumentedLock hygiene in the serving hot modules
(ISSUE 14).

The lock-contention ledger (/hotspots/locks, ``lock_*_{wait,hold}_us``)
and the runtime lock-order witness only see locks that go through
``butil.lockprof.InstrumentedLock``.  A raw ``threading.Lock`` in a
hot subsystem is invisible to both — exactly how psserve/ grew five
modules of exactly-once logic with zero ledger coverage.  This pass
flags raw ``threading.Lock()``/``RLock()``/bare ``Condition()``
construction in the hot directories; an RLock passed as
``InstrumentedLock(name, threading.RLock())`` (the wrapper's inner)
and ``Condition(InstrumentedLock(...))`` are the sanctioned forms.
"""
from __future__ import annotations

import ast

from brpc_tpu.check.base import (Finding, Repo, base_name, last_segment,
                                 qualname_stack)

PASS_ID = "lock-hygiene"

HOT_PREFIXES = (
    "brpc_tpu/serving/",
    "brpc_tpu/kvcache/",
    "brpc_tpu/psserve/",
    "brpc_tpu/migrate/",
    # ISSUE 15: the flight-recorder surface feeds every wedge autopsy —
    # a raw lock here would be invisible to the very dump it renders
    "brpc_tpu/butil/flight.py",
)


class LockHygienePass:
    pass_id = PASS_ID
    title = "hot modules use InstrumentedLock, not raw threading locks"

    def __init__(self, prefixes=HOT_PREFIXES):
        self.prefixes = prefixes

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for sf in repo.files(("brpc_tpu",)):
            if sf.tree is None or \
                    not sf.rel.startswith(tuple(self.prefixes)):
                continue
            out.extend(self._scan(sf))
        return out

    def _scan(self, sf) -> list[Finding]:
        found: dict[str, Finding] = {}

        def target_of(stack_parents, call) -> str:
            # nearest Assign ancestor names the lock for the key
            for p in reversed(stack_parents):
                if isinstance(p, ast.Assign) and len(p.targets) == 1:
                    t = p.targets[0]
                    if isinstance(t, ast.Attribute):
                        return t.attr
                    if isinstance(t, ast.Name):
                        return t.id
            return f"anon@{call.lineno}"

        def walk(node, func_stack, parents):
            for child in ast.iter_child_nodes(node):
                fs = func_stack
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    fs = func_stack + [child.name]
                if isinstance(child, ast.Call):
                    seg = last_segment(child.func)
                    base = base_name(child.func)
                    raw = (seg in ("Lock", "RLock")
                           or (seg == "Condition" and not child.args)) \
                        and (base == "threading"
                             or isinstance(child.func, ast.Name))
                    if raw:
                        # sanctioned: the inner of InstrumentedLock(...)
                        wrapped = any(
                            isinstance(p, ast.Call) and
                            last_segment(p.func) == "InstrumentedLock"
                            for p in parents)
                        if not wrapped and \
                                not sf.allowed(child.lineno, PASS_ID):
                            qual = qualname_stack(func_stack)
                            tgt = target_of(parents, child)
                            key = f"{PASS_ID}:{sf.rel}:{qual}:{tgt}"
                            if key not in found:
                                kind = seg if seg != "Condition" \
                                    else "bare Condition"
                                found[key] = Finding(
                                    pass_id=PASS_ID, path=sf.rel,
                                    line=child.lineno, key=key,
                                    message=(
                                        f"raw threading.{kind}() for "
                                        f"{tgt!r} in hot module (in "
                                        f"{qual}) — use a named "
                                        f"InstrumentedLock so the "
                                        f"ledger and the lock-order "
                                        f"witness can see it"))
                walk(child, fs, parents + [child])

        walk(sf.tree, [], [])
        return list(found.values())
