"""Pass 1: static lock-order graph + cycle detection (ISSUE 14).

The runtime witness (butil/lockprof.py) observes orders that actually
executed; this pass extracts the orders the CODE permits.  It

  1. identifies lock objects syntactically — ``InstrumentedLock("n")``
     (canonical id: the shared ledger name), raw ``threading.Lock/
     RLock`` and ``Condition`` (canonical id: ``module:Class.attr``),
     and ``Condition(InstrumentedLock("n"))`` (the inner name) — bound
     to ``self.attr`` or module/function variables;
  2. summarises every function: which locks it acquires (``with l:``
     spans and paired ``l.acquire()``/``l.release()`` calls) under
     which statically-held set, and which repo functions it calls while
     holding locks;
  3. propagates transitively — a call made while holding A contributes
     A -> L for every lock L the callee's transitive closure acquires.
     Calls resolve conservatively: ``self.m()`` to the same class,
     bare names to the same module, ``alias.f()`` through brpc_tpu
     module imports, and ``obj.m()`` only when exactly one method of
     that name exists in the module (else repo-wide unique) — an
     unresolvable call contributes nothing rather than guessing;
  4. reports every strongly-connected component of the resulting
     lock-order graph with > 1 lock as a cycle finding, with the
     source site that first contributed each edge.

An under-approximation by construction (unresolved calls drop edges),
so a reported cycle is worth believing; the committed baseline freezes
any pre-existing ones.
"""
from __future__ import annotations

import ast
import re as _re
import threading as _threading

from brpc_tpu.check.base import (Finding, Repo, base_name, iter_functions,
                                 last_segment)

PASS_ID = "lock-order"

_LOCK_CTORS = {"Lock", "RLock", "Condition"}

# method names that collide with builtin/stdlib types: `s.replace()` or
# `pat.match()` must NEVER resolve to a same-named repo method through
# the repo-wide-unique fallback — one such false edge fuses unrelated
# lock clusters into a giant bogus SCC
_BUILTIN_METHODS = (
    set(dir(str)) | set(dir(bytes)) | set(dir(bytearray))
    | set(dir(dict)) | set(dir(list)) | set(dir(set)) | set(dir(tuple))
    | set(dir(frozenset)) | set(dir(int)) | set(dir(float))
    | set(dir(_re.compile(""))) | set(dir(_re.match("", "")))
    | set(dir(_threading.Thread)) | set(dir(_threading.Condition()))
    | set(dir(Exception)))


def _lock_ctor_id(call: ast.expr, rel: str, cls: str | None,
                  target: str) -> str | None:
    """Canonical lock id when `call` constructs a lock, else None."""
    if not isinstance(call, ast.Call):
        return None
    seg = last_segment(call.func)
    if seg == "InstrumentedLock":
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            return call.args[0].value
        return f"{rel}:{cls + '.' if cls else ''}{target}"
    if seg in _LOCK_CTORS:
        base = base_name(call.func)
        # accept threading.Lock() and bare Lock() (from-import); a
        # dotted base other than `threading` is someone else's Lock
        if not (base == "threading" or isinstance(call.func, ast.Name)):
            return None
        if seg == "Condition" and call.args:
            inner = _lock_ctor_id(call.args[0], rel, cls, target)
            if inner is not None:
                return inner
            # Condition(self._mu): same lock as the referenced attr —
            # leave to the attr's own binding (alias unresolved here)
            return None
        return f"{rel}:{cls + '.' if cls else ''}{target}"
    return None


class _ModuleLocks:
    """Lock bindings of one module: (class, attr) and bare names."""

    def __init__(self, sf):
        self.attr: dict[tuple[str | None, str], str] = {}
        self.var: dict[str, str] = {}
        for qual, cls, fn in [("<module>", None, sf.tree)] \
                + iter_functions(sf.tree):
            for node in ast.walk(fn) if fn is not sf.tree else \
                    list(ast.iter_child_nodes(sf.tree)):
                if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                    continue
                t = node.targets[0]
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and t.value.id == "self":
                    lid = _lock_ctor_id(node.value, sf.rel, cls, t.attr)
                    if lid is not None:
                        self.attr[(cls, t.attr)] = lid
                elif isinstance(t, ast.Name):
                    lid = _lock_ctor_id(node.value, sf.rel, None, t.id)
                    if lid is not None:
                        self.var[t.id] = lid
        # class-body assignments (rare) ride the walk above via
        # iter_functions only for funcs; add module-tree class bodies
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                for st in node.body:
                    if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                            and isinstance(st.targets[0], ast.Name):
                        lid = _lock_ctor_id(st.value, sf.rel, node.name,
                                            st.targets[0].id)
                        if lid is not None:
                            self.attr[(node.name, st.targets[0].id)] = lid


def _module_imports(tree: ast.Module) -> dict[str, str]:
    """alias -> brpc_tpu module rel path (best effort)."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith("brpc_tpu"):
                    out[a.asname or a.name.split(".")[-1]] = \
                        a.name.replace(".", "/") + ".py"
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.module.startswith("brpc_tpu"):
            for a in node.names:
                cand = node.module.replace(".", "/") + "/" + a.name + ".py"
                out[a.asname or a.name] = cand
    return out


class _FuncSummary:
    __slots__ = ("key", "acquires", "calls")

    def __init__(self, key):
        self.key = key
        # acquires: (lock_id, frozenset(held), "rel:line")
        self.acquires: list[tuple[str, frozenset, str]] = []
        # calls: (callee_name_info, frozenset(held), "rel:line")
        self.calls: list[tuple[tuple, frozenset, str]] = []


class _FuncWalker:
    """Walks one function body in order, tracking the statically-held
    lock set through `with` nesting and acquire()/release() pairs."""

    def __init__(self, summary, locks: _ModuleLocks, cls, rel,
                 imports: dict[str, str]):
        self.s = summary
        self.locks = locks
        self.cls = cls
        self.rel = rel
        self.imports = imports
        self.held: list[str] = []

    def _resolve_lock(self, expr) -> str | None:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            lid = self.locks.attr.get((self.cls, expr.attr))
            if lid is not None:
                return lid
            # single class defining that attr in this module
            cands = {v for (c, a), v in self.locks.attr.items()
                     if a == expr.attr}
            return cands.pop() if len(cands) == 1 else None
        if isinstance(expr, ast.Name):
            return self.locks.var.get(expr.id)
        return None

    def _site(self, node) -> str:
        return f"{self.rel}:{node.lineno}"

    def _note_acquire(self, lid, node):
        self.s.acquires.append((lid, frozenset(self.held),
                                self._site(node)))

    def _resolve_call(self, func) -> tuple | None:
        if isinstance(func, ast.Name):
            return ("local", self.rel, None, func.id)
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name):
                if func.value.id == "self":
                    return ("method", self.rel, self.cls, func.attr)
                mod = self.imports.get(func.value.id)
                if mod is not None:
                    return ("local", mod, None, func.attr)
            return ("unique", None, None, func.attr)
        return None

    def _scan_expr(self, node):
        """Record calls inside an expression tree (held set applies),
        skipping nested function/lambda bodies."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                seg = last_segment(sub.func)
                if seg in ("acquire", "release"):
                    continue        # handled as events by the caller
                ref = self._resolve_call(sub.func)
                if ref is not None and self.held:
                    self.s.calls.append((ref, frozenset(self.held),
                                         self._site(sub)))
                elif ref is not None:
                    self.s.calls.append((ref, frozenset(), self._site(sub)))

    def walk(self, body: list[ast.stmt]):
        for st in body:
            self._stmt(st)

    def _stmt(self, st: ast.stmt):
        if isinstance(st, ast.With) or isinstance(st, ast.AsyncWith):
            pushed = []
            for item in st.items:
                expr = item.context_expr
                self._scan_expr(expr)
                lid = self._resolve_lock(expr)
                if lid is None and isinstance(expr, ast.Call):
                    # with lock.acquire_timeout(...) style: ignore;
                    # with self._mu: is the Name/Attribute case above
                    lid = None
                if lid is not None:
                    self._note_acquire(lid, st)
                    self.held.append(lid)
                    pushed.append(lid)
            for sub in st.body:
                self._stmt(sub)
            for lid in reversed(pushed):
                self.held.remove(lid)
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return          # nested defs are summarised separately
        compound = any(getattr(st, a, None)
                       for a in ("body", "orelse", "finalbody", "handlers"))
        if compound:
            # scan only the HEADER expression here; the blocks recurse
            # below (scanning the whole subtree now would double-count
            # events and pair locks across branches)
            for header in ("test", "iter", "subject"):
                expr = getattr(st, header, None)
                if expr is not None:
                    self._scan_expr(expr)
            for attr in ("body", "orelse", "finalbody"):
                for sub in getattr(st, attr, []):
                    self._stmt(sub)
            for h in getattr(st, "handlers", []):
                for sub in h.body:
                    self._stmt(sub)
            return
        # simple statement: acquire()/release() events + calls
        for sub in ast.walk(st):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute):
                if sub.func.attr == "acquire":
                    lid = self._resolve_lock(sub.func.value)
                    if lid is not None:
                        self._note_acquire(lid, sub)
                        self.held.append(lid)
                elif sub.func.attr == "release":
                    lid = self._resolve_lock(sub.func.value)
                    if lid is not None and lid in self.held:
                        self.held.remove(lid)
        self._scan_expr(st)


class LockOrderPass:
    pass_id = PASS_ID
    title = "static lock-order graph is acyclic"

    def __init__(self, subdirs=("brpc_tpu",)):
        self.subdirs = subdirs

    def run(self, repo: Repo) -> list[Finding]:
        files = [sf for sf in repo.files(self.subdirs)
                 if sf.tree is not None]
        mod_locks = {sf.rel: _ModuleLocks(sf) for sf in files}
        summaries: dict[tuple, _FuncSummary] = {}
        by_name: dict[str, list[tuple]] = {}
        for sf in files:
            imports = _module_imports(sf.tree)
            for qual, cls, fn in iter_functions(sf.tree):
                key = (sf.rel, cls, fn.name)
                s = _FuncSummary(key)
                w = _FuncWalker(s, mod_locks[sf.rel], cls, sf.rel, imports)
                w.walk(fn.body)
                # last summary of a key wins (overloads are rare and
                # an either/or choice is fine for an under-approx)
                summaries[key] = s
                by_name.setdefault(fn.name, []).append(key)

        def resolve(ref) -> tuple | None:
            kind, rel, cls, name = ref
            if kind == "method":
                if (rel, cls, name) in summaries:
                    return (rel, cls, name)
                kind = "local"      # fall through: module function
            if kind == "local":
                if (rel, None, name) in summaries:
                    return (rel, None, name)
                cands = [k for k in by_name.get(name, ()) if k[0] == rel]
                if len(cands) == 1:
                    return cands[0]
                return None
            # unique: obj.m() — resolve only when m names exactly one
            # function in the whole repo AND cannot be a builtin-type
            # method (str.replace, pattern.match, thread.start ...)
            if name in _BUILTIN_METHODS:
                return None
            cands = by_name.get(name, ())
            return cands[0] if len(cands) == 1 else None

        # transitive acquired-lock closure per function
        closure: dict[tuple, set] = {}

        def acq(key, stack) -> set:
            got = closure.get(key)
            if got is not None:
                return got
            if key in stack:
                return set()        # recursion: partial is fine
            stack = stack | {key}
            out = set()
            s = summaries[key]
            for lid, _, _ in s.acquires:
                out.add(lid)
            for ref, _, _ in s.calls:
                ck = resolve(ref)
                if ck is not None:
                    out |= acq(ck, stack)
            closure[key] = out
            return out

        edges: dict[tuple, str] = {}    # (a,b) -> first site
        for key, s in summaries.items():
            for lid, held, site in s.acquires:
                for h in held:
                    if h != lid:
                        edges.setdefault((h, lid), site)
            for ref, held, site in s.calls:
                if not held:
                    continue
                ck = resolve(ref)
                if ck is None:
                    continue
                callee = (ck[1] + "." if ck[1] else "") + ck[2]
                for lid in acq(ck, frozenset()):
                    for h in held:
                        if h != lid:
                            edges.setdefault((h, lid),
                                             f"{site} (via {callee})")

        return _cycle_findings(edges)


def _cycle_findings(edges: dict[tuple, str]) -> list[Finding]:
    adj: dict[str, set] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    # Tarjan SCC, iterative
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(v0):
        work = [(v0, iter(sorted(adj[v0])))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                elif w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    out = []
    for scc in sccs:
        inner = [((a, b), site) for (a, b), site in sorted(edges.items())
                 if a in scc and b in scc]
        detail = "; ".join(f"{a}->{b} at {site}" for (a, b), site in inner)
        site0 = inner[0][1] if inner else "?:0"
        relpath, _, line = site0.partition(":")
        try:
            lineno = int(line.split()[0].rstrip(")"))
        except ValueError:
            lineno = 0
        out.append(Finding(
            pass_id=PASS_ID, path=relpath, line=lineno,
            key=f"{PASS_ID}:cycle:" + "|".join(scc),
            message=(f"lock-order cycle between {', '.join(scc)} — a "
                     f"thread taking these in one order can deadlock a "
                     f"thread taking the other ({detail})")))
    return out
