"""brpc-check pass registry + orchestration (ISSUE 14)."""
from __future__ import annotations

import time

from brpc_tpu.check.base import Finding, Repo
from brpc_tpu.check.bounded_decode import BoundedDecodePass
from brpc_tpu.check.fault_sites import FaultSitePass
from brpc_tpu.check.jit_hot_path import JitHotPathPass
from brpc_tpu.check.lock_hygiene import LockHygienePass
from brpc_tpu.check.lock_order import LockOrderPass
from brpc_tpu.check.wedge_hygiene import WedgeHygienePass


def all_passes() -> list:
    return [
        LockOrderPass(),
        BoundedDecodePass(),
        JitHotPathPass(),
        FaultSitePass(),
        LockHygienePass(),
        WedgeHygienePass(),
    ]


def run_checks(root: str, pass_ids=None):
    """Run the suite; returns (findings, timings: {pass_id: seconds}).

    A file that no longer parses is itself a finding (the tree must
    fail the check, not crash it)."""
    repo = Repo(root)
    findings: list[Finding] = []
    timings: dict[str, float] = {}
    for p in all_passes():
        if pass_ids and p.pass_id not in pass_ids:
            continue
        t0 = time.monotonic()
        findings.extend(p.run(repo))
        timings[p.pass_id] = time.monotonic() - t0
    for rel, sf in sorted(repo._cache.items()):
        if sf.parse_error is not None:
            findings.append(Finding(
                pass_id="parse", path=rel, line=0,
                key=f"parse:{rel}", message=sf.parse_error))
    return findings, timings
