"""Pass 6: wedge hygiene in tests (ISSUE 14).

The PR 11/12/13 tier-1 wedge class: a ctypes entry into the native
core intermittently never returns deep in a full run, and an unbounded
``.join()`` behind it turns one wedged call into a hung suite.  The
discipline (tests/wedge_guard.py) is: every direct native entry in a
test module runs under a WedgeGuard deadline, and thread joins carry a
timeout.  This pass flags, in tests/:

  * ``.join()`` calls with no timeout (positional or keyword) — an
    unbounded join is the amplifier that turns a wedge into a hang;
    joins on server-shaped receivers (``srv``/``server``/...) are
    exempt: ``Server.join()`` takes no timeout and is internally
    bounded by ``graceful_quit_timeout_s``;
  * direct native entries (``*.brpc_*`` attribute calls — the ctypes
    surface of libbrpc_core) in modules that never touch WedgeGuard.
"""
from __future__ import annotations

import ast

from brpc_tpu.check.base import Finding, Repo, qualname_stack

PASS_ID = "wedge-hygiene"


class WedgeHygienePass:
    pass_id = PASS_ID
    title = "test joins are bounded; native entries ride WedgeGuard"

    def __init__(self, subdirs=("tests",)):
        self.subdirs = subdirs

    def run(self, repo: Repo) -> list[Finding]:
        out: list[Finding] = []
        for sf in repo.files(self.subdirs):
            if sf.tree is None or "/" in sf.rel.replace("tests/", "", 1) \
                    or not sf.rel.split("/")[-1].startswith("test_"):
                # only test modules proper (not fixtures/corpus dirs)
                continue
            out.extend(self._scan(sf))
        return out

    def _scan(self, sf) -> list[Finding]:
        has_guard = "WedgeGuard" in sf.text
        found: dict[str, Finding] = {}

        def flag(node, qual, what, message):
            key = f"{PASS_ID}:{sf.rel}:{qual}:{what}"
            if key in found or sf.allowed(node.lineno, PASS_ID):
                return
            found[key] = Finding(pass_id=PASS_ID, path=sf.rel,
                                 line=node.lineno, key=key, message=message)

        def walk(node, func_stack):
            for child in ast.iter_child_nodes(node):
                fs = func_stack
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    fs = func_stack + [child.name]
                if isinstance(child, ast.Call) and \
                        isinstance(child.func, ast.Attribute):
                    attr = child.func.attr
                    qual = qualname_stack(func_stack)
                    recv = child.func.value
                    recv_name = recv.attr if isinstance(recv, ast.Attribute) \
                        else (recv.id if isinstance(recv, ast.Name) else "")
                    server_like = any(s in recv_name.lower()
                                      for s in ("srv", "server", "router",
                                                "replica"))
                    if attr == "join" and not child.args and \
                            not any(k.arg in ("timeout", None)
                                    for k in child.keywords) and \
                            not server_like:
                        flag(child, qual, "join",
                             f".join() with no timeout in {qual} — an "
                             f"unbounded join turns one wedged native "
                             f"call into a hung suite; pass a deadline "
                             f"or use WedgeGuard.join_thread")
                    elif attr.startswith("brpc_") and not has_guard:
                        flag(child, qual, f"native:{attr}",
                             f"direct native entry {attr} in {qual} "
                             f"without a WedgeGuard in the module — a "
                             f"wedged ctypes call must skip, not hang "
                             f"(tests/wedge_guard.py)")
                walk(child, fs)

        walk(sf.tree, [])
        return list(found.values())
