"""RPC error codes, mirroring the reference's errno space.

Reference: /root/reference/src/brpc/errno.proto (codes 1001-2004) — same
numbering so operators moving from bRPC read identical codes in logs and
metrics; negative system errnos pass through untouched.
"""
from __future__ import annotations

# brpc-compatible error space (errno.proto:20-49)
ENOSERVICE = 1001        # service not found
ENOMETHOD = 1002         # method not found
EREQUEST = 1003          # bad request
ERPCAUTH = 1004          # authentication failed
ETOOMANYFAILS = 1005     # too many sub-channel failures (ParallelChannel)
EPCHANFINISH = 1006      # ParallelChannel finished
EBACKUPREQUEST = 1007    # backup request timer fired (internal trigger)
ERPCTIMEDOUT = 1008      # RPC deadline exceeded
EFAILEDSOCKET = 1009     # the connection broke during the RPC
EHTTP = 1010             # non-2xx HTTP status
EOVERCROWDED = 1011      # too many buffered writes / server overcrowded
ERTMPPUBLISHABLE = 1012
ERTMPCREATESTREAM = 1013
EEOF = 1014              # stream EOF
EUNUSED = 1015
ESSL = 1016
EH2RUNOUTSTREAMS = 1017
EREJECT = 1018           # concurrency limiter rejected the request

EINTERNAL = 2001         # server-side internal error
ERESPONSE = 2002         # bad response
ELOGOFF = 2003           # server is stopping
ELIMIT = 2004            # concurrency limit reached

# Locally-originated (client library) codes
EINVAL = 22
ENODATA = 61
ECONNREFUSED = 111
ECANCELED = 125          # call canceled by the caller (StartCancel analog)

_DESCRIPTIONS = {
    ENOSERVICE: "The service was not found",
    ENOMETHOD: "The method was not found",
    EREQUEST: "Bad request",
    ERPCAUTH: "Authentication failed",
    ETOOMANYFAILS: "Too many sub-channel failures",
    EPCHANFINISH: "ParallelChannel finished",
    EBACKUPREQUEST: "Backup request triggered",
    ERPCTIMEDOUT: "RPC call timed out",
    EFAILEDSOCKET: "Broken socket during RPC",
    EHTTP: "HTTP error",
    EOVERCROWDED: "The server is overcrowded",
    EEOF: "End of stream",
    EREJECT: "Request rejected by interceptor",
    EINTERNAL: "Internal server error",
    ERESPONSE: "Bad response",
    ELOGOFF: "Server is stopping",
    ELIMIT: "Reached server's concurrency limit",
    ECANCELED: "The RPC was canceled by the caller",
}


def describe(code: int) -> str:
    import os
    return _DESCRIPTIONS.get(code) or os.strerror(code) if code else "OK"


class RpcError(Exception):
    """Raised by synchronous call helpers when the RPC failed."""

    def __init__(self, code: int, text: str = ""):
        self.code = code
        self.text = text or describe(code)
        super().__init__(f"[E{code}] {self.text}")
