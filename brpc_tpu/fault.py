"""Deterministic fault injection for the full RPC/ICI data path.

Chaos testing needs two properties production failures lack:
*determinism* (a seeded schedule produces the same fault sequence every
run, so a chaos test is a regression test) and *observability* (every
injected fault is counted per site on /vars, so rpcz/console can show
what chaos actually ran).  This module provides both as a process-global
layer with NAMED INJECTION SITES threaded through the transport and ICI
layers:

    site               layer   faults
    transport.connect  L3      refuse, latency
    transport.send     L3      error, overcrowd, reset, partial, corrupt,
                               latency
    transport.recv     L3      drop, corrupt, latency  (see caveat below)
    stream.frame       L4      drop, dup, latency        (rpc/stream.py)
    stream.feedback    L4      drop                      (credit loss)
    h2.send            L4      error, corrupt, latency   (rpc/h2.py)
    h2.recv            L4      drop, latency
    ici.send           ICI     error, latency            (ici/endpoint.py)
    ici.alloc          ICI     exhaust                   (ici/block_pool.py)
    dcn.call           DCN     error, latency            (ici/dcn.py)
    dcn.serve          DCN     error, latency
    serving.batch      L6      error  (serving/batcher.py: mid-batch
                               failure — every member completes with a
                               definite error, never a partial scatter)
    serving.slot_alloc L6      error  (serving/engine.py: KV slot lease
                               fails; that request errors, the loop and
                               the block pool stay healthy)
    serving.step       L6      error, latency  (serving/engine.py: the
                               decode step itself fails — supervised
                               engines crash and the EngineSupervisor
                               fails over the in-flight generations;
                               unsupervised engines fail their
                               requests definitively)
    serving.heartbeat  L6      error  (serving/engine.py: SUPPRESSES
                               the step-progress heartbeat while the
                               loop keeps running — from the
                               supervisor's watchdog this is exactly a
                               wedged loop, so takeover-from-a-live-
                               loop is deterministically testable)
    kvcache.page_alloc KV      exhaust (kvcache/pages.py: page alloc
                               raises MemoryError — the store evicts
                               LRU radix leaves and retries; still dry
                               -> that request errors mid-decode)
    kvcache.evict      KV      error  (kvcache/radix.py: eviction
                               itself fails — pressure relief is
                               unavailable, allocation pressure
                               surfaces to the caller)
    dcn.migrate_send   MIG     error  (migrate/plane.py: the source
                               loses the page offer before anything
                               leaves the process — pins released,
                               caller falls back to recompute)
    dcn.migrate_recv   MIG     error  (migrate/plane.py: the
                               destination refuses the Offer before
                               pulling — the source gets a definite
                               error, nothing was spliced)
    migrate.splice     MIG     error  (kvcache/store.py import_prefix:
                               the splice fails mid-import — every
                               already-spliced page rolls back, the
                               tree never holds a partial chain)
    router.admit       L7      error  (serving/router.py: session
                               admission fails before any forward —
                               the client gets a definite error,
                               nothing crossed DCN)
    router.forward     L7      error  (serving/router.py: one forward
                               attempt fails pre-flight — counted as a
                               replica failure, the driver re-routes
                               and the session resumes after its
                               cursor)
    router.resume      L7      error  (serving/router.py: a client
                               reconnect/attach fails — the session
                               record is untouched, the client's retry
                               replays from its cursor)

Disabled (the default), every site is a single module-attribute check —
``if fault.ENABLED:`` — before ANY per-site work, so the production data
path pays one predicted-not-taken branch and nothing else.  Enabled,
``hit(site)`` consults the installed :class:`FaultPlan`: rules fire
deterministically by per-site hit index (``after``/``times``) or by a
per-rule seeded RNG (``prob``) — never by wall clock or thread identity.

    plan = fault.FaultPlan(seed=7)
    plan.on("transport.send", fault.RESET, times=1, after=2)
    plan.on("stream.frame", fault.DROP, prob=0.05)
    with fault.injected(plan):
        ...run traffic...
    assert plan.injected["transport.send"] == 1

Sites interpret a fired fault in their OWN failure convention (an rc for
the socket writers, ConnectionError for connect, MemoryError for the
block pool) — this module only decides *whether* and *what*; LATENCY is
the one kind applied here (sleep, then proceed) so it composes with any
site.

CAVEAT — transport.recv sees only messages delivered through the Python
message trampoline (stream frames, full-meta fallback messages, server
messages without the fast path).  Pre-parsed unary requests/responses
ride the C fastrpc trampolines and never pass this site: to lose or
delay a unary RESPONSE, inject at the sender (`transport.send` scoped to
the server-side sid), as the chaos backup-request scenario does.
"""
from __future__ import annotations

import threading
import time
import random
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Optional

from brpc_tpu.bvar import Adder

# ---------------------------------------------------------------------------
# fault kinds
# ---------------------------------------------------------------------------

REFUSE = "refuse"        # connect: raise ConnectionError
RESET = "reset"          # send: fail the socket mid-call (ECONNRESET)
ERROR = "error"          # generic failure in the site's own convention
OVERCROWD = "overcrowd"  # send: the native -2 write-queue-bound rc
LATENCY = "latency"      # sleep latency_s, then proceed (applied here)
PARTIAL = "partial"      # send: torn prefix on the wire, then socket death
CORRUPT = "corrupt"      # mangle the payload (site applies mangle())
DROP = "drop"            # recv/frame: swallow the message
DUP = "dup"              # stream frame: deliver twice (transport replay).
#                          Only SEQUENCED DATA frames duplicate — scope
#                          DUP rules with match=... on msg_type/stream_seq
#                          or a firing on another frame is a counted no-op
EXHAUST = "exhaust"      # block pool: alloc raises MemoryError

# Module-level fast gate.  Sites check this BEFORE any per-site work;
# install()/clear() are the only writers.  Reading a module attribute is
# the whole disabled-path cost.
ENABLED = False

_plan: Optional["FaultPlan"] = None
_mu = threading.Lock()

# per-site injected counters on /vars (created once per process, reused
# across plans — bvar names must stay unique)
_counters: dict[str, Adder] = {}
_counters_mu = threading.Lock()


def _counter(site: str) -> Adder:
    with _counters_mu:
        c = _counters.get(site)
        if c is None:
            c = Adder("fault_injected_" + site.replace(".", "_"))
            _counters[site] = c
        return c


def injected_counts() -> dict[str, int]:
    """Process-lifetime injected counts per site (the /vars view)."""
    with _counters_mu:
        return {site: c.get_value() for site, c in _counters.items()}


@dataclass
class Fault:
    """One fired decision, handed to the site for interpretation."""
    site: str
    kind: str
    latency_s: float = 0.0
    rc: int = -1


class _Rule:
    __slots__ = ("kind", "times", "after", "prob", "latency_s", "rc",
                 "match", "seen", "fired", "rng")

    def __init__(self, kind: str, times: int, after: int, prob: float,
                 latency_s: float, rc: int,
                 match: Optional[Callable[[dict], bool]], rng_seed: str):
        self.kind = kind
        self.times = times          # fire at most this many; <0 = forever
        self.after = after          # skip the first `after` matching hits
        self.prob = prob
        self.latency_s = latency_s
        self.rc = rc
        self.match = match
        self.seen = 0
        self.fired = 0
        # per-rule RNG: decisions at one site never perturb another's
        # sequence, and re-running the same plan replays the same schedule
        self.rng = random.Random(rng_seed)


class FaultPlan:
    """A seeded schedule of faults.  Thread-safe; rules are evaluated in
    the order added and the FIRST matching rule fires."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._mu = threading.Lock()
        self._rules: dict[str, list[_Rule]] = {}
        # per-plan fired counts, for test assertions (the bvar counters
        # are process-cumulative)
        self.injected: dict[str, int] = {}

    def on(self, site: str, kind: str, *, times: int = 1, after: int = 0,
           prob: float = 1.0, latency_s: float = 0.01, rc: int = -1,
           match: Optional[Callable[[dict], bool]] = None) -> "FaultPlan":
        """Schedule `kind` at `site`.  `times` bounds total firings (<0 =
        persistent), `after` skips the first N matching hits (one-shot
        mid-sequence faults), `prob` gates each hit through the rule's
        seeded RNG, `match` (a predicate over the site's context kwargs,
        e.g. ``lambda ctx: ctx.get("port") == p``) scopes the rule so
        unrelated in-process traffic cannot consume its budget."""
        with self._mu:
            idx = sum(len(r) for r in self._rules.values())
            self._rules.setdefault(site, []).append(
                _Rule(kind, times, after, prob, latency_s, rc, match,
                      f"{self.seed}:{site}:{idx}"))
        return self

    def _hit(self, site: str, ctx: dict) -> Optional[Fault]:
        with self._mu:
            rules = self._rules.get(site)
            if not rules:
                return None
            for r in rules:
                if r.match is not None and not r.match(ctx):
                    continue
                r.seen += 1
                if r.seen <= r.after:
                    continue
                if r.times >= 0 and r.fired >= r.times:
                    continue
                if r.prob < 1.0 and r.rng.random() >= r.prob:
                    continue
                r.fired += 1
                self.injected[site] = self.injected.get(site, 0) + 1
                return Fault(site, r.kind, r.latency_s, r.rc)
        return None


def install(plan: FaultPlan) -> None:
    global _plan, ENABLED
    with _mu:
        _plan = plan
        ENABLED = True


def clear() -> None:
    global _plan, ENABLED
    with _mu:
        ENABLED = False
        _plan = None


@contextmanager
def injected(plan: FaultPlan):
    """``with fault.injected(plan): ...`` — installs the plan for the
    block and always clears it (a leaked ENABLED flag would poison every
    later test in the process)."""
    install(plan)
    try:
        yield plan
    finally:
        clear()


def hit(site: str, **ctx) -> Optional[Fault]:
    """Decide whether a fault fires at `site` (call ONLY behind an
    ``if fault.ENABLED:`` guard).  LATENCY is applied here — sleep, then
    return None so the site proceeds; every other kind returns the Fault
    for the site to interpret in its own failure convention."""
    plan = _plan
    if plan is None:
        return None
    f = plan._hit(site, ctx)
    if f is None:
        return None
    _counter(site).add(1)
    if f.kind == LATENCY:
        time.sleep(f.latency_s)
        return None
    return f


def mangle(data: bytes) -> bytes:
    """Deterministically corrupt a payload: flip every bit of the middle
    byte.  Enough to break any CRC/framing check downstream; position and
    value are functions of the payload alone so runs replay exactly."""
    if not data:
        return data
    b = bytearray(data)
    i = len(b) // 2
    b[i] ^= 0xFF
    return bytes(b)
