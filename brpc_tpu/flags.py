"""Flag system (reference: gflags + reloadable_flags.{h,cpp}; SURVEY.md §5.9).

Every tunable is defined near its use site with define_flag(); the /flags
builtin lists them and live-edits the ones marked reloadable — same two-tier
scheme as the reference (typed option structs carry per-instance config).
bvar export: each flag is visible through dump_exposed("flag_*").
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Optional

_flags: dict[str, "Flag"] = {}
_mu = threading.Lock()


@dataclass
class Flag:
    name: str
    value: Any
    default: Any
    help: str = ""
    reloadable: bool = False
    validator: Optional[Callable[[Any], bool]] = None
    type_: type = str


def define_flag(name: str, default: Any, help: str = "",
                reloadable: bool = False,
                validator: Callable[[Any], bool] | None = None) -> Flag:
    with _mu:
        if name in _flags:
            return _flags[name]
        f = Flag(name, default, default, help, reloadable, validator,
                 type(default))
        _flags[name] = f
        return f


def get_flag(name: str, default: Any = None) -> Any:
    # lock-free read: dict.get is GIL-atomic and flag objects are never
    # removed — this sits on the per-request hot path (rpc_dump gate)
    f = _flags.get(name)
    return f.value if f is not None else default


def set_flag(name: str, value: Any, *, force: bool = False) -> bool:
    with _mu:
        f = _flags.get(name)
        if f is None:
            return False
        if not f.reloadable and not force:
            return False
        try:
            if f.type_ is bool and isinstance(value, str):
                value = value.lower() in ("1", "true", "yes", "on")
            else:
                value = f.type_(value)
        except (TypeError, ValueError):
            return False
        if f.validator is not None and not f.validator(value):
            return False
        f.value = value
        return True


def list_flags() -> list[Flag]:
    with _mu:
        return sorted(_flags.values(), key=lambda f: f.name)


# Core flags (mirroring prominent reference gflags)
define_flag("max_body_size", 2 * 1024 * 1024 * 1024,
            "Maximum frame body bytes accepted")
define_flag("health_check_interval_s", 1.0,
            "Seconds between reconnect probes of broken servers",
            reloadable=True)
define_flag("rpcz_enabled", False, "Collect per-RPC spans (off by default "
            "like FLAGS_enable_rpcz; span objects are only built when on)",
            reloadable=True)
define_flag("rpcz_sample_rate", 1.0, "Fraction of spans kept",
            reloadable=True)
define_flag("rpcz_database_dir", "", "Persist collected spans to recordio "
            "segments under this directory (reference on-disk SpanDB, "
            "span.h:227); empty = in-memory only", reloadable=True)
