"""ICI transport — the TPU-native answer to the reference's RDMA subsystem.

Mapping (SURVEY.md §5.8):
  rdma::BlockPool (pinned, NIC-registered slabs)  -> HBM BlockPool (device
      buffers in 8KB/64KB/2MB classes, brpc_tpu/ici/block_pool.py)
  RdmaEndpoint (ibverbs QP send/recv + credit)    -> IciEndpoint (XLA
      device-to-device transfers over ICI + the same credit window,
      brpc_tpu/ici/endpoint.py)
  StreamWrite over RDMA                           -> TensorStream: zero-copy
      HBM->HBM tensor pipe (brpc_tpu/ici/stream.py)
  ParallelChannel/PartitionChannel socket fan-out -> ONE jitted shard_map
      with psum/all_gather/ppermute over the mesh
      (brpc_tpu/ici/collective.py)
"""
from brpc_tpu.ici.mesh import get_mesh, local_devices, device_for  # noqa: F401
from brpc_tpu.ici.block_pool import BlockPool, get_block_pool  # noqa: F401
from brpc_tpu.ici.endpoint import IciEndpoint, link_stats  # noqa: F401
from brpc_tpu.ici.stream import TensorStream  # noqa: F401
from brpc_tpu.ici.collective import CollectiveGroup  # noqa: F401
from brpc_tpu.ici.channel import (  # noqa: F401
    IciChannel, register_device_service, device_service_registry,
)
from brpc_tpu.ici import rail  # noqa: F401  (RPC data-path rail)
