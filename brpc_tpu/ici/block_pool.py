"""HBM BlockPool — the device-memory analog of rdma::BlockPool.

Reference (rdma/block_pool.cpp:52,69-70): large pinned regions registered
with the NIC, slab-allocated into 8KB/64KB/2MB blocks, wired in as IOBuf's
block allocator so payloads are *born registered* — zero copy end-to-end.

TPU build: the pool owns per-device jax buffers in the same size classes.
A block is a view (offset, length) into a device arena; tensors serialized
into blocks live in HBM and move chip-to-chip without host round-trips.
XLA owns physical allocation (there is no cudaMalloc-style API), so the
arena is a set of device arrays kept alive by the pool; blocks are views
with a free-list, and donation happens naturally when a transfer consumes
the arena slice.
"""
from __future__ import annotations

import functools
import threading
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from brpc_tpu import fault
from brpc_tpu.bvar import Adder, PassiveStatus

# Host-bounce counters for the rail's zero-host-copy proof
# (ici/rail.py host_copy_count): staging host bytes into a block and
# reading a block back to host are the only block-pool paths that touch
# host memory.
host_stage_count = Adder("blockpool_host_stages")
host_read_count = Adder("blockpool_host_reads")


@functools.partial(jax.jit, static_argnums=(1,))
def _stage(x, cls: int):
    """Reinterpret a tensor's bytes as uint8 and pad into a block-class
    buffer — entirely on device (no host bounce).  Runs on the source
    array's device; the output is always a fresh buffer."""
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    flat = x.ravel()
    if flat.dtype != jnp.uint8:
        flat = jax.lax.bitcast_convert_type(flat, jnp.uint8).ravel()
    out = jnp.zeros((cls,), jnp.uint8)
    return jax.lax.dynamic_update_slice(out, flat, (0,))


@functools.partial(jax.jit, static_argnums=(1, 2))
def _unstage(buf, dtype_name: str, shape: tuple):
    """Rebuild a tensor from a block's byte buffer, on device."""
    dt = np.dtype(dtype_name)
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    nbytes = n * (1 if dt == np.bool_ else dt.itemsize)
    raw = jax.lax.dynamic_slice(buf, (0,), (nbytes,))
    if dt == np.bool_:
        return raw.reshape(shape).astype(jnp.bool_)
    if dt.itemsize == 1:
        return jax.lax.bitcast_convert_type(raw, dt).reshape(shape)
    return jax.lax.bitcast_convert_type(
        raw.reshape(n, dt.itemsize), dt).reshape(shape)

@functools.partial(jax.jit, static_argnums=(2,))
def _slice_bytes(buf, off, nbytes: int):
    """Read nbytes out of a block buffer at a dynamic byte offset, on
    device (the page-granularity read half of splice)."""
    return jax.lax.dynamic_slice(buf, (off,), (nbytes,))


@jax.jit
def _splice_bytes(buf, piece, off):
    """Write `piece` into a block buffer at a dynamic byte offset, on
    device — the rest of the buffer is untouched, so several sub-block
    regions (KV pages) can share one block without clobbering each
    other the way a wholesale put() would."""
    return jax.lax.dynamic_update_slice(buf, piece, (off,))


# size classes, mirroring the reference's 8KB/64KB/2MB (block_pool.cpp:52)
BLOCK_CLASSES = (8 * 1024, 64 * 1024, 2 * 1024 * 1024)
_ARENA_BLOCKS_PER_CLASS = 64


@dataclass
class Block:
    """A view into a device arena: arena array index + slot."""
    pool: "BlockPool"
    size_class: int
    slot: int
    used: int = 0

    @property
    def nbytes(self) -> int:
        return self.size_class

    def view(self):
        """The device buffer of this slot (uint8[size_class])."""
        with self.pool._lock:
            return self.pool._slots[self.size_class][self.slot]

    def put(self, data) -> "Block":
        """Stage host/device bytes into this block's slot.  Device-resident
        sources are reinterpreted and padded entirely on device (`_stage`
        under jit — no host round-trip), then DMA'd to the pool's device if
        they live elsewhere; host bytes pad host-side and ship in a single
        device_put.  The slot buffer is replaced atomically under the pool
        lock — concurrent puts to different slots never interfere."""
        if isinstance(data, jax.Array):
            n = data.nbytes
            if n > self.size_class:
                raise ValueError(f"{n}B > block class {self.size_class}")
            dev = _stage(data, self.size_class)   # on the source device
            if dev.devices() != {self.pool.device}:
                dev = jax.device_put(dev, self.pool.device)
            self._src_meta = (str(data.dtype), tuple(data.shape))
        else:
            host_stage_count.add(1)
            buf = np.frombuffer(memoryview(data), dtype=np.uint8)
            n = buf.size
            if n > self.size_class:
                raise ValueError(f"{n}B > block class {self.size_class}")
            padded = np.zeros((self.size_class,), np.uint8)
            padded[:n] = buf
            dev = jax.device_put(padded, self.pool.device)
            self._src_meta = None
        self.used = n
        with self.pool._lock:
            self.pool._slots[self.size_class][self.slot] = dev
        return self

    def install(self, dev_array: jax.Array, used: int,
                meta: tuple | None = None) -> "Block":
        """Adopt an already-transferred device buffer as this block's
        contents — the receive half of the block pipe (no staging, no
        copy).  The buffer need not match the slot's class exactly (alloc
        falls through to a larger class when the preferred one is
        exhausted); it only has to cover the payload."""
        if used > dev_array.nbytes:
            raise ValueError(
                f"payload {used}B exceeds buffer {dev_array.nbytes}B")
        self.used = used
        self._src_meta = meta
        with self.pool._lock:
            self.pool._slots[self.size_class][self.slot] = dev_array
        return self

    def get(self) -> bytes:
        host_read_count.add(1)
        return bytes(np.asarray(self.view())[: self.used])

    def get_array(self, dtype=None, shape=None) -> jax.Array:
        """Rebuild the staged tensor on device.  dtype/shape default to the
        source tensor's (recorded by put)."""
        if dtype is None or shape is None:
            if getattr(self, "_src_meta", None) is None:
                raise ValueError("no recorded dtype/shape; pass them")
            dtype, shape = self._src_meta
        return _unstage(self.view(), str(np.dtype(dtype)), tuple(shape))

    def free(self) -> None:
        self.pool.free(self)


class BlockPool:
    """Per-device slab pool of HBM blocks."""

    def __init__(self, device=None):
        self.device = device or jax.devices()[0]
        self._lock = threading.Lock()
        # one device buffer per slot: replaced wholesale on put() so slots
        # are independent (XLA owns the physical pages; keeping per-slot
        # arrays alive is what pins the "arena")
        self._slots: dict[int, list] = {}
        self._free: dict[int, list[int]] = {}
        self._allocated = Adder()
        self._freed = Adder()
        for cls in BLOCK_CLASSES:
            with jax.default_device(self.device):
                zero = jnp.zeros((cls,), jnp.uint8)
            self._slots[cls] = [zero] * _ARENA_BLOCKS_PER_CLASS
            self._free[cls] = list(range(_ARENA_BLOCKS_PER_CLASS))

    def alloc(self, nbytes: int) -> Block:
        """Smallest class that fits (AllocBlock, block_pool.h:76-88)."""
        if fault.ENABLED and fault.hit(
                "ici.alloc", device=self.device.id,
                nbytes=nbytes) is not None:
            # injected arena exhaustion: same shape as every class being
            # out of slots, so callers walk their real fallback paths
            raise MemoryError(
                f"injected HBM block exhaustion ({nbytes}B)")
        for cls in BLOCK_CLASSES:
            if nbytes <= cls:
                with self._lock:
                    if self._free[cls]:
                        slot = self._free[cls].pop()
                        self._allocated.add(1)
                        return Block(self, cls, slot)
        raise MemoryError(
            f"no free HBM block for {nbytes}B "
            f"(classes {BLOCK_CLASSES}, {_ARENA_BLOCKS_PER_CLASS}/class)")

    def free(self, block: Block) -> None:
        with self._lock:
            self._free[block.size_class].append(block.slot)
            self._freed.add(1)

    def stats(self) -> dict:
        with self._lock:
            return {
                "device": str(self.device),
                "classes": {str(cls): {
                    "free": len(self._free[cls]),
                    "total": _ARENA_BLOCKS_PER_CLASS,
                } for cls in BLOCK_CLASSES},
                "allocated": self._allocated.get_value(),
                "freed": self._freed.get_value(),
            }


def stage_chunks(data, src_pool: "BlockPool"):
    """Yield `data` staged into src_pool Blocks in order, chunked by the
    largest block class.  The single staging path shared by
    IciEndpoint.send_bytes and TensorStream.write_bytes; caller frees each
    block once its transfer is dispatched."""
    view = memoryview(data)
    chunk = BLOCK_CLASSES[-1]
    for off in range(0, len(view), chunk):
        piece = view[off:off + chunk]
        blk = src_pool.alloc(len(piece))
        try:
            blk.put(piece)
        except BaseException:
            # a failed put must not leak the freshly-allocated block
            # (error-path discipline: the block is only the consumer's
            # once it has been yielded)
            blk.free()
            raise
        yield blk


_pools: dict[int, BlockPool] = {}
_pools_lock = threading.Lock()


def get_block_pool(device=None) -> BlockPool:
    device = device or jax.devices()[0]
    with _pools_lock:
        p = _pools.get(device.id)
        if p is None:
            p = BlockPool(device)
            _pools[device.id] = p
        return p
