"""HBM BlockPool — the device-memory analog of rdma::BlockPool.

Reference (rdma/block_pool.cpp:52,69-70): large pinned regions registered
with the NIC, slab-allocated into 8KB/64KB/2MB blocks, wired in as IOBuf's
block allocator so payloads are *born registered* — zero copy end-to-end.

TPU build: the pool owns per-device jax buffers in the same size classes.
A block is a view (offset, length) into a device arena; tensors serialized
into blocks live in HBM and move chip-to-chip without host round-trips.
XLA owns physical allocation (there is no cudaMalloc-style API), so the
arena is a set of device arrays kept alive by the pool; blocks are views
with a free-list, and donation happens naturally when a transfer consumes
the arena slice.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from brpc_tpu.bvar import Adder, PassiveStatus

# size classes, mirroring the reference's 8KB/64KB/2MB (block_pool.cpp:52)
BLOCK_CLASSES = (8 * 1024, 64 * 1024, 2 * 1024 * 1024)
_ARENA_BLOCKS_PER_CLASS = 64


@dataclass
class Block:
    """A view into a device arena: arena array index + slot."""
    pool: "BlockPool"
    size_class: int
    slot: int
    used: int = 0

    @property
    def nbytes(self) -> int:
        return self.size_class

    def view(self):
        """The device buffer of this slot (uint8[size_class])."""
        with self.pool._lock:
            return self.pool._slots[self.size_class][self.slot]

    def put(self, data) -> "Block":
        """Copy host/device bytes into this block's slot (device_put to the
        pool's device; on-device source stays on device).  The slot buffer
        is replaced atomically under the pool lock — concurrent puts to
        different slots never interfere and nothing copies the whole class
        arena."""
        if isinstance(data, jax.Array):
            # reinterpret the tensor's bytes, never value-cast
            buf = np.asarray(data).ravel().view(np.uint8)
        else:
            buf = np.frombuffer(memoryview(data), dtype=np.uint8)
        n = buf.size
        if n > self.size_class:
            raise ValueError(f"{n}B > block class {self.size_class}")
        self.used = n
        padded = jnp.zeros((self.size_class,), jnp.uint8).at[:n].set(
            jnp.asarray(buf, jnp.uint8))
        dev = jax.device_put(padded, self.pool.device)
        with self.pool._lock:
            self.pool._slots[self.size_class][self.slot] = dev
        return self

    def get(self) -> bytes:
        return bytes(np.asarray(self.view())[: self.used])

    def free(self) -> None:
        self.pool.free(self)


class BlockPool:
    """Per-device slab pool of HBM blocks."""

    def __init__(self, device=None):
        self.device = device or jax.devices()[0]
        self._lock = threading.Lock()
        # one device buffer per slot: replaced wholesale on put() so slots
        # are independent (XLA owns the physical pages; keeping per-slot
        # arrays alive is what pins the "arena")
        self._slots: dict[int, list] = {}
        self._free: dict[int, list[int]] = {}
        self._allocated = Adder()
        self._freed = Adder()
        for cls in BLOCK_CLASSES:
            with jax.default_device(self.device):
                zero = jnp.zeros((cls,), jnp.uint8)
            self._slots[cls] = [zero] * _ARENA_BLOCKS_PER_CLASS
            self._free[cls] = list(range(_ARENA_BLOCKS_PER_CLASS))

    def alloc(self, nbytes: int) -> Block:
        """Smallest class that fits (AllocBlock, block_pool.h:76-88)."""
        for cls in BLOCK_CLASSES:
            if nbytes <= cls:
                with self._lock:
                    if self._free[cls]:
                        slot = self._free[cls].pop()
                        self._allocated.add(1)
                        return Block(self, cls, slot)
        raise MemoryError(
            f"no free HBM block for {nbytes}B "
            f"(classes {BLOCK_CLASSES}, {_ARENA_BLOCKS_PER_CLASS}/class)")

    def free(self, block: Block) -> None:
        with self._lock:
            self._free[block.size_class].append(block.slot)
            self._freed.add(1)

    def stats(self) -> dict:
        with self._lock:
            return {
                "device": str(self.device),
                "classes": {str(cls): {
                    "free": len(self._free[cls]),
                    "total": _ARENA_BLOCKS_PER_CLASS,
                } for cls in BLOCK_CLASSES},
                "allocated": self._allocated.get_value(),
                "freed": self._freed.get_value(),
            }


_pools: dict[int, BlockPool] = {}
_pools_lock = threading.Lock()


def get_block_pool(device=None) -> BlockPool:
    device = device or jax.devices()[0]
    with _pools_lock:
        p = _pools.get(device.id)
        if p is None:
            p = BlockPool(device)
            _pools[device.id] = p
        return p
