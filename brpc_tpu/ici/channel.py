"""IciChannel — the Channel API over ICI endpoints.

An RPC to ici://<slice>/<chip> runs a registered *device service* — a jax
function compiled for that chip — with the request tensor moved over ICI
(device_put) instead of a socket.  Same Controller surface as the TCP
channel (latency, error codes, rpcz spans), so callers swap transports by
changing the address string, mirroring how the reference swaps TCP for
RDMA behind `use_rdma` without touching call sites (channel.h:109).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

import jax

from brpc_tpu import errors, rpcz
from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.bvar import LatencyRecorder
from brpc_tpu.rpc.controller import Controller, OneShotEvent
from brpc_tpu.ici.mesh import device_for

_registry_lock = threading.Lock()
# (fn, jit): jit=False marks services that manage their own
# compilation/sharding and must never be wrapped in an outer jit
_device_services: dict[tuple[str, str], tuple[Callable, bool]] = {}
_jitted: dict[tuple[str, str], Callable] = {}
_call_latency = LatencyRecorder("ici_channel")


def register_device_service(service: str, method: str, fn: Callable,
                            *, jit: bool = True) -> None:
    """Register a jax function as (service, method) for ICI channels.
    fn(request_array) -> response_array; jit specializes per input
    placement, so one compiled entry serves every chip.  jit=False for
    services that manage their own compilation/sharding (an
    already-jitted shard_map program re-placing inputs onto a mesh must
    not be wrapped in an outer single-device jit)."""
    with _registry_lock:
        _device_services[(service, method)] = (fn, jit)
        _jitted.pop((service, method), None)


def device_service_registry() -> dict:
    """(service, method) -> fn for services that tolerate an OUTER jit
    wrap (the collective-lowering contract: ParallelChannel fan-out
    wraps these in shard_map+jit).  jit=False services are deliberately
    EXCLUDED — wrapping a self-sharding program in an outer jit raises
    at trace time; those targets take the per-channel call path."""
    with _registry_lock:
        return {k: fn for k, (fn, jit_it) in _device_services.items()
                if jit_it}


def _compiled(service: str, method: str) -> Optional[Callable]:
    key = (service, method)
    with _registry_lock:
        f = _jitted.get(key)
        if f is None:
            entry = _device_services.get(key)
            if entry is None:
                return None
            fn, jit_it = entry
            # Inputs arrive committed to the target device (call_sync does
            # the device_put), so outputs follow — no deprecated
            # jit(device=...) needed.
            f = jax.jit(fn) if jit_it else fn
            _jitted[key] = f
        return f


class IciChannel:
    """Channel to one chip.  call()/call_sync() mirror rpc.Channel."""

    def __init__(self, address: str | EndPoint):
        ep = str2endpoint(address) if isinstance(address, str) else address
        if not ep.is_ici:
            raise ValueError(f"IciChannel needs an ici:// address, got {ep}")
        self.endpoint = ep
        self.device = device_for(ep.port)

    def call_sync(self, service: str, method: str, request: Any,
                  cntl: Controller | None = None, serializer: str = "tensor",
                  **_kw) -> Any:
        # serializer is accepted for Channel API parity; tensors travel as
        # device arrays, no byte serialization happens on the ICI path.
        cntl = cntl or Controller()
        cntl.remote_side = str(self.endpoint)
        span = rpcz.new_span("client", service, method,
                             *rpcz.current_trace())
        span.remote_side = cntl.remote_side
        t0 = time.monotonic()
        fn = _compiled(service, method)
        if fn is None:
            cntl.set_failed(errors.ENOMETHOD,
                            f"no device service {service}.{method}")
            span.error_code = cntl.error_code
            rpcz.submit(span)
            cntl.raise_if_failed()
        try:
            x = jax.device_put(request, self.device)   # ICI transfer
            out = fn(x)
            out.block_until_ready()
            cntl.response = out
        except Exception as e:
            cntl.set_failed(errors.EINTERNAL, f"{type(e).__name__}: {e}")
        cntl.latency_us = int((time.monotonic() - t0) * 1e6)
        _call_latency.add(cntl.latency_us)
        span.error_code = cntl.error_code
        rpcz.submit(span)
        cntl.raise_if_failed()
        return cntl.response

    def call(self, service: str, method: str, request: Any,
             cntl: Controller | None = None,
             done: Callable[[Controller], None] | None = None,
             serializer: str = "tensor", **_kw) -> Controller:
        """Async variant: runs on a worker thread (jax dispatch is itself
        async; the thread only exists to run `done` off the caller)."""
        cntl = cntl or Controller()
        if done is None:
            cntl._done_event = OneShotEvent()

        def run():
            try:
                self.call_sync(service, method, request, cntl)
            except errors.RpcError:
                pass
            if done is not None:
                done(cntl)
            if cntl._done_event is not None:
                cntl._done_event.set()

        threading.Thread(target=run, daemon=True).start()
        return cntl
