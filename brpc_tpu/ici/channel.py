"""IciChannel — the Channel API over ICI endpoints.

An RPC to ici://<slice>/<chip> runs a registered *device service* — a jax
function compiled for that chip — with the request tensor moved over ICI
(device_put) instead of a socket.  Same Controller surface as the TCP
channel (latency, error codes, rpcz spans), so callers swap transports by
changing the address string, mirroring how the reference swaps TCP for
RDMA behind `use_rdma` without touching call sites (channel.h:109).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

import jax

from brpc_tpu import errors, rpcz
from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.bvar import LatencyRecorder
from brpc_tpu.rpc.controller import Controller, OneShotEvent
from brpc_tpu.ici.mesh import device_for

_registry_lock = threading.Lock()
_device_services: dict[tuple[str, str], Callable] = {}
_jitted: dict[tuple[str, str], Callable] = {}
_call_latency = LatencyRecorder("ici_channel")


def register_device_service(service: str, method: str, fn: Callable) -> None:
    """Register a jax function as (service, method) for ICI channels.
    fn(request_array) -> response_array; jit specializes per input
    placement, so one compiled entry serves every chip."""
    with _registry_lock:
        _device_services[(service, method)] = fn
        _jitted.pop((service, method), None)


def device_service_registry() -> dict:
    with _registry_lock:
        return dict(_device_services)


def _compiled(service: str, method: str) -> Optional[Callable]:
    key = (service, method)
    with _registry_lock:
        f = _jitted.get(key)
        if f is None:
            fn = _device_services.get(key)
            if fn is None:
                return None
            # Inputs arrive committed to the target device (call_sync does
            # the device_put), so outputs follow — no deprecated
            # jit(device=...) needed.
            f = jax.jit(fn)
            _jitted[key] = f
        return f


class IciChannel:
    """Channel to one chip.  call()/call_sync() mirror rpc.Channel."""

    def __init__(self, address: str | EndPoint):
        ep = str2endpoint(address) if isinstance(address, str) else address
        if not ep.is_ici:
            raise ValueError(f"IciChannel needs an ici:// address, got {ep}")
        self.endpoint = ep
        self.device = device_for(ep.port)

    def call_sync(self, service: str, method: str, request: Any,
                  cntl: Controller | None = None, serializer: str = "tensor",
                  **_kw) -> Any:
        # serializer is accepted for Channel API parity; tensors travel as
        # device arrays, no byte serialization happens on the ICI path.
        cntl = cntl or Controller()
        cntl.remote_side = str(self.endpoint)
        span = rpcz.new_span("client", service, method,
                             *rpcz.current_trace())
        span.remote_side = cntl.remote_side
        t0 = time.monotonic()
        fn = _compiled(service, method)
        if fn is None:
            cntl.set_failed(errors.ENOMETHOD,
                            f"no device service {service}.{method}")
            span.error_code = cntl.error_code
            rpcz.submit(span)
            cntl.raise_if_failed()
        try:
            x = jax.device_put(request, self.device)   # ICI transfer
            out = fn(x)
            out.block_until_ready()
            cntl.response = out
        except Exception as e:
            cntl.set_failed(errors.EINTERNAL, f"{type(e).__name__}: {e}")
        cntl.latency_us = int((time.monotonic() - t0) * 1e6)
        _call_latency.add(cntl.latency_us)
        span.error_code = cntl.error_code
        rpcz.submit(span)
        cntl.raise_if_failed()
        return cntl.response

    def call(self, service: str, method: str, request: Any,
             cntl: Controller | None = None,
             done: Callable[[Controller], None] | None = None,
             serializer: str = "tensor", **_kw) -> Controller:
        """Async variant: runs on a worker thread (jax dispatch is itself
        async; the thread only exists to run `done` off the caller)."""
        cntl = cntl or Controller()
        if done is None:
            cntl._done_event = OneShotEvent()

        def run():
            try:
                self.call_sync(service, method, request, cntl)
            except errors.RpcError:
                pass
            if done is not None:
                done(cntl)
            if cntl._done_event is not None:
                cntl._done_event.set()

        threading.Thread(target=run, daemon=True).start()
        return cntl
