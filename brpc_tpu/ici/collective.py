"""Collective lowering — fan-out/fan-in as ONE compiled program.

The reference's ParallelChannel sends N copies over N sockets and merges N
responses on the host (§2.5).  Inside a TPU slice that plan wastes the
fabric: the idiomatic lowering is a single jitted shard_map over the mesh
where the "fan-out" is a broadcast (or shard), every chip runs the service
function locally, and the "merge" is a collective (psum / all_gather /
concat) riding ICI at link speed.  This module is that lowering; combo
channels use it automatically when all targets are ICI endpoints.
"""
from __future__ import annotations

import threading
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(f, mesh, in_specs, out_specs):
    # Replication of collective outputs (all_gather/psum) can't always be
    # statically inferred; disable the varying-manual-axes check (named
    # check_vma on current jax, check_rep on older releases).
    try:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
    except TypeError:  # pragma: no cover
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)

from brpc_tpu.bvar import Adder, LatencyRecorder
from brpc_tpu.ici.mesh import get_mesh

_lowered_calls = Adder("ici_collective_calls")
_lowered_latency = LatencyRecorder("ici_collective")


class CollectiveGroup:
    """Fan-out execution over a mesh axis."""

    def __init__(self, mesh=None, axis: str = "chip"):
        self.mesh = mesh if mesh is not None else get_mesh()
        self.axis = axis
        self._cache: dict = {}
        self._mu = threading.Lock()

    @property
    def size(self) -> int:
        return self.mesh.shape[self.axis]

    def _get(self, key, build):
        with self._mu:
            f = self._cache.get(key)
            if f is None:
                f = build()
                self._cache[key] = f
            return f

    # ---- ParallelChannel lowering: same request to every chip ----

    def parallel_apply(self, fn: Callable, x, merge: str = "stack"):
        """Broadcast x, run fn per chip, merge: "stack" | "sum" | "concat"
        | "none" (leave per-chip results sharded)."""
        axis = self.axis

        def build():
            def per_chip(xb):
                y = fn(xb)
                if merge == "sum":
                    return jax.lax.psum(y, axis)
                return y
            out_spec = P() if merge == "sum" else P(axis)

            def wrapper(xb):
                y = per_chip(xb)
                if merge in ("stack", "concat"):
                    # leading axis = chip; shard_map concatenates shards
                    y = y[None] if merge == "stack" else y
                return y
            sm = shard_map(wrapper, self.mesh, in_specs=P(),
                           out_specs=out_spec)
            return jax.jit(sm)

        import time
        t0 = time.monotonic()
        # keyed by the fn OBJECT (kept alive by the cache): id() keys could
        # be reused after GC and serve a stale compiled program
        out = self._get(("par", fn, merge), build)(x)
        _lowered_calls.add(1)
        _lowered_latency.add(int((time.monotonic() - t0) * 1e6))
        return out

    # ---- PartitionChannel lowering: shard the request ----

    def partition_apply(self, fn: Callable, x, merge: str = "concat"):
        """Shard x along axis 0 across chips, run fn per shard, merge:
        "concat" | "sum" | "none" (keep sharded)."""
        axis = self.axis

        def build():
            def per_chip(xs):
                y = fn(xs)
                if merge == "sum":
                    return jax.lax.psum(y, axis)
                return y
            in_spec = P(axis)
            out_spec = P() if merge == "sum" else P(axis)
            return jax.jit(shard_map(per_chip, self.mesh,
                                     in_specs=in_spec, out_specs=out_spec))

        import time
        t0 = time.monotonic()
        out = self._get(("part", fn, merge), build)(x)
        _lowered_calls.add(1)
        _lowered_latency.add(int((time.monotonic() - t0) * 1e6))
        return out

    # ---- primitives for the ici_performance ladder ----

    def ring_shift(self, x, steps: int = 1):
        """ppermute ring shift: chip i's shard moves to chip (i+steps)%n.
        The unit transfer of ring collectives (and the §5.8 ladder)."""
        axis = self.axis
        n = self.size

        def build():
            def shift(xs):
                perm = [(i, (i + steps) % n) for i in range(n)]
                return jax.lax.ppermute(xs, axis, perm)
            return jax.jit(shard_map(shift, self.mesh, in_specs=P(axis),
                                     out_specs=P(axis)))

        return self._get(("shift", steps), build)(x)

    def all_gather(self, x):
        axis = self.axis

        def build():
            def g(xs):
                return jax.lax.all_gather(xs, axis, tiled=True)
            return jax.jit(shard_map(g, self.mesh, in_specs=P(axis),
                                     out_specs=P()))

        return self._get(("gather",), build)(x)

    def all_reduce(self, x):
        axis = self.axis

        def build():
            def r(xs):
                return jax.lax.psum(xs, axis)
            return jax.jit(shard_map(r, self.mesh, in_specs=P(axis),
                                     out_specs=P()))

        return self._get(("reduce",), build)(x)

    def reduce_scatter(self, x):
        """Each chip contributes its full view of x; chip i receives the
        i-th slice of the summed result (classic reduce-scatter)."""
        axis = self.axis

        def build():
            def rs(xs):
                return jax.lax.psum_scatter(xs, axis, tiled=True)
            return jax.jit(shard_map(rs, self.mesh, in_specs=P(),
                                     out_specs=P(axis)))

        return self._get(("rscatter",), build)(x)
