"""DCN — cross-process/cross-host device RPC with an out-of-band data
plane (VERDICT r2 task 4 groundwork; r3 #5 the real data path).

Reference pattern (rdma_endpoint.h:112-115,180; SURVEY §5.8): RdmaEndpoint
rides an existing TCP connection for its handshake — a magic preamble and
an exchange of lid/gid/qp_num — after which data moves out-of-band on the
RC queue pair and TCP stays as the control/fallback channel.

TPU build, two processes that do NOT share a jax runtime (separate hosts,
or separate processes on one host):

  1. **Handshake**: the `_dcn` service's `Hello` method exchanges device
     topology AND this process's transfer-fabric address (the
     lid/gid/qp_num analog) over the ordinary TRPC connection.
  2. **Data path**: each process runs a `jax.experimental.transfer`
     server — XLA's cross-host device transfer fabric (DCN/RDMA-backed
     on real pods).  A `DcnChannel.call_sync` registers its device
     arrays with the local fabric under a ticket, sends a CONTROL
     envelope (service, method, chip, ticket, shape/dtype specs — no
     tensor bytes) over the socket; the remote pulls the buffers
     device-to-device, runs the jitted device service on the target
     chip, registers the results, and the client pulls them back.  The
     tensor serializer never touches the payload.
  3. **Fallback**: when either side has no fabric (old peer, failed
     init), payloads move host-serialized over the socket — same
     wire-compatible envelope, flagged in the reply.
  4. Addressing: ``ici://host:port/chip`` — host:port is the remote RPC
     server, chip the device index in the REMOTE process's mesh.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Optional

import numpy as np

from brpc_tpu import errors, fault, rpcz
from brpc_tpu.bvar import Adder
from brpc_tpu.rpc.service import Service, method

DCN_SERVICE = "_dcn"
DCN_MAGIC = "DCN1"          # handshake version tag (the "RDMA" preamble)

import uuid as _uuid

_PROCESS_NONCE = _uuid.uuid4().hex[:16]

_MAX_HEADER = 64 * 1024     # envelope header bound (bounded trust)

dcn_zero_copy_calls = Adder("dcn_zero_copy_calls")
dcn_fallback_calls = Adder("dcn_fallback_calls")

# ---------------------------------------------------------------------------
# transfer fabric: jax.experimental.transfer server + cached connections
# ---------------------------------------------------------------------------

_xfer_mu = threading.Lock()
_xfer_server = None
_xfer_failed = False
_xfer_conns: dict[str, Any] = {}
# tickets must be unique across processes sharing a fabric: salt with pid
_ticket_counter = itertools.count((os.getpid() & 0xFFFF) << 32)
# offered arrays are pinned until the peer pulled them; the control-plane
# round-trip normally confirms that, and a TTL bounds leaks from peers
# that die mid-call (the rail registry's discipline)
_OFFER_TTL_S = 120.0
_offers_mu = threading.Lock()
_offers: dict[int, tuple[list, float]] = {}


def _bind_host() -> str:
    # multi-host pods set the routable interface; loopback covers
    # same-host multi-process (and tests)
    return os.environ.get("BRPC_DCN_BIND_HOST", "127.0.0.1")


def transfer_server():
    """This process's transfer-fabric server (lazily started); None when
    the fabric is unavailable — callers fall back to host serialization.
    BRPC_DCN_DISABLE_XFER=1 forces the fallback (benchmark A/B and
    debugging)."""
    global _xfer_server, _xfer_failed
    with _xfer_mu:
        if os.environ.get("BRPC_DCN_DISABLE_XFER"):
            return None
        if _xfer_server is not None or _xfer_failed:
            return _xfer_server
        try:
            import jax
            from jax.experimental import transfer
            backend = jax.devices()[0].client
            host = _bind_host()
            _xfer_server = transfer.start_transfer_server(
                backend, f"{host}:0", [f"{host}:0"])
        except Exception:
            import logging
            logging.getLogger(__name__).info(
                "DCN transfer fabric unavailable; host-serialized "
                "fallback in effect", exc_info=True)
            _xfer_failed = True
        return _xfer_server


def transfer_address() -> Optional[str]:
    s = transfer_server()
    return s.address() if s is not None else None


def _connect(address: str):
    with _xfer_mu:
        conn = _xfer_conns.get(address)
    if conn is not None:
        return conn
    s = transfer_server()
    if s is None:
        raise RuntimeError("no local transfer fabric")
    conn = s.connect(address)
    with _xfer_mu:
        # two threads can race here: keep ONE connection per peer (the
        # loser's is dropped and GC'd, never used)
        conn = _xfer_conns.setdefault(address, conn)
    return conn


def _purge_offers_locked(now: float) -> None:
    dead = [t for t, (_, dl) in _offers.items() if dl < now]
    for t in dead:
        del _offers[t]


_sweeper_started = False


def _ensure_sweeper() -> None:
    # offered-but-never-pulled arrays must not stay pinned past the TTL
    # just because no further offer() ever runs (the rail registry's
    # own-clock discipline)
    global _sweeper_started
    if not _sweeper_started:
        _sweeper_started = True

        def _loop():
            while True:
                time.sleep(_OFFER_TTL_S / 4)
                with _offers_mu:
                    _purge_offers_locked(time.monotonic())

        threading.Thread(target=_loop, daemon=True,
                         name="dcn-offer-sweeper").start()


def offer(arrays: list) -> tuple[int, list[dict]]:
    """Register device arrays for a remote pull.  Returns (ticket,
    specs) where specs describe shape/dtype for the peer's pull call.

    Pinning caveat (ADVICE r4): ``TransferServer`` exposes no
    cancel/deregister (only address/await_pull/connect — verified against
    the installed jax), so the fabric-side ``await_pull`` registration
    for a never-pulled ticket lives until the transfer server itself is
    torn down.  The TTL sweeper and release_offer() bound only the
    PYTHON-side strong reference; the fabric may keep the buffers pinned
    past the TTL.  Offer sparingly for speculative sends."""
    s = transfer_server()
    assert s is not None
    ticket = next(_ticket_counter)
    s.await_pull(ticket, list(arrays))
    # TTL purging belongs to the sweeper alone (same O(pending)-scan
    # reasoning as rail.deposit)
    with _offers_mu:
        _offers[ticket] = (list(arrays), time.monotonic() + _OFFER_TTL_S)
    _ensure_sweeper()
    return ticket, [{"shape": list(a.shape), "dtype": str(np.dtype(a.dtype))}
                    for a in arrays]


def release_offer(ticket: int) -> None:
    with _offers_mu:
        _offers.pop(ticket, None)


def live_offer_count() -> int:
    """Offers still pinned Python-side (not yet acked or TTL-swept) —
    the offer-table bound a migration burst must leave at zero: every
    migrate/call path acks on pull completion, the TTL sweeper is the
    backstop for dead peers, not the steady state."""
    with _offers_mu:
        return len(_offers)


def pull(address: str, ticket: int, specs: list[dict], device) -> list:
    """Pull the peer's offered arrays straight onto `device`."""
    import jax
    from jax.sharding import SingleDeviceSharding
    sh = SingleDeviceSharding(device)
    shaped = [jax.ShapeDtypeStruct(tuple(sp["shape"]),
                                   np.dtype(sp["dtype"]), sharding=sh)
              for sp in specs]
    return list(_connect(address).pull(ticket, shaped))


def _pack_envelope(header: dict, arrays: list) -> bytes:
    """json header + tensor-serialized arrays: u32 header_len, header
    json, u32 tensor_header_len, tensor header, tensor bodies.  The
    arrays ride the framework's TensorSerializer (raw dtype/shape/bytes),
    so nothing on this path interprets network bytes as code."""
    import json as _json
    import struct
    hdr = _json.dumps(header).encode()
    if not arrays:
        # control-only envelope (zero-copy mode): no serializer touch,
        # so the host-encode counters provably stay flat
        return struct.pack("<I", len(hdr)) + hdr + struct.pack("<I", 0)
    from brpc_tpu.rpc.serialization import TensorSerializer
    tbody, theader = TensorSerializer().encode(arrays)
    return (struct.pack("<I", len(hdr)) + hdr +
            struct.pack("<I", len(theader)) + theader + tbody)


def _unpack_envelope(data: bytes) -> tuple[dict, list]:
    import json as _json
    import struct
    if len(data) < 8:
        raise ValueError("envelope too short")
    (hlen,) = struct.unpack_from("<I", data, 0)
    if hlen > _MAX_HEADER or 4 + hlen + 4 > len(data):
        raise ValueError("bad envelope header length")
    header = _json.loads(data[4:4 + hlen].decode())
    (tlen,) = struct.unpack_from("<I", data, 4 + hlen)
    off = 8 + hlen
    if off + tlen > len(data):
        raise ValueError("bad tensor header length")
    if tlen == 0:
        return header, []           # control-only (zero-copy mode)
    from brpc_tpu.rpc.serialization import TensorSerializer
    theader = data[off:off + tlen]
    arrays = TensorSerializer().decode(data[off + tlen:], theader)
    if not isinstance(arrays, (list, tuple)):
        arrays = [arrays]
    return header, list(arrays)


def local_topology() -> dict:
    """This process's device inventory — the handshake payload."""
    import jax
    devs = jax.devices()
    return {
        "magic": DCN_MAGIC,
        "pid": os.getpid(),
        # process identity for the same-process check: pids collide
        # across hosts/containers (both pid 1), a random nonce does not
        "nonce": _PROCESS_NONCE,
        "platform": devs[0].platform if devs else "none",
        "devices": [{"id": d.id, "kind": getattr(d, "device_kind", "")}
                    for d in devs],
    }


class DcnService(Service):
    """Server half: topology exchange + remote device-service invocation.

    Registered by ``Server(enable_dcn=True)``; the ``Hello`` reply is the
    handshake, ``CallDevice`` bridges to the device-service registry."""

    NAME = DCN_SERVICE

    @method(request="json", response="json")
    def Hello(self, cntl, req):
        peer = req if isinstance(req, dict) else {}
        if peer.get("magic") != DCN_MAGIC:
            cntl.set_failed(errors.EREQUEST, "bad DCN handshake magic")
            return None
        topo = local_topology()
        # the qp_num analog: advertise this process's transfer-fabric
        # address so the peer can move payloads out-of-band
        topo["xfer"] = transfer_address()
        return topo

    @method(request="raw", response="raw")
    def CallDevice(self, cntl, req):
        # wire format: a bounded-trust envelope (json header + tensor
        # bytes, _pack_envelope) — NOT pickle: this method is reachable by
        # anything that can open the RPC port, and unpickling network
        # bytes is arbitrary code execution
        if fault.ENABLED and fault.hit("dcn.serve") is not None:
            # injected server-side hop loss: the caller gets a definite
            # EINTERNAL instead of silence (the transport owns failure
            # semantics — "RPC Considered Harmful" discipline)
            cntl.set_failed(errors.EINTERNAL,
                            "injected DCN hop loss (server)")
            return None
        import jax
        from brpc_tpu.ici.channel import _compiled
        from brpc_tpu.ici.mesh import device_for
        try:
            hdr, arrays = _unpack_envelope(bytes(req))
        except Exception as e:
            cntl.set_failed(errors.EREQUEST, f"bad DCN envelope: {e}")
            return None
        if hdr.get("ack") is not None:
            # client confirmed pulling a previous response: unpin it.
            # Processed FIRST so an ack piggybacks on any envelope —
            # including the ack-only "Ack" form a concurrent caller sends
            # when the piggyback slot is already taken.
            try:
                release_offer(int(hdr["ack"]))
            except (TypeError, ValueError):
                pass
        if hdr.get("method") == "Ack" and hdr.get("svc") == DCN_SERVICE:
            # svc-qualified so a user device service with a method
            # literally named "Ack" is still dispatched normally
            # control-only reply: the caller discards the body, and a
            # tensor payload here would dirty the host-encode counters a
            # pure control message must keep flat
            return _pack_envelope({"single": True, "control": True}, [])
        try:
            svc = str(hdr["svc"])
            meth = str(hdr["method"])
            chip = int(hdr["chip"])
        except Exception as e:
            cntl.set_failed(errors.EREQUEST, f"bad DCN envelope: {e}")
            return None
        fn = _compiled(svc, meth)
        if fn is None:
            cntl.set_failed(errors.ENOMETHOD,
                            f"no device service {svc}.{meth}")
            return None
        try:
            dev = device_for(chip)
        except Exception:
            cntl.set_failed(errors.EREQUEST, f"no local chip {chip}")
            return None
        # device-execution span: joins the caller's trace.  The ingress
        # span (this handler's server span) is preferred as parent when
        # it already belongs to the trace the envelope names; otherwise
        # the envelope's trace_id/parent_span_id/trace_sampled fields
        # carry the join — the DCN call metadata path for deployments
        # where the socket meta did not propagate the trace.
        try:
            env_tid = int(hdr.get("trace_id") or 0)
            env_psid = int(hdr.get("parent_span_id") or 0)
        except (TypeError, ValueError):
            env_tid = env_psid = 0
        cur = rpcz.get_current_span()
        cur_tid = getattr(cur, "trace_id", 0) if cur is not None else 0
        if cur_tid and (not env_tid or cur_tid == env_tid):
            # the ingress span already belongs to the caller's trace
            # (socket meta propagated): nest under it for a clean tree
            span = rpcz.new_span("device", svc, meth,
                                 trace_id=cur_tid,
                                 parent_span_id=cur.span_id,
                                 sampled=cur.sampled)
        elif env_tid:
            # the socket hop did NOT carry the caller's trace (the
            # ingress span rooted a fresh local one, or rpcz is off on
            # the transport path): the envelope is authoritative
            span = rpcz.new_span("device", svc, meth,
                                 trace_id=env_tid,
                                 parent_span_id=env_psid,
                                 sampled=bool(hdr.get("trace_sampled",
                                                      True)))
        else:
            span = rpcz.new_span("device", svc, meth)
        span.annotate(f"chip {chip}")
        peer_xfer = hdr.get("xfer")
        if peer_xfer and hdr.get("ticket") is not None:
            # ZERO-COPY request: pull the client's device buffers
            # straight onto the target chip over the transfer fabric —
            # the socket carried only the control header
            try:
                placed = pull(peer_xfer, int(hdr["ticket"]),
                              hdr.get("specs") or [], dev)
                span.annotate(f"zero-copy pull: ticket {hdr['ticket']}")
            except Exception as e:
                span.error_code = errors.EINTERNAL
                rpcz.submit(span)
                cntl.set_failed(errors.EINTERNAL,
                                f"DCN pull failed: {e}")
                return None
        else:
            try:
                placed = [jax.device_put(a, dev) for a in arrays]
            except BaseException:
                # the failing hop must still appear on the timeline —
                # same discipline as the pull and execute paths
                span.error_code = errors.EINTERNAL
                rpcz.submit(span)
                raise
        try:
            out = fn(placed[0] if len(placed) == 1 else placed)
        except BaseException:
            span.error_code = errors.EINTERNAL
            rpcz.submit(span)
            raise
        rpcz.submit(span)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        resp_hdr = {"single": not isinstance(out, (list, tuple)),
                    "devices": [next(iter(o.devices())).id for o in outs]}
        if peer_xfer and transfer_server() is not None:
            # ZERO-COPY response: offer the results for the client's
            # pull; only the control header rides back
            ticket, specs = offer(outs)
            resp_hdr["xfer"] = transfer_address()
            resp_hdr["ticket"] = ticket
            resp_hdr["specs"] = specs
            dcn_zero_copy_calls.add(1)
            return _pack_envelope(resp_hdr, [])
        dcn_fallback_calls.add(1)
        return _pack_envelope(resp_hdr, [np.asarray(o) for o in outs])


def parse_dcn_address(address: str) -> tuple[str, int, Optional[int]]:
    """``ici://host:port/chip`` | ``ici://host:port`` | ``host:port``
    -> (host, port, chip|None)."""
    s = address
    if s.startswith("ici://"):
        s = s[len("ici://"):]
    chip: Optional[int] = None
    if "/" in s:
        s, chip_s = s.split("/", 1)
        chip = int(chip_s)
    host, port_s = s.rsplit(":", 1)
    return host, int(port_s), chip


class DcnChannel:
    """Client half: call a device service in a REMOTE process.

    ``DcnChannel("ici://hostB:8000/3")`` handshakes with hostB's RPC
    server, then ``call_sync("MatSvc", "Inc", x)`` runs that device
    service on hostB's chip 3 and returns the result on the local default
    device.  Same call surface as IciChannel, so moving a service across
    the DCN boundary is an address change, not a code change."""

    def __init__(self, address: str, timeout_ms: int = 10_000,
                 default_chip: Optional[int] = None):
        from brpc_tpu.rpc.channel import Channel
        host, port, chip = parse_dcn_address(address)
        self.remote = f"{host}:{port}"
        self.default_chip = chip if chip is not None else default_chip
        self._ch = Channel(self.remote, timeout_ms=timeout_ms)
        self.topology: Optional[dict] = None
        # piggyback-ack ticket from the last pulled response; guarded by
        # _ack_mu so concurrent call_sync on one channel can't lose or
        # double-send an ack (ADVICE r4 — lost acks leave server offers
        # pinned until TTL)
        self._unacked_resp: Optional[int] = None
        self._ack_mu = threading.Lock()

    @property
    def channel(self):
        """The underlying control-plane RPC channel — services that
        ride beside the DCN data plane (the ``_kvmig`` page stream, the
        disagg pairing RPCs) issue their control calls over the same
        connection the handshake used."""
        return self._ch

    def handshake(self) -> dict:
        """Exchange topologies (idempotent); returns the remote's."""
        if self.topology is None:
            self.topology = self._ch.call_sync(
                DCN_SERVICE, "Hello", local_topology(),
                serializer="json", response_serializer="json")
        return self.topology

    def remote_device_ids(self) -> list[int]:
        topo = self.handshake()
        return [d["id"] for d in topo["devices"]]

    def call_sync(self, service: str, method_name: str, request: Any,
                  chip: Optional[int] = None):
        # rpcz client span for the whole DCN call (handshake amortized,
        # offer/pull/fallback annotated).  Installed as the CURRENT span
        # for the duration, so the inner socket RPC's meta inherits this
        # trace and the remote ingress span joins it; the control
        # envelope ALSO carries the trace (trace_id/parent_span_id/
        # trace_sampled header fields), so the remote device-execution
        # span joins even where the socket meta does not follow.
        span = rpcz.child_span("client", service, method_name)
        span.remote_side = self.remote
        if span is rpcz.NULL_SPAN:
            return self._call_sync_traced(service, method_name, request,
                                          chip, span)
        prev = rpcz.get_current_span()
        rpcz.set_current_span(span)
        try:
            return self._call_sync_traced(service, method_name, request,
                                          chip, span)
        except errors.RpcError as e:
            span.error_code = e.code
            raise
        finally:
            rpcz.set_current_span(prev)
            rpcz.submit(span)

    def _call_sync_traced(self, service: str, method_name: str,
                          request: Any, chip: Optional[int], span):
        import jax
        if fault.ENABLED and fault.hit("dcn.call",
                                       remote=self.remote) is not None:
            raise errors.RpcError(errors.EINTERNAL,
                                  f"injected DCN hop loss to {self.remote}")
        topo = self.handshake()
        target_chip = chip if chip is not None else (self.default_chip or 0)
        if target_chip not in {d["id"] for d in topo["devices"]}:
            raise errors.RpcError(
                errors.EREQUEST,
                f"remote has no chip {target_chip} "
                f"(topology: {len(topo['devices'])} devices)")
        arrays = request if isinstance(request, (list, tuple)) else [request]
        header = {"svc": service, "method": method_name,
                  "chip": target_chip}
        if span.trace_id:
            # cross-host trace join (ISSUE 5): the control envelope
            # carries the trace so the remote's device-execution span
            # lands in THIS trace with the root's sampling decision
            header["trace_id"] = span.trace_id
            header["parent_span_id"] = span.span_id
            header["trace_sampled"] = span.sampled
        ack_ticket = None
        with self._ack_mu:
            if self._unacked_resp is not None:
                # piggyback ACK: the previous call's response was pulled,
                # so the server can unpin those result buffers now instead
                # of waiting out the TTL
                ack_ticket = self._unacked_resp
                self._unacked_resp = None
        if ack_ticket is not None:
            header["ack"] = ack_ticket
        ticket = None
        # zero-copy when BOTH fabrics exist (handshaked like qp_nums):
        # device buffers stay registered locally; the socket carries
        # control only.  Same-process peers keep the fallback — the
        # fabric's loopback-to-self bulk transport is not supported (and
        # in-process callers should ride IciChannel anyway).
        if topo.get("xfer") and topo.get("nonce") != _PROCESS_NONCE \
                and transfer_server() is not None:
            jarrs = [a if isinstance(a, jax.Array) else jax.numpy.asarray(a)
                     for a in arrays]
            ticket, specs = offer(jarrs)
            header["xfer"] = transfer_address()
            header["ticket"] = ticket
            header["specs"] = specs
            body = _pack_envelope(header, [])
            span.annotate(f"zero-copy request: offered ticket {ticket}, "
                          f"{len(jarrs)} device arrays")
        else:
            body = _pack_envelope(header, [np.asarray(a) for a in arrays])
            span.annotate("host-serialized request (fallback data path)")
        span.request_size = len(body)
        try:
            raw = self._ch.call_sync(DCN_SERVICE, "CallDevice", body,
                                     serializer="raw",
                                     response_serializer="raw")
        except BaseException:
            if ack_ticket is not None:
                # the piggybacked ack may never have reached the server;
                # re-park it so the next call retries (release_offer is an
                # idempotent pop, so a duplicate ack is harmless)
                with self._ack_mu:
                    if self._unacked_resp is None:
                        self._unacked_resp = ack_ticket
            raise
        finally:
            if ticket is not None:
                # the reply means the server pulled (it needed the
                # request to compute); on failure this unpins early
                release_offer(ticket)
        hdr, out_arrays = _unpack_envelope(bytes(raw))
        span.response_size = len(raw)
        if hdr.get("xfer") and hdr.get("ticket") is not None:
            span.annotate(f"zero-copy response: pulling ticket "
                          f"{hdr['ticket']}")
            # pull results straight onto the local device the request
            # came from (or the default device)
            local_dev = None
            for a in arrays:
                if isinstance(a, jax.Array):
                    local_dev = next(iter(a.devices()))
                    break
            if local_dev is None:
                local_dev = jax.devices()[0]
            outs = pull(hdr["xfer"], int(hdr["ticket"]),
                        hdr.get("specs") or [], local_dev)
            oob_ticket = None
            with self._ack_mu:
                if self._unacked_resp is None:
                    self._unacked_resp = int(hdr["ticket"])
                else:
                    # a concurrent call already parked a ticket; ack this
                    # one out-of-band rather than dropping either
                    oob_ticket = int(hdr["ticket"])
            if oob_ticket is not None:
                # fire-and-forget OUTSIDE _ack_mu and off the caller's
                # critical path: a blocking ack round-trip would add up to
                # the channel timeout before returning already-pulled
                # results.  Failure is fine — the TTL backstop reclaims.
                try:
                    self._ch.call(
                        DCN_SERVICE, "CallDevice",
                        _pack_envelope({"svc": DCN_SERVICE, "method": "Ack",
                                        "ack": oob_ticket}, []),
                        done=lambda c: None,
                        serializer="raw", response_serializer="raw")
                except errors.RpcError:
                    pass  # TTL backstop reclaims it
        else:
            outs = [jax.numpy.asarray(a) for a in out_arrays]
        return outs[0] if hdr.get("single", True) else outs
