"""DCN groundwork — cross-process/cross-host device RPC (VERDICT r2 task 4).

Reference pattern (rdma_endpoint.h:112-115,180; SURVEY §5.8): RdmaEndpoint
rides an existing TCP connection for its handshake — a magic preamble and
an exchange of lid/gid/qp_num — after which data moves out-of-band and TCP
stays as the control/fallback channel.

TPU build, two processes that do NOT share a jax runtime (separate hosts,
or separate processes on one host):

  1. **Handshake**: the `_dcn` service's `Hello` method exchanges device
     topology (pid, platform, device inventory, advertised device) over
     the ordinary TRPC connection — the lid/gid/qp_num analog.
  2. **Data path**: `DcnChannel.call_sync` invokes a *device service*
     registered in the remote process (ici/channel.py registry); the
     payload moves host-serialized over the socket (the explicit fallback
     path — XLA cross-host collectives need a shared runtime, which two
     independent processes don't have), lands on the target chip via
     device_put, the jitted service runs there, and the result returns.
  3. Addressing: ``ici://host:port/chip`` — host:port is the remote RPC
     server, chip the device index in the REMOTE process's mesh.

This makes `Channel on A calls device service on B` work today and pins
the handshake/addressing surface that a zero-copy DCN transport can slot
under later without touching call sites (exactly how RdmaEndpoint slid
under Socket::Write).
"""
from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

from brpc_tpu import errors
from brpc_tpu.rpc.service import Service, method

DCN_SERVICE = "_dcn"
DCN_MAGIC = "DCN1"          # handshake version tag (the "RDMA" preamble)

_MAX_HEADER = 64 * 1024     # envelope header bound (bounded trust)


def _pack_envelope(header: dict, arrays: list) -> bytes:
    """json header + tensor-serialized arrays: u32 header_len, header
    json, u32 tensor_header_len, tensor header, tensor bodies.  The
    arrays ride the framework's TensorSerializer (raw dtype/shape/bytes),
    so nothing on this path interprets network bytes as code."""
    import json as _json
    import struct
    from brpc_tpu.rpc.serialization import TensorSerializer
    tbody, theader = TensorSerializer().encode(arrays)
    hdr = _json.dumps(header).encode()
    return (struct.pack("<I", len(hdr)) + hdr +
            struct.pack("<I", len(theader)) + theader + tbody)


def _unpack_envelope(data: bytes) -> tuple[dict, list]:
    import json as _json
    import struct
    from brpc_tpu.rpc.serialization import TensorSerializer
    if len(data) < 8:
        raise ValueError("envelope too short")
    (hlen,) = struct.unpack_from("<I", data, 0)
    if hlen > _MAX_HEADER or 4 + hlen + 4 > len(data):
        raise ValueError("bad envelope header length")
    header = _json.loads(data[4:4 + hlen].decode())
    (tlen,) = struct.unpack_from("<I", data, 4 + hlen)
    off = 8 + hlen
    if off + tlen > len(data):
        raise ValueError("bad tensor header length")
    theader = data[off:off + tlen]
    arrays = TensorSerializer().decode(data[off + tlen:], theader)
    if not isinstance(arrays, (list, tuple)):
        arrays = [arrays]
    return header, list(arrays)


def local_topology() -> dict:
    """This process's device inventory — the handshake payload."""
    import jax
    devs = jax.devices()
    return {
        "magic": DCN_MAGIC,
        "pid": os.getpid(),
        "platform": devs[0].platform if devs else "none",
        "devices": [{"id": d.id, "kind": getattr(d, "device_kind", "")}
                    for d in devs],
    }


class DcnService(Service):
    """Server half: topology exchange + remote device-service invocation.

    Registered by ``Server(enable_dcn=True)``; the ``Hello`` reply is the
    handshake, ``CallDevice`` bridges to the device-service registry."""

    NAME = DCN_SERVICE

    @method(request="json", response="json")
    def Hello(self, cntl, req):
        peer = req if isinstance(req, dict) else {}
        if peer.get("magic") != DCN_MAGIC:
            cntl.set_failed(errors.EREQUEST, "bad DCN handshake magic")
            return None
        return local_topology()

    @method(request="raw", response="raw")
    def CallDevice(self, cntl, req):
        # wire format: a bounded-trust envelope (json header + tensor
        # bytes, _pack_envelope) — NOT pickle: this method is reachable by
        # anything that can open the RPC port, and unpickling network
        # bytes is arbitrary code execution
        import jax
        from brpc_tpu.ici.channel import _compiled
        from brpc_tpu.ici.mesh import device_for
        try:
            hdr, arrays = _unpack_envelope(bytes(req))
            svc = str(hdr["svc"])
            meth = str(hdr["method"])
            chip = int(hdr["chip"])
        except Exception as e:
            cntl.set_failed(errors.EREQUEST, f"bad DCN envelope: {e}")
            return None
        fn = _compiled(svc, meth)
        if fn is None:
            cntl.set_failed(errors.ENOMETHOD,
                            f"no device service {svc}.{meth}")
            return None
        try:
            dev = device_for(chip)
        except Exception:
            cntl.set_failed(errors.EREQUEST, f"no local chip {chip}")
            return None
        placed = [jax.device_put(a, dev) for a in arrays]
        out = fn(placed[0] if len(placed) == 1 else placed)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        return _pack_envelope(
            {"single": not isinstance(out, (list, tuple)),
             "devices": [next(iter(o.devices())).id for o in outs]},
            [np.asarray(o) for o in outs])


def parse_dcn_address(address: str) -> tuple[str, int, Optional[int]]:
    """``ici://host:port/chip`` | ``ici://host:port`` | ``host:port``
    -> (host, port, chip|None)."""
    s = address
    if s.startswith("ici://"):
        s = s[len("ici://"):]
    chip: Optional[int] = None
    if "/" in s:
        s, chip_s = s.split("/", 1)
        chip = int(chip_s)
    host, port_s = s.rsplit(":", 1)
    return host, int(port_s), chip


class DcnChannel:
    """Client half: call a device service in a REMOTE process.

    ``DcnChannel("ici://hostB:8000/3")`` handshakes with hostB's RPC
    server, then ``call_sync("MatSvc", "Inc", x)`` runs that device
    service on hostB's chip 3 and returns the result on the local default
    device.  Same call surface as IciChannel, so moving a service across
    the DCN boundary is an address change, not a code change."""

    def __init__(self, address: str, timeout_ms: int = 10_000,
                 default_chip: Optional[int] = None):
        from brpc_tpu.rpc.channel import Channel
        host, port, chip = parse_dcn_address(address)
        self.remote = f"{host}:{port}"
        self.default_chip = chip if chip is not None else default_chip
        self._ch = Channel(self.remote, timeout_ms=timeout_ms)
        self.topology: Optional[dict] = None

    def handshake(self) -> dict:
        """Exchange topologies (idempotent); returns the remote's."""
        if self.topology is None:
            self.topology = self._ch.call_sync(
                DCN_SERVICE, "Hello", local_topology(),
                serializer="json", response_serializer="json")
        return self.topology

    def remote_device_ids(self) -> list[int]:
        topo = self.handshake()
        return [d["id"] for d in topo["devices"]]

    def call_sync(self, service: str, method_name: str, request: Any,
                  chip: Optional[int] = None):
        import jax
        topo = self.handshake()
        target_chip = chip if chip is not None else (self.default_chip or 0)
        if target_chip not in {d["id"] for d in topo["devices"]}:
            raise errors.RpcError(
                errors.EREQUEST,
                f"remote has no chip {target_chip} "
                f"(topology: {len(topo['devices'])} devices)")
        arrays = request if isinstance(request, (list, tuple)) else [request]
        body = _pack_envelope(
            {"svc": service, "method": method_name, "chip": target_chip},
            [np.asarray(a) for a in arrays])
        raw = self._ch.call_sync(DCN_SERVICE, "CallDevice", body,
                                 serializer="raw", response_serializer="raw")
        hdr, out_arrays = _unpack_envelope(bytes(raw))
        outs = [jax.numpy.asarray(a) for a in out_arrays]
        return outs[0] if hdr.get("single", True) else outs
