"""IciEndpoint — chip-to-chip transfer in RdmaEndpoint's socket slot.

Reference (rdma_endpoint.h; SURVEY.md §5.8): after a TCP-assisted handshake
the endpoint moves data on an RC queue pair with a credit window =
min(local SQ, remote RQ), completions surfacing through the dispatcher.

TPU build: the "queue pair" is XLA's device-to-device transfer engine —
`jax.device_put(x, device)` lowers to an ICI copy on hardware (no host
bounce), and dispatch is async, so starting a transfer and touching the
result later gives the same start/wait split as ibverbs post-send/poll-cq.
The credit window survives unchanged: in-flight bytes are bounded, and
"completion events" are jax futures observed via block_until_ready in a
drainer thread that feeds the same bvar counters the socket path uses.
No handshake is needed inside one process/slice; cross-host setup arrives
with the DCN path in a later round.
"""
from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Optional

import jax

from brpc_tpu import fault
from brpc_tpu.bvar import Adder, LatencyRecorder

_send_bytes = Adder("ici_send_bytes")
_send_count = Adder("ici_send_count")
_recv_bytes = Adder("ici_recv_bytes")
_same_device_copies = Adder("ici_same_device_copies")
_cross_device_moves = Adder("ici_cross_device_moves")
_transfer_latency = LatencyRecorder("ici_transfer")

DEFAULT_WINDOW_BYTES = 64 * 1024 * 1024

# Compiled HBM->HBM copy for same-device "transfers".  jax forwards
# unmodified jit outputs to their input buffers, and device_put to the
# array's own device is a no-op alias — so a loopback send must go through
# an explicit copy primitive to actually exercise the memory system and
# yield a distinct destination buffer (the single-chip analog of
# RdmaEndpoint moving bytes through the NIC even on loopback).
# jnp.copy lowers to the copy HLO, which XLA may not alias without
# donation; tests assert unsafe_buffer_pointer() inequality.
import jax.numpy as _jnp

_device_copy = jax.jit(_jnp.copy)

# Pre-compiled MULTI-chunk copy: one XLA program holding k copy HLOs, so a
# k-chunk batch costs ONE Python->PJRT dispatch instead of k (VERDICT r2
# task 2 — per-chunk dispatch was the pipe's bottleneck: ~ms of host work
# per chunk vs ~0.2ms of HBM time for a 64MB copy).  jit specializes and
# caches per (arity, shapes, dtypes), so this single definition is the
# whole "transfer program" cache.  No donation here: donating would let
# XLA alias outputs onto inputs and the copies must provably move bytes.
_multi_copy = jax.jit(lambda *xs: tuple(_jnp.copy(x) for x in xs))


def _collect_batch(q, first):
    """Drain everything already sitting in `q` behind `first` without
    blocking.  Returns (batch, stop) where stop means the None close
    sentinel was reached.  Shared by IciEndpoint and TensorStream so the
    two drain loops cannot diverge."""
    batch = [first]
    stop = False
    while True:
        try:
            nxt = q.get_nowait()
        except queue_mod.Empty:
            break
        if nxt is None:
            stop = True
            break
        batch.append(nxt)
    return batch, stop


class IciEndpoint:
    """Point-to-point ordered transfer pipe to one target device."""

    def __init__(self, device, window_bytes: int = DEFAULT_WINDOW_BYTES):
        self.device = device
        self.window_bytes = window_bytes
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        # serializes dispatch + completion-enqueue so the completion queue
        # is in dispatch order — the batch drain's tail-sync relies on it
        self._dispatch_mu = threading.Lock()
        self._inflight = 0
        self._closed = False
        # single long-lived completion drainer (the "poll-cq" thread);
        # started lazily on the first send
        import queue
        self._completions: "queue.Queue" = queue.Queue()
        self._drainer: Optional[threading.Thread] = None

    def _ensure_drainer(self) -> None:
        if self._drainer is None:
            with self._mu:
                if self._drainer is None:
                    self._drainer = threading.Thread(
                        target=self._drain_completions, daemon=True,
                        name=f"ici-cq-{self.device.id}")
                    self._drainer.start()

    def _drain_completions(self) -> None:
        while True:
            item = self._completions.get()
            if item is None:
                return
            # batch drain: collect everything already queued and host-sync
            # only the NEWEST — send() dispatches AND enqueues under
            # _dispatch_mu, so queue order == dispatch order, and one
            # device completes d2d copies in dispatch order; the tail's
            # readiness therefore implies the whole batch's.  This turns N
            # host round-trips (ruinous over a tunneled chip, ~RTT each)
            # into one per drain cycle.
            batch, stop = _collect_batch(self._completions, item)
            out, _, t0 = batch[-1]
            try:
                out.block_until_ready()
            except Exception:  # transfer failure: free the window anyway
                pass
            # only the tail's completion was actually observed — record
            # one latency sample per drain cycle rather than charging
            # every earlier chunk the full batch duration
            _transfer_latency.add(int((time.monotonic() - t0) * 1e6))
            total = 0
            for _, nbytes, _ in batch:
                _recv_bytes.add(nbytes)
                total += nbytes
            with self._cv:
                self._inflight -= total
                self._cv.notify_all()
            if stop:
                return

    def _transfer(self, array: jax.Array) -> jax.Array:
        """One async transfer to self.device that provably produces a
        distinct destination buffer.  Cross-device: device_put (a real ICI
        DMA / host copy).  Same-device loopback: compiled copy kernel —
        device_put to the source device would alias, moving zero bytes."""
        try:
            src = array.devices()
        except Exception:  # uncommitted / non-jax input
            src = set()
        if src == {self.device}:
            _same_device_copies.add(1)
            return _device_copy(array)
        _cross_device_moves.add(1)
        return jax.device_put(array, self.device)

    def _reserve_window(self, nbytes: int, timeout_s: float) -> None:
        """Block until `nbytes` of credit is available, then reserve it —
        the EAGAIN discipline of RdmaEndpoint's SQ/window check
        (rdma_endpoint.h:235-240).  Shared by send and send_batch so the
        credit protocol has exactly one implementation."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._inflight + nbytes > self.window_bytes:
                if self._closed:
                    raise RuntimeError("endpoint closed")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"ICI window full ({self.window_bytes}B)")
                self._cv.wait(min(remaining, 1.0))
            self._inflight += nbytes

    def _release_window(self, nbytes: int) -> None:
        if nbytes <= 0:
            return
        with self._cv:
            self._inflight -= nbytes
            self._cv.notify_all()

    def send(self, array: jax.Array, timeout_s: float = 30.0) -> jax.Array:
        """Start an async transfer of `array` to this endpoint's device;
        returns the (not-yet-ready) destination array.  Blocks while the
        credit window is exhausted."""
        nbytes = array.nbytes
        self._reserve_window(nbytes, timeout_s)
        t0 = time.monotonic()
        try:
            with self._dispatch_mu:
                if fault.ENABLED and fault.hit(
                        "ici.send", device=self.device.id) is not None:
                    # injected transfer failure BEFORE dispatch: the
                    # except below must release the window reservation
                    raise RuntimeError("injected ici transfer fault")
                # dispatch and enqueue atomically: with concurrent senders
                # the completion queue must mirror device dispatch order,
                # or the drainer's tail-sync would free window credit for
                # transfers that are still in flight
                out = self._transfer(array)  # async ICI DMA / HBM copy
                self._completions.put((out, nbytes, t0))
        except Exception:
            # release the window reservation or failed sends would shrink
            # the window permanently
            self._release_window(nbytes)
            raise
        _send_bytes.add(nbytes)
        _send_count.add(1)
        self._ensure_drainer()
        return out

    def send_sync(self, array: jax.Array) -> jax.Array:
        out = self.send(array)
        out.block_until_ready()
        return out

    def send_batch(self, arrays, timeout_s: float = 30.0) -> list:
        """Transfer a batch of arrays with ONE dispatch and ONE completion
        record.  Same-device arrays ride a single pre-compiled multi-copy
        program (_multi_copy); cross-device arrays ride one device_put of
        the whole list.  The window is reserved for the batch total, so
        size batches <= window_bytes (larger batches raise).

        This is the pipe's fast path: per-chunk Python dispatch and
        per-chunk completion observation — the costs that capped r2's
        ladder at ~5 GB/s while the chip streams 670 — are amortized over
        the batch."""
        arrays = list(arrays)
        if not arrays:
            return []
        total = sum(a.nbytes for a in arrays)
        if total > self.window_bytes:
            raise ValueError(
                f"batch of {total}B exceeds window {self.window_bytes}B; "
                f"split it or widen the window")
        self._reserve_window(total, timeout_s)
        t0 = time.monotonic()
        # bytes whose completion entry is already queued: the drainer will
        # release their window share, so a partial-dispatch failure must
        # release only the remainder (releasing `total` would double-free
        # the queued share and drive the window counter negative)
        queued = 0
        try:
            if fault.ENABLED and fault.hit(
                    "ici.send", device=self.device.id) is not None:
                # nothing queued yet: the except releases the full total
                raise RuntimeError("injected ici transfer fault")
            with self._dispatch_mu:
                same = []
                cross = []
                for i, a in enumerate(arrays):
                    try:
                        is_same = a.devices() == {self.device}
                    except Exception:
                        is_same = False
                    (same if is_same else cross).append(i)
                outs = [None] * len(arrays)
                # one completion entry per dispatch group (compiled copies
                # and device_put DMAs may ride different engines, so one
                # group's tail cannot vouch for the other's)
                if same:
                    copied = _multi_copy(*[arrays[i] for i in same])
                    for i, c in zip(same, copied):
                        outs[i] = c
                    _same_device_copies.add(len(same))
                    same_bytes = sum(arrays[i].nbytes for i in same)
                    self._completions.put((copied[-1], same_bytes, t0))
                    queued += same_bytes
                if cross:
                    moved = jax.device_put([arrays[i] for i in cross],
                                           self.device)
                    for i, m in zip(cross, moved):
                        outs[i] = m
                    _cross_device_moves.add(len(cross))
                    cross_bytes = sum(arrays[i].nbytes for i in cross)
                    self._completions.put((moved[-1], cross_bytes, t0))
                    queued += cross_bytes
        except Exception:
            self._release_window(total - queued)
            if queued:
                self._ensure_drainer()   # someone must observe the queued part
            raise
        _send_bytes.add(total)
        _send_count.add(len(arrays))
        self._ensure_drainer()
        return outs

    # ------------------------------------------------------------------
    # Block pipe: BlockPool-staged byte transfers.  The analog of the
    # reference's RDMA path where IOBuf blocks come from the registered
    # BlockPool so payloads are born in NIC-visible memory
    # (rdma/block_pool.cpp:52 wired in at socket.cpp:1751) — here payloads
    # are staged into HBM arena slots on the source device, DMA'd to the
    # target device through the windowed send path, and installed into
    # destination-pool slots without a host bounce.
    # ------------------------------------------------------------------

    def send_blocks(self, blocks, timeout_s: float = 30.0) -> list:
        """Transfer the source Blocks' device buffers to this endpoint's
        device, installing results into blocks allocated from the target
        device's pool.  Returns the destination Blocks (caller frees).
        Blocks are grouped into window-sized batches so a multi-block
        payload costs one dispatch per window, not one per block."""
        from brpc_tpu.ici.block_pool import get_block_pool
        dst_pool = get_block_pool(self.device)
        out = []
        i = 0
        while i < len(blocks):
            batch = []
            views = []            # one view() (one pool-lock hit) per block
            batch_bytes = 0
            while i < len(blocks):
                v = blocks[i].view()
                if batch and batch_bytes + v.nbytes > self.window_bytes:
                    break
                batch.append(blocks[i])
                views.append(v)
                batch_bytes += v.nbytes
                i += 1
            moved = self.send_batch(views, timeout_s=timeout_s)
            for b, m in zip(batch, moved):
                # alloc by the transferred buffer's size (not b.used) so the
                # destination class always covers the source class, even
                # when either pool has fallen through to a larger class
                dst = dst_pool.alloc(m.nbytes)
                dst.install(m, b.used, meta=getattr(b, "_src_meta", None))
                out.append(dst)
        return out

    def send_bytes(self, data, src_pool, timeout_s: float = 30.0) -> list:
        """Chunk `data` into blocks from `src_pool` (staged into that
        device's HBM arena), move them over this endpoint, and return the
        destination Blocks.  Frees the staging blocks — INCLUDING on a
        mid-staging failure: blocks are collected as the generator yields
        them, so an alloc exhaustion on chunk k still frees chunks 1..k-1
        (with `staged = list(...)` the partial list was discarded and the
        already-staged blocks leaked; found by the chaos suite's injected
        block-pool exhaustion)."""
        from brpc_tpu.ici.block_pool import stage_chunks
        staged: list = []
        try:
            for blk in stage_chunks(data, src_pool):
                staged.append(blk)
            return self.send_blocks(staged, timeout_s=timeout_s)
        finally:
            for blk in staged:
                blk.free()

    @property
    def inflight_bytes(self) -> int:
        with self._mu:
            return self._inflight

    def close(self, join: bool = True) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._drainer is not None:
            self._completions.put(None)
            if join:
                # joining matters: a daemon drainer killed at interpreter
                # exit while inside PJRT block_until_ready aborts the
                # process ("FATAL: exception not rethrown" on axon)
                self._drainer.join(timeout=30)


def link_stats() -> dict:
    """Exported on the /ici console page."""
    return {
        "send_bytes": _send_bytes.get_value(),
        "send_count": _send_count.get_value(),
        "recv_bytes": _recv_bytes.get_value(),
        "same_device_copies": _same_device_copies.get_value(),
        "cross_device_moves": _cross_device_moves.get_value(),
        "transfer_avg_us": round(_transfer_latency.latency(), 1),
        "transfer_p99_us": round(_transfer_latency.latency_percentile(0.99), 1),
        "devices": [str(d) for d in jax.devices()],
    }
