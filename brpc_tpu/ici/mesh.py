"""Device mesh management.

One place decides what "the local slice" is: real TPU chips when present,
the virtual CPU mesh under tests (conftest forces 8 CPU devices).  Channels
address chips as ici://<slice>/<chip> (EndPoint scheme "ici").
"""
from __future__ import annotations

import threading
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

_lock = threading.Lock()
_meshes: dict[tuple, Mesh] = {}


def local_devices():
    return jax.devices()


def device_for(chip_index: int):
    devs = jax.devices()
    return devs[chip_index % len(devs)]


def get_mesh(n_devices: Optional[int] = None,
             axis_names: tuple[str, ...] = ("chip",),
             shape: Optional[tuple[int, ...]] = None) -> Mesh:
    """Mesh over the first n local devices (default: all).  Multi-axis
    meshes (e.g. ("dp","tp")) reshape the device list row-major."""
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    if n_devices > len(devs):
        raise ValueError(f"want {n_devices} devices, have {len(devs)}")
    if shape is None:
        shape = (n_devices,)
    key = (n_devices, axis_names, shape)
    with _lock:
        m = _meshes.get(key)
        if m is None:
            arr = np.array(devs[:n_devices]).reshape(shape)
            m = Mesh(arr, axis_names)
            _meshes[key] = m
        return m
