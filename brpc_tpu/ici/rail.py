"""Device-payload rail — ICI inside the ordinary RPC data path.

Reference: RdmaEndpoint::CutFromIOBufList replaces
cut_into_file_descriptor inside Socket::StartWrite/KeepWrite
(/root/reference/src/brpc/socket.cpp:1751-1757, rdma/rdma_endpoint.h:82):
once both peers complete the RDMA handshake, an ordinary RPC's IOBuf
payload rides the RC queue pair while TCP carries only control traffic —
call sites never change.

TPU build: when a Channel.call request (or a handler's response) is made
of jax device arrays and the target server has advertised an
ICI-reachable device, the payload is staged into BlockPool HBM slots
(on-device bitcast, no host bounce), moved through IciEndpoint's
credit-windowed send path, and parked in the process-wide payload
registry.  The TRPC frame then carries only a claim ticket in its user
fields; the receiving side claims the blocks and rebuilds device arrays
with an on-device unstage.  The payload never exists as host bytes —
`host_copy_count()` gives tests a provable zero.
"""
from __future__ import annotations

import functools
import itertools
import threading
import time
from dataclasses import dataclass

import jax
import numpy as np

from brpc_tpu.bvar import Adder
from brpc_tpu.ici.block_pool import (BLOCK_CLASSES, Block, _stage, _unstage,
                                     get_block_pool)
from brpc_tpu.ici.endpoint import IciEndpoint

rail_payloads = Adder("rail_payloads")
rail_bytes = Adder("rail_bytes")
rail_fallbacks = Adder("rail_fallbacks")
_ticket_counter = itertools.count(1)

_CHUNK = BLOCK_CLASSES[-1]

# user-field keys riding the TRPC meta (control plane only)
# canonical definitions live with the wire format (rpc/meta.py); aliased
# here so rail code reads naturally
from brpc_tpu.rpc.meta import F_SRC_DEV, F_TICKET  # noqa: E402,F401

# ---------------------------------------------------------------------------
# rail map: which endpoints are ICI-reachable
# ---------------------------------------------------------------------------

_map_lock = threading.Lock()
_advertised: dict[int, object] = {}       # port -> jax device
_LOCAL_HOSTS = {"127.0.0.1", "localhost", "0.0.0.0", "::1"}


def advertise(port: int, device) -> None:
    """Server-side: declare that the RPC server on `port` can receive
    payloads on `device` (the handshake-complete bit of the RDMA path)."""
    with _map_lock:
        _advertised[port] = device


def unadvertise(port: int) -> None:
    with _map_lock:
        _advertised.pop(port, None)


def lookup(endpoint) -> object | None:
    """Client-side: the device an endpoint receives on, or None when the
    payload must stay on the socket.  In-process only until the DCN
    handshake lands (SURVEY §5.8); remote hosts return None."""
    if getattr(endpoint, "host", None) not in _LOCAL_HOSTS:
        return None
    with _map_lock:
        return _advertised.get(endpoint.port)


# ---------------------------------------------------------------------------
# staging: device arrays <-> BlockPool slots, entirely on device
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(2,))
def _slice_chunk(flat, offset, size: int):
    return jax.lax.dynamic_slice(flat, (offset,), (size,))


@jax.jit
def _cat(bufs):
    import jax.numpy as jnp
    return jnp.concatenate(bufs)


@dataclass
class _Entry:
    """One staged array: destination blocks + how to rebuild it."""
    blocks: list
    dtype: str
    shape: tuple
    nbytes: int

    def unstage(self, free: bool = True):
        if len(self.blocks) == 1:
            buf = self.blocks[0].view()
        else:
            buf = _cat([b.view() for b in self.blocks])
        out = _unstage(buf, self.dtype, self.shape)
        if free:
            for b in self.blocks:
                b.free()
        return out

    def free(self) -> None:
        for b in self.blocks:
            b.free()


@dataclass
class _DirectEntry:
    """One whole-array transfer: the moved device array itself.

    The fast path for arrays that fit the endpoint's credit window: the
    async copy's output (already the right dtype/shape on the target
    device) IS the deliverable — no block staging, no slice/concat, no
    unstage rebuild.  One XLA dispatch per array instead of ~6; over a
    tunneled chip (each dispatch ~an RTT) that difference is the whole
    streaming-tensor throughput story."""
    array: object
    nbytes: int

    def unstage(self, free: bool = True):
        out = self.array
        if free:
            self.array = None
        return out

    def free(self) -> None:
        self.array = None


def _stage_one(arr: jax.Array, pool) -> list[Block]:
    """Stage one device array into source-pool blocks without touching the
    host: small arrays pad into one slot (block_pool._stage), large ones
    flatten to uint8 on device and slice into 2MB chunks."""
    n = arr.nbytes
    if n <= _CHUNK:
        b = pool.alloc(n)
        b.put(arr)  # jax.Array branch: on-device _stage
        return [b]
    padded = ((n + _CHUNK - 1) // _CHUNK) * _CHUNK
    flat = _stage(arr, padded)  # uint8[padded] on the source device
    blocks = []
    try:
        for off in range(0, n, _CHUNK):
            piece = _slice_chunk(flat, off, _CHUNK)
            b = pool.alloc(_CHUNK)
            b.install(piece, min(_CHUNK, n - off))
            blocks.append(b)
    except Exception:
        for b in blocks:
            b.free()
        raise
    return blocks


def _is_device_array(x) -> bool:
    if not isinstance(x, jax.Array):
        return False
    try:
        return len(x.devices()) == 1
    except Exception:
        return False


def railable(obj) -> bool:
    """True when `obj` is a single-device jax array or a non-empty
    list/tuple of them — the payload shapes the rail can carry."""
    if isinstance(obj, (list, tuple)):
        return len(obj) > 0 and all(_is_device_array(a) for a in obj)
    return _is_device_array(obj)


def source_device(obj):
    first = obj[0] if isinstance(obj, (list, tuple)) else obj
    return next(iter(first.devices()))


def device_by_id(device_id: int):
    for d in jax.devices():
        if d.id == device_id:
            return d
    raise KeyError(f"no local device with id {device_id}")


# The rail's claim registry is PER-PROCESS: a ticket shipped to a peer in
# another process can never be claimed (its blocks would pin HBM until
# the TTL sweeper).  Device advertisements on the wire therefore carry
# this process token; resolution fails closed for any other process.
import uuid as _uuid

_PROCESS_TOKEN = _uuid.uuid4().hex[:16]


def device_advert(device) -> str:
    """Wire value advertising `device` as a tensor receive endpoint
    (stream settings F_SDEV): process token + device id."""
    return f"{_PROCESS_TOKEN}:{device.id}"


def device_from_wire(value):
    """Resolve a peer's device advertisement.  None unless the advert
    came from THIS process (token match) and names a local device — the
    single gate keeping rail tickets off cross-process streams."""
    if value is None:
        return None
    if isinstance(value, bytes):
        value = value.decode()
    token, _, dev_id = value.partition(":")
    if token != _PROCESS_TOKEN or not dev_id:
        return None
    try:
        return device_by_id(int(dev_id))
    except (KeyError, ValueError):
        return None


# ---------------------------------------------------------------------------
# payload registry: ticket -> staged entries (the claim table)
# ---------------------------------------------------------------------------

_REGISTRY_TTL_S = 60.0
_reg_lock = threading.Lock()
_registry: dict[str, tuple[list, bool, float]] = {}
_sweeper_started = False


def _purge_locked(now: float) -> None:
    dead = [t for t, (_, _, dl) in _registry.items() if dl < now]
    for t in dead:
        entries, _, _ = _registry.pop(t)
        for e in entries:
            e.free()


def _sweep_loop() -> None:
    # Orphaned tickets must not pin HBM blocks forever in a process that
    # stopped depositing — the TTL fires on its own clock, not on traffic.
    while True:
        time.sleep(_REGISTRY_TTL_S / 4)
        with _reg_lock:
            _purge_locked(time.monotonic())


def _ensure_sweeper() -> None:
    global _sweeper_started
    if not _sweeper_started:
        _sweeper_started = True
        threading.Thread(target=_sweep_loop, daemon=True,
                         name="rail-ttl-sweeper").start()


def deposit(entries: list, single: bool) -> str:
    # TTL purging belongs to the sweeper thread alone: purging inline
    # here scanned the WHOLE registry under the lock on every deposit —
    # O(pending) per message, measured at ~19us/msg with 2k outstanding
    # stream chunks (a quadratic drag exactly when streaming is busiest)
    ticket = f"t{next(_ticket_counter)}"
    with _reg_lock:
        _registry[ticket] = (entries, single,
                             time.monotonic() + _REGISTRY_TTL_S)
    _ensure_sweeper()
    return ticket


def _norm(ticket) -> str:
    # user-field values come off the wire as bytes (meta.py decode)
    return ticket.decode() if isinstance(ticket, bytes) else ticket


def claim(ticket):
    """Pop the ticket and rebuild device arrays (frees the blocks)."""
    ticket = _norm(ticket)
    with _reg_lock:
        item = _registry.pop(ticket, None)
    if item is None:
        raise KeyError(f"rail ticket {ticket!r} expired or already claimed")
    entries, single, _ = item
    arrays = [e.unstage() for e in entries]
    return arrays[0] if single else arrays


def withdraw(ticket) -> None:
    """Free an unclaimed ticket (failed/abandoned attempt).  Claim is an
    atomic pop, so racing the receiver cannot double-free."""
    ticket = _norm(ticket)
    with _reg_lock:
        item = _registry.pop(ticket, None)
    if item is None:
        return
    for e in item[0]:
        e.free()


def pending_tickets() -> int:
    with _reg_lock:
        return len(_registry)


# ---------------------------------------------------------------------------
# the send half: stage + ICI transfer + deposit
# ---------------------------------------------------------------------------

_ep_lock = threading.Lock()
_endpoints: dict[int, IciEndpoint] = {}


# Rail endpoints get a wider credit window than the 64MB transport
# default: stream writers burst whole messages (the streaming bench's
# batch is 128MB), and releasing credit costs a completion sync — a full
# tunnel RTT on axon.  The window is BANDWIDTH-DELAY sized per device
# (the rdma_endpoint.h:235-240 SQ/window discipline, solved the way TCP
# solves it): only `window` bytes can be in flight during the RTT it
# takes to observe a completion, so steady-state throughput is capped at
# window/RTT.  A fixed 256MB window on a 64ms tunnel caps the rail at
# 4 GB/s while the same chip streams 30+; sizing the window to
# measured_rtt x target bandwidth restores the ceiling, and the floor/cap
# keep HBM pinning bounded on well-connected (rtt~us) and pathological
# links alike.
_RAIL_WINDOW_FLOOR = 256 * 1024 * 1024
_RAIL_WINDOW_CAP = 2 * 1024 * 1024 * 1024
_RAIL_TARGET_BW = 32e9  # bytes/s the BDP sizing budgets for


def _completion_rtt(device) -> float:
    """Median seconds to dispatch a tiny same-device copy and observe its
    completion — the credit-release cost the BDP window must cover.  On
    directly attached hardware this is ~us; over a tunneled runtime it is
    a network RTT."""
    import jax.numpy as jnp
    with jax.default_device(device):
        x = jnp.zeros((256,), jnp.uint8)
    x.block_until_ready()
    samples = []
    for _ in range(3):
        t0 = time.monotonic()
        _device_copy_probe(x).block_until_ready()
        samples.append(time.monotonic() - t0)
    samples.sort()
    return samples[len(samples) // 2]


_device_copy_probe = jax.jit(lambda x: x + np.uint8(0))


def _window_for(device) -> int:
    try:
        rtt = _completion_rtt(device)
    except Exception:
        return _RAIL_WINDOW_FLOOR
    return int(min(max(_RAIL_WINDOW_FLOOR, rtt * _RAIL_TARGET_BW),
                   _RAIL_WINDOW_CAP))

# Largest send_batch arity ship_many will emit: bounds both the XLA
# program cache (log2 entries per chunk shape) and single-program size.
_MAX_ARITY = 32


def _endpoint_for(device) -> IciEndpoint:
    with _ep_lock:
        ep = _endpoints.get(device.id)
    if ep is not None:
        return ep
    # probe OUTSIDE the lock: the RTT measurement blocks on the device
    # (3 round-trips + a possible first-call compile, ~200ms+ over a
    # tunnel) and must not serialize endpoint creation for OTHER devices
    window = _window_for(device)
    with _ep_lock:
        ep = _endpoints.get(device.id)   # double-checked: lost race reuses
        if ep is None:
            ep = IciEndpoint(device, window_bytes=window)
            _endpoints[device.id] = ep
            _ensure_atexit()
        return ep


_atexit_registered = False


def _ensure_atexit() -> None:
    """Join every rail drainer before the interpreter finalizes.  A daemon
    drainer killed at exit while inside PJRT block_until_ready aborts the
    whole process ('FATAL: exception not rethrown' on axon) — which would
    turn a clean bench/driver run into a nonzero exit AFTER the results
    printed."""
    global _atexit_registered
    if _atexit_registered:
        return
    _atexit_registered = True
    import atexit

    def _close_endpoints():
        with _ep_lock:
            eps = list(_endpoints.values())
            _endpoints.clear()
        for ep in eps:
            try:
                ep.close(join=True)
            except Exception:
                pass

    atexit.register(_close_endpoints)


def ship(obj, target_device) -> str:
    """Move a railable payload to `target_device` through the block pipe
    and park it in the registry; returns the claim ticket for the meta.

    This is the CutFromIOBufList moment: bytes that would have been
    serialized into the socket ride the ICI send path instead."""
    return ship_many([obj], target_device)[0]


def ship_many(objs, target_device) -> list[str]:
    """Ship several railable payloads with batched dispatch ACROSS
    payloads: the whole run of window-fitting arrays — regardless of
    which message they belong to — rides one send_batch (one compiled
    multi-copy program, one completion record), and each payload still
    gets its OWN registry ticket so per-message claim/withdraw semantics
    are unchanged.  On a tunneled chip where every dispatch costs a host
    round-trip this is the difference between per-message and per-batch
    transfer cost (the h2 frame-coalescing story, applied to tensors)."""
    ep = _endpoint_for(target_device)
    # (payload idx, array, nbytes): jax.Array.nbytes is a COMPUTED
    # property (prod(shape) * itemsize per access) — cache it once per
    # array; the run-packing loop below reads it repeatedly
    flat: list[tuple[int, jax.Array, int]] = []
    singles = []
    for oi, obj in enumerate(objs):
        singles.append(not isinstance(obj, (list, tuple)))
        for a in (obj if isinstance(obj, (list, tuple)) else [obj]):
            flat.append((oi, a, a.nbytes))
    per_obj: list[list] = [[] for _ in objs]
    try:
        i = 0
        while i < len(flat):
            oi, a, a_nbytes = flat[i]
            if a_nbytes > ep.window_bytes:
                # oversize payloads still ride the block pipe so the
                # credit window keeps bounding in-flight HBM per chunk
                src_pool = get_block_pool(source_device(a))
                staged = _stage_one(a, src_pool)
                try:
                    moved = ep.send_blocks(staged)
                finally:
                    for b in staged:
                        b.free()
                per_obj[oi].append(_Entry(moved, str(np.dtype(a.dtype)),
                                          tuple(a.shape), a_nbytes))
                rail_bytes.add(a_nbytes)
                i += 1
                continue
            # whole-array fast path: group a window-fitting run of arrays
            # into ONE batched dispatch (send_batch compiles k copy HLOs
            # into one program); the moved arrays are the deliverables
            run = [flat[i]]
            run_bytes = a_nbytes
            while (i + len(run) < len(flat)
                   and flat[i + len(run)][2] <= ep.window_bytes
                   and run_bytes + flat[i + len(run)][2]
                       <= ep.window_bytes):
                run.append(flat[i + len(run)])
                run_bytes += run[-1][2]
            # Power-of-2 sub-batches: send_batch compiles one XLA program
            # per (arity, shapes), and adaptive coalescing would otherwise
            # produce an unbounded set of arities — every new one a fresh
            # compile (~100ms+ over a tunneled chip, worse than the
            # per-message dispatches it replaces).  Decomposing 27 chunks
            # as 16+8+2+1 bounds the program set to log2(cap) per shape.
            moved_run = []
            j = 0
            while j < len(run):
                k = min(1 << ((len(run) - j).bit_length() - 1), _MAX_ARITY)
                sub = [x for _, x, _ in run[j:j + k]]
                moved_run.extend(ep.send_batch(sub) if k > 1
                                 else [ep.send(sub[0])])
                j += k
            for (roi, _, src_nb), m in zip(run, moved_run):
                per_obj[roi].append(_DirectEntry(m, src_nb))
                rail_bytes.add(src_nb)
            i += len(run)
    except Exception:
        for es in per_obj:
            for e in es:
                e.free()
        raise
    rail_payloads.add(len(objs))
    return [deposit(es, single) for es, single in zip(per_obj, singles)]


# ---------------------------------------------------------------------------
# proof hooks
# ---------------------------------------------------------------------------

def host_copy_count() -> int:
    """Total payload-bytes-materialized-on-host events across the tensor
    serializer and the block pool.  A rail round-trip must leave this
    unchanged — the test's 'provably never bounced through host bytes'."""
    from brpc_tpu.ici import block_pool
    from brpc_tpu.rpc import serialization
    return (serialization.tensor_host_encodes.get_value()
            + serialization.tensor_host_decodes.get_value()
            + block_pool.host_stage_count.get_value()
            + block_pool.host_read_count.get_value())
