"""TensorStream — StreamWrite as a zero-copy HBM→HBM tensor pipe.

The credit loop of rpc/stream.py (§5.7) applied to device arrays: writer
pushes tensors, each rides an async ICI transfer (IciEndpoint), consumer
callbacks run in submission order, the window bounds HBM held by in-flight
chunks.  Double buffering falls out of the async dispatch: chunk N+1's
transfer starts while N's consumer runs.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

import jax

from brpc_tpu.ici.endpoint import IciEndpoint, _collect_batch


class TensorStream:
    def __init__(self, device,
                 consumer: Optional[Callable[[jax.Array], None]] = None,
                 window_bytes: int = 64 * 1024 * 1024):
        self.endpoint = IciEndpoint(device, window_bytes)
        self._consumer = consumer
        self._write_mu = threading.Lock()
        self._q: "queue.Queue" = queue.Queue()
        self._error: Exception | None = None
        self._closed = threading.Event()
        self._drained = threading.Event()
        self._drainer = threading.Thread(target=self._drain, daemon=True,
                                         name=f"tensor-stream-{device.id}")
        self._drainer.start()

    def write(self, array: jax.Array) -> None:
        """Queue one tensor; transfer starts immediately (async), order is
        preserved for the consumer."""
        if self._closed.is_set():
            raise RuntimeError("stream closed")
        with self._write_mu:
            # dispatch + enqueue atomically so _q mirrors dispatch order —
            # the drainer's batch tail-sync depends on it (endpoint.py has
            # the same discipline for its completion queue)
            out = self.endpoint.send(array)
            self._q.put(("tensor", out, 0, None))

    def write_many(self, arrays) -> list:
        """Queue a batch of tensors with ONE dispatch (endpoint.send_batch)
        — the amortized fast path for uniform chunk streams; consumer
        ordering is unchanged.  Returns the destination handles so callers
        can observe transfer completion directly (block_until_ready on the
        last handle) without waiting for consumer delivery."""
        if self._closed.is_set():
            raise RuntimeError("stream closed")
        if not arrays:
            return []
        with self._write_mu:
            outs = self.endpoint.send_batch(arrays)
            for out in outs:
                self._q.put(("tensor", out, 0, None))
        return outs

    def write_bytes(self, data, src_pool=None) -> None:
        """Stream a byte payload staged through BlockPool slots on the
        source side (HBM-born, like the reference's pool-allocated IOBuf
        blocks — block_pool.cpp:52); the consumer receives destination-pool
        Blocks in order.  Chunking follows the pool's largest class."""
        if self._closed.is_set():
            raise RuntimeError("stream closed")
        from brpc_tpu.ici.block_pool import get_block_pool, stage_chunks
        src_pool = src_pool or get_block_pool()
        for blk in stage_chunks(data, src_pool):
            with self._write_mu:
                out = self.endpoint.send(blk.view())
                self._q.put(("block", out, blk.used,
                             getattr(blk, "_src_meta", None)))
            # the dispatched transfer holds its own reference to the staged
            # buffer; the slot can go back to the free list immediately
            blk.free()

    def _drain(self) -> None:
        try:
            while True:
                try:
                    item = self._q.get(timeout=0.1)
                except queue.Empty:
                    if self._closed.is_set():
                        break
                    continue
                if item is None:
                    break
                # batch: sync the newest queued chunk once (one device
                # executes d2d copies in dispatch order, so the tail being
                # ready implies the earlier ones are) and feed the
                # consumer in order — N tunnel round-trips become 1
                batch, stop = _collect_batch(self._q, item)
                try:
                    batch[-1][1].block_until_ready()   # ordered completion
                except Exception:
                    # one failed transfer must not kill the drainer or
                    # swallow delivery of the batch's completed chunks
                    import traceback
                    traceback.print_exc()
                if self._consumer is not None:
                    for kind, arr, used, meta in batch:
                        # pipe-side work (dst-pool alloc/install) is NOT
                        # covered by the consumer-bug guard: its failure
                        # means data loss and must surface via close()
                        if kind == "block":
                            try:
                                from brpc_tpu.ici.block_pool import \
                                    get_block_pool
                                item = get_block_pool(
                                    self.endpoint.device).alloc(arr.nbytes)
                                item.install(arr, used, meta=meta)
                            except Exception as e:
                                import traceback
                                traceback.print_exc()
                                if self._error is None:
                                    self._error = e
                                continue
                        else:
                            item = arr
                        try:
                            self._consumer(item)
                        except Exception:  # consumer bug must not kill pipe
                            import traceback
                            traceback.print_exc()
                if stop:
                    break
        finally:
            self._drained.set()

    def close(self, wait: bool = True) -> None:
        if not self._closed.is_set():
            self._closed.set()
            self._q.put(None)
        if wait:
            self._drained.wait(30)
        self.endpoint.close()
        if self._error is not None:
            raise RuntimeError(
                "stream dropped data on the pipe side") from self._error
