"""brpc_tpu.kvcache — paged KV cache over the ICI BlockPool.

Three layers (see README "KV cache"):

  * :class:`PagePool` (pages.py) — fixed-size, refcounted KV pages
    carved from leased HBM blocks (block<->page table, copy-on-write
    copies, idle blocks return to the BlockPool);
  * :class:`RadixTree` (radix.py) — longest-prefix reuse at page
    granularity with LRU-by-leaf eviction under pool pressure;
  * :class:`KVCacheStore` (store.py) — the engine-facing
    admit/extend/fork/retire lifecycle with hit-rate/occupancy bvars.

Every live store self-registers here (weakly, by name) so the
``/kvcache`` builtin-console page can render hit-rate, page occupancy,
radix-tree size, and eviction counters without holding stores alive.
"""
from __future__ import annotations

import threading
from brpc_tpu.butil.lockprof import InstrumentedLock
import weakref

_reg_mu = InstrumentedLock("kvcache.registry")
_stores: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()


def _register_store(s) -> None:
    with _reg_mu:
        _stores[s.name] = s


def kvcache_snapshot() -> dict:
    """Live stores' stats — the /kvcache console page's data."""
    with _reg_mu:
        stores = dict(_stores)
    return {"stores": {name: s.stats()
                       for name, s in sorted(stores.items())}}


from brpc_tpu.kvcache.pages import KVPage, PagePool  # noqa: E402,F401
from brpc_tpu.kvcache.radix import RadixTree  # noqa: E402,F401
from brpc_tpu.kvcache.store import (  # noqa: E402,F401
    KVCacheStore, KVSeq, RecoveryPin,
)
