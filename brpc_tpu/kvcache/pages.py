"""Fixed-size KV pages carved from leased ICI BlockPool blocks.

The paper's north star is IOBuf blocks backed by HBM as the substrate
for zero-copy tensor serving; RDMAbox (arXiv:2104.12197) argues the
same discipline for RDMA — treat attention state as pooled,
reference-counted, pre-registered device memory.  This module is that
discipline for KV caches:

  * the :class:`PagePool` leases whole blocks from the per-device
    :class:`~brpc_tpu.ici.block_pool.BlockPool` and carves each into
    ``pages_per_block`` fixed-size pages (the block<->page table);
  * every page carries a refcount — sequences share pages
    copy-on-write, the radix tree holds one ref per cached page, and a
    page returns to the free list only at refcount zero;
  * a block whose pages are ALL free is released back to the BlockPool,
    so engine/chaos occupancy leak checks see the exact baseline
    discipline PR 2 established for raw slot leases.

Page layout: ``page_tokens`` slots of ``kv_bytes_per_token`` bytes.  A
token's slot holds EITHER its token id as a little-endian int32 in the
leading bytes (the pure-token harness stand-in) OR the token's real
packed K/V vectors (``write_slots`` — the ModelRunner path, ISSUE 10:
``[n_layers, 2, n_kv_heads, head_dim]`` f32 per slot, written by the
transformer and read back by the paged-attention kernel).  All page
writes and page-to-page copies are on-device ``dynamic_update_slice``
splices into the block buffer — sibling pages in the same block are
never clobbered and no full-block host bounce happens on the extend
path.

ARENA VIEW (ISSUE 10): the paged-attention kernel wants ONE fixed-shape
device array indexable by page, compiled once for the life of the
model.  Blocks come and go, so each leased block is pinned to a STABLE
row in ``[0, max_blocks)`` for its lifetime and every page gets a flat
arena index ``row * pages_per_block + page.index``; :meth:`arena`
stacks the live block buffers (zeros for unleased rows) into
``[max_blocks * pages_per_block, page_bytes]`` and :meth:`flat_ids`
translates the engine's pid page tables into arena indices.  The stack
is O(arena bytes) per call — on TPU a production path would pin one
arena buffer; the layout contract (stable flat index per live page) is
what the kernel compiles against either way.
"""
from __future__ import annotations

import itertools
import threading
from brpc_tpu.butil.lockprof import InstrumentedLock
from typing import Optional, Sequence

import numpy as np

from brpc_tpu import fault
from brpc_tpu.bvar import Adder

_page_ids = itertools.count(1)


class KVPage:
    """One fixed-size page: a (block, index) cell in the block<->page
    table plus a refcount.  Identity is the stable integer ``pid`` —
    page tables handed to a jitted step function are int32 arrays of
    pids."""

    __slots__ = ("pid", "block", "index", "refs")

    def __init__(self, block, index: int):
        self.pid = next(_page_ids)
        self.block = block           # leased BlockPool block
        self.index = index           # page slot within the block
        self.refs = 0

    def __repr__(self):
        return f"<KVPage {self.pid} blk={self.block.slot} " \
               f"idx={self.index} refs={self.refs}>"


class PagePool:
    """Carves BlockPool blocks into refcounted KV pages.

    ``max_blocks`` bounds how many blocks this pool may hold leased at
    once — the pool's own pressure signal (callers run eviction and
    retry) arrives before the shared device pool is drained under
    every other subsystem's feet.
    """

    def __init__(self, pool=None, device=None, *,
                 page_bytes: int = 1024, page_tokens: int = 16,
                 max_blocks: int = 8, name: str = "kv"):
        if pool is None:
            from brpc_tpu.ici.block_pool import get_block_pool
            pool = get_block_pool(device)
        from brpc_tpu.ici.block_pool import BLOCK_CLASSES
        if page_bytes % page_tokens:
            raise ValueError("page_bytes must be a multiple of page_tokens")
        self.kv_bytes_per_token = page_bytes // page_tokens
        if self.kv_bytes_per_token < 4:
            raise ValueError("need >= 4 bytes per token slot (int32 id)")
        self.pool = pool
        self.page_bytes = int(page_bytes)
        self.page_tokens = int(page_tokens)
        self.block_class = next(
            (c for c in BLOCK_CLASSES if c >= page_bytes), None)
        if self.block_class is None:
            raise ValueError(f"page_bytes {page_bytes} exceeds the largest "
                             f"block class {BLOCK_CLASSES[-1]}")
        self.pages_per_block = self.block_class // self.page_bytes
        self.max_blocks = int(max_blocks)
        self.name = name
        self._mu = InstrumentedLock("kvcache.pool")
        # serializes _splice's read-modify-write: two concurrent
        # splices into sibling pages of ONE block would otherwise each
        # rebuild the block buffer from the same base and the loser's
        # write would vanish
        self._io_mu = InstrumentedLock("kvcache.pool_io")
        # block<->page table: block key -> the pages carved from it
        self._blocks: dict[tuple, tuple] = {}   # key -> (block, [pages])
        self._free: list[KVPage] = []
        # stable arena rows (ISSUE 10): a leased block keeps one row in
        # [0, max_blocks) for its whole lease, so every live page's
        # flat arena index is constant and the paged-attention kernel
        # compiles once against the [max_blocks * pages_per_block]
        # layout
        self._row_of: dict[tuple, int] = {}     # block key -> arena row
        self._free_rows: list[int] = list(range(self.max_blocks))[::-1]
        self._pid_flat: dict[int, int] = {}     # pid -> flat arena index
        self._zero_row = None                   # cached empty-row buffer
        self.page_allocs = Adder()
        self.page_frees = Adder()
        self.block_leases = Adder()
        self.block_releases = Adder()
        self.batch_splices = Adder()

    @staticmethod
    def _bkey(block) -> tuple:
        return (block.size_class, block.slot)

    # ---- allocation / refcounting ----

    def alloc_page(self) -> KVPage:
        """A fresh exclusive page (refs=1 for the caller).  Leases and
        carves a new block when the free list is dry; raises
        MemoryError at ``max_blocks`` (callers evict and retry)."""
        if fault.ENABLED and fault.hit(
                "kvcache.page_alloc", pool=self.name) is not None:
            raise MemoryError("injected KV page exhaustion")
        with self._mu:
            if not self._free:
                if len(self._blocks) >= self.max_blocks:
                    raise MemoryError(
                        f"KV page pool at max_blocks={self.max_blocks} "
                        f"({self.pages_per_block} pages/block)")
                block = self.pool.alloc(self.block_class)
                self.block_leases.add(1)
                pages = [KVPage(block, i)
                         for i in range(self.pages_per_block)]
                key = self._bkey(block)
                self._blocks[key] = (block, pages)
                row = self._free_rows.pop()
                self._row_of[key] = row
                for p in pages:
                    self._pid_flat[p.pid] = \
                        row * self.pages_per_block + p.index
                self._free.extend(reversed(pages))
            page = self._free.pop()
            assert page.refs == 0, f"free-list page with refs: {page}"
            page.refs = 1
            self.page_allocs.add(1)
            return page

    def ref(self, page: KVPage) -> None:
        with self._mu:
            if page.refs <= 0:
                raise RuntimeError(f"ref on dead page {page}")
            page.refs += 1

    def refs(self, page: KVPage) -> int:
        with self._mu:
            return page.refs

    def unref(self, page: KVPage) -> None:
        """Drop one reference; at zero the page joins the free list and
        a fully-free block is released back to the BlockPool (the
        occupancy-baseline discipline the chaos suite leak-checks)."""
        release = None
        with self._mu:
            if page.refs <= 0:
                raise RuntimeError(f"unref on dead page {page} "
                                   f"(double free?)")
            page.refs -= 1
            if page.refs:
                return
            self.page_frees.add(1)
            key = self._bkey(page.block)
            entry = self._blocks.get(key)
            if entry is None:          # block already released (bug guard)
                raise RuntimeError(f"page {page} has no block entry")
            block, pages = entry
            if all(p.refs == 0 for p in pages):
                # whole block idle: return it to the device pool and
                # retire its pages (ids are never reused)
                del self._blocks[key]
                self._free = [p for p in self._free
                              if self._bkey(p.block) != key]
                self._free_rows.append(self._row_of.pop(key))
                for p in pages:
                    self._pid_flat.pop(p.pid, None)
                self.block_releases.add(1)
                release = block
            else:
                self._free.append(page)
        if release is not None:
            release.free()

    # ---- page I/O (on-device splices; see module docstring) ----

    def _offset(self, page: KVPage, slot: int = 0) -> int:
        return page.index * self.page_bytes + slot * self.kv_bytes_per_token

    def write(self, page: KVPage, slot: int,
              tokens: Sequence[int]) -> None:
        """Write token ids into consecutive slots of `page` starting at
        `slot`.  The int32 payload ships H2D once; the splice into the
        block buffer runs on device."""
        n = len(tokens)
        if slot < 0 or slot + n > self.page_tokens:
            raise ValueError(f"write [{slot},{slot + n}) exceeds "
                             f"page_tokens={self.page_tokens}")
        piece = np.zeros((n * self.kv_bytes_per_token,), np.uint8)
        ids = np.asarray(tokens, dtype="<i4").view(np.uint8)
        piece.reshape(n, self.kv_bytes_per_token)[:, :4] = \
            ids.reshape(n, 4)
        self._splice(page.block, piece, self._offset(page, slot))

    def write_slots(self, page: KVPage, slot: int, rows) -> None:
        """Write RAW per-token vector payloads (the ModelRunner path,
        ISSUE 10) into consecutive slots of `page` starting at `slot`:
        ``rows`` is ``[n, kv_bytes_per_token]`` uint8 — each row is one
        token's packed K/V vectors, spliced on device exactly like the
        stand-in :meth:`write` (one splice per contiguous run)."""
        rows = np.ascontiguousarray(rows, np.uint8)
        if rows.ndim != 2 or rows.shape[1] != self.kv_bytes_per_token:
            raise ValueError(
                f"write_slots rows must be [n, {self.kv_bytes_per_token}]"
                f" uint8, got {rows.shape}")
        n = rows.shape[0]
        if slot < 0 or slot + n > self.page_tokens:
            raise ValueError(f"write_slots [{slot},{slot + n}) exceeds "
                             f"page_tokens={self.page_tokens}")
        self._splice(page.block, rows.reshape(-1),
                     self._offset(page, slot))

    def write_slots_batch(self, runs) -> None:
        """Splice MANY per-token vector runs as ONE batch (ISSUE 11 —
        the decode-side write primitive): ``runs`` is a sequence of
        ``(page, slot, rows)`` triples with the :meth:`write_slots`
        shapes.  The whole batch ships host-to-device in ONE
        ``device_put`` of the concatenated payload and splices under
        ONE ``_io_mu`` acquisition — a verify-commit (or a plain decode
        step) pays one call across every slot instead of a lock +
        transfer round-trip per slot.  Runs are validated up front; a
        bad run fails the whole batch before any byte lands."""
        import jax
        staged = []
        for page, slot, rows in runs:
            rows = np.ascontiguousarray(rows, np.uint8)
            if rows.ndim != 2 or rows.shape[1] != self.kv_bytes_per_token:
                raise ValueError(
                    f"write_slots_batch rows must be "
                    f"[n, {self.kv_bytes_per_token}] uint8, "
                    f"got {rows.shape}")
            n = rows.shape[0]
            if slot < 0 or slot + n > self.page_tokens:
                raise ValueError(
                    f"write_slots_batch [{slot},{slot + n}) exceeds "
                    f"page_tokens={self.page_tokens}")
            staged.append((page, slot, rows))
        if not staged:
            return
        payload = np.concatenate([r.reshape(-1) for _, _, r in staged])
        dev = jax.device_put(payload, self.pool.device)
        self.batch_splices.add(1)
        off = 0
        with self._io_mu:
            for page, slot, rows in staged:
                nb = rows.size
                self._splice_locked(page.block, dev[off:off + nb],
                                    self._offset(page, slot))
                off += nb

    def flat_ids(self, pids) -> list:
        """Translate page ids (the engine's gathered page tables) into
        FLAT ARENA indices for :meth:`arena`; -1 (padding) and dead
        pids map to -1."""
        with self._mu:
            return [self._pid_flat.get(int(p), -1) for p in pids]

    def arena(self):
        """The whole pool as ONE fixed-shape device array
        ``[max_blocks * pages_per_block, page_bytes]`` uint8 — the
        paged-attention kernel's K/V substrate.  Row assignment is
        stable per leased block (see module docstring), unleased rows
        read as zeros, so the shape (and thus the kernel's compilation)
        never changes however blocks churn."""
        import jax.numpy as jnp
        nbytes = self.pages_per_block * self.page_bytes
        with self._mu:
            if self._zero_row is None:
                import jax
                with jax.default_device(self.pool.device):
                    self._zero_row = jnp.zeros((nbytes,), jnp.uint8)
            by_row = {row: self._blocks[key][0]
                      for key, row in self._row_of.items()}
            # snapshot the slot buffers under the pool lock (Block.view
            # would retake it per row)
            with self.pool._lock:
                bufs = []
                for row in range(self.max_blocks):
                    blk = by_row.get(row)
                    if blk is None:
                        bufs.append(self._zero_row)
                    else:
                        buf = self.pool._slots[blk.size_class][blk.slot]
                        bufs.append(buf[:nbytes] if buf.shape[0] != nbytes
                                    else buf)
        return jnp.stack(bufs).reshape(
            self.max_blocks * self.pages_per_block, self.page_bytes)

    def read(self, page: KVPage, count: Optional[int] = None) -> np.ndarray:
        """Token ids stored in `page` (host read — test/debug path, the
        decode data path never calls this)."""
        if count is None:
            count = self.page_tokens
        from brpc_tpu.ici.block_pool import host_read_count
        host_read_count.add(1)
        raw = np.asarray(page.block.view())[
            self._offset(page):self._offset(page, count)]
        return raw.reshape(count, self.kv_bytes_per_token)[:, :4] \
            .copy().view("<i4").ravel()

    def page_slice(self, page: KVPage):
        """This page's raw bytes as a DEVICE array (uint8, page_bytes
        long) — the migration export path's zero-copy payload: sliced
        out of the block buffer on device, it rides the DCN transfer
        fabric without a host bounce."""
        from brpc_tpu.ici.block_pool import _slice_bytes
        return _slice_bytes(page.block.view(), self._offset(page),
                            self.page_bytes)

    def read_raw(self, page: KVPage) -> np.ndarray:
        """Host copy of the page's raw bytes (the migration FALLBACK
        payload when no transfer fabric exists, and the test oracle for
        splice round-trips)."""
        from brpc_tpu.ici.block_pool import host_read_count
        host_read_count.add(1)
        return np.asarray(self.page_slice(page)).copy()

    def write_raw(self, page: KVPage, data) -> None:
        """Splice a full page of raw bytes into `page` — the import
        half of page migration: whatever KV layout the source page
        held (token-id stand-ins today, real K/V vectors under a
        pallas kernel) lands bit-exact without this module
        interpreting it."""
        arr = np.asarray(data, np.uint8).ravel()
        if arr.shape[0] != self.page_bytes:
            raise ValueError(f"raw page payload is {arr.shape[0]}B, "
                             f"page_bytes={self.page_bytes}")
        self._splice(page.block, arr, self._offset(page))

    def copy_page(self, dst: KVPage, src: KVPage) -> None:
        """Device-to-device page copy — the copy half of copy-on-write.
        Slices the source page out of its block buffer and splices it
        into the destination's, entirely on device."""
        from brpc_tpu.ici.block_pool import _slice_bytes
        piece = _slice_bytes(src.block.view(), self._offset(src),
                             self.page_bytes)
        self._splice(dst.block, piece, self._offset(dst))

    def _splice(self, block, piece, off: int) -> None:
        """dynamic_update_slice `piece` into `block`'s buffer at byte
        `off` and swap the slot atomically under the block pool's lock
        (the same replace-wholesale discipline put()/install() use, so
        concurrent splices to different blocks never interfere).  The
        whole read-modify-write holds this pool's ``_io_mu`` — without
        it, concurrent splices into sibling pages of one block would
        silently drop one write."""
        import jax
        if not isinstance(piece, jax.Array):
            piece = jax.device_put(np.ascontiguousarray(piece),
                                   self.pool.device)
        with self._io_mu:
            self._splice_locked(block, piece, off)

    def _splice_locked(self, block, piece, off: int) -> None:
        """One read-modify-write splice; caller holds ``_io_mu``."""
        from brpc_tpu.ici.block_pool import _splice_bytes
        with self.pool._lock:
            buf = self.pool._slots[block.size_class][block.slot]
        out = _splice_bytes(buf, piece, off)
        with self.pool._lock:
            self.pool._slots[block.size_class][block.slot] = out

    # ---- introspection / invariants ----

    def pages_in_use(self) -> int:
        with self._mu:
            return sum(1 for _, pages in self._blocks.values()
                       for p in pages if p.refs > 0)

    def blocks_leased(self) -> int:
        with self._mu:
            return len(self._blocks)

    def assert_consistent(self) -> None:
        """Invariant check for tests/chaos: free-listed pages have no
        refs, every page belongs to a live block entry, and no block is
        simultaneously released and referenced."""
        with self._mu:
            for p in self._free:
                assert p.refs == 0, f"free page with refs: {p}"
                assert self._bkey(p.block) in self._blocks, \
                    f"free page of released block: {p}"
            free_ids = {p.pid for p in self._free}
            for block, pages in self._blocks.values():
                for p in pages:
                    assert p.refs >= 0, p
                    if p.refs == 0:
                        assert p.pid in free_ids, \
                            f"idle page missing from free list: {p}"

    def stats(self) -> dict:
        with self._mu:
            total = len(self._blocks) * self.pages_per_block
            in_use = sum(1 for _, pages in self._blocks.values()
                         for p in pages if p.refs > 0)
            return {
                "page_bytes": self.page_bytes,
                "page_tokens": self.page_tokens,
                "pages_per_block": self.pages_per_block,
                "blocks_leased": len(self._blocks),
                "max_blocks": self.max_blocks,
                "pages_total": total,
                "pages_in_use": in_use,
                "pages_free": total - in_use,
                "page_allocs": self.page_allocs.get_value(),
                "page_frees": self.page_frees.get_value(),
                "batch_splices": self.batch_splices.get_value(),
                "block_leases": self.block_leases.get_value(),
                "block_releases": self.block_releases.get_value(),
            }
