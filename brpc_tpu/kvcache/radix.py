"""Radix/prefix tree mapping token prefixes to cached KV pages.

Keyed at PAGE granularity: every edge is one page's worth of tokens
(``page_tokens`` ids), so a node == one cached page and longest-prefix
match returns whole shared pages — a cache hit skips prefill for
exactly the tokens those pages cover, the "RPC Considered Harmful"
(arXiv:1805.08430) lesson applied to attention state: never recompute
(or re-ship) what the device already holds.

Refcount contract with :class:`~brpc_tpu.kvcache.pages.PagePool`:
the tree holds ONE ref on every page it retains.  Active sequences
hold their own refs, so an evictable page has ``refs == 1`` (tree
only) — eviction can NEVER free a page a live or forked sequence
still references, which is the safety property the chaos suite
asserts under injected pool exhaustion.

Eviction is LRU-by-leaf: leaves are the only removable nodes (an
interior node's pages are a prefix of its children's cached
sequences), ordered by a deterministic logical clock bumped on every
match — no wall-time in the decision, so seeded chaos runs replay.
"""
from __future__ import annotations

import itertools
import threading
from brpc_tpu.butil.lockprof import InstrumentedLock
from typing import Optional, Sequence

from brpc_tpu import fault


class _Node:
    __slots__ = ("chunk", "page", "children", "parent", "last_used")

    def __init__(self, chunk: tuple, page, parent: Optional["_Node"]):
        self.chunk = chunk              # page_tokens token ids
        self.page = page                # the KVPage holding their KV
        self.children: dict[tuple, _Node] = {}
        self.parent = parent
        self.last_used = 0


class RadixTree:
    """Prefix tree of cached KV pages (one page per node)."""

    def __init__(self, pagepool, *, name: str = "kv"):
        self.pagepool = pagepool
        self.page_tokens = pagepool.page_tokens
        self.name = name
        self._mu = InstrumentedLock("kvcache.radix")
        self._root = _Node((), None, None)
        self._clock = itertools.count(1)
        self._nodes = 0

    def _chunks(self, tokens: Sequence[int],
                max_chunks: Optional[int] = None):
        pt = self.page_tokens
        n = len(tokens) // pt
        if max_chunks is not None:
            n = min(n, max_chunks)
        return [tuple(int(t) for t in tokens[i * pt:(i + 1) * pt])
                for i in range(n)]

    # ---- lookup ----

    def match(self, tokens: Sequence[int], *,
              max_chunks: Optional[int] = None) -> list:
        """Longest cached prefix of `tokens`, in whole pages.  Returns
        the shared page handles in order; bumps LRU on the path.  The
        caller refs the pages it keeps — match itself takes none."""
        with self._mu:
            node = self._root
            pages = []
            now = next(self._clock)
            for chunk in self._chunks(tokens, max_chunks):
                child = node.children.get(chunk)
                if child is None:
                    break
                child.last_used = now
                pages.append(child.page)
                node = child
            return pages

    # ---- insert ----

    def insert(self, tokens: Sequence[int], pages: Sequence) -> int:
        """Cache `tokens`' full-page chunks backed by `pages` (aligned,
        one per chunk).  For each chunk not already cached the tree
        takes its own ref on the offered page; chunks already present
        keep their existing page (the caller's copy stays the
        caller's).  Returns how many pages the tree newly retained."""
        chunks = self._chunks(tokens, max_chunks=len(pages))
        retained = 0
        with self._mu:
            node = self._root
            now = next(self._clock)
            for chunk, page in zip(chunks, pages):
                child = node.children.get(chunk)
                if child is None:
                    self.pagepool.ref(page)
                    child = _Node(chunk, page, node)
                    node.children[chunk] = child
                    self._nodes += 1
                    retained += 1
                child.last_used = now
                node = child
        return retained

    # ---- eviction ----

    def evict(self, min_pages: int, span=None) -> int:
        """Free at least `min_pages` cached pages, LRU leaves first.
        Only pages with refcount 1 (tree-only) are candidates — a page
        an active/forked sequence still references is untouchable, as
        is every ancestor it pins.  Returns pages actually freed (may
        be < min_pages when the tree runs out of evictable leaves).
        ``span`` (the rpcz span of whoever forced the eviction — a
        page-alloc retry under pool pressure) gets the freed page ids
        annotated, so a timeline shows WHOSE cached prefixes paid."""
        if fault.ENABLED and fault.hit(
                "kvcache.evict", tree=self.name) is not None:
            raise MemoryError("injected KV eviction failure")
        freed = 0
        while freed < min_pages:
            # one DFS per ROUND collects every currently-evictable leaf
            # (LRU order), not one full scan per page — rounds only
            # repeat because evicting a leaf layer can expose its
            # parents as the next layer of leaves
            with self._mu:
                victims = []
                stack = [self._root]
                while stack:
                    n = stack.pop()
                    for c in n.children.values():
                        if c.children:
                            stack.append(c)
                        elif c.page.refs == 1:
                            victims.append(c)
                victims.sort(key=lambda v: v.last_used)
                victims = victims[: min_pages - freed]
                for v in victims:
                    del v.parent.children[v.chunk]
                self._nodes -= len(victims)
                pages = [v.page for v in victims]
            if not pages:
                break
            if span is not None and getattr(span, "trace_id", 0):
                pids = [p.pid for p in pages[:8]]
                span.annotate(
                    f"kv evict: freed {len(pages)} LRU cached pages "
                    f"(pids {pids}{'...' if len(pages) > 8 else ''})")
            # unref outside _mu: it may release whole blocks back to
            # the BlockPool (its own locking)
            for page in pages:
                self.pagepool.unref(page)
            freed += len(pages)
        return freed

    def evict_all(self) -> int:
        """Drop every evictable page (cache clear / shutdown): evict()
        already rounds until nothing is removable, so blocks pinned
        only by the cache return to the BlockPool baseline."""
        return self.evict(1 << 30)

    # ---- introspection ----

    def node_count(self) -> int:
        with self._mu:
            return self._nodes

    def cached_tokens(self) -> int:
        with self._mu:
            return self._nodes * self.page_tokens
