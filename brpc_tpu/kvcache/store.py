"""KVCacheStore — the engine-facing paged KV cache.

Ties :class:`~brpc_tpu.kvcache.pages.PagePool` (refcounted pages in
leased HBM blocks) and :class:`~brpc_tpu.kvcache.radix.RadixTree`
(longest-prefix reuse) behind the lifecycle the DecodeEngine drives:

  admit(prompt)  -> KVSeq whose cached-prefix pages are SHARED (the
                    engine prefills only the suffix — a cache hit is
                    compute skipped, not recomputed);
  extend(seq, t) -> one generated token's KV appended; allocates a new
                    page at page boundaries and copies-on-write when
                    the tail page is shared with the tree or a fork;
  fork(seq)      -> a second sequence sharing every page (speculative /
                    divergent continuations); divergence is isolated by
                    the extend-path COW;
  retire(seq)    -> full-page chunks are offered to the radix tree
                    (future admits hit them), every seq ref drops, and
                    idle blocks return to the BlockPool.

Draft leases (ISSUE 11 — speculative decoding): the engine's
propose->verify->commit loop appends DRAFT tokens it may throw away:

  speculate(seq, toks) -> append draft tokens WITHOUT materializing
                    (``kv_filled`` does not advance, nothing
                    live-commits — the radix tree can never serve an
                    unverified draft);
  rollback(seq, n)  -> truncate back to `n` tokens, releasing the
                    rejected tail's pages to the pool (never below the
                    materialized prefix);
  commit_draft(seq, n) -> accept: materialization advances over the
                    verified prefix (vector-KV callers advance it via
                    ``write_kv_batch`` instead — splicing the verified
                    rows IS the commit).

Tree-shaped drafts put side branches on ``fork``: the fork shares the
base pages, its first speculate copies-on-write the shared tail, and a
rejected branch retires — refcounts return to baseline by the same
discipline every other holder uses.

Pool pressure: when the page pool is exhausted the store evicts
LRU-by-leaf from the radix tree and retries once — eviction can only
free pages nothing else references, so exhaustion under load degrades
hit-rate, never correctness.

Crash recovery (``detach``): a supervisor tearing down a crashed
engine detaches each in-flight sequence — its full-page chunks are
committed to the radix tree ATOMICALLY with a recovery pin (extra
refs), so re-admitting the request hits the committed prefix and
re-decodes only the uncommitted tail, and pressure eviction cannot
free that prefix in the detach->re-admit window.

Locking is fine-grained: the store-wide lock covers only the
match/ref/insert/evict compositions (where a ref must be taken before
eviction could observe the page) and the seq-lifecycle bookkeeping.
The cold-admit device splice — writing a long uncached suffix to HBM —
runs OUTSIDE it: the suffix pages are exclusively owned and the
PagePool serializes raw splices itself, so a long uncached prompt no
longer stalls concurrent ``acquire_prefix``/``extend``/batch
formation behind its device writes.

Instrumented on /vars (and the /kvcache console page): hit-rate
(prefix tokens reused / prompt tokens seen), pages in use, evictions,
copy-on-write forks, admit/retire/fork counters, radix-tree size.
"""
from __future__ import annotations

import itertools
import re
import threading
from typing import Optional, Sequence

import numpy as np

from brpc_tpu import fault, rpcz
from brpc_tpu.bvar import Adder, PassiveStatus
from brpc_tpu.kvcache.pages import KVPage, PagePool
from brpc_tpu.kvcache.radix import RadixTree

_seq_ids = itertools.count(1)


class MissingShippedPrefix(ValueError):
    """An incremental migration import (``import_prefix(have > 0)``)
    found the peer's already-shipped prefix chunks evicted — the peer
    must fall back to a full send."""


class RecoveryPin:
    """Refs taken by :meth:`KVCacheStore.detach` on a crashed
    sequence's committed prefix pages.  While held, pressure eviction
    cannot free that prefix; ``release()`` (idempotent) drops the refs
    once the request has been re-admitted (admission takes its own
    refs on the pages it matches)."""

    __slots__ = ("_store", "_pages", "tokens")

    def __init__(self, store, pages, tokens: int):
        self._store = store
        self._pages = list(pages)
        self.tokens = tokens          # committed prefix length pinned

    def release(self) -> None:
        pages, self._pages = self._pages, []
        if pages:
            self._store.release(pages)

    def __len__(self) -> int:
        return len(self._pages)


class KVSeq:
    """One sequence's view of the cache: its materialized tokens and
    the page table covering them.  ``prefill_from`` is where compute
    must start — everything before it was served from shared pages."""

    __slots__ = ("seq_id", "tokens", "pages", "prefill_from", "retired",
                 "span", "committed_full", "kv_filled")

    def __init__(self):
        self.seq_id = next(_seq_ids)
        self.tokens: list[int] = []
        self.pages: list[KVPage] = []
        self.prefill_from = 0
        self.retired = False
        # full pages already committed LIVE to the radix tree (the
        # commit_live_pages streaming-commit cursor) — counts pages,
        # monotone, so each boundary commits only the new chunk
        self.committed_full = 0
        # MATERIALIZATION cursor (ISSUE 10): how many leading positions
        # hold real KV bytes.  Harness mode writes the token-id
        # stand-in at append, so it tracks len(tokens); vector mode
        # (a real ModelRunner) materializes a position only when
        # ``write_kv`` lands its packed K/V vectors — the final
        # generated token is never stepped, so its slot never fills,
        # and every caching path caps at this cursor so the radix tree
        # can never serve a page whose tail slot was never written
        self.kv_filled = 0
        # the owning generation's rpcz span (ISSUE 5): KV events on this
        # sequence — COW, page-alloc retries, pressure evictions, detach
        # — annotate it.  NULL_SPAN when tracing is off: every annotate
        # below is a guarded no-op.
        self.span = rpcz.NULL_SPAN

    @property
    def prefix_hit_tokens(self) -> int:
        return self.prefill_from

    def page_ids(self) -> list[int]:
        return [p.pid for p in self.pages]


class KVCacheStore:
    """Paged KV cache with radix prefix reuse (see module docstring)."""

    def __init__(self, pool=None, device=None, *,
                 page_bytes: int = 1024, page_tokens: int = 16,
                 max_blocks: int = 8, commit_live_pages: bool = False,
                 vector_kv: bool = False,
                 name: str = "kv"):
        self.pagepool = PagePool(pool, device, page_bytes=page_bytes,
                                 page_tokens=page_tokens,
                                 max_blocks=max_blocks, name=name)
        self.radix = RadixTree(self.pagepool, name=name)
        self.page_tokens = self.pagepool.page_tokens
        # vector-KV mode (ISSUE 10): pages hold REAL packed K/V vectors
        # written by a ModelRunner through write_kv, so the append path
        # skips the token-id stand-in splice (lifecycle/COW/radix
        # bookkeeping unchanged — the tree is keyed on token ids either
        # way) and materialization is tracked by seq.kv_filled instead
        # of len(tokens)
        self.vector_kv = bool(vector_kv)
        # streaming commit (ISSUE 7): every page a live sequence FILLS
        # is inserted into the radix tree right away instead of at
        # retire/detach, so a StandbySync (or a reader racing a long
        # generation) can acquire_prefix the finished pages while the
        # sequence is still decoding.  Safe: only FULL pages commit, the
        # tree takes its own refs, and the partially-written tail stays
        # exclusive — the next extend never COWs against the tree.
        self.commit_live_pages = bool(commit_live_pages)
        self.name = name
        # NAMED hot lock (ISSUE 6): acquire_prefix/extend/evict/retire
        # all serialize here — its wait/hold ledger row on
        # /hotspots/locks is the fine-grained-locking scorecard
        from brpc_tpu.butil.lockprof import InstrumentedLock
        self._mu = InstrumentedLock("kvcache.store", threading.RLock())
        self._live = 0                   # admitted-but-not-retired seqs

        safe = re.sub(r"\W", "_", name)
        # record the EXACT names exposed here so close() hides only this
        # store's variables (the serving-layer discipline)
        from brpc_tpu.bvar.variable import exposed_variables
        pre = set(exposed_variables(f"kvcache_{safe}*"))
        self.hit_tokens = Adder(f"kvcache_{safe}_hit_tokens")
        self.prompt_tokens = Adder(f"kvcache_{safe}_prompt_tokens")
        self.evictions = Adder(f"kvcache_{safe}_evictions")
        self.cow = Adder(f"kvcache_{safe}_cow_forks")
        self.admitted = Adder(f"kvcache_{safe}_admitted")
        self.retired = Adder(f"kvcache_{safe}_retired")
        self.forks = Adder(f"kvcache_{safe}_forks")
        self.speculated = Adder(f"kvcache_{safe}_speculated_tokens")
        self.rolled_back = Adder(f"kvcache_{safe}_rolled_back_pages")
        self.detached = Adder(f"kvcache_{safe}_detached")
        self.imported = Adder(f"kvcache_{safe}_imported_pages")
        PassiveStatus(self.hit_rate).expose(f"kvcache_{safe}_hit_rate")
        PassiveStatus(self.pagepool.pages_in_use).expose(
            f"kvcache_{safe}_pages_in_use")
        PassiveStatus(self.radix.node_count).expose(
            f"kvcache_{safe}_radix_nodes")
        self._bvar_names = [n for n in exposed_variables(f"kvcache_{safe}*")
                            if n not in pre]
        from brpc_tpu import kvcache as _kvcache
        _kvcache._register_store(self)

    # ---- lifecycle ----

    def admit(self, prompt: Sequence[int], *,
              span=None) -> KVSeq:
        """Start a sequence for `prompt`: its longest cached prefix is
        served by SHARED pages (capped at len(prompt)-1 so at least one
        token always computes — the model needs the last position's
        output), fresh pages hold the suffix's KV.  ``span`` (an rpcz
        span) becomes the sequence's owning span: prefix hit/miss, COW,
        eviction and page-alloc-retry events annotate it."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        with self._mu:
            # match+ref is the one composition that MUST be atomic
            # against eviction: between match returning a tree-only
            # page (refs==1) and our ref, an evict could free it
            max_chunks = (len(prompt) - 1) // self.page_tokens
            shared = self.radix.match(prompt, max_chunks=max_chunks)
            seq = KVSeq()
            if span is not None:
                seq.span = span
            for p in shared:
                self.pagepool.ref(p)
                seq.pages.append(p)
        hit = len(shared) * self.page_tokens
        seq.tokens = prompt[:hit]
        seq.prefill_from = hit
        seq.kv_filled = hit     # cached pages hold materialized KV
        if seq.span is not rpcz.NULL_SPAN:
            seq.span.annotate(
                f"kv admit: prefix_hit={hit}/{len(prompt)} tokens "
                f"({len(shared)} shared pages)" if hit else
                f"kv admit: prefix miss ({len(prompt)} tokens uncached)")
        try:
            # the cold-admit device splice runs OUTSIDE the store lock
            # (ROADMAP open item): the suffix pages are exclusively
            # ours and the PagePool serializes raw splices itself, so
            # a long uncached prompt cannot stall concurrent
            # acquire_prefix/extend/batch formation behind its writes
            self._append_run(seq, prompt[hit:])
        except BaseException:
            # a failed admit must not leak the refs already taken
            for p in seq.pages:
                self.pagepool.unref(p)
            raise
        # count the hit only once the admit SUCCEEDS — a failed
        # admit skipped no compute and must not inflate hit-rate
        self.hit_tokens.add(hit)
        self.prompt_tokens.add(len(prompt))
        self.admitted.add(1)
        with self._mu:
            self._live += 1
        return seq

    def extend(self, seq: KVSeq, token: int) -> None:
        """Append one generated token's KV to `seq`."""
        with self._mu:
            if seq.retired:
                raise RuntimeError(f"extend on retired seq {seq.seq_id}")
            self._append(seq, int(token))

    def write_kv(self, seq: KVSeq, pos: int, rows, *,
                 final: bool = True) -> None:
        """Materialize REAL K/V vectors (ISSUE 10): splice ``rows`` —
        ``[n, kv_bytes_per_token]`` uint8, one packed K/V payload per
        token — into `seq`'s pages at positions ``[pos, pos + n)``.
        Positions must already be appended (admit/extend own the page
        table; this writes payloads, it never grows the table).  A
        target page shared with the radix tree or a fork is
        copied-on-write first, exactly like the extend-path tail COW —
        a runner rewriting a committed position can never corrupt
        another holder's KV.

        ``final=True`` (the default) declares the slots COMPLETE:
        ``seq.kv_filled`` advances (the caching cap) and the streaming
        commit runs.  A multi-pass writer — the runner's per-layer
        prefill, which rewrites the same slots once per layer — MUST
        pass ``final=False`` until its last pass, or a half-written
        slot (upper layers still zero) could be committed to the radix
        tree / pinned by a detach and served to a future admit as
        valid KV."""
        failures = self.write_kv_batch([(seq, pos, rows)], final=final)
        if failures:
            raise failures[0][1]

    def write_kv_batch(self, writes, *, final: bool = True) -> list:
        """The BATCHED decode-side write primitive (ISSUE 11): splice
        many sequences' K/V rows — ``writes`` is a sequence of
        ``(seq, pos, rows)`` with :meth:`write_kv` semantics — in ONE
        pool batch (one host-to-device transfer, one splice critical
        section; :meth:`~brpc_tpu.kvcache.pages.PagePool.write_slots_batch`)
        instead of a device round-trip per slot.  Both the plain decode
        step and the speculative verify-commit ride this.

        Per-item isolation: a write whose validation or COW fails is
        SKIPPED and reported — the healthy slots' rows still land, so
        one exhausted sequence cannot starve its step-mates.  Returns
        ``[(index, exception), ...]`` for the failed items (empty when
        all landed); a pool-level batch failure fails every surviving
        item."""
        staged = []               # (write index, seq, pos, rows, runs)
        failures: list = []
        with self._mu:
            for wi, (seq, pos, rows) in enumerate(writes):
                try:
                    rows = np.ascontiguousarray(rows, dtype=np.uint8)
                    n = rows.shape[0]
                    if seq.retired:
                        raise RuntimeError(
                            f"write_kv on retired seq {seq.seq_id}")
                    if pos < 0 or pos + n > len(seq.tokens):
                        raise ValueError(
                            f"write_kv [{pos},{pos + n}) exceeds "
                            f"materialized tokens ({len(seq.tokens)})")
                    runs = []
                    idx = 0
                    while idx < n:
                        p = pos + idx
                        pi = p // self.page_tokens
                        slot = p % self.page_tokens
                        page = seq.pages[pi]
                        if page.refs > 1:
                            # copy-on-write: the target page is shared
                            # (radix tree, fork, live commit) — writing
                            # in place would corrupt the other
                            # holder's view
                            if seq.span is not rpcz.NULL_SPAN:
                                seq.span.annotate(
                                    f"kv cow: page {page.pid} shared "
                                    f"(refs={page.refs}), copied "
                                    f"before KV write")
                            fresh = self._alloc_page(span=seq.span)
                            try:
                                self.pagepool.copy_page(fresh, page)
                            except BaseException:
                                self.pagepool.unref(fresh)
                                raise
                            seq.pages[pi] = fresh
                            self.pagepool.unref(page)
                            self.cow.add(1)
                            page = fresh
                        k = min(self.page_tokens - slot, n - idx)
                        runs.append((page, slot, rows[idx:idx + k]))
                        idx += k
                except Exception as e:
                    failures.append((wi, e))
                    continue
                staged.append((wi, seq, pos, rows.shape[0], runs))
            if not staged:
                return failures
            try:
                self.pagepool.write_slots_batch(
                    [r for _, _, _, _, runs in staged for r in runs])
            except Exception as e:
                failures.extend((wi, e) for wi, _, _, _, _ in staged)
                return failures
            if final:
                for _, seq, pos, n, _ in staged:
                    seq.kv_filled = max(seq.kv_filled, pos + n)
                    self._commit_live(seq)
        return failures

    def fork(self, seq: KVSeq) -> KVSeq:
        """A second sequence sharing every page of `seq` (divergent
        continuations isolate via copy-on-write on extend)."""
        with self._mu:
            if seq.retired:
                raise RuntimeError(f"fork on retired seq {seq.seq_id}")
            child = KVSeq()
            child.tokens = list(seq.tokens)
            child.prefill_from = len(seq.tokens)
            child.kv_filled = min(seq.kv_filled, len(seq.tokens))
            for p in seq.pages:
                self.pagepool.ref(p)
                child.pages.append(p)
            self.forks.add(1)
            self._live += 1
            return child

    # ---- draft leases (ISSUE 11: speculative decoding) ----

    def speculate(self, seq: KVSeq, tokens: Sequence[int]) -> None:
        """Append DRAFT tokens to `seq` without materializing them:
        pages are allocated (and a shared tail copies-on-write) exactly
        like :meth:`extend`, but ``kv_filled`` holds and nothing
        live-commits — verification decides whether these positions
        ever become real.  Pair with :meth:`rollback` (reject) and
        :meth:`commit_draft` / ``write_kv_batch`` (accept)."""
        if not tokens:
            return
        with self._mu:
            if seq.retired:
                raise RuntimeError(
                    f"speculate on retired seq {seq.seq_id}")
            self._append_run(seq, tokens, materialize=False)
            self.speculated.add(len(tokens))

    def rollback(self, seq: KVSeq, keep_tokens: int) -> int:
        """Reject a draft tail: truncate `seq` back to its first
        `keep_tokens` tokens and release the pages past the boundary
        to the pool (the chaos suite's zero-leaked-draft-pages
        discipline).  Never cuts below the materialized prefix — real
        KV is not un-written by a rejected speculation.  Returns the
        pages released."""
        keep = int(keep_tokens)
        with self._mu:
            if seq.retired:
                raise RuntimeError(
                    f"rollback on retired seq {seq.seq_id}")
            if keep > len(seq.tokens):
                raise ValueError(
                    f"rollback to {keep} > {len(seq.tokens)} tokens")
            if keep < seq.kv_filled:
                raise ValueError(
                    f"rollback to {keep} would cut the materialized "
                    f"prefix (kv_filled={seq.kv_filled})")
            del seq.tokens[keep:]
            need = -(-keep // self.page_tokens)
            dropped, seq.pages = seq.pages[need:], seq.pages[:need]
            for p in dropped:
                self.pagepool.unref(p)
            if dropped:
                self.rolled_back.add(len(dropped))
            return len(dropped)

    def commit_draft(self, seq: KVSeq, upto: int) -> None:
        """Accept a verified draft prefix: the materialization cursor
        advances to `upto` tokens and the streaming commit runs.  The
        harness path's commit — the token-id stand-in bytes were
        already spliced at :meth:`speculate` time.  Vector-KV callers
        commit by splicing the verified rows through
        :meth:`write_kv_batch` instead (``final=True`` advances the
        cursor); calling this without real bytes in the slots would
        declare garbage attendable."""
        upto = int(upto)
        with self._mu:
            if seq.retired:
                raise RuntimeError(
                    f"commit_draft on retired seq {seq.seq_id}")
            if upto > len(seq.tokens):
                raise ValueError(
                    f"commit_draft to {upto} > {len(seq.tokens)} tokens")
            if upto > seq.kv_filled:
                seq.kv_filled = upto
                self._commit_live(seq)

    def retire(self, seq: KVSeq, *, cache: bool = True) -> None:
        """End a sequence.  With ``cache=True`` its full-page chunks
        are offered to the radix tree (the tree takes its own refs), so
        the next prompt sharing this prefix hits.  All of the
        sequence's refs drop either way; fully-idle blocks return to
        the BlockPool."""
        with self._mu:
            if seq.retired:
                return
            seq.retired = True
            if cache:
                nfull = self._cacheable_full(seq)
                if nfull:
                    self.radix.insert(seq.tokens[:nfull * self.page_tokens],
                                      seq.pages[:nfull])
            for p in seq.pages:
                self.pagepool.unref(p)
            seq.pages = []
            self.retired.add(1)
            self._live -= 1

    def detach(self, seq: KVSeq) -> RecoveryPin:
        """Crash-recovery re-attach API: atomically commit a LIVE
        sequence's full-page chunks to the radix tree, take a recovery
        ref on the committed pages, and retire the sequence.  The next
        ``admit`` of ``seq.tokens + ...`` prefix-hits the committed
        pages (prefill-skip on recovery — only the uncommitted tail
        re-decodes), and the returned pin guarantees pressure eviction
        cannot free that prefix before the re-admit lands.  Atomicity
        matters: done as separate retire(cache=True) + acquire_prefix
        calls, eviction could strike between them and recovery would
        silently degrade to a full replay."""
        with self._mu:
            if seq.retired:
                return RecoveryPin(self, [], 0)
            nfull = self._cacheable_full(seq)
            pinned: list = []
            if nfull:
                toks = seq.tokens[:nfull * self.page_tokens]
                self.radix.insert(toks, seq.pages[:nfull])
                # pin the pages the TREE actually holds (an already-
                # cached chunk keeps the tree's page, not this seq's
                # copy) — those are the ones a re-admit will match
                pinned = self.radix.match(toks, max_chunks=nfull)
                for p in pinned:
                    self.pagepool.ref(p)
            seq.retired = True
            for p in seq.pages:
                self.pagepool.unref(p)
            seq.pages = []
            self.detached.add(1)
            self.retired.add(1)
            self._live -= 1
            if seq.span is not rpcz.NULL_SPAN:
                seq.span.annotate(
                    f"kv detach: {nfull} full pages committed to the "
                    f"radix tree, {len(pinned)} pinned for recovery "
                    f"({len(pinned) * self.page_tokens} tokens)")
            return RecoveryPin(self, pinned,
                               len(pinned) * self.page_tokens)

    def import_prefix(self, tokens: Sequence[int], payloads,
                      *, have: int = 0, span=None) -> int:
        """Migration splice (ISSUE 7): install `payloads` — one raw
        page of KV bytes per full-page chunk of `tokens` past the
        first `have`, exported by a PEER store's
        :meth:`~brpc_tpu.kvcache.pages.PagePool.page_slice` — as
        COMMITTED radix nodes, so the next ``admit`` of a prompt
        opening with `tokens` prefix-hits state this process never
        computed.  ``have`` is the incremental-shipping offset: the
        peer believes this store already holds the first `have`
        chunks; if eviction has since dropped any of them the import
        raises ``MissingShippedPrefix`` (a DEFINITE signal — the peer
        falls back to a full send) rather than splicing a chain whose
        head is gone.

        All-or-nothing: pages are allocated and spliced first, then
        the whole chunk chain inserts into the tree under the store
        lock (the `have`-prefix check is atomic with the insert); ANY
        failure (allocation pressure with a dry tree, a bad payload,
        the ``migrate.splice`` fault site) rolls every already-spliced
        page back to the pool — a half-imported radix chain would
        serve a prefix whose tail was never written.  Chunks the tree
        already holds keep their existing pages (the arriving copy is
        dropped — refcounts stay baseline).  Returns how many pages
        the tree newly retained."""
        tokens = [int(t) for t in tokens]
        nfull = len(tokens) // self.page_tokens
        payloads = list(payloads)
        have = int(have)
        if have < 0 or have >= nfull or nfull == 0 \
                or len(payloads) != nfull - have:
            raise ValueError(
                f"import_prefix: {len(payloads)} payload pages for "
                f"chunks {have}..{nfull} ({len(tokens)} tokens at "
                f"{self.page_tokens}/page)")
        fresh: list[KVPage] = []
        try:
            for i in range(nfull - have):
                if fault.ENABLED and fault.hit(
                        "migrate.splice", store=self.name,
                        page=have + i) is not None:
                    raise MemoryError(
                        "injected migration splice failure")
                page = self._alloc_page(span=span)
                fresh.append(page)
                self.pagepool.write_raw(page, payloads[i])
            with self._mu:
                pre: list = []
                if have:
                    # the peer skipped these chunks as already-shipped;
                    # verify atomically with the insert — between its
                    # last send and now, eviction may have dropped them
                    pre = self.radix.match(tokens, max_chunks=have)
                    if len(pre) < have:
                        raise MissingShippedPrefix(
                            f"incremental import expected {have} "
                            f"resident chunks, found {len(pre)}")
                retained = self.radix.insert(
                    tokens[:nfull * self.page_tokens],
                    list(pre) + fresh)
        except BaseException:
            # rollback: every allocated page returns to the pool; the
            # tree never saw a partial chain
            for page in fresh:
                self.pagepool.unref(page)
            raise
        # drop the allocation refs — retained pages live on the tree's
        # own refs; duplicate chunks' pages go straight back to the pool
        for page in fresh:
            self.pagepool.unref(page)
        self.imported.add(retained)
        if span is not None and span is not rpcz.NULL_SPAN:
            span.annotate(
                f"kv import: {retained}/{nfull - have} migrated pages "
                f"spliced as committed radix nodes (chunks "
                f"{have}..{nfull}, {nfull * self.page_tokens} tokens)")
        return retained

    # ---- internals ----

    def _cacheable_full(self, seq: KVSeq) -> int:
        """Full pages eligible for the radix tree: bounded by the
        MATERIALIZED prefix (ISSUE 10) — in vector-KV mode the last
        generated token's slot never holds real vectors (it is never
        stepped), so a page it lands in must not be cached and later
        served as valid KV.  Harness mode: kv_filled == len(tokens),
        identical behavior to before."""
        return min(len(seq.tokens), seq.kv_filled) // self.page_tokens

    def _append(self, seq: KVSeq, token: int) -> None:
        self._append_run(seq, [token])

    def _append_run(self, seq: KVSeq, tokens: Sequence[int],
                    materialize: bool = True) -> None:
        """Append tokens in PAGE-SIZED runs: one device splice per page
        touched, not one per token — the difference dominates cold-admit
        latency for long uncached suffixes."""
        idx, n = 0, len(tokens)
        while idx < n:
            pos = len(seq.tokens)
            slot = pos % self.page_tokens
            if slot == 0:
                seq.pages.append(self._alloc_page(span=seq.span))
            else:
                tail = seq.pages[-1]
                if tail.refs > 1:
                    # copy-on-write: the tail page is shared (radix tree
                    # or a forked sequence) — writing in place would
                    # corrupt the other holder's KV.  Copy device-to-
                    # device, swap our table entry, drop our ref on the
                    # shared page.
                    if seq.span is not rpcz.NULL_SPAN:
                        seq.span.annotate(
                            f"kv cow: tail page {tail.pid} shared "
                            f"(refs={tail.refs}), copied before write")
                    fresh = self._alloc_page(span=seq.span)
                    try:
                        self.pagepool.copy_page(fresh, tail)
                    except BaseException:
                        self.pagepool.unref(fresh)
                        raise
                    seq.pages[-1] = fresh
                    self.pagepool.unref(tail)
                    self.cow.add(1)
            k = min(self.page_tokens - slot, n - idx)
            run = [int(t) for t in tokens[idx:idx + k]]
            if not self.vector_kv:
                # harness mode: the token-id stand-in IS the KV payload
                # — the splice materializes the slot.  Vector mode skips
                # it entirely: the ModelRunner's write_kv fills the slot
                # with real vectors (and skipping saves one splice per
                # appended page)
                self.pagepool.write(seq.pages[-1], slot, run)
            seq.tokens.extend(run)
            idx += k
        if not materialize:
            # draft append (speculate): the token-id stand-in bytes are
            # in place (harness mode) but the MATERIALIZATION cursor
            # holds — an unverified draft must never live-commit, cache
            # at retire, or be pinned by a detach
            return
        if not self.vector_kv:
            seq.kv_filled = len(seq.tokens)
        self._commit_live(seq)

    def _commit_live(self, seq: KVSeq) -> None:
        if not self.commit_live_pages:
            return
        # streaming commit: every newly FILLED page joins the radix
        # tree now (the tree refs it; this seq keeps its own ref),
        # so acquire_prefix/export sees a live generation's finished
        # pages without waiting for retire/detach.  Capped at the
        # materialized prefix (vector mode: a page whose tail slot
        # lacks real vectors commits one write_kv later)
        nfull = self._cacheable_full(seq)
        if nfull > seq.committed_full:
            self.radix.insert(seq.tokens[:nfull * self.page_tokens],
                              seq.pages[:nfull])
            seq.committed_full = nfull

    def _alloc_page(self, span=None) -> KVPage:
        """Page allocation with pressure-driven eviction: on
        exhaustion, evict one block's worth of LRU leaves from the
        radix tree and retry — LOOPING while eviction keeps freeing,
        because with the cold-admit path outside the store lock a
        CONCURRENT allocator may steal the pages this thread's evict
        just freed (the thief made progress; this thread evicts more).
        Exhaustion degrades hit-rate, never correctness, until the
        tree is genuinely dry.  Each evict runs under the store lock —
        every eviction path does, so a concurrent
        admit/acquire_prefix can never ref a page eviction is mid-way
        through freeing.  ``span`` (the allocating sequence's owning
        rpcz span) gets one annotation per retry — a slow extend under
        pool pressure shows WHY on the timeline."""
        while True:
            try:
                return self.pagepool.alloc_page()
            except MemoryError:
                with self._mu:
                    freed = self.radix.evict(
                        self.pagepool.pages_per_block, span=span)
                self.evictions.add(freed)
                if span is not None and span is not rpcz.NULL_SPAN:
                    span.annotate(
                        f"kv page_alloc retry: pool exhausted, evicted "
                        f"{freed} LRU cached pages")
                if freed == 0:
                    raise

    # ---- probes / maintenance ----

    def probe(self, tokens: Sequence[int]) -> int:
        """Non-mutating prefix-hit length in TOKENS for `tokens` (an
        ADVISORY answer — admission decisions only; nothing is pinned,
        so the pages may be evicted a microsecond later).  Takes no
        refs; bumps LRU so hot prefixes stay."""
        tokens = [int(t) for t in tokens]
        if not tokens:
            return 0
        max_chunks = (len(tokens) - 1) // self.page_tokens
        return len(self.radix.match(tokens, max_chunks=max_chunks)) \
            * self.page_tokens

    def acquire_prefix(self, tokens: Sequence[int], *,
                       full_pages: bool = False) -> tuple:
        """PINNED prefix lookup for compute that relies on the cached
        KV staying resident (the batcher's formation-time trim): like
        :meth:`probe`, but takes a ref on every matched page so
        eviction cannot free them mid-batch.  The default match is
        capped one token short of the prompt — admission semantics, at
        least one position always computes; ``full_pages=True`` lifts
        the cap to cover a final exactly-full page (the migration
        export wants the complete committed prefix).  Returns
        ``(hit_tokens, pages)``; the caller MUST hand `pages` back to
        :meth:`release` once its compute finishes."""
        tokens = [int(t) for t in tokens]
        if not tokens:
            return 0, []
        with self._mu:
            max_chunks = (len(tokens) if full_pages
                          else len(tokens) - 1) // self.page_tokens
            pages = self.radix.match(tokens, max_chunks=max_chunks)
            for p in pages:
                self.pagepool.ref(p)
            return len(pages) * self.page_tokens, list(pages)

    def acquire_pages(self, tokens: Sequence[int]) -> tuple:
        """Sugar for ``acquire_prefix(tokens, full_pages=True)`` — the
        migration-export spelling."""
        return self.acquire_prefix(tokens, full_pages=True)

    def release(self, pages) -> None:
        """Drop the refs taken by :meth:`acquire_prefix`."""
        with self._mu:
            for p in pages:
                self.pagepool.unref(p)

    def evict_pages(self, n: int) -> int:
        """Evict up to `n` LRU cached pages (degradation-ladder
        pressure relief — an overloaded supervisor trades hit-rate for
        headroom).  Returns pages actually freed."""
        with self._mu:
            freed = self.radix.evict(n)
        self.evictions.add(freed)
        return freed

    def clear(self) -> int:
        """Evict every cached (tree-only) page — after all sequences
        retire this returns block-pool occupancy to baseline.  Returns
        pages freed."""
        with self._mu:
            freed = self.radix.evict_all()
            self.evictions.add(freed)
            return freed

    def hit_rate(self) -> float:
        seen = self.prompt_tokens.get_value()
        return round(self.hit_tokens.get_value() / seen, 4) if seen else 0.0

    def close(self) -> None:
        """Drop the cache and unpin this store's bvars (bound-method
        PassiveStatus would otherwise keep it alive in the registry)."""
        self.clear()
        from brpc_tpu.bvar.variable import find_exposed
        for n in self._bvar_names:
            v = find_exposed(n)
            if v is not None:
                v.hide()

    def stats(self) -> dict:
        # deliberately lock-free: every value is a thread-safe bvar,
        # a sub-lock'd component, or an atomic int read — the console
        # and registry snapshots must not stall behind a long admit's
        # device writes (which hold _mu)
        live = self._live
        return {
            "page_tokens": self.page_tokens,
            "live_seqs": live,
            "hit_rate": self.hit_rate(),
            "hit_tokens": self.hit_tokens.get_value(),
            "prompt_tokens": self.prompt_tokens.get_value(),
            "admitted": self.admitted.get_value(),
            "retired": self.retired.get_value(),
            "forks": self.forks.get_value(),
            "speculated_tokens": self.speculated.get_value(),
            "rolled_back_pages": self.rolled_back.get_value(),
            "detached": self.detached.get_value(),
            "imported_pages": self.imported.get_value(),
            "cow_forks": self.cow.get_value(),
            "evictions": self.evictions.get_value(),
            "radix_nodes": self.radix.node_count(),
            "cached_tokens": self.radix.cached_tokens(),
            "pages": self.pagepool.stats(),
        }
