"""brpc_tpu.migrate — the cross-host KV data plane (ISSUE 7).

Three capabilities on one page-shipping core (README "Cross-host data
plane"):

  * :class:`PageMigrator` / :class:`MigrateService` (plane.py) — a
    committed radix prefix's pages (plus token runs, fingerprints and
    refcounts-at-source) ship over the DCN offer/pull fabric and
    splice into a peer :class:`~brpc_tpu.kvcache.KVCacheStore` as
    committed radix nodes; ``migrate_on_rebalance`` wires the
    prefix-affinity balancer's remap path to push warm prefixes to
    their new owner instead of recomputing;
  * disaggregated prefill/decode (disagg.py) — a
    :class:`PrefillReplica` runs admit+prefill and streams finished
    pages to a decode process (which installs them via the migration
    splice and runs only the decode loop), paired by a
    :class:`DisaggCoordinator` over DcnChannel;
  * cross-process failover (disagg.py) — :class:`StandbySync`
    write-ahead-streams emitted-token cursors and the live radix state
    to a :class:`StandbyReplica`, so a process death recovers the way
    an engine death does: exactly-once, bit-exact.

Every live migrator/service self-registers here (weakly) so the
``/migration`` console page renders route matrices and the
kvcache_migrate_* counters without holding components alive.
"""
from __future__ import annotations

import threading  # noqa: F401  (weakref tables below)
import weakref

from brpc_tpu.butil.lockprof import InstrumentedLock

_reg_mu = InstrumentedLock("migrate.registry")
_migrators: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()
_services: "weakref.WeakValueDictionary[int, object]" = \
    weakref.WeakValueDictionary()
_standby: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()


def _register_migrator(m) -> None:
    with _reg_mu:
        _migrators[m.name] = m


def _register_service(s) -> None:
    with _reg_mu:
        _services[id(s)] = s


def _register_standby(s) -> None:
    with _reg_mu:
        _standby[s.name] = s


def migration_snapshot() -> dict:
    """Live migration state — the /migration console page's data:
    global counters, per-migrator outbound route matrices, per-service
    inbound matrices, standby sync state, and the live offer-table
    size (must idle at zero — the ack-on-pull discipline)."""
    from brpc_tpu.ici import dcn
    from brpc_tpu.migrate import plane
    with _reg_mu:
        migrators = dict(_migrators)
        services = dict(_services)
        standby = dict(_standby)
    return {
        "counters": {
            "pages": plane.migrate_pages.get_value(),
            "bytes": plane.migrate_bytes.get_value(),
            "migrations_ok": plane.migrations_ok.get_value(),
            "migrations_failed": plane.migrations_failed.get_value(),
            "rollbacks": plane.migrate_rollbacks.get_value(),
            "zero_copy": plane.migrate_zero_copy.get_value(),
            "fallback": plane.migrate_fallback.get_value(),
            "splice_p99_us": round(
                plane.migrate_splice_rec.latency_percentile(0.99), 1),
            "live_offers": dcn.live_offer_count(),
        },
        "outbound": {name: m.stats()
                     for name, m in sorted(migrators.items())},
        "inbound": [s.stats() for _, s in sorted(services.items())],
        "standby": {name: s.stats()
                    for name, s in sorted(standby.items())},
    }


from brpc_tpu.migrate.plane import (  # noqa: E402,F401
    MIGRATE_SERVICE, MigrateService, PageMigrator, chunk_fingerprints,
    make_prefix_fetcher, rebalance_pusher, register_migration,
)
from brpc_tpu.migrate.disagg import (  # noqa: E402,F401
    DisaggCoordinator, PrefillReplica, StandbyReplica, StandbySync,
    register_disagg_decode, register_disagg_prefill, register_standby,
)
