"""Disaggregated prefill/decode and cross-process failover over the
KV migration plane (ISSUE 7 tentpole, capabilities b and c).

DISAGGREGATION.  Prefill and decode have opposite resource profiles
(compute-bound bursts vs memory-bandwidth-bound streaming); splitting
them into separate processes smooths both at scale.  The split here is
the migration plane applied end-to-end:

  * :class:`PrefillReplica` (the prefill process) runs admit+prefill
    against its own store — the prompt's uncached suffix is written to
    device pages (optionally through a caller-supplied
    :class:`~brpc_tpu.serving.DynamicBatcher`, reusing the batching
    stack on the prefill side), committed to the local radix tree, and
    the finished pages stream to the decode process through
    :class:`~brpc_tpu.migrate.PageMigrator` along with the
    emitted-prompt cursor;
  * the decode process installs them via the migration splice
    (``register_migration``) and runs ONLY the decode loop — its
    :class:`~brpc_tpu.serving.DecodeEngine` admission prefix-hits the
    migrated pages, so the slot pool never re-prefills what the
    prefill replica computed;
  * :class:`DisaggCoordinator` pairs the two over
    :class:`~brpc_tpu.ici.dcn.DcnChannel`: one ``generate`` call runs
    Prefill on the prefill address, then streams tokens from
    ``Serving.Generate`` on the decode address, under one rpcz trace.

A failed migration is a RECOMPUTE FALLBACK, never a failure: the
decode-side admit misses, prefills the suffix itself, and the
generation completes bit-exact — migration only moves work, it cannot
lose it.

FAILOVER.  PR 4's supervisor recovers an ENGINE death inside one
process; a process death needs the same cursor+pages state to already
live elsewhere.  :class:`StandbySync` wraps any engine-shaped
``submit`` and write-ahead-streams to a standby process:

  * the emitted-token cursor (token VALUES, not just counts) is
    appended to the standby BEFORE each token is delivered to the
    consumer, so the standby's record is always a superset of what any
    client saw;
  * the live radix state ships incrementally at page boundaries
    (``KVCacheStore(commit_live_pages=True)`` commits each page the
    moment it fills — the ``detach``/``RecoveryPin`` commit semantics
    applied continuously) through the same migration splice;
  * on primary death the client calls :meth:`StandbyReplica.assume`
    (directly or via the ``_standby`` service's streaming ``Assume``)
    with ITS OWN cursor: the standby replays the tokens the client
    never saw from the write-ahead record, then resumes decode from
    ``prompt + emitted`` — admission prefix-hits the migrated pages,
    so only the unshipped tail re-decodes.  Exactly-once and bit-exact
    by the same cursor argument the supervisor makes in-process.
"""
from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence

import numpy as np

from brpc_tpu import errors, rpcz
from brpc_tpu.butil import stagetag
from brpc_tpu.butil.lockprof import InstrumentedLock
from brpc_tpu.ici.dcn import DcnChannel
from brpc_tpu.migrate.plane import PageMigrator, register_migration
from brpc_tpu.rpc.service import Service, method

STANDBY_SERVICE = "_standby"

_sids = itertools.count(1)


# ---------------------------------------------------------------------------
# disaggregated prefill/decode
# ---------------------------------------------------------------------------

class PrefillReplica:
    """The prefill process's role: admit+prefill against a local store,
    then stream the finished pages (and the emitted-prompt cursor) to
    the decode process (see module docstring)."""

    def __init__(self, store, decode_addr: str, *,
                 batcher=None, runner=None, name: str = "prefill",
                 timeout_ms: int = 10_000):
        self.store = store
        self.decode_addr = decode_addr
        # the caller's DynamicBatcher (built around its prefill model
        # fn): concurrent Prefill RPCs coalesce into bucket-padded
        # batches exactly like the unary serving path
        self.batcher = batcher
        # a ModelRunner (ISSUE 10): the prefill replica runs the REAL
        # model's prefill against its admitted sequence — each layer's
        # suffix K/V splices into the local pages, and the migration
        # plane then ships pages holding real attention state the
        # decode process's paged kernel reads directly
        self.runner = runner
        self.name = name
        self.migrator = PageMigrator(store, name=f"{name}_migrator",
                                     timeout_ms=timeout_ms)
        self.prefills = 0
        self.fallbacks = 0
        self._mu = InstrumentedLock("migrate.prefill")

    def prefill(self, prompt: Sequence[int]) -> dict:
        """Run one prompt's prefill and ship its pages.  Returns the
        handoff record the coordinator forwards to the decode side:
        the emitted-prompt cursor, the local prefix hit, pages
        migrated, and whether the decode process must recompute
        (migration failed — the fallback, not an error)."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise errors.RpcError(errors.EREQUEST, "empty prompt")
        with stagetag.stage("prefill"):
            seq = self.store.admit(prompt)
            hit = seq.prefix_hit_tokens
            suffix = prompt[hit:]
            if self.runner is not None and suffix:
                try:
                    from brpc_tpu.models.runner import run_prefill
                    run_prefill(self.runner, seq, prompt)
                except Exception as e:
                    self.store.retire(seq, cache=False)
                    if isinstance(e, errors.RpcError):
                        raise
                    raise errors.RpcError(
                        errors.EINTERNAL,
                        f"model prefill failed: "
                        f"{type(e).__name__}: {e}")
            elif self.batcher is not None and suffix:
                try:
                    self.batcher.submit_wait(
                        np.asarray(suffix, np.float32), timeout_s=60)
                except errors.RpcError:
                    self.store.retire(seq, cache=False)
                    raise
            # commit: the prompt's full pages become radix state the
            # migrator can export
            self.store.retire(seq, cache=True)
        migrated, fallback = 0, False
        try:
            migrated = self.migrator.migrate(prompt, self.decode_addr)
        except errors.RpcError:
            # recompute fallback: the decode-side admit will miss and
            # prefill the suffix itself; the generation still completes
            fallback = True
        with self._mu:
            self.prefills += 1
            if fallback:
                self.fallbacks += 1
        return {"cursor": len(prompt), "prefix_hit": hit,
                "migrated_pages": migrated,
                "recompute_fallback": fallback}

    def stats(self) -> dict:
        with self._mu:
            return {"prefills": self.prefills,
                    "fallbacks": self.fallbacks,
                    "decode_addr": self.decode_addr}


class DisaggPrefillService(Service):
    NAME = "DisaggPrefill"

    def __init__(self, replica: PrefillReplica):
        self._replica = replica

    @method(request="json", response="json")
    def Prefill(self, cntl, req):
        prompt = (req or {}).get("prompt")
        if not prompt:
            cntl.set_failed(errors.EREQUEST, 'missing "prompt"')
            return None
        try:
            return self._replica.prefill(prompt)
        except errors.RpcError as e:
            cntl.set_failed(e.code, e.text)
            return None


def register_disagg_prefill(server, store, decode_addr: str, *,
                            batcher=None, runner=None,
                            name: str = "prefill",
                            timeout_ms: int = 10_000) -> PrefillReplica:
    """Stand up the PREFILL role on `server`: the DisaggPrefill service
    over a PrefillReplica shipping pages to `decode_addr`."""
    replica = PrefillReplica(store, decode_addr, batcher=batcher,
                             runner=runner, name=name,
                             timeout_ms=timeout_ms)
    server.add_service(DisaggPrefillService(replica))
    return replica


def register_disagg_decode(server, store, engine):
    """Stand up the DECODE role on `server`: the migration splice
    (pages arriving from prefill replicas land in `store`) plus the
    standard ``Serving.Generate`` stream over `engine` — whose
    admission prefix-hits the migrated pages, so this process runs
    only the decode loop."""
    from brpc_tpu.serving.service import register_serving
    svc = register_migration(server, store)
    register_serving(server, engine=engine)
    return svc


from brpc_tpu.rpc import StreamHandler as _StreamHandler


class _TokenCollector(_StreamHandler):
    """Client stream handler: parses ``{"token": t}`` / ``{"done"}``
    messages, forwards tokens, latches the terminal."""

    def __init__(self, emit: Optional[Callable[[int], None]] = None):
        self.tokens: list[int] = []
        self.error: Optional[int] = None
        self.done = threading.Event()
        self._emit = emit
        self._terminal_seen = False

    def on_received_messages(self, stream, messages):
        for m in messages:
            try:
                d = json.loads(m)
            except ValueError:
                continue
            if "token" in d:
                t = int(d["token"])
                self.tokens.append(t)
                if self._emit is not None:
                    self._emit(t)
            if d.get("done"):
                self._terminal_seen = True
                if d.get("error"):
                    self.error = int(d["error"])
                self.done.set()

    def on_closed(self, stream):
        if not self._terminal_seen:
            # the stream died before the {"done"} terminal: whatever
            # tokens arrived are a TRUNCATED stream, not a completed
            # generation — callers must never count it as success
            self.error = errors.EFAILEDSOCKET
        self.done.set()

    def on_idle_timeout(self, stream):
        pass


class DisaggCoordinator:
    """Pairs one prefill process and one decode process over DcnChannel
    and drives generations across the split (see module docstring)."""

    def __init__(self, prefill_addr: str, decode_addr: str, *,
                 timeout_ms: int = 20_000):
        self.prefill = DcnChannel(prefill_addr, timeout_ms=timeout_ms)
        self.decode = DcnChannel(decode_addr, timeout_ms=timeout_ms)
        self.timeout_ms = int(timeout_ms)

    def pair(self) -> tuple:
        """Handshake both roles (idempotent); returns their
        topologies."""
        return self.prefill.handshake(), self.decode.handshake()

    def generate(self, prompt: Sequence[int], max_new_tokens: int, *,
                 emit: Optional[Callable[[int], None]] = None,
                 timeout_s: float = 60.0) -> dict:
        """One generation across the split: Prefill on the prefill
        process (pages stream to the decode store), then tokens from
        ``Serving.Generate`` on the decode process.  Returns
        ``{"tokens", "prefill", "error"}``; the whole flow runs under
        one rpcz trace when tracing is on."""
        from brpc_tpu.rpc import Controller, stream_create
        prompt = [int(t) for t in prompt]
        span = rpcz.child_span("client", "Disagg", "Generate")
        prev = rpcz.get_current_span()
        if span is not rpcz.NULL_SPAN:
            rpcz.set_current_span(span)
        try:
            info = self.prefill.channel.call_sync(
                "DisaggPrefill", "Prefill", {"prompt": prompt},
                serializer="json", response_serializer="json")
            span.annotate(
                f"prefill handoff: cursor={info.get('cursor')} "
                f"migrated_pages={info.get('migrated_pages')} "
                f"fallback={info.get('recompute_fallback')}")
            col = _TokenCollector(emit)
            cntl = Controller(timeout_ms=self.timeout_ms)
            stream_create(cntl, col)
            self.decode.channel.call_sync(
                "Serving", "Generate",
                {"prompt": prompt, "max_new_tokens": int(max_new_tokens)},
                serializer="json", cntl=cntl)
            if not col.done.wait(timeout_s):
                raise errors.RpcError(errors.ERPCTIMEDOUT,
                                      "decode stream never finished")
            span.annotate(f"decoded {len(col.tokens)} tokens"
                          + (f" err={col.error}" if col.error else ""))
            if col.error:
                span.error_code = col.error
            return {"tokens": col.tokens, "prefill": info,
                    "error": col.error}
        except errors.RpcError as e:
            span.error_code = e.code
            raise
        finally:
            if span is not rpcz.NULL_SPAN:
                rpcz.set_current_span(prev)
            rpcz.submit(span)


# ---------------------------------------------------------------------------
# cross-process failover
# ---------------------------------------------------------------------------

class _StandbyGen:
    """One replicated generation on the standby: the write-ahead token
    record plus the assume-once guard."""

    __slots__ = ("sid", "prompt", "budget", "emitted", "finished",
                 "error_code", "assumed", "trace", "mu")

    def __init__(self, sid: int, prompt, budget: int, trace):
        self.sid = sid
        self.prompt = [int(t) for t in prompt]
        self.budget = int(budget)
        self.emitted: list[int] = []
        self.finished = False
        self.error_code = 0
        self.assumed = False
        self.trace = trace          # (trace_id, parent_span_id, sampled)
        self.mu = InstrumentedLock("migrate.standby_gen")


class StandbyReplica:
    """The standby process's role: hold each supervised generation's
    write-ahead record (prompt, budget, emitted tokens) beside a store
    the migration splice keeps warm, and — on ``assume`` — complete
    the generation exactly-once from the caller's cursor."""

    def __init__(self, store, engine, *, name: str = "standby"):
        self.store = store
        self.engine = engine
        self.name = name
        self._mu = InstrumentedLock("migrate.standby")
        self._gens: dict[int, _StandbyGen] = {}
        self.assumed_total = 0
        self.replayed_tokens = 0
        self.resumed_tokens = 0
        from brpc_tpu import migrate as _migrate
        _migrate._register_standby(self)

    # ---- the write-ahead record (driven by the primary's sync) ----

    def begin(self, sid: int, prompt, budget: int,
              trace: tuple = (0, 0, True)) -> None:
        with self._mu:
            if sid not in self._gens:
                self._gens[sid] = _StandbyGen(sid, prompt, budget, trace)

    def append(self, sid: int, cursor: int, toks: Sequence[int]) -> int:
        """Write-ahead append: `toks` are the tokens starting at
        position `cursor` of the generation's emitted stream.
        Idempotent against retries (an overlap keeps the first copy);
        a GAP is refused — the record must stay a prefix of the true
        stream or the replay guarantee dies.  Returns the new cursor."""
        with self._mu:
            g = self._gens.get(sid)
        if g is None:
            raise errors.RpcError(errors.EREQUEST,
                                  f"no standby record for sid {sid}")
        with g.mu:
            have = len(g.emitted)
            if cursor > have:
                raise errors.RpcError(
                    errors.EREQUEST,
                    f"append gap: cursor {cursor} but only {have} "
                    f"tokens recorded")
            fresh = list(toks)[have - cursor:]
            g.emitted.extend(int(t) for t in fresh)
            return len(g.emitted)

    def finish(self, sid: int, error_code: int = 0) -> None:
        with self._mu:
            g = self._gens.get(sid)
        if g is not None:
            with g.mu:
                g.finished = True
                g.error_code = int(error_code)

    # ---- failover ----

    def assume(self, sid: int, client_cursor: int,
               emit: Callable[[int], None],
               on_done: Optional[Callable] = None) -> dict:
        """Complete generation `sid` from the CLIENT's cursor: replay
        the write-ahead tokens the client never received, then resume
        decode from ``prompt + emitted`` on the local engine —
        admission prefix-hits whatever pages the migration splice
        already installed, so only the unshipped tail re-decodes.
        Exactly-once: a generation can be assumed once, and the
        write-ahead record is always a superset of any client's view.
        Returns ``{"replayed", "remaining", "prefix_hit_possible"}``;
        terminal state arrives via ``on_done(err)``."""
        with self._mu:
            g = self._gens.get(sid)
        if g is None:
            raise errors.RpcError(errors.EREQUEST,
                                  f"no standby record for sid {sid}")
        with g.mu:
            if g.assumed:
                raise errors.RpcError(
                    errors.EREQUEST,
                    f"sid {sid} already assumed (exactly-once)")
            g.assumed = True
            emitted = list(g.emitted)
            finished, err_code = g.finished, g.error_code
            tid, psid, smp = g.trace
        if client_cursor < 0 or client_cursor > len(emitted):
            raise errors.RpcError(
                errors.EREQUEST,
                f"client cursor {client_cursor} outside the recorded "
                f"stream ({len(emitted)} tokens)")
        with self._mu:
            self.assumed_total += 1
            self.replayed_tokens += len(emitted) - client_cursor
        # the assume attempt joins the generation's trace, mirroring a
        # supervisor re-admission (an attempt span per process epoch)
        span = rpcz.new_span("generation", "Standby", self.name,
                             trace_id=tid, parent_span_id=psid,
                             sampled=smp if tid else None)
        span.annotate(
            f"standby assume: sid={sid} client_cursor={client_cursor} "
            f"recorded={len(emitted)} replaying "
            f"{len(emitted) - client_cursor}")
        # replay: tokens the standby recorded (write-ahead) but the
        # client never saw — delivered before any freshly decoded one
        for t in emitted[client_cursor:]:
            emit(t)
        remaining = g.budget - len(emitted)
        if finished or remaining <= 0:
            err = None if not err_code else errors.RpcError(
                err_code, "primary recorded a failed terminal")
            span.annotate("nothing left to decode")
            rpcz.submit(span)
            if on_done is not None:
                on_done(err)
            return {"replayed": len(emitted) - client_cursor,
                    "remaining": 0}
        resume_prompt = g.prompt + emitted
        hit = 0
        try:
            hit = int(self.store.probe(resume_prompt))
        except Exception:
            pass
        span.annotate(
            f"resuming decode: {remaining} tokens from cursor "
            f"{len(emitted)}; migrated prefix hit covers {hit}/"
            f"{len(resume_prompt)} resume tokens")
        with self._mu:
            self.resumed_tokens += remaining

        def wrapped_emit(t: int) -> None:
            with g.mu:
                g.emitted.append(int(t))
            emit(t)

        def wrapped_done(err) -> None:
            with g.mu:
                g.finished = True
                g.error_code = err.code if err is not None else 0
            if err is not None:
                span.error_code = err.code
            rpcz.submit(span)
            if on_done is not None:
                on_done(err)

        try:
            self.engine.submit(resume_prompt, remaining, wrapped_emit,
                               wrapped_done,
                               trace_ctx=(span.trace_id, span.span_id,
                                          span.sampled))
        except TypeError:
            # engine-shaped submit without trace_ctx (a supervisor):
            # the attempt span still brackets the resume
            self.engine.submit(resume_prompt, remaining, wrapped_emit,
                               wrapped_done)
        return {"replayed": len(emitted) - client_cursor,
                "remaining": remaining, "resume_prefix_hit": hit}

    def stats(self) -> dict:
        with self._mu:
            gens = list(self._gens.values())
            out = {
                "live_records": sum(1 for g in gens if not g.finished),
                "records": len(gens),
                "assumed": self.assumed_total,
                "replayed_tokens": self.replayed_tokens,
                "resumed_tokens": self.resumed_tokens,
            }
        return out


class StandbyService(Service):
    """RPC surface of a StandbyReplica: Begin/Append/Finish feed the
    write-ahead record; the streaming Assume completes a generation
    for a failed-over client."""

    NAME = STANDBY_SERVICE

    def __init__(self, replica: StandbyReplica):
        self._replica = replica

    @method(request="json", response="json")
    def Begin(self, cntl, req):
        req = req or {}
        try:
            trace = tuple(req.get("trace") or (0, 0, True))
            self._replica.begin(int(req["sid"]), req.get("prompt") or [],
                                int(req.get("budget", 0)), trace)
        except (KeyError, TypeError, ValueError) as e:
            cntl.set_failed(errors.EREQUEST, f"bad Begin: {e}")
            return None
        return {"ok": True}

    @method(request="json", response="json")
    def Append(self, cntl, req):
        req = req or {}
        try:
            cur = self._replica.append(int(req["sid"]),
                                       int(req.get("cursor", 0)),
                                       req.get("toks") or [])
        except errors.RpcError as e:
            cntl.set_failed(e.code, e.text)
            return None
        except (KeyError, TypeError, ValueError) as e:
            cntl.set_failed(errors.EREQUEST, f"bad Append: {e}")
            return None
        return {"cursor": cur}

    @method(request="json", response="json")
    def Finish(self, cntl, req):
        req = req or {}
        try:
            self._replica.finish(int(req["sid"]),
                                 int(req.get("error", 0)))
        except (KeyError, TypeError, ValueError) as e:
            cntl.set_failed(errors.EREQUEST, f"bad Finish: {e}")
            return None
        return {"ok": True}

    @method(request="json", response="json")
    def Assume(self, cntl, req):
        req = req or {}
        stream = cntl.accept_stream()

        def emit(tok: int) -> None:
            stream.write(json.dumps({"token": tok}).encode(),
                         timeout_s=2.0)

        def on_done(err) -> None:
            msg = {"done": True}
            if err is not None:
                msg["error"] = err.code
                msg["error_text"] = err.text
            try:
                stream.write(json.dumps(msg).encode(), timeout_s=2.0)
            except errors.RpcError:
                pass
            stream.close()

        try:
            info = self._replica.assume(int(req["sid"]),
                                        int(req.get("cursor", 0)),
                                        emit, on_done)
        except errors.RpcError as e:
            cntl.set_failed(e.code, e.text)
            return None
        except (KeyError, TypeError, ValueError) as e:
            cntl.set_failed(errors.EREQUEST, f"bad Assume: {e}")
            return None
        if info.get("remaining", 0) == 0:
            # nothing left to decode: assume() already fired on_done,
            # which wrote the terminal and closed the stream
            pass
        return {"accepted": True, **info}


def register_standby(server, store, engine, *,
                     name: str = "standby") -> StandbyReplica:
    """Stand up the STANDBY role on `server`: the migration splice
    (the primary's page stream lands in `store`) plus the ``_standby``
    write-ahead/assume service over `engine`."""
    replica = StandbyReplica(store, engine, name=name)
    register_migration(server, store)
    server.add_service(StandbyService(replica))
    return replica


def assume_stream(standby_addr: str, sid: int, client_cursor: int, *,
                  emit: Optional[Callable[[int], None]] = None,
                  timeout_s: float = 60.0,
                  timeout_ms: int = 20_000) -> dict:
    """Failed-over client helper: call the standby's streaming
    ``Assume`` and collect the completed tail.  Returns
    ``{"tokens", "error", ...info}``."""
    from brpc_tpu.rpc import Channel, Controller, stream_create
    ch = Channel(standby_addr, timeout_ms=timeout_ms)
    col = _TokenCollector(emit)
    cntl = Controller(timeout_ms=timeout_ms)
    stream_create(cntl, col)
    info = ch.call_sync(STANDBY_SERVICE, "Assume",
                        {"sid": int(sid), "cursor": int(client_cursor)},
                        serializer="json", cntl=cntl)
    if not col.done.wait(timeout_s):
        raise errors.RpcError(errors.ERPCTIMEDOUT,
                              "standby assume stream never finished")
    return {"tokens": col.tokens, "error": col.error, **(info or {})}


class StandbySync:
    """Primary-side replication: wraps an engine-shaped ``submit`` so
    every generation's cursor write-ahead-streams to a standby process
    and its live radix state ships at page boundaries (see module
    docstring).  Pair the primary's store with
    ``commit_live_pages=True`` so filled pages are exportable while
    the generation is still decoding."""

    # terminal codes that mean THE PRIMARY broke, not the generation:
    # the standby record stays open so the client can assume
    FAILOVER_CODES = (errors.ELOGOFF, errors.EINTERNAL)

    def __init__(self, store, standby_addr: str, *,
                 submit_fn: Callable,
                 name: str = "standby_sync",
                 timeout_ms: int = 10_000,
                 ship_pages: bool = True):
        self.store = store
        self.standby_addr = standby_addr
        self.submit_fn = submit_fn
        self.name = name
        self.ship_pages = bool(ship_pages)
        # pairing over DcnChannel: the control RPCs ride the same
        # connection the topology handshake used
        self._ch = DcnChannel(standby_addr, timeout_ms=timeout_ms)
        self.migrator = PageMigrator(store, name=f"{name}_migrator",
                                     timeout_ms=timeout_ms)
        self._mu = InstrumentedLock("migrate.standby_sync")
        self._toks: dict[int, list[int]] = {}     # sid -> prompt+emitted
        self._shipped: dict[int, int] = {}        # sid -> full pages sent
        self._traces: dict[int, tuple] = {}
        self.sync_errors = 0
        self.ship_errors = 0
        self.synced_tokens = 0
        self.shipped_pages = 0
        # one ship worker: page exports are device reads + an RPC and
        # must not ride the emit path; jobs coalesce per sid to the
        # newest prefix
        self._ship_cv = threading.Condition(
            InstrumentedLock("migrate.ship"))
        self._ship_q: deque[int] = deque()
        self._ship_pending: set[int] = set()
        self._ship_inflight = 0     # jobs popped but not yet migrated
        self._running = True
        self._ship_thread = threading.Thread(
            target=self._ship_loop, daemon=True,
            name=f"kv-migrate-{name}")
        self._ship_thread.start()

    def _call(self, method_name: str, body: dict):
        return self._ch.channel.call_sync(
            STANDBY_SERVICE, method_name, body,
            serializer="json", response_serializer="json")

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               emit: Callable[[int], None],
               on_done: Optional[Callable] = None) -> int:
        """Engine-shaped submit with standby replication.  Returns the
        standby sid (hand it to the failed-over client: it is the
        ``Assume`` key)."""
        prompt = [int(t) for t in prompt]
        sid = next(_sids)
        trace = rpcz.current_trace_ctx()
        with self._mu:
            self._toks[sid] = list(prompt)
            self._shipped[sid] = 0
            self._traces[sid] = trace
        # Begin is synchronous and unconditional: a standby that never
        # heard of a sid cannot replay it
        self._call("Begin", {"sid": sid, "prompt": prompt,
                             "budget": int(max_new_tokens),
                             "trace": list(trace)})
        self._enqueue_ship(sid)   # the prompt's own pages, once admitted
        state_mu = InstrumentedLock("migrate.sync_state")
        synced = [0]               # tokens the standby ACKED
        pending: list[int] = []    # emitted but not yet acked

        def wrapped_emit(tok: int) -> None:
            tok = int(tok)
            # WRITE-AHEAD: the standby records the token before the
            # consumer sees it, so its record is a superset of any
            # client's view — replay-on-assume can only fill gaps,
            # never duplicate.  The cursor advances ONLY on ack: after
            # a transient sync failure the unacked tail rides along
            # with the next token, so the record self-heals instead of
            # freezing behind a permanent "append gap".
            with state_mu:
                pending.append(tok)
                cur = synced[0]
                batch = list(pending)
            try:
                self._call("Append", {"sid": sid, "cursor": cur,
                                      "toks": batch})
                with state_mu:
                    synced[0] = cur + len(batch)
                    del pending[:len(batch)]
                with self._mu:
                    self.synced_tokens += len(batch)
            except errors.RpcError:
                # standby unreachable: degraded (a failover now would
                # replay only up to the last acked cursor) but the
                # primary keeps serving and the tail retries next emit
                with self._mu:
                    self.sync_errors += 1
            boundary = False
            with self._mu:
                toks = self._toks.get(sid)
                if toks is not None:
                    toks.append(tok)
                    boundary = (len(toks) // self.store.page_tokens
                                > self._shipped.get(sid, 0))
            if boundary:
                self._enqueue_ship(sid)
            emit(tok)

        def wrapped_done(err) -> None:
            code = err.code if err is not None else 0
            if code not in self.FAILOVER_CODES:
                # a real terminal (success, or the generation's own
                # error): close the standby record
                try:
                    self._call("Finish", {"sid": sid, "error": code})
                except errors.RpcError:
                    with self._mu:
                        self.sync_errors += 1
                with self._mu:
                    self._toks.pop(sid, None)
                    self._shipped.pop(sid, None)
                    self._traces.pop(sid, None)
            # a FAILOVER code leaves the record open: the primary is
            # dying and the client's next stop is the standby's Assume
            if on_done is not None:
                on_done(err)

        self.submit_fn(prompt, int(max_new_tokens), wrapped_emit,
                       wrapped_done)
        return sid

    # ---- incremental page shipping ----

    def _enqueue_ship(self, sid: int) -> None:
        if not self.ship_pages:
            return
        with self._ship_cv:
            if sid not in self._ship_pending:
                self._ship_pending.add(sid)
                self._ship_q.append(sid)
                self._ship_cv.notify()

    def _ship_loop(self) -> None:
        while True:
            with self._ship_cv:
                while self._running and not self._ship_q:
                    self._ship_cv.wait(0.25)
                if not self._running:
                    return
                sid = self._ship_q.popleft()
                self._ship_pending.discard(sid)
                self._ship_inflight += 1
            try:
                self._ship_one(sid)
            finally:
                with self._ship_cv:
                    self._ship_inflight -= 1
                    self._ship_cv.notify_all()

    def _ship_one(self, sid: int) -> None:
        with self._mu:
            toks = list(self._toks.get(sid) or ())
            shipped = self._shipped.get(sid, 0)
            trace = self._traces.get(sid, (0, 0, True))
        pt = self.store.page_tokens
        if len(toks) // pt <= shipped:
            return
        try:
            pages = self.migrator.migrate(toks, self.standby_addr,
                                          trace_ctx=trace)
            with self._mu:
                if sid in self._shipped:
                    self._shipped[sid] = max(self._shipped[sid], pages)
                self.shipped_pages += pages
        except errors.RpcError:
            # the standby will recompute whatever never arrived
            with self._mu:
                self.ship_errors += 1

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Drain the ship queue INCLUDING the job the worker may be
        mid-migrate on (tests / graceful handoff — a flush that
        returned while the final page batch was still on the wire
        would hand over less state than the caller believes)."""
        deadline = time.monotonic() + timeout_s
        with self._ship_cv:
            while self._ship_q or self._ship_pending \
                    or self._ship_inflight:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return False
                self._ship_cv.wait(min(rem, 0.25))
            return True

    def close(self) -> None:
        with self._ship_cv:
            self._running = False
            self._ship_cv.notify_all()
        self._ship_thread.join(5.0)

    def stats(self) -> dict:
        with self._mu:
            return {
                "standby_addr": self.standby_addr,
                "live": len(self._toks),
                "synced_tokens": self.synced_tokens,
                "shipped_pages": self.shipped_pages,
                "sync_errors": self.sync_errors,
                "ship_errors": self.ship_errors,
            }
