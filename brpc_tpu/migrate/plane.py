"""Cross-host KV page migration — the data plane's page-shipping core.

"RPC Considered Harmful" (PAPERS.md) argues that tensor state should
move as one-sided bulk transfers, never be recomputed; the DMA
Streaming Framework argues for a dedicated bulk-buffer path beside the
RPC control plane.  This module is both, applied to the paged KV
cache: a radix prefix's pages (plus the tree metadata that makes them
meaningful — token runs, per-chunk fingerprints, refcounts at source)
ship over the DCN bridge's zero-copy offer/pull fabric (2.15x
host-serialized, BENCH_r05) and splice into the destination
:class:`~brpc_tpu.kvcache.KVCacheStore` as COMMITTED radix nodes, so
the destination prefix-hits state it never computed.

Wire shape: the ``_kvmig`` service's ``Offer`` method takes the same
bounded-trust envelope the ``_dcn`` service uses (json header + tensor
bytes, never pickle).  With transfer fabrics on both sides the
envelope carries control only and the page bytes move device-to-device
(one stacked ``[n_pages, page_bytes]`` array per migration); without
one they ride the envelope host-serialized — wire-compatible, flagged
in the stats.

Offer-table discipline: a migration's offer is released the moment the
``Offer`` RPC returns — the destination pulls before it can splice,
so the reply IS the pull-completion ack.  The TTL sweeper remains the
backstop for peers that die mid-pull, never the steady state; a burst
of migrations leaves ``dcn.live_offer_count() == 0``.

Failure semantics (chaos scenario 13): ``dcn.migrate_send`` fires on
the source before anything leaves the process, ``dcn.migrate_recv``
on the destination before anything is pulled, ``migrate.splice``
(kvcache/store.py) mid-splice.  Whatever fires, the source's pinned
pages are released, the destination either fully splices or fully
rolls back, and the caller falls back to recompute — migration is an
optimization, never a correctness dependency.

Observability: migrations run under rpcz spans that JOIN the
generation's trace over the envelope's trace fields; the destination's
splice span links the source's migrate span via ``migrated_from``
(mirroring the supervisor's ``recovered_from``).  Migration threads
are stage-tagged ``migrate`` for /hotspots, and
``kvcache_migrate_{pages,bytes,splice_us}`` ride /brpc_metrics.  The
``/migration`` console page renders the route matrix.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Optional, Sequence

import numpy as np

from brpc_tpu import errors, fault, rpcz
from brpc_tpu.butil import stagetag
from brpc_tpu.butil.lockprof import InstrumentedLock
from brpc_tpu.bvar import Adder, LatencyRecorder
from brpc_tpu.ici import dcn
from brpc_tpu.kvcache.store import MissingShippedPrefix
from brpc_tpu.rpc.service import Service, method

MIGRATE_SERVICE = "_kvmig"

# process-wide migration counters (ISSUE 7 satellite: the
# kvcache_migrate_* family on /brpc_metrics)
migrate_pages = Adder("kvcache_migrate_pages")
migrate_bytes = Adder("kvcache_migrate_bytes")
migrate_splice_rec = LatencyRecorder("kvcache_migrate_splice_us")
migrations_ok = Adder("kvcache_migrations_ok")
migrations_failed = Adder("kvcache_migrations_failed")
migrate_rollbacks = Adder("kvcache_migrate_rollbacks")
migrate_zero_copy = Adder("kvcache_migrate_zero_copy")
migrate_fallback = Adder("kvcache_migrate_fallback")
migrate_offer_frames = Adder("kvcache_migrate_offer_frames")

_mig_ids = itertools.count(1)


def _envelope_frame_fields(header: dict, arrays: list) -> dict:
    """The Offer envelope as tensorframe fields (ISSUE 17 adopter):
    the page METADATA that used to bloat the json header — token runs,
    chunk fingerprints, refcounts — rides as native little-endian
    tensors, the page payload as one uint8 tensor, and only the small
    irregular remainder (trace ids, zero-copy ticket/specs) stays as a
    json bytes field.  :func:`_frame_envelope` reconstructs EXACTLY
    the ``(header, arrays)`` the legacy json-header envelope decodes
    to, so both wire formats feed one splice path."""
    import json as _json
    hdr = dict(header)
    fields = {
        "tokens": np.asarray(hdr.pop("tokens", []), np.int64),
        # murmur-like 64-bit fingerprints may exceed int64: uint64
        "fingerprints": np.asarray(hdr.pop("fingerprints", []),
                                   np.uint64),
        "refcounts": np.asarray(hdr.pop("refcounts", []), np.int64),
        "hdr": _json.dumps(hdr).encode(),
    }
    if arrays:
        fields["pages"] = np.ascontiguousarray(arrays[0], np.uint8)
    return fields


def _frame_envelope(req: dict) -> tuple[dict, list]:
    """Inverse of :func:`_envelope_frame_fields`: back to the legacy
    decode's ``(header, arrays)`` shape — bit-for-bit the same header
    values and payload bytes (the regression test pins this)."""
    import json as _json
    hdr = _json.loads(bytes(req["hdr"]).decode())
    hdr["tokens"] = [int(t) for t in np.asarray(req["tokens"])]
    hdr["fingerprints"] = [int(f) for f in
                           np.asarray(req["fingerprints"])]
    hdr["refcounts"] = [int(r) for r in np.asarray(req["refcounts"])]
    arrays = [np.asarray(req["pages"], np.uint8)] \
        if "pages" in req else []
    return hdr, arrays


def chunk_fingerprints(tokens: Sequence[int], page_tokens: int) -> list:
    """Per-full-page-chunk 64-bit fingerprints of `tokens` — the tree
    metadata that travels with migrated pages.  The destination
    recomputes them from the token runs it received and refuses a
    migration whose fingerprints disagree (a torn or reordered payload
    must roll back, not serve wrong KV)."""
    from brpc_tpu.policy.load_balancer import _hash_murmur_like
    pt = page_tokens
    out = []
    for i in range(len(tokens) // pt):
        chunk = tokens[i * pt:(i + 1) * pt]
        out.append(_hash_murmur_like(b"".join(
            (int(t) & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")
            for t in chunk)))
    return out


class PageMigrator:
    """Source half: exports a committed radix prefix from a local
    :class:`~brpc_tpu.kvcache.KVCacheStore` and ships it to a peer's
    ``_kvmig`` service (see module docstring).  One migrator per
    store; destination channels are cached per address."""

    # per-destination incremental-shipping memory: chains cached beyond
    # this are dropped wholesale (a clear only costs re-shipping)
    MAX_CACHED_CHAINS = 8192

    def __init__(self, store, *, name: str = "migrator",
                 timeout_ms: int = 10_000):
        self.store = store
        self.name = name
        self.timeout_ms = int(timeout_ms)
        self._mu = InstrumentedLock("migrate.plane")
        self._chans: dict[str, dcn.DcnChannel] = {}
        # per-destination route matrix for the /migration console page
        self.routes: dict[str, dict] = {}
        # dest -> set of fingerprint-chain tuples already shipped there:
        # a repeat prefix (the 90%-shared steady state) ships only its
        # UN-shipped suffix pages, not the whole chain again
        self._shipped: dict[str, set] = {}
        # per-source pull-fetch matrix (ISSUE 16, /migration page)
        self.fetch_routes: dict[str, dict] = {}
        # per-destination offer wire format: "frame" (tensorframe
        # OfferT) until a peer answers ENOMETHOD, then STICKY "legacy"
        # (json-header envelope) — the PS client's negotiation contract
        self._wire_mode: dict[str, str] = {}
        self.n_negotiation_fallbacks = 0
        from brpc_tpu import migrate as _migrate
        _migrate._register_migrator(self)

    def _channel(self, dest: str) -> dcn.DcnChannel:
        with self._mu:
            ch = self._chans.get(dest)
            if ch is None:
                ch = dcn.DcnChannel(dest, timeout_ms=self.timeout_ms)
                self._chans[dest] = ch
        return ch

    def _route(self, dest: str) -> dict:
        with self._mu:
            r = self.routes.get(dest)
            if r is None:
                r = {"migrations": 0, "pages": 0, "bytes": 0,
                     "failed": 0, "zero_copy": 0}
                self.routes[dest] = r
            return r

    def migrate(self, tokens: Sequence[int], dest: str, *,
                trace_ctx: Optional[tuple] = None) -> int:
        """Ship the longest COMMITTED full-page prefix of `tokens` to
        `dest`'s store; returns the number of pages migrated (0 when
        the local radix tree holds none of the prefix).  Raises
        RpcError on transport/splice failure — the source pages are
        released either way, and the caller's recompute path is the
        fallback.  ``trace_ctx=(trace_id, parent_span_id, sampled)``
        joins the migration to an existing generation trace; by
        default the calling thread's current span is inherited."""
        with stagetag.stage("migrate"):
            return self._migrate(tokens, dest, trace_ctx)

    def _migrate(self, tokens, dest, trace_ctx) -> int:
        tokens = [int(t) for t in tokens]
        if trace_ctx is not None:
            tid, psid, smp = trace_ctx
            span = rpcz.new_span("migrate", "KvMigrate", "Offer",
                                 trace_id=tid, parent_span_id=psid,
                                 sampled=smp if tid else None)
        else:
            span = rpcz.child_span("migrate", "KvMigrate", "Offer")
        span.remote_side = dest
        route = self._route(dest)
        hit, pages = self.store.acquire_pages(tokens)
        try:
            if not pages:
                span.annotate("nothing committed to migrate")
                return 0
            return self._ship(tokens, dest, span, route, hit, pages)
        except errors.RpcError as e:
            migrations_failed.add(1)
            with self._mu:
                route["failed"] += 1
            span.error_code = e.code
            span.annotate(f"migration failed: {e.text}")
            raise
        except Exception as e:
            migrations_failed.add(1)
            with self._mu:
                route["failed"] += 1
            span.error_code = errors.EINTERNAL
            span.annotate(f"migration failed: {type(e).__name__}: {e}")
            raise errors.RpcError(
                errors.EINTERNAL,
                f"page migration to {dest} failed: "
                f"{type(e).__name__}: {e}") from e
        finally:
            # the pins outlive the send, never more: whatever happened
            # on the wire, the SOURCE's refcounts return to baseline
            self.store.release(pages)
            rpcz.submit(span)

    def _shipped_prefix(self, dest: str, fps: list) -> int:
        """Longest fingerprint-chain prefix already shipped to `dest`
        (the incremental-shipping offset)."""
        with self._mu:
            chains = self._shipped.get(dest)
            if not chains:
                return 0
            have = 0
            for k in range(1, len(fps) + 1):
                if tuple(fps[:k]) not in chains:
                    break
                have = k
            return have

    def _remember_shipped(self, dest: str, fps: list) -> None:
        with self._mu:
            chains = self._shipped.setdefault(dest, set())
            if len(chains) > self.MAX_CACHED_CHAINS:
                chains.clear()
            for k in range(1, len(fps) + 1):
                chains.add(tuple(fps[:k]))

    def _ship(self, tokens, dest, span, route, hit, pages) -> int:
        if fault.ENABLED and fault.hit(
                "dcn.migrate_send", dest=dest) is not None:
            raise errors.RpcError(
                errors.EINTERNAL,
                f"injected migration send loss to {dest}")
        pt = self.store.page_tokens
        nfull = len(pages)
        toks = tokens[:nfull * pt]
        fps = chunk_fingerprints(toks, pt)
        have = self._shipped_prefix(dest, fps)
        if have >= nfull:
            # the whole chain already shipped: nothing to send.  If
            # the destination has since evicted it, the next admit
            # there degrades to recompute — correctness never depends
            # on this cache being right, only wire bytes do.
            span.annotate(f"already shipped: all {nfull} pages "
                          f"cached at {dest}")
            return nfull
        try:
            return self._ship_chunks(toks, dest, span, route, pages,
                                     fps, have)
        except errors.RpcError as e:
            if have and "missing shipped prefix" in (e.text or ""):
                # the destination evicted chunks we skipped: forget
                # the cached chains for this dest and send the full
                # chain once
                with self._mu:
                    self._shipped.pop(dest, None)
                span.annotate(
                    f"incremental send refused (dest evicted "
                    f"{have}-chunk prefix); retrying full")
                return self._ship_chunks(toks, dest, span, route,
                                         pages, fps, 0)
            raise

    def _ship_chunks(self, toks, dest, span, route, pages, fps,
                     have: int) -> int:
        pt = self.store.page_tokens
        pb = self.store.pagepool.page_bytes
        nfull = len(pages)
        send = pages[have:]
        ch = self._channel(dest)
        try:
            topo = ch.handshake()
        except errors.RpcError:
            # peer without the _dcn service: the control RPC still
            # works, only the zero-copy path is off the table
            topo = {}
        header = {
            "mig_id": next(_mig_ids),
            "tokens": toks,
            "page_tokens": pt,
            "page_bytes": pb,
            "have": have,
            "fingerprints": fps,
            "refcounts": [p.refs for p in pages],
            "src": self.store.name,
            "src_span_id": span.span_id,
        }
        if span.trace_id:
            # cross-host trace join: the destination's splice span
            # lands in THIS trace (and links us via migrated_from)
            header["trace_id"] = span.trace_id
            header["parent_span_id"] = span.span_id
            header["trace_sampled"] = span.sampled
        ticket = None
        arrays: list = []
        if topo.get("xfer") and topo.get("nonce") != dcn._PROCESS_NONCE \
                and dcn.transfer_server() is not None:
            # ZERO-COPY: page bytes stay device-resident, registered
            # for the peer's pull; the socket carries control only
            import jax.numpy as jnp
            stacked = jnp.stack(
                [self.store.pagepool.page_slice(p) for p in send])
            ticket, specs = dcn.offer([stacked])
            header["xfer"] = dcn.transfer_address()
            header["ticket"] = ticket
            header["specs"] = specs
            migrate_zero_copy.add(1)
            with self._mu:
                route["zero_copy"] += 1
            span.annotate(f"zero-copy offer: ticket {ticket}, pages "
                          f"{have}..{nfull} ({len(send) * pb}B stay "
                          f"on device)")
        else:
            arrays = [np.stack(
                [self.store.pagepool.read_raw(p) for p in send])]
            migrate_fallback.add(1)
            span.annotate(f"host-serialized fallback: pages "
                          f"{have}..{nfull} ({len(send) * pb}B on the "
                          f"envelope)")
        try:
            hdr = self._post_offer(ch, dest, header, arrays, span)
        finally:
            if ticket is not None:
                # ack-on-pull-completion (ISSUE 7 satellite): a reply
                # means the destination pulled before splicing, so the
                # offer unpins NOW — the TTL sweeper is the backstop
                # for a peer that died mid-pull, not the release path
                dcn.release_offer(ticket)
        retained = int(hdr.get("imported", 0))
        span.annotate(f"destination spliced: {retained}/{len(send)} "
                      f"sent pages newly retained (dst span "
                      f"{hdr.get('dst_span_id', 0)})")
        self._remember_shipped(dest, fps)
        migrations_ok.add(1)
        migrate_pages.add(len(send))
        migrate_bytes.add(len(send) * pb)
        with self._mu:
            route["migrations"] += 1
            route["pages"] += len(send)
            route["bytes"] += len(send) * pb
        return nfull

    def _post_offer(self, ch, dest: str, header: dict, arrays: list,
                    span) -> dict:
        """Send one Offer envelope, preferring the tensorframe method
        (``OfferT``, ISSUE 17 adopter) and downgrading STICKY per
        destination to the legacy json-header envelope when the peer
        answers ENOMETHOD — the same per-peer negotiation contract the
        PS client runs per shard.  Returns the reply header dict."""
        with self._mu:
            mode = self._wire_mode.get(dest)
        if mode != "legacy":
            fields = _envelope_frame_fields(header, arrays)
            span.request_size = sum(
                v.nbytes if isinstance(v, np.ndarray) else len(v)
                for v in fields.values())
            try:
                resp = ch.channel.call_sync(
                    MIGRATE_SERVICE, "OfferT", fields,
                    serializer="tensorframe")
                with self._mu:
                    self._wire_mode[dest] = "frame"
                migrate_offer_frames.add(1)
                return dict(resp or {})
            except errors.RpcError as e:
                if e.code != errors.ENOMETHOD:
                    raise
                with self._mu:
                    self._wire_mode[dest] = "legacy"
                    self.n_negotiation_fallbacks += 1
                span.annotate(f"peer {dest} lacks OfferT; sticky "
                              f"json-envelope downgrade")
        body = dcn._pack_envelope(header, arrays)
        span.request_size = len(body)
        raw = ch.channel.call_sync(
            MIGRATE_SERVICE, "Offer", body,
            serializer="raw", response_serializer="raw")
        hdr, _ = dcn._unpack_envelope(bytes(raw))
        span.response_size = len(raw)
        return hdr

    def fetch(self, tokens: Sequence[int], src: str, dest: str,
              model: Optional[str] = None) -> int:
        """PULL-based prefix warm-up (ISSUE 16): ask `src`'s
        ``_kvmig`` service to push `tokens`' committed prefix to
        `dest` — normally this process's own migration address, so a
        cache-MISS replica fetches the prefix from its owner instead
        of recomputing it.  Returns pages landed (0 when the owner
        holds none of the prefix); raises RpcError on a dead or
        refusing owner — the caller's recompute path is the fallback,
        exactly the ``migrate()`` contract in the other direction.
        ``model`` tags the request on the multi-model plane (ISSUE 18):
        a model-tagged ``_kvmig`` owner REFUSES a mismatched fetch, so
        a stale holder list can never splice one model's pages into
        another's store."""
        with stagetag.stage("migrate"):
            if fault.ENABLED and fault.hit(
                    "migrate.prefix_fetch", src=src) is not None:
                with self._mu:
                    self._fetch_route(src)["failed"] += 1
                raise errors.RpcError(
                    errors.EINTERNAL,
                    f"injected prefix fetch failure from {src}")
            ch = self._channel(str(src))
            req = {"tokens": [int(t) for t in tokens],
                   "dest": str(dest)}
            if model:
                req["model"] = str(model)
            try:
                out = ch.channel.call_sync(
                    MIGRATE_SERVICE, "PushTo", req,
                    serializer="json", response_serializer="json")
            except errors.RpcError:
                with self._mu:
                    self._fetch_route(src)["failed"] += 1
                raise
            pages = int((out or {}).get("migrated_pages", 0))
            with self._mu:
                r = self._fetch_route(src)
                r["fetches"] += 1
                r["pages"] += pages
            return pages

    def _fetch_route(self, src: str) -> dict:
        # caller holds self._mu
        r = self.fetch_routes.get(src)
        if r is None:
            r = {"fetches": 0, "pages": 0, "failed": 0}
            self.fetch_routes[src] = r
        return r

    def stats(self) -> dict:
        with self._mu:
            routes = {d: dict(r) for d, r in self.routes.items()}
            fetches = {s: dict(r) for s, r in self.fetch_routes.items()}
            modes = dict(self._wire_mode)
            fallbacks = self.n_negotiation_fallbacks
        return {"store": self.store.name, "routes": routes,
                "fetch_routes": fetches, "wire_modes": modes,
                "negotiation_fallbacks": fallbacks}


class MigrateService(Service):
    """Destination half: receives ``Offer`` envelopes, pulls (or
    unpacks) the page bytes, verifies the chunk fingerprints, and
    splices the pages into the local store as committed radix nodes —
    atomically, rolling back on any failure.  ``PushTo`` lets a remote
    coordinator (the prefix-affinity balancer's rebalance hook) ask
    THIS process to push one of its prefixes to a new owner."""

    NAME = MIGRATE_SERVICE

    def __init__(self, store, *, migrator: Optional[PageMigrator] = None,
                 model: str = ""):
        self.store = store
        self.migrator = migrator or PageMigrator(
            store, name=f"{store.name}_pusher")
        # multi-model plane (ISSUE 18): the deployment this store's
        # pages belong to.  "" (pre-plane) accepts anything; a tagged
        # service refuses a PushTo carrying a DIFFERENT model — the
        # same-model fetch constraint that makes cross-model page
        # splices structurally impossible.
        self.model = str(model or "")
        self.n_model_refusals = 0
        self._mu = InstrumentedLock("migrate.service")
        # per-source route matrix (the inbound half of /migration)
        self.inbound: dict[str, dict] = {}
        from brpc_tpu import migrate as _migrate
        _migrate._register_service(self)

    def _inbound(self, src: str) -> dict:
        with self._mu:
            r = self.inbound.get(src)
            if r is None:
                r = {"migrations": 0, "pages": 0, "bytes": 0,
                     "rolled_back": 0}
                self.inbound[src] = r
            return r

    @method(request="raw", response="raw")
    def Offer(self, cntl, req):
        with stagetag.stage("migrate"):
            try:
                hdr, arrays = dcn._unpack_envelope(bytes(req))
            except Exception as e:
                cntl.set_failed(errors.EREQUEST,
                                f"bad migration envelope: {e}")
                return None
            resp = self._splice(cntl, hdr, arrays)
            return None if resp is None \
                else dcn._pack_envelope(resp, [])

    @method(request="tensorframe", response="tensorframe")
    def OfferT(self, cntl, req):
        """The same Offer on the BINARY tensor wire (ISSUE 17
        adopter): page metadata and payload arrive as tensorframe
        fields, decode to exactly the legacy envelope's (header,
        arrays), and feed the one splice path.  Old sources never call
        this; new sources downgrade sticky on ENOMETHOD."""
        with stagetag.stage("migrate"):
            try:
                hdr, arrays = _frame_envelope(req or {})
            except Exception as e:
                cntl.set_failed(errors.EREQUEST,
                                f"bad migration envelope: {e}")
                return None
            return self._splice(cntl, hdr, arrays)

    def _splice(self, cntl, hdr, arrays):
        if fault.ENABLED and fault.hit(
                "dcn.migrate_recv", store=self.store.name) is not None:
            cntl.set_failed(errors.EINTERNAL,
                            "injected migration recv loss")
            return None
        try:
            toks = [int(t) for t in hdr["tokens"]]
            pt = int(hdr["page_tokens"])
            pb = int(hdr["page_bytes"])
            have = int(hdr.get("have", 0))
            fps = [int(f) for f in hdr.get("fingerprints") or []]
        except Exception as e:
            cntl.set_failed(errors.EREQUEST,
                            f"bad migration envelope: {e}")
            return None
        if pt != self.store.page_tokens \
                or pb != self.store.pagepool.page_bytes:
            cntl.set_failed(
                errors.EREQUEST,
                f"page geometry mismatch: peer ships {pt} tokens x "
                f"{pb}B pages, this store holds "
                f"{self.store.page_tokens} x "
                f"{self.store.pagepool.page_bytes}B")
            return None
        if fps != chunk_fingerprints(toks, pt):
            cntl.set_failed(errors.EREQUEST,
                            "chunk fingerprint mismatch: migration "
                            "metadata does not describe its token runs")
            return None
        # splice span: joins the SOURCE's trace over the envelope
        # fields and links its migrate span via migrated_from — the
        # cross-process mirror of the supervisor's recovered_from
        try:
            env_tid = int(hdr.get("trace_id") or 0)
            env_psid = int(hdr.get("parent_span_id") or 0)
        except (TypeError, ValueError):
            env_tid = env_psid = 0
        if env_tid:
            span = rpcz.new_span("migrate", "KvMigrate", "Splice",
                                 trace_id=env_tid,
                                 parent_span_id=env_psid,
                                 sampled=bool(hdr.get("trace_sampled",
                                                      True)))
        else:
            span = rpcz.new_span("migrate", "KvMigrate", "Splice")
        span.migrated_from = int(hdr.get("src_span_id") or 0)
        span.annotate(f"migration from store "
                      f"{hdr.get('src', '?')}: {len(toks)} tokens "
                      f"(chunks {have}..{len(toks) // pt} on the "
                      f"wire), source refcounts {hdr.get('refcounts')}")
        route = self._inbound(str(hdr.get("src", "?")))
        try:
            if hdr.get("xfer") and hdr.get("ticket") is not None:
                stacked = dcn.pull(hdr["xfer"], int(hdr["ticket"]),
                                   hdr.get("specs") or [],
                                   self.store.pagepool.pool.device)[0]
                span.annotate(f"zero-copy pull: ticket {hdr['ticket']}")
            elif arrays:
                stacked = arrays[0]
            else:
                raise ValueError("no page payload on the envelope")
            rows = np.asarray(stacked, np.uint8).reshape(-1, pb)
            if rows.shape[0] != len(toks) // pt - have:
                raise ValueError(
                    f"{rows.shape[0]} payload pages for chunks "
                    f"{have}..{len(toks) // pt}")
            t0 = time.monotonic()
            retained = self.store.import_prefix(toks, list(rows),
                                                have=have, span=span)
            migrate_splice_rec.add(int((time.monotonic() - t0) * 1e6))
        except MissingShippedPrefix as e:
            # NOT a rollback: the peer's incremental-send assumption
            # was stale (we evicted its earlier chunks).  A definite
            # refusal makes it fall back to a full send.
            span.error_code = errors.EREQUEST
            span.annotate(f"incremental import refused: {e}")
            rpcz.submit(span)
            cntl.set_failed(errors.EREQUEST,
                            f"missing shipped prefix: {e}")
            return None
        except Exception as e:
            # all-or-nothing: import_prefix already rolled its pages
            # back; the source gets a DEFINITE error and keeps serving
            # the prefix itself (recompute fallback)
            migrate_rollbacks.add(1)
            with self._mu:
                route["rolled_back"] += 1
            span.error_code = errors.EINTERNAL
            span.annotate(f"splice rolled back: {type(e).__name__}: {e}")
            rpcz.submit(span)
            cntl.set_failed(errors.EINTERNAL,
                            f"migration splice failed: "
                            f"{type(e).__name__}: {e}")
            return None
        with self._mu:
            route["migrations"] += 1
            route["pages"] += len(toks) // pt - have
            route["bytes"] += (len(toks) // pt - have) * pb
        resp = {"imported": retained, "pages": len(toks) // pt - have,
                "dst_span_id": span.span_id}
        rpcz.submit(span)
        return resp

    @method(request="json", response="json")
    def PushTo(self, cntl, req):
        """Coordinator-initiated push: migrate `tokens`' committed
        prefix FROM this process's store TO `dest` — the RPC the
        prefix-affinity balancer's ``migrate_on_rebalance`` hook sends
        to a prefix's old owner when the ring remaps it."""
        req = req or {}
        tokens = req.get("tokens") or []
        dest = req.get("dest")
        if not tokens or not dest:
            cntl.set_failed(errors.EREQUEST,
                            'PushTo needs "tokens" and "dest"')
            return None
        want = str(req.get("model") or "")
        if want and self.model and want != self.model:
            with self._mu:
                self.n_model_refusals += 1
            cntl.set_failed(
                errors.EREQUEST,
                f"model mismatch: this store holds {self.model!r} "
                f"pages, refusing a {want!r} fetch")
            return None
        try:
            pages = self.migrator.migrate(tokens, str(dest))
        except errors.RpcError as e:
            cntl.set_failed(e.code, f"push migration failed: {e.text}")
            return None
        return {"migrated_pages": pages}

    def stats(self) -> dict:
        with self._mu:
            inbound = {s: dict(r) for s, r in self.inbound.items()}
        return {"store": self.store.name, "model": self.model,
                "model_refusals": self.n_model_refusals,
                "inbound": inbound}


def register_migration(server, store,
                       migrator: Optional[PageMigrator] = None,
                       model: str = "") -> MigrateService:
    """Expose `store` as a migration destination (and PushTo source) on
    `server`.  Call before ``server.start()``.  ``model`` tags the
    store's deployment on the multi-model plane (see MigrateService)."""
    svc = MigrateService(store, migrator=migrator, model=model)
    server.add_service(svc)
    return svc


def make_prefix_fetcher(migrator: PageMigrator, self_addr: str,
                        model: Optional[str] = None):
    """Build the ``prefix_fetcher`` hook Serving.Generate calls on a
    cache miss (ISSUE 16): try each holder the router named (skipping
    this replica itself) until one push lands, returning pages fetched.
    Any holder failure falls through to the next; exhausting them
    returns 0 and the caller recomputes — fetch is an optimization,
    never a correctness dependency.  ``model`` tags every fetch on the
    multi-model plane so a mismatched owner refuses it (ISSUE 18)."""
    self_addr = str(self_addr)

    def fetch(prompt, holders) -> int:
        for h in holders:
            h = str(h)
            if h == self_addr:
                continue
            try:
                pages = migrator.fetch(prompt, h, self_addr,
                                       model=model)
            except Exception:
                continue
            if pages:
                return pages
        return 0

    return fetch


def rebalance_pusher(timeout_ms: int = 10_000):
    """The default ``migrate_on_rebalance`` hook: when the
    prefix-affinity ring remaps a prefix from `old_ep` to `new_ep`,
    ask the OLD owner (whose store holds the warm pages) to push them
    to the new one — ``PushTo`` over the old owner's ``_kvmig``
    service.  Returns pages migrated; swallows nothing (the balancer
    wraps hook calls so one dead replica cannot wedge the remap)."""
    from brpc_tpu.rpc.channel import Channel
    chans: dict[str, Channel] = {}
    mu = InstrumentedLock("migrate.rebalance")

    def hook(tokens, old_ep, new_ep) -> int:
        src = str(old_ep)
        with mu:
            ch = chans.get(src)
            if ch is None:
                ch = Channel(src, timeout_ms=timeout_ms)
                chans[src] = ch
        out = ch.call_sync(MIGRATE_SERVICE, "PushTo",
                           {"tokens": [int(t) for t in tokens],
                            "dest": str(new_ep)},
                           serializer="json", response_serializer="json")
        return int((out or {}).get("migrated_pages", 0))

    return hook
