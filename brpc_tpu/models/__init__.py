from brpc_tpu.models.parameter_server import (  # noqa: F401
    PSConfig, init_params, forward_step, train_step, make_sharded_train_step,
    register_ps_services,
)
from brpc_tpu.models.moe import (  # noqa: F401
    MoEConfig, init_moe_params, make_ep_mesh, make_sharded_moe_layer,
    make_sharded_moe_train_step, moe_layer_reference, place_moe_params,
)
from brpc_tpu.models.runner import (  # noqa: F401
    LegacyFnRunner, ModelRunner, TransformerConfig, TransformerRunner,
    as_runner, dense_forward, dense_generate, init_runner_params,
    make_store_for, make_tp_mesh, place_runner_params, run_prefill,
)
from brpc_tpu.models.registry import (  # noqa: F401
    DeploymentRegistry, ModelDeployment, global_registry,
)
