from brpc_tpu.models.parameter_server import (  # noqa: F401
    PSConfig, init_params, forward_step, train_step, make_sharded_train_step,
    register_ps_services,
)
