from brpc_tpu.models.parameter_server import (  # noqa: F401
    PSConfig, init_params, forward_step, train_step, make_sharded_train_step,
    register_ps_services,
)
from brpc_tpu.models.moe import (  # noqa: F401
    MoEConfig, init_moe_params, make_ep_mesh, make_sharded_moe_layer,
    make_sharded_moe_train_step, moe_layer_reference, place_moe_params,
)
