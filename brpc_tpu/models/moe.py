"""Mixture-of-Experts block with expert parallelism over the device mesh.

The second flagship model family: a Switch-style top-1 MoE layer whose
experts shard over an ``ep`` mesh axis and whose token dispatch rides
``lax.all_to_all`` inside ``shard_map`` — the canonical TPU MoE recipe
(GShard/Switch): static-shape one-hot dispatch einsums (no dynamic
shapes, so XLA tiles everything onto the MXU), capacity-bounded expert
buffers, and ICI all_to_alls for the token exchange in both directions.

Everything is a pure function over parameters; the sharded layer is
validated against the identical-math single-device reference in
tests/test_moe.py.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MoEConfig:
    d_model: int = 64
    d_ff: int = 128
    n_experts: int = 8          # global expert count (divisible by ep)
    capacity: int = 16          # per-expert token slots PER SHARD
    seq: int = 32               # tokens per shard


def init_moe_params(cfg: MoEConfig, key=None):
    key = key if key is not None else jax.random.PRNGKey(0)
    kr, k1, k2 = jax.random.split(key, 3)
    scale = 1.0 / np.sqrt(cfg.d_model)
    return {
        "router": jax.random.normal(kr, (cfg.d_model, cfg.n_experts),
                                    jnp.float32) * scale,
        # per-expert FFN stacks: [E, d_model, d_ff] / [E, d_ff, d_model]
        "wup": jax.random.normal(k1, (cfg.n_experts, cfg.d_model, cfg.d_ff),
                                 jnp.float32) * scale,
        "wdown": jax.random.normal(k2, (cfg.n_experts, cfg.d_ff,
                                        cfg.d_model), jnp.float32) * scale,
    }


def _dispatch_tensors(x, router_w, n_experts: int, capacity: int):
    """Switch-style top-1 routing with static shapes.

    Returns (dispatch[S,E,C] one-hot, combine[S,E,C] gated) — the GShard
    einsum pair.  Tokens overflowing an expert's capacity are DROPPED
    (their combine weights are zero), exactly the reference behavior of
    capacity-factor MoEs.
    """
    logits = x @ router_w                         # [S, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)           # [S]
    gate = jnp.max(probs, axis=-1)                # [S]
    onehot = jax.nn.one_hot(expert, n_experts, dtype=x.dtype)   # [S, E]
    # position of each token within its expert's buffer
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot
    keep = pos < capacity
    onehot = onehot * keep
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                            dtype=x.dtype)        # [S, E, C]
    dispatch = onehot[..., None] * pos_oh         # [S, E, C]
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def _expert_ffn(inp, wup, wdown):
    """[E, C, D] tokens through per-expert FFNs (batched matmul — one
    MXU-friendly einsum per projection)."""
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", inp, wup))
    return jnp.einsum("ecf,efd->ecd", h, wdown)


def moe_layer_reference(params, x, cfg: MoEConfig):
    """Single-device reference: the exact math the sharded layer must
    reproduce (dispatch -> all experts locally -> combine)."""
    dispatch, combine = _dispatch_tensors(x, params["router"],
                                          cfg.n_experts, cfg.capacity)
    expert_in = jnp.einsum("sec,sd->ecd", dispatch, x)
    expert_out = _expert_ffn(expert_in, params["wup"], params["wdown"])
    return jnp.einsum("sec,ecd->sd", combine, expert_out)


def make_ep_mesh(n_devices: int) -> Mesh:
    return Mesh(np.array(jax.devices()[:n_devices]), ("ep",))


def _check_divisible(cfg: MoEConfig, ep: int) -> None:
    if cfg.n_experts % ep:
        raise ValueError(f"n_experts {cfg.n_experts} must divide by ep={ep}")


def _shard_forward(router_w, wup, wdown, x, cfg: MoEConfig):
    """ONE per-shard forward shared by the inference layer and the train
    step (training and serving must compute identical math): dispatch,
    all_to_all out, local expert FFN, all_to_all back, combine."""
    dispatch, combine = _dispatch_tensors(x, router_w, cfg.n_experts,
                                          cfg.capacity)
    expert_in = jnp.einsum("sec,sd->ecd", dispatch, x)       # [E, C, D]
    # exchange: split the expert axis across ep, concat the slots —
    # each chip ends with ITS experts' buffers from EVERY shard
    gathered = lax.all_to_all(expert_in, "ep", split_axis=0,
                              concat_axis=1, tiled=True)
    out = _expert_ffn(gathered, wup, wdown)   # [E/ep, ep*C, D] locally
    # reverse exchange: send each shard its tokens back
    returned = lax.all_to_all(out, "ep", split_axis=1, concat_axis=0,
                              tiled=True)                    # [E, C, D]
    return jnp.einsum("sec,ecd->sd", combine, returned)


def make_sharded_moe_layer(mesh: Mesh, cfg: MoEConfig):
    """The expert-parallel layer: tokens sharded over ``ep``, experts
    sharded over ``ep``, two ICI all_to_alls exchanging capacity
    buffers.  Per shard:

      [S,E,C] dispatch -> expert_in [E,C,D]
      all_to_all(E -> local experts, gathering every shard's slots)
      local expert FFN on [E/ep, ep*C, D]
      all_to_all back -> combine locally

    Drop-in identical math to moe_layer_reference when the same tokens
    flow through (each shard routes ITS tokens with the full router).
    """
    ep = mesh.shape["ep"]
    _check_divisible(cfg, ep)

    def shard_fn(router_w, wup, wdown, x):
        # x: [S_local, D]; wup/wdown: [E/ep, ...] (this shard's experts)
        return _shard_forward(router_w, wup, wdown, x, cfg)

    from brpc_tpu.ici.collective import shard_map
    return jax.jit(shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P("ep", None, None), P("ep", None, None),
                  P("ep", None)),
        out_specs=P("ep", None)))


def make_sharded_moe_train_step(mesh: Mesh, cfg: MoEConfig,
                                lr: float = 1e-2):
    """One SGD step through the expert-parallel layer: the loss runs the
    sharded forward (all_to_alls included) and jax.grad differentiates
    THROUGH the collectives — the backward pass's token returns are the
    transposed all_to_alls, which XLA emits as ICI traffic exactly like
    the forward.  Router gradients flow through the gate weights (the
    dispatch one-hots are straight-through: argmax itself has no
    gradient, matching Switch)."""
    ep = mesh.shape["ep"]
    _check_divisible(cfg, ep)

    def shard_loss(router_w, wup, wdown, x, target):
        y = _shard_forward(router_w, wup, wdown, x, cfg)
        # this shard's CONTRIBUTION to the global mean — the psum is
        # deliberately OUTSIDE the differentiated function: psum
        # transposes to psum, so a psum'd loss inflates every cotangent
        # by ep (measured exactly ep x vs the single-device reference)
        local = jnp.sum((y - target) ** 2)
        # normalize by the ACTUAL global element count (the layer is
        # shape-polymorphic in S; cfg.seq here would silently mis-scale
        # loss and gradients for any other batch length)
        return local / (y.size * ep)

    def shard_step(router_w, wup, wdown, x, target):
        contrib, grads = jax.value_and_grad(shard_loss,
                                            argnums=(0, 1, 2))(
            router_w, wup, wdown, x, target)
        gr, gu, gd = grads
        # report the GLOBAL loss; gradients through the all_to_alls are
        # already the true global-mean grads (the collectives transpose
        # cotangents back to the experts that produced them)
        loss = lax.psum(contrib, "ep")
        # router is REPLICATED: each shard's gr is its tokens'
        # contribution — the true grad is their sum
        gr = lax.psum(gr, "ep")
        return (router_w - lr * gr, wup - lr * gu, wdown - lr * gd, loss)

    from brpc_tpu.ici.collective import shard_map
    return jax.jit(shard_map(
        shard_step, mesh=mesh,
        in_specs=(P(), P("ep", None, None), P("ep", None, None),
                  P("ep", None), P("ep", None)),
        out_specs=(P(), P("ep", None, None), P("ep", None, None), P())))


def place_moe_params(params, mesh: Mesh):
    """Router replicated; expert stacks sharded over ep."""
    return {
        "router": jax.device_put(params["router"],
                                 NamedSharding(mesh, P())),
        "wup": jax.device_put(params["wup"],
                              NamedSharding(mesh, P("ep", None, None))),
        "wdown": jax.device_put(params["wdown"],
                                NamedSharding(mesh, P("ep", None, None))),
    }
