"""Flagship workload: a sharded-embedding parameter-server service.

The reference's BASELINE.json north star is "a bRPC-based parameter-server /
sharded-embedding service running entirely inside a TPU pod".  This module
is that service built on tpu-rpc: an embedding table sharded over chips
(expert/vocab parallel), a transformer-style MLP block (tensor parallel),
batch data parallel, sequence sharding for long contexts, and a pipeline
axis over stacked layers — all expressed as jit sharding annotations over a
Mesh so XLA inserts the ICI collectives (the scaling-book recipe: pick a
mesh, annotate shardings, let XLA do the rest).

Axes used (dryrun_multichip exercises all of them):
  dp — batch            tp — hidden/heads       ep — vocab (embedding shards)
  sp — sequence         pp — stacked layers (scan over stages)

The per-chip service functions are also registered as tpu-rpc device
services, so PartitionChannel/ParallelChannel can drive lookups through the
RPC surface (see register_ps_services / examples/parallel_echo.py).
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class PSConfig:
    vocab: int = 1024
    d_model: int = 128
    d_ff: int = 256
    n_layers: int = 2       # pipeline stages (scanned)
    seq: int = 32
    batch: int = 8
    dtype: str = "bfloat16"


def init_params(cfg: PSConfig, key=None):
    # Master weights stay float32; forward casts to cfg.dtype (bfloat16) for
    # the MXU.  bf16 master weights would round away lr*grad updates.
    key = key if key is not None else jax.random.PRNGKey(0)
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    dt = jnp.float32
    scale = 0.02
    return {
        "embed": (jax.random.normal(k1, (cfg.vocab, cfg.d_model)) * scale
                  ).astype(dt),
        # stacked per-layer weights: leading axis is the pipeline axis
        "w_qk": (jax.random.normal(k2, (cfg.n_layers, cfg.d_model,
                                        cfg.d_model)) * scale).astype(dt),
        "w_up": (jax.random.normal(k3, (cfg.n_layers, cfg.d_model,
                                        cfg.d_ff)) * scale).astype(dt),
        "w_down": (jax.random.normal(k4, (cfg.n_layers, cfg.d_ff,
                                          cfg.d_model)) * scale).astype(dt),
        "w_out": (jax.random.normal(k5, (cfg.d_model, cfg.vocab)) * scale
                  ).astype(dt),
    }


def _block(x, wqk, wup, wdown):
    # attention-flavored mixing (scores over sequence) + MLP, bf16 matmuls
    # shaped for the MXU; float32 softmax for stability
    q = x @ wqk
    scores = jax.nn.softmax(
        (q @ x.swapaxes(-1, -2)).astype(jnp.float32) /
        np.sqrt(x.shape[-1]), axis=-1).astype(x.dtype)
    x = x + scores @ x
    h = jax.nn.gelu(x @ wup)
    return x + h @ wdown


def forward_step(params, tokens, compute_dtype=jnp.bfloat16):
    """Forward pass: embed -> scanned blocks (pipeline axis) -> logits.
    Compute in bfloat16 on the MXU; master params stay float32."""
    p = jax.tree_util.tree_map(lambda a: a.astype(compute_dtype), params)
    x = p["embed"][tokens]               # [B, S, D]

    def body(x, layer):
        wqk, wup, wdown = layer
        return _block(x, wqk, wup, wdown), None

    x, _ = jax.lax.scan(body, x, (p["w_qk"], p["w_up"], p["w_down"]))
    return x @ p["w_out"]                # [B, S, V]


def loss_fn(params, tokens, targets):
    logits = forward_step(params, tokens).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return -jnp.mean(ll)


def train_step(params, tokens, targets, lr=1e-2):
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
    new_params = jax.tree_util.tree_map(
        lambda p, g: (p - lr * g.astype(p.dtype)), params, grads)
    return new_params, loss


def make_mesh(n_devices: int) -> Mesh:
    """Factor n into (dp, tp); pp/sp/ep alias these axes (pp rides the
    scanned layer axis placement, sp shards sequence over tp, ep shards
    vocab over tp)."""
    devs = jax.devices()[:n_devices]
    dp = 1
    for cand in (4, 2, 1):
        if n_devices % cand == 0 and cand <= n_devices:
            dp = cand if n_devices // cand >= 1 else 1
            break
    tp = n_devices // dp
    arr = np.array(devs).reshape(dp, tp)
    return Mesh(arr, ("dp", "tp"))


def param_shardings(mesh: Mesh):
    # ep: the embedding table is ROW-sharded over the WHOLE mesh
    # (("dp","tp") — every chip owns a distinct contiguous vocab range,
    # the parameter-server ownership map psserve serves over RPC), not
    # merely tp-sharded-and-dp-replicated: at dp=4,tp=2 the old spec
    # left 4 replicas of each row shard, which is exactly the layout a
    # sharded-embedding service cannot tolerate (an Update would have
    # to write 4 places)
    return {
        "embed": NamedSharding(mesh, P(("dp", "tp"), None)),
        "w_qk": NamedSharding(mesh, P(None, None, "tp")),
        "w_up": NamedSharding(mesh, P(None, None, "tp")),   # tp: ff-sharded
        "w_down": NamedSharding(mesh, P(None, "tp", None)),
        "w_out": NamedSharding(mesh, P(None, "tp")),
    }


def data_shardings(mesh: Mesh):
    # dp over batch, sp (sequence) over tp — long-context residency is
    # spread across chips; XLA inserts the gathers the attention needs
    return (NamedSharding(mesh, P("dp", "tp")),       # tokens [B, S]
            NamedSharding(mesh, P("dp", "tp")))       # targets [B, S]


def make_sharded_train_step(mesh: Mesh, cfg: PSConfig, lr: float = 1e-2):
    """jit train_step with in/out shardings over the mesh; XLA lowers the
    cross-chip math to ICI collectives."""
    ps = param_shardings(mesh)
    ts, gs = data_shardings(mesh)
    out_shardings = (ps, NamedSharding(mesh, P()))
    step = jax.jit(
        partial(train_step, lr=lr),
        in_shardings=(ps, ts, gs),
        out_shardings=out_shardings,
    )
    return step


def make_example_batch(cfg: PSConfig, key=None):
    key = key if key is not None else jax.random.PRNGKey(1)
    k1, k2 = jax.random.split(key)
    tokens = jax.random.randint(k1, (cfg.batch, cfg.seq), 0, cfg.vocab)
    targets = jax.random.randint(k2, (cfg.batch, cfg.seq), 0, cfg.vocab)
    return tokens, targets


def register_ps_services(cfg: PSConfig | None = None) -> None:
    """Expose lookup/forward as tpu-rpc device services so the RPC surface
    (IciChannel / ParallelChannel / PartitionChannel) can drive them."""
    from brpc_tpu.ici.channel import register_device_service
    cfg = cfg or PSConfig()
    params = init_params(cfg)
    register_device_service("ParameterServer", "EmbedLookup",
                            lambda tokens: params["embed"][tokens])
    register_device_service("ParameterServer", "Forward",
                            lambda tokens: forward_step(params, tokens))
