"""Model deployment registry — the named manifest behind the
multi-model plane (ISSUE 18).

A :class:`ModelDeployment` is the MANIFEST for one ``(model_id,
version)``: how to build its :class:`~brpc_tpu.models.runner.
ModelRunner` (a zero-arg factory, so registration costs nothing until
a replica actually deploys it) plus the KV geometry its store must be
cut with (``page_tokens`` x ``kv_bytes_per_token`` — the same
geometry-compatibility check ``_kvmig`` splices enforce on the wire).
The :class:`DeploymentRegistry` is the process-wide name table:
``rpc_press --models`` and the bench spin replicas straight from it,
and a replica's :class:`~brpc_tpu.serving.modelplane.
ReplicaDeployments` rows are born from these manifests.

This module is intentionally jax-free at import: factories are opaque
callables, so the control plane (router, WAL recovery, console) can
consult the manifest without paying the accelerator import.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from brpc_tpu.serving.modelplane import (DEFAULT_MODEL, deployment_key,
                                         split_deployment_key)


@dataclass
class ModelDeployment:
    """One named deployment manifest (see module docstring).

    ``runner_factory`` returns whatever the engine accepts as a model
    (a :class:`~brpc_tpu.models.runner.ModelRunner` or a legacy step
    fn); ``weight`` is the canary weight of THIS version inside its
    ``model_id``; ``kv_geometry`` is advisory metadata the spin-up
    helpers cut stores with (``page_tokens``, ``kv_bytes_per_token``,
    ...)."""

    model_id: str
    version: str = ""
    runner_factory: Optional[Callable[[], object]] = None
    weight: int = 1
    kv_geometry: dict = field(default_factory=dict)
    meta: dict = field(default_factory=dict)

    @property
    def key(self) -> str:
        return deployment_key(self.model_id, self.version)

    def build_runner(self):
        """Instantiate the deployment's model (None without a
        factory — a catalog-only deployment)."""
        return None if self.runner_factory is None \
            else self.runner_factory()

    def describe(self) -> dict:
        return {"model": self.key, "model_id": self.model_id,
                "version": self.version, "weight": max(1, int(self.weight)),
                "kv_geometry": dict(self.kv_geometry),
                "has_factory": self.runner_factory is not None,
                "meta": dict(self.meta)}


class DeploymentRegistry:
    """Thread-safe ``key -> ModelDeployment`` manifest table."""

    def __init__(self):
        self._mu = threading.Lock()
        self._deps: dict[str, ModelDeployment] = {}

    def register(self, dep: ModelDeployment) -> ModelDeployment:
        with self._mu:
            if dep.key in self._deps:
                raise ValueError(
                    f"deployment {dep.key!r} already registered; "
                    f"unregister it first to replace the manifest row")
            self._deps[dep.key] = dep
        return dep

    def unregister(self, key: str) -> bool:
        with self._mu:
            return self._deps.pop(str(key), None) is not None

    def get(self, key: str) -> Optional[ModelDeployment]:
        with self._mu:
            return self._deps.get(str(key))

    def resolve(self, model: Optional[str]) -> ModelDeployment:
        """Manifest lookup with the plane's resolution rules: ``None``
        means the sole registration (or the default model); a bare
        ``model_id`` with exactly one version resolves to it.  Raises
        ``KeyError`` otherwise — the caller's EREQUEST path."""
        with self._mu:
            if model:
                d = self._deps.get(str(model))
                if d is not None:
                    return d
                versions = [d for d in self._deps.values()
                            if d.model_id == str(model)]
                if len(versions) == 1:
                    return versions[0]
                raise KeyError(
                    f"unknown or ambiguous model {model!r} "
                    f"({len(versions)} versions registered)")
            if len(self._deps) == 1:
                return next(iter(self._deps.values()))
            d = self._deps.get(DEFAULT_MODEL)
            if d is not None:
                return d
            raise KeyError(
                f"model-less lookup over {len(self._deps)} "
                f"registrations and no {DEFAULT_MODEL!r}")

    def versions_of(self, model_id: str) -> list[ModelDeployment]:
        with self._mu:
            return sorted((d for d in self._deps.values()
                           if d.model_id == str(model_id)),
                          key=lambda d: d.key)

    def keys(self) -> list[str]:
        with self._mu:
            return sorted(self._deps)

    def __len__(self) -> int:
        with self._mu:
            return len(self._deps)

    def snapshot(self) -> list[dict]:
        with self._mu:
            deps = list(self._deps.values())
        return [d.describe() for d in deps]


# the process-wide manifest ``rpc_press --models`` / bench spin from
_global_registry = DeploymentRegistry()


def global_registry() -> DeploymentRegistry:
    return _global_registry


__all__ = ["ModelDeployment", "DeploymentRegistry", "global_registry",
           "deployment_key", "split_deployment_key", "DEFAULT_MODEL"]
