"""ModelRunner — real model serving over the paged KV cache (ISSUE 10).

PRs 2–9 built the serving stack around two ad-hoc model protocols: the
engine's 2-arg/3-arg ``step_fn``/``prefill_fn`` and the batcher's
1-arg/2-arg ``batch_fn``, all driven with token ids standing in for KV.
This module replaces them with ONE interface and ships the first model
that actually uses the paged HBM layout:

  :class:`ModelRunner`       the interface: ``prefill(tokens, positions,
                             pages)`` / ``step(tokens, positions, pages)``
                             — fixed shapes, one compile per bucket, the
                             engine's trace-counter discipline unchanged;
  :class:`LegacyFnRunner`    the adapter wrapping the old fn protocols
                             byte-for-byte (required-positional
                             detection, jnp conversion, pass_page_table
                             override), so every existing test and the
                             pure-token harness keep passing unmodified;
  :class:`TransformerRunner` a small real transformer (GQA attention +
                             gelu MLP, RMS-norm, tied embeddings, greedy
                             decode) whose K/V live IN the KV cache's
                             pages: prefill writes each layer's suffix
                             K/V through ``KVCacheStore.write_kv`` (the
                             PagePool splice path — COW and refcounts
                             apply) then attends over the page table
                             with :func:`~brpc_tpu.ops.paged_attention`;
                             decode steps attend over the arena plus the
                             position's in-flight K/V (the self key) and
                             return packed K/V rows the engine splices
                             back — so prefix reuse, COW forks, radix
                             eviction and crash recovery all operate on
                             REAL attention state.

Position/materialization contract (the whole stack hinges on it):

  * a sequence at ``position p`` has tokens 0..p-1 appended and REAL
    K/V materialized for positions 0..p-2 at minimum (``seq.kv_filled``);
  * ``step(tok=t_{p-1}, pos=p)`` recomputes position p-1's hidden state
    (embedding + per-layer q/k/v), attends over arena keys 0..p-2 PLUS
    its own in-flight k/v, and returns (next token, position p-1's
    packed K/V rows) — the engine writes the rows before extending, so
    the NEXT step reads them from the arena;
  * prefill covers suffix positions f..n-1 write-then-attend per layer:
    layer l's K/V are spliced into the pages FIRST, then the layer
    attends over the page table (cached prefix pages + just-written
    suffix) with per-row causal lengths.  Cold (f=0) and warm (f>0)
    prefill therefore run the SAME kernel over the SAME fixed arena
    shapes — prefix reuse changes which pages already hold bytes, not
    the compute path — which is what makes prefill-skip produce
    identical tokens to cold prefill.

Sharding: parameters place over an ICI ``tp`` mesh axis with
``NamedSharding`` (:func:`place_runner_params` — q/k/v/o projections and
the MLP shard on the head/ff dim, embeddings replicate) and the jitted
step partitions under GSPMD exactly like the pjit pattern in
SNIPPETS.md [1]/[3]; a 1-device mesh (the CPU tier-1 path) is the
degenerate case of the same code.
"""
from __future__ import annotations

import functools
import math
import threading
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

import numpy as np

from brpc_tpu import fault

DEFAULT_PREFILL_BUCKETS = (16, 64, 256, 1024, 4096)


# ---------------------------------------------------------------------------
# the interface + legacy adapter
# ---------------------------------------------------------------------------

class ModelRunner:
    """The model interface the serving stack drives (see module
    docstring).  ``wants_pages`` tells the engine to gather per-slot
    page tables; ``kv_bytes_per_token`` > 0 means the runner produces
    REAL packed K/V rows (the engine writes step rows via
    ``KVCacheStore.write_kv``; prefill writes its own, layer by layer);
    ``has_prefill`` gates the engine's prefill stage."""

    wants_pages: bool = False
    kv_bytes_per_token: int = 0
    has_prefill: bool = False
    name: str = "runner"

    def bind(self, store) -> None:
        """Called by the engine at construction with its KV store (may
        be None for raw-block engines).  Idempotent."""

    def prefill(self, tokens, positions, pages, seq=None):
        """Prefill one sequence's uncached suffix: ``tokens`` is the
        bucket-padded suffix (int32), ``positions`` the matching global
        positions, ``pages`` the slot's page-id table (-1 padded),
        ``seq`` the owning KVSeq (vector runners write K/V through
        it).  Returns nothing; K/V lands in the pages."""
        raise NotImplementedError

    def step(self, tokens, positions, pages):
        """One decode step across every slot: fixed-shape ``tokens`` /
        ``positions`` ``[num_slots]`` plus the gathered page table
        ``[num_slots, max_pages_per_slot]`` (None unless
        ``wants_pages``).  Returns ``(next_tokens, kv_rows)`` — int32
        per-slot next tokens and the query positions' packed K/V rows
        (``[num_slots, kv_bytes_per_token]`` uint8, or None for
        token-harness runners)."""
        raise NotImplementedError

    def verify(self, tokens, positions, tables, base_len, mask):
        """Speculative-verify (ISSUE 11): score a whole draft tree in
        ONE call.  ``tokens``/``positions`` are ``[num_slots, K1]`` —
        per slot, row 0 is the normal decode query (the last real
        token) and rows 1.. are draft positions (engine position
        convention: a token at sequence index p rides position p+1,
        exactly what :meth:`step` would have been handed when that
        token was newest).  ``tables`` ``[num_slots*K1,
        max_pages_per_slot]`` is the PER-ROW page-id table (tree side
        branches ride their fork's table), ``base_len``
        ``[num_slots*K1]`` the per-row count of MATERIALIZED arena
        keys, and ``mask`` ``[num_slots, K1, K1]`` the draft-tree
        ancestry mask (row i sees local row j's in-call K/V iff
        ``mask[s, i, j]``; always includes self).  Returns
        ``(out_tokens, kv_rows)`` — per-ROW greedy next tokens
        ``[num_slots, K1]`` (the accept rule is greedy match against
        these) and the rows' packed K/V ``[num_slots, K1,
        kv_bytes_per_token]`` uint8 (None for token-harness runners);
        only the ACCEPTED rows' K/V should ever be spliced."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class LegacyFnRunner(ModelRunner):
    """Adapter for the PR 2/3 fn protocols: a 2-arg
    ``step_fn(tokens, positions)`` or 3-arg ``step_fn(tokens,
    positions, pages)`` plus an optional ``prefill_fn(padded_suffix,
    prefill_from)``.  Behavior is byte-for-byte the engine's old
    inline calls — required-positional detection included — so the
    pure-token harness and every existing test ride through
    unchanged."""

    def __init__(self, step_fn: Callable,
                 prefill_fn: Optional[Callable] = None, *,
                 store=None, pass_page_table: Optional[bool] = None,
                 name: str = "legacy"):
        self.step_fn = step_fn
        self.prefill_fn = prefill_fn
        self.has_prefill = prefill_fn is not None
        self.name = name
        # pass the gathered page tables only to a step_fn built for
        # them — a 2-arg step_fn keeps the PR 2 contract unchanged.
        # Detection counts REQUIRED positionals (an optional third
        # parameter like rng=None must not silently receive the
        # table); pass_page_table overrides for *args step functions
        if pass_page_table is not None:
            self.wants_pages = bool(pass_page_table)
        else:
            from brpc_tpu.serving.batcher import required_positional_args
            self.wants_pages = (store is not None and
                                required_positional_args(step_fn) >= 3)

    def prefill(self, tokens, positions, pages, seq=None):
        import jax.numpy as jnp
        self.prefill_fn(jnp.asarray(tokens),
                        jnp.int32(int(positions[0])))

    def step(self, tokens, positions, pages):
        import jax.numpy as jnp
        if pages is not None:
            out = self.step_fn(jnp.asarray(tokens),
                               jnp.asarray(positions),
                               jnp.asarray(pages))
        else:
            out = self.step_fn(jnp.asarray(tokens),
                               jnp.asarray(positions))
        return np.asarray(out), None

    def verify(self, tokens, positions, tables, base_len, mask):
        """Speculative-verify for the fn protocols: the PR 2 step_fn
        contract is elementwise over its slot axis (each slot is an
        independent (token, position) query — that independence is
        what lets requests share a fixed-shape batch at all), so a
        draft tree verifies as ONE step_fn call with the rows flattened
        onto the slot axis.  kv_rows is None — token-harness pages
        materialize at append time."""
        import jax.numpy as jnp
        tokens = np.asarray(tokens, np.int32)
        s, k1 = tokens.shape
        flat_t = jnp.asarray(tokens.reshape(-1))
        flat_p = jnp.asarray(np.asarray(positions,
                                        np.int32).reshape(-1))
        if self.wants_pages and tables is not None:
            out = self.step_fn(flat_t, flat_p, jnp.asarray(tables))
        else:
            out = self.step_fn(flat_t, flat_p)
        return np.asarray(out).reshape(s, k1), None


def as_runner(step_fn=None, prefill_fn=None, *, runner=None, store=None,
              pass_page_table=None) -> ModelRunner:
    """The engine's construction shim: hand back ``runner`` as-is, or
    wrap legacy fns in a :class:`LegacyFnRunner`."""
    if runner is not None:
        if step_fn is not None or prefill_fn is not None:
            raise ValueError("pass either runner= or step_fn/prefill_fn,"
                             " not both")
        return runner
    if step_fn is None:
        raise ValueError("a step_fn or a runner is required")
    return LegacyFnRunner(step_fn, prefill_fn, store=store,
                          pass_page_table=pass_page_table)


# ---------------------------------------------------------------------------
# the real transformer
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 128
    d_model: int = 32
    n_layers: int = 2
    n_heads: int = 4
    n_kv_heads: int = 2
    head_dim: int = 8
    d_ff: int = 64

    @property
    def kv_bytes_per_token(self) -> int:
        """One token slot: all layers' K then V vectors, f32, the
        token-major layout ``[n_layers, 2, n_kv_heads, head_dim]``
        (``ops.paged_attention.arena_kv_view``)."""
        return self.n_layers * 2 * self.n_kv_heads * self.head_dim * 4


def init_runner_params(cfg: TransformerConfig, key=None) -> dict:
    """Seeded random parameters, stacked per layer (every layer shares
    one compiled step: params index by layer inside the jit)."""
    import jax
    import jax.numpy as jnp
    key = key if key is not None else jax.random.PRNGKey(0)
    ks = jax.random.split(key, 7)
    dm, h, hkv, d, ff = (cfg.d_model, cfg.n_heads, cfg.n_kv_heads,
                         cfg.head_dim, cfg.d_ff)
    L = cfg.n_layers

    def init(k, shape, fan_in):
        return jax.random.normal(k, shape, jnp.float32) \
            / math.sqrt(fan_in)

    return {
        "emb": init(ks[0], (cfg.vocab, dm), dm),
        "wq": init(ks[1], (L, dm, h * d), dm),
        "wk": init(ks[2], (L, dm, hkv * d), dm),
        "wv": init(ks[3], (L, dm, hkv * d), dm),
        "wo": init(ks[4], (L, h * d, dm), h * d),
        "w1": init(ks[5], (L, dm, ff), dm),
        "w2": init(ks[6], (L, ff, dm), ff),
    }


def make_tp_mesh(n_devices: Optional[int] = None):
    """A 1-D ``tp`` (tensor-parallel) ICI mesh — the moe.py ``ep``
    pattern applied to attention heads."""
    import jax
    from jax.sharding import Mesh
    n = n_devices or len(jax.devices())
    return Mesh(np.array(jax.devices()[:n]), ("tp",))


def place_runner_params(params: dict, mesh) -> dict:
    """Shard the parameter tree over the ``tp`` axis with
    NamedSharding (the SNIPPETS.md [1]/[3] pjit partitioning applied
    here under GSPMD): q/k/v projections and the MLP up-projection
    shard their OUTPUT (head/ff) dim, the o/down projections their
    INPUT dim, embeddings replicate.  The jitted step inherits the
    layout — no per-call resharding."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    specs = {
        "emb": P(),
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
        "w1": P(None, None, "tp"),
        "w2": P(None, "tp", None),
    }
    tp = mesh.shape["tp"]
    for name, dim in (("wq", params["wq"].shape[2]),
                      ("wk", params["wk"].shape[2]),
                      ("wv", params["wv"].shape[2]),
                      ("w1", params["w1"].shape[2])):
        if dim % tp:
            raise ValueError(f"{name} dim {dim} must divide tp={tp}")
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in params.items()}


def _posenc(pos, dm: int):
    """Parameter-free sinusoidal position encoding (deterministic, so
    the dense reference and the paged path agree by construction)."""
    import jax.numpy as jnp
    half = dm // 2
    freq = jnp.exp(-math.log(10000.0)
                   * jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[..., None] * freq
    return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)


def _rms(x):
    import jax.numpy as jnp
    return x / jnp.sqrt(jnp.mean(x * x, axis=-1, keepdims=True) + 1e-6)


def _mlp(x, w1, w2):
    import jax
    import jax.numpy as jnp
    return jax.nn.gelu(x @ w1) @ w2


def dense_forward(params: dict, cfg: TransformerConfig, tokens,
                  positions, use_flash: bool = True):
    """The DENSE reference forward: full causal self-attention over the
    whole sequence, no cache — the oracle the paged path is validated
    against, and the batcher's scoring path.  ``tokens``/``positions``
    are ``[B, S]``; returns per-position logits ``[B, S, vocab]``.
    Attention runs through the ops/attention.py flash kernel (the
    pallas TPU path with its CPU fallback) — the prefill-compute reuse
    the ISSUE names."""
    import jax.numpy as jnp

    from brpc_tpu.ops.attention import flash_attention, local_attention
    b, s = tokens.shape
    h_, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    h = params["emb"][tokens] + _posenc(positions, cfg.d_model)
    for l in range(cfg.n_layers):
        x = _rms(h)
        q = (x @ params["wq"][l]).reshape(b, s, h_, d)
        k = (x @ params["wk"][l]).reshape(b, s, hkv, d)
        v = (x @ params["wv"][l]).reshape(b, s, hkv, d)
        attn = flash_attention if use_flash else local_attention
        o = attn(q, k, v, causal=True)
        h = h + o.reshape(b, s, h_ * d) @ params["wo"][l]
        h = h + _mlp(_rms(h), params["w1"][l], params["w2"][l])
    return _rms(h) @ params["emb"].T


def dense_generate(params: dict, cfg: TransformerConfig,
                   prompt: Sequence[int], max_new_tokens: int) -> list:
    """Greedy decode with NO cache: the full sequence recomputes every
    step through :func:`dense_forward`.  The equivalence oracle for
    the paged runner — same math, none of the paging machinery."""
    import jax.numpy as jnp
    out = [int(t) for t in prompt]
    for _ in range(max_new_tokens):
        toks = jnp.asarray([out], jnp.int32)
        pos = jnp.arange(len(out), dtype=jnp.int32)[None]
        logits = dense_forward(params, cfg, toks, pos)
        out.append(int(jnp.argmax(logits[0, -1])))
    return out[len(prompt):]


# ---- jitted compute (module level, cfg static: the compile cache is
# shared by every runner instance with the same config — a supervisor
# rebuilding engines, the chaos seeds, and the bench trials all reuse
# one trace per bucket shape) ----

def _kv_view(arena_u8, cfg: TransformerConfig, page_tokens: int):
    from brpc_tpu.ops.paged_attention import arena_kv_view
    return arena_kv_view(arena_u8, page_tokens, cfg.n_layers,
                         cfg.n_kv_heads, cfg.head_dim)


def _jit(fn):
    import jax
    return jax.jit(fn, static_argnames=("cfg", "page_tokens", "backend"))


@functools.cache
def _jits():
    """Build the jitted kernels lazily (first runner construction), so
    importing brpc_tpu.models costs no jax tracing."""
    import jax
    import jax.numpy as jnp

    from brpc_tpu.ops.paged_attention import paged_attention

    def embed(params, tokens, positions, *, cfg, page_tokens, backend):
        return params["emb"][tokens] + _posenc(positions, cfg.d_model)

    def proj(params, h, l, *, cfg, page_tokens, backend):
        n = h.shape[0]
        x = _rms(h)
        q = (x @ params["wq"][l]).reshape(n, cfg.n_heads, cfg.head_dim)
        k = (x @ params["wk"][l]).reshape(n, cfg.n_kv_heads,
                                          cfg.head_dim)
        v = (x @ params["wv"][l]).reshape(n, cfg.n_kv_heads,
                                          cfg.head_dim)
        return q, k, v

    def attend(params, h, q, arena_u8, tables, lengths, l, *,
               cfg, page_tokens, backend):
        kv = _kv_view(arena_u8, cfg, page_tokens)
        o = paged_attention(q, kv[:, :, l, 0], kv[:, :, l, 1],
                            tables, lengths, backend=backend)
        h = h + o.reshape(h.shape[0], cfg.n_heads * cfg.head_dim) \
            @ params["wo"][l]
        return h + _mlp(_rms(h), params["w1"][l], params["w2"][l])

    def step(params, tokens, positions, tables, arena_u8, *,
             cfg, page_tokens, backend):
        s = tokens.shape[0]
        qpos = positions - 1      # the query position (see contract)
        kv = _kv_view(arena_u8, cfg, page_tokens)
        h = params["emb"][tokens] + _posenc(qpos, cfg.d_model)
        new_k, new_v = [], []
        for l in range(cfg.n_layers):
            x = _rms(h)
            q = (x @ params["wq"][l]).reshape(s, cfg.n_heads,
                                              cfg.head_dim)
            k = (x @ params["wk"][l]).reshape(s, cfg.n_kv_heads,
                                              cfg.head_dim)
            v = (x @ params["wv"][l]).reshape(s, cfg.n_kv_heads,
                                              cfg.head_dim)
            new_k.append(k)
            new_v.append(v)
            # arena keys 0..qpos-1 plus the in-flight self key: the
            # query position's slot may hold stale bytes (it is
            # written only after this step returns), so lengths
            # EXCLUDE it and extra_k/extra_v supply the value computed
            # right here
            o = paged_attention(q, kv[:, :, l, 0], kv[:, :, l, 1],
                                tables, qpos, extra_k=k, extra_v=v,
                                backend=backend)
            h = h + o.reshape(s, cfg.n_heads * cfg.head_dim) \
                @ params["wo"][l]
            h = h + _mlp(_rms(h), params["w1"][l], params["w2"][l])
        logits = _rms(h) @ params["emb"].T
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # pack this position's K/V rows in the token-major slot layout
        kv_rows = jnp.stack(
            [jnp.stack(new_k, axis=1), jnp.stack(new_v, axis=1)],
            axis=2)                     # [S, L, 2, Hkv, D]
        rows_u8 = jax.lax.bitcast_convert_type(
            kv_rows, jnp.uint8).reshape(s, cfg.kv_bytes_per_token)
        return nxt, rows_u8

    def verify(params, tokens, positions, tables, base_len, mask,
               arena_u8, *, cfg, page_tokens, backend):
        """Draft-tree verify (ISSUE 11): every row of every slot in ONE
        paged-attention call.  The arena part covers each slot's
        MATERIALIZED keys (per-row ``base_len`` — draft pages in the
        table hold nothing attendable and stay masked); the draft
        positions' K/V, computed right here, fold in as the kernel's
        LOCAL BLOCK under the ancestry ``mask`` — the multi-key
        generalization of the decode step's self-key merge, so a slot
        with zero drafts reduces exactly to a plain step row."""
        s, k1 = tokens.shape
        r = s * k1
        qpos = positions.reshape(r) - 1    # engine position convention
        kv = _kv_view(arena_u8, cfg, page_tokens)
        h = params["emb"][tokens.reshape(r)] \
            + _posenc(qpos, cfg.d_model)
        new_k, new_v = [], []
        for l in range(cfg.n_layers):
            x = _rms(h)
            q = (x @ params["wq"][l]).reshape(r, cfg.n_heads,
                                              cfg.head_dim)
            k = (x @ params["wk"][l]).reshape(r, cfg.n_kv_heads,
                                              cfg.head_dim)
            v = (x @ params["wv"][l]).reshape(r, cfg.n_kv_heads,
                                              cfg.head_dim)
            new_k.append(k)
            new_v.append(v)
            o = paged_attention(
                q, kv[:, :, l, 0], kv[:, :, l, 1], tables, base_len,
                local_k=k.reshape(s, k1, cfg.n_kv_heads, cfg.head_dim),
                local_v=v.reshape(s, k1, cfg.n_kv_heads, cfg.head_dim),
                local_mask=mask, backend=backend)
            h = h + o.reshape(r, cfg.n_heads * cfg.head_dim) \
                @ params["wo"][l]
            h = h + _mlp(_rms(h), params["w1"][l], params["w2"][l])
        logits = _rms(h) @ params["emb"].T
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        kv_rows = jnp.stack(
            [jnp.stack(new_k, axis=1), jnp.stack(new_v, axis=1)],
            axis=2)                     # [R, L, 2, Hkv, D]
        rows_u8 = jax.lax.bitcast_convert_type(
            kv_rows, jnp.uint8).reshape(s, k1, cfg.kv_bytes_per_token)
        return nxt.reshape(s, k1), rows_u8

    return {"embed": _jit(embed), "proj": _jit(proj),
            "attend": _jit(attend), "step": _jit(step),
            "verify": _jit(verify)}


def make_store_for(cfg: TransformerConfig, *, page_tokens: int = 8,
                   max_blocks: int = 8, pool=None, device=None,
                   commit_live_pages: bool = False, name: str = "kv"):
    """A KVCacheStore whose page geometry matches ``cfg``'s packed
    K/V slots (``vector_kv=True`` — the runner owns materialization)."""
    from brpc_tpu.kvcache import KVCacheStore
    return KVCacheStore(
        pool, device, page_bytes=page_tokens * cfg.kv_bytes_per_token,
        page_tokens=page_tokens, max_blocks=max_blocks,
        commit_live_pages=commit_live_pages, vector_kv=True, name=name)


class TransformerRunner(ModelRunner):
    """The real model (see module docstring).  One instance may serve
    any number of engine incarnations (the supervisor's factory reuses
    it across restarts — parameters and jit caches survive the
    rebuild)."""

    wants_pages = True
    has_prefill = True

    def __init__(self, params: dict, cfg: TransformerConfig, *,
                 store=None, mesh=None,
                 attn_backend: Optional[str] = None,
                 name: str = "model"):
        import jax
        self.cfg = cfg
        self.kv_bytes_per_token = cfg.kv_bytes_per_token
        self.name = name
        if mesh is not None:
            self.mesh = mesh
            self.params = place_runner_params(params, mesh)
        else:
            # params already placed by the caller (place_runner_params)
            # carry their mesh — the runner must know it to place the
            # arena consistently (below)
            sh = getattr(params.get("wq"), "sharding", None)
            self.mesh = getattr(sh, "mesh", None)
            self.params = params
        self.store = None
        self._mu = threading.Lock()
        # backend=None lets ops/paged_attention pick (pallas on TPU,
        # gather on CPU) at TRACE time, inside the shared jits
        self._backend = attn_backend
        self._fns = _jits()
        if store is not None:
            self.bind(store)

    def _statics(self) -> dict:
        return {"cfg": self.cfg, "page_tokens": self.store.page_tokens,
                "backend": self._backend}

    # ---- binding / validation ----

    def bind(self, store) -> None:
        if store is None:
            raise ValueError(
                "TransformerRunner needs a paged KVCacheStore "
                "(store=) — raw-block engines have no page layout "
                "for the kernel to read")
        with self._mu:
            if self.store is store:
                return
            if self.store is not None:
                raise ValueError("runner already bound to a store")
            if not getattr(store, "vector_kv", False):
                raise ValueError(
                    "store must be vector_kv=True (make_store_for) — "
                    "token-id stand-in pages are not attendable KV")
            kbpt = store.pagepool.kv_bytes_per_token
            if kbpt != self.cfg.kv_bytes_per_token:
                raise ValueError(
                    f"store kv_bytes_per_token={kbpt} != model slot "
                    f"{self.cfg.kv_bytes_per_token} "
                    f"(page_bytes/page_tokens must match the packed "
                    f"[L, 2, Hkv, D] f32 layout)")
            self.store = store

    # ---- the ModelRunner surface ----

    def _flat_tables(self, pages) -> np.ndarray:
        """pid page tables -> flat arena indices (fixed shape)."""
        pages = np.asarray(pages, np.int32)
        flat = self.store.pagepool.flat_ids(pages.ravel().tolist())
        return np.asarray(flat, np.int32).reshape(pages.shape)

    def _arena(self):
        """The pool arena, placed CONSISTENTLY with the params: page
        buffers are committed to the pool's single device, and a jit
        whose params shard over a tp mesh rejects mixed placements —
        replicate the arena over the mesh (plain single-device serving
        returns it untouched).  Sharding the K/V pages themselves over
        the mesh heads is the ROADMAP follow-on; replication is the
        correct-if-wasteful tensor-parallel baseline."""
        import jax
        arena = self.store.pagepool.arena()
        if self.mesh is None:
            return arena
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(arena, NamedSharding(self.mesh, P()))

    def step(self, tokens, positions, pages):
        import jax.numpy as jnp
        if fault.ENABLED and fault.hit(
                "model.step_compute", runner=self.name) is not None:
            raise RuntimeError("injected model step-compute failure")
        tables = self._flat_tables(pages)
        arena = self._arena()
        nxt, rows = self._fns["step"](self.params,
                                      jnp.asarray(tokens, jnp.int32),
                                      jnp.asarray(positions, jnp.int32),
                                      jnp.asarray(tables), arena,
                                      **self._statics())
        return np.asarray(nxt), np.asarray(rows)

    def verify(self, tokens, positions, tables, base_len, mask):
        import jax.numpy as jnp
        flat = self._flat_tables(tables)
        arena = self._arena()
        nxt, rows = self._fns["verify"](
            self.params,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(flat),
            jnp.asarray(base_len, jnp.int32),
            jnp.asarray(mask, bool),
            arena, **self._statics())
        return np.asarray(nxt), np.asarray(rows)

    def prefill(self, tokens, positions, pages, seq=None):
        """Write-then-attend per layer (see module docstring): layer
        l's suffix K/V splice into the pages BEFORE the layer attends,
        so every query reads every key — its own included — from the
        ONE arena layout, cold and warm alike."""
        import jax.numpy as jnp
        if seq is None:
            raise ValueError("TransformerRunner.prefill needs the "
                             "owning KVSeq (seq=)")
        cfg = self.cfg
        start = int(positions[0])
        n = len(seq.tokens) - start       # valid (un-padded) rows
        if n <= 0:
            return
        b = len(tokens)
        toks = jnp.asarray(tokens, jnp.int32)
        pos = jnp.asarray(positions, jnp.int32)
        lengths = np.asarray(positions, np.int32) + 1   # causal: 0..i
        statics = self._statics()
        h = self._fns["embed"](self.params, toks, pos, **statics)
        # host-side running slot buffer: after layer l, each valid
        # row's slot holds layers 0..l — layers above are zeros, which
        # layer l never reads
        kvbuf = np.zeros((b, cfg.n_layers, 2, cfg.n_kv_heads,
                          cfg.head_dim), np.float32)
        for l in range(cfg.n_layers):
            q, k, v = self._fns["proj"](self.params, h, l, **statics)
            kvbuf[:, l, 0] = np.asarray(k)
            kvbuf[:, l, 1] = np.asarray(v)
            rows = kvbuf[:n].reshape(n, -1).view(np.uint8)
            # only the LAST layer's pass completes the slots: advancing
            # kv_filled (or live-committing) earlier would publish
            # pages whose upper layers are still zeros
            self.store.write_kv(seq, start, rows,
                                final=(l == cfg.n_layers - 1))
            # re-gather after the write: a COW inside write_kv swaps
            # page identities, and the arena must reflect the splice
            tab_row = self._flat_tables(seq.page_ids())
            mp = len(pages) if pages is not None else len(tab_row)
            padded = np.full((mp,), -1, np.int32)
            padded[:min(len(tab_row), mp)] = tab_row[:mp]
            tables = np.broadcast_to(padded, (b, mp))
            arena = self._arena()
            h = self._fns["attend"](self.params, h, q, arena,
                                    jnp.asarray(np.ascontiguousarray(
                                        tables)),
                                    jnp.asarray(lengths), l, **statics)

    # ---- the batcher surface (Serving.Score over the real model) ----

    def score(self, padded):
        """1-arg batch_fn: per-position greedy next-token ids
        ``[B, L]`` over the dense forward (flash-kernel prefill
        compute) — the batcher trims row i back to the request's raw
        length."""
        return self._score(padded, None)

    def score_with_offsets(self, padded, offsets):
        """2-arg batch_fn for prefix-trimmed batchers: rows are
        suffixes, ``offsets`` their global start positions."""
        return self._score(padded, offsets)

    def _score(self, padded, offsets):
        import jax.numpy as jnp
        toks = np.asarray(padded)
        if toks.dtype != np.int32:
            toks = toks.astype(np.int32)
        b, s = toks.shape
        pos = np.tile(np.arange(s, dtype=np.int32), (b, 1))
        if offsets is not None:
            pos = pos + np.asarray(offsets, np.int32)[:b, None]
        logits = dense_forward(self.params, self.cfg,
                               jnp.asarray(toks), jnp.asarray(pos))
        return np.asarray(jnp.argmax(logits, axis=-1),
                          dtype=np.float32)


def run_prefill(runner: ModelRunner, seq, prompt: Sequence[int], *,
                buckets: Sequence[int] = DEFAULT_PREFILL_BUCKETS,
                max_pages: int = 64) -> int:
    """Standalone prefill driver for callers OUTSIDE the engine (the
    disagg PrefillReplica): bucket-pad the uncached suffix and run
    ``runner.prefill`` against the admitted ``seq``.  Returns the
    suffix length prefilled."""
    suffix = [int(t) for t in prompt[seq.prefill_from:]]
    if not suffix or not runner.has_prefill:
        return 0
    n = len(suffix)
    bucket = next((x for x in sorted(buckets) if n <= x), n)
    padded = np.zeros((bucket,), np.int32)
    padded[:n] = suffix
    positions = seq.prefill_from + np.arange(bucket, dtype=np.int32)
    ids = seq.page_ids()
    pages = np.full((max(max_pages, len(ids)),), -1, np.int32)
    pages[:len(ids)] = ids
    runner.prefill(padded, positions, pages, seq=seq)
    return n
