"""Native hot-path gate (ISSUE 9).

One switchboard for the serving stack's de-GIL'd paths — the native
emit token rings (engine), GIL-released batch assembly (batcher), the
native span queue (rpcz) and the flight-recorder surface (ISSUE 15)
all ask HERE whether the native road is available:

  * the reloadable flag ``native_hot_path_enabled`` (default True,
    flip live on /flags) is the operator's kill switch — platforms
    where ``libbrpc_core.so`` cannot build, or a suspected native bug,
    fall back to the pure-Python implementations with identical
    semantics (tier-1 passes either way);
  * availability is probed lazily and cached: importing ``_core``
    builds the library on first use, and a failed build must degrade
    to the Python path, not break serving.

The pure-Python fallbacks are the PR 2/3 implementations, kept in
place (``serving/engine.py`` ``_EmitBuf``, the batcher's numpy pad
loop, the collector submit path) — the flag chooses per REQUEST /
per BATCH / per SPAN, so flipping it live is safe: in-flight native
rings keep draining natively while new requests take the Python path.
"""
from __future__ import annotations

from brpc_tpu.flags import define_flag, get_flag

define_flag("native_hot_path_enabled", True,
            "serve the per-token hot path (emit rings, batch assembly, "
            "span queue) through the native core; off = pure-Python "
            "fallback with identical semantics", reloadable=True)

_lib = None
_lib_failed = False
_fastrpc = None


def _core_lib():
    """brpc_tpu._core.lib, or None when the native build is
    unavailable (cached either way)."""
    global _lib, _lib_failed
    if _lib is None and not _lib_failed:
        try:
            from brpc_tpu._core import lib as _l
            _lib = _l
        except Exception:
            _lib_failed = True
    return _lib


def _fastrpc_mod():
    # cache only SUCCESS: lib._fastrpc_mod returns None while the
    # extension is still building (and caps its own import attempts),
    # so a first call landing mid-build must not freeze this process
    # on the slow path forever — keep asking until the module loads
    global _fastrpc
    if _fastrpc is None:
        lib = _core_lib()
        if lib is not None:
            _fastrpc = lib._fastrpc_mod()
    return _fastrpc


def enabled() -> bool:
    """True when the flag is on AND the native core loaded."""
    return bool(get_flag("native_hot_path_enabled", True)) \
        and _core_lib() is not None


def spanq() -> object | None:
    """The _fastrpc module exposing spanq_push/drain, or None when the
    native span queue should not be used."""
    if not get_flag("native_hot_path_enabled", True):
        return None
    return _fastrpc_mod()


def token_ring(cap: int):
    """A native TokenRing, or None to use the Python _EmitBuf."""
    if not enabled():
        return None
    return _core_lib().TokenRing(cap)


def tokring_live() -> int:
    lib = _core_lib()
    return lib.tokring_live() if lib is not None else 0


def flight_recorder():
    """The native flight-recorder surface (brpc_tpu.butil.flight over
    src/cc/butil/flight.h), or None when the native core is
    unavailable.  Unlike the hot paths above, the recorder has no
    pure-Python fallback — it observes the native core, so without the
    core there is nothing to observe; callers treat None as "no
    evidence", never as an error (ISSUE 15)."""
    if _core_lib() is None:
        return None
    from brpc_tpu.butil import flight
    return flight


def batch_pad_available() -> bool:
    return enabled()


def batch_pad(out, rows, lengths) -> None:
    """Zero-fill the 2-D C-contiguous numpy array ``out`` and copy
    ``rows[i]`` (1-D arrays of out.dtype, C-contiguous, exactly
    ``lengths[i]`` elements long — the batcher's enqueue coercion
    guarantees it) into ``out[i, :lengths[i]]`` — one native call, GIL
    released for the memset+memcpy pass."""
    fb = _fastrpc_mod()
    if fb is not None:
        # buffer-protocol arg parsing: no per-row .ctypes view objects
        # (the ctypes path below pays ~25us of marshalling per call,
        # which swamps the copy for serving-sized batches)
        fb.batch_pad(out, rows)
        return
    import ctypes
    lib = _core_lib()
    n = len(rows)
    ptrs = (ctypes.c_void_p * n)(
        *[r.ctypes.data for r in rows])
    itemsize = out.itemsize
    nbytes = (ctypes.c_int64 * n)(
        *[int(ln) * itemsize for ln in lengths])
    lib.core.brpc_batch_pad(ptrs, nbytes, n, out.ctypes.data,
                            out.shape[1] * itemsize, out.nbytes)


def page_table_fill(table, lists, slot_idx) -> None:
    """Fill the fixed-shape int32 ``table`` with -1 and copy each
    int32 page-id array ``lists[k]`` into row ``slot_idx[k]``
    (truncated to the table width) — one GIL-released native call."""
    fb = _fastrpc_mod()
    if fb is not None:
        fb.page_table_fill(table, lists, slot_idx)
        return
    import ctypes
    lib = _core_lib()
    n = len(lists)
    ptrs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in lists])
    lens = (ctypes.c_int64 * n)(*[len(a) for a in lists])
    idx = (ctypes.c_int32 * n)(*slot_idx)
    lib.core.brpc_page_table_fill(ptrs, lens, idx, n, table.ctypes.data,
                                  table.shape[0], table.shape[1])
