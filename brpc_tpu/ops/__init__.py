"""Hot compute ops — sequence-parallel attention for long context.

This fills the "long-context is first-class" slot (SURVEY.md §5.7): the
reference moves unbounded payloads through bounded memory with credit-
windowed streams; on TPU the analogous scale axis is sequence length, and
the framework ships exact sequence-parallel attention over the mesh:

  ring_attention     K/V blocks circulate a ppermute ring; online-softmax
                     keeps the result exact with each chip holding only
                     1/n of the sequence (the StreamWrite credit loop in
                     collective form).
  ulysses_attention  all_to_all reshard: sequence-sharded -> head-sharded,
                     full attention locally per head group, reshard back.
  flash_attention    blockwise local attention; a Pallas TPU kernel with a
                     lax fallback for non-TPU backends.  causal=True cuts
                     the K loop at the diagonal (~2x fewer FLOPs); 69.7
                     TFLOP/s measured on a v5 lite vs 23.6 for fused XLA.
  paged_attention    attention over the KV cache's HBM page layout
                     (ISSUE 10): queries gather K/V through the engine's
                     per-slot page tables — a scalar-prefetch Pallas
                     kernel on TPU, a pure-jax gather on CPU, bit-equal
                     contracts (see ops/paged_attention.py).
"""
from brpc_tpu.ops.attention import (flash_attention, local_attention,
                                    ring_attention, ulysses_attention)
from brpc_tpu.ops.paged_attention import (arena_kv_view, paged_attention,
                                          paged_attention_gather,
                                          paged_attention_pallas)

__all__ = ["flash_attention", "local_attention", "ring_attention",
           "ulysses_attention", "paged_attention",
           "paged_attention_gather", "paged_attention_pallas",
           "arena_kv_view"]
