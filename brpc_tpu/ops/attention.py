"""Sequence-parallel attention: ring (ppermute + online softmax), Ulysses
(all_to_all head/sequence reshard), and a Pallas flash kernel for the
local block computation.

Design notes (TPU-first):
- All matmuls are batched [B*H, blk, d] x [B*H, d, blk] — large enough to
  tile onto the MXU; bf16-friendly (accumulate in f32).
- Ring steps use `jax.lax.fori_loop` with static shapes; the per-step
  ppermute rides ICI while the current block's FLOPs overlap it when the
  compiler can (same overlap discipline as the reference's KeepWrite
  draining while callers keep appending, socket.cpp:1692-1800).
- Online softmax (running max m, normalizer l) keeps ring attention EXACT
  — not an approximation — with each chip holding 1/n of K/V.
- Causal masking is done with GLOBAL positions, so sharded and unsharded
  results match bit-for-bit up to reduction order.

Shapes: q, k, v are [batch, seq_shard, heads, head_dim] inside shard_map
(sequence axis sharded over `axis_name`), or [batch, seq, heads, head_dim]
for the local/single-device paths.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


# ---- GQA (grouped-query) broadcast ----------------------------------------

def _expand_kv(q, k, v):
    """Grouped-query attention: when K/V carry fewer heads than Q
    (n_kv_heads divides n_heads — LLaMA/Mistral-style GQA, MQA at
    n_kv_heads=1), repeat each K/V head across its query-head group.
    XLA lowers the repeat to a broadcast that fuses into the einsum, so
    the expanded tensors are a view of the computation, not 8x HBM."""
    h_q, h_kv = q.shape[2], k.shape[2]
    if h_kv == h_q:
        return k, v
    if h_q % h_kv:
        raise ValueError(
            f"n_heads ({h_q}) must be a multiple of n_kv_heads ({h_kv})")
    g = h_q // h_kv
    return jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2)


# ---- local (single-chip) reference ----------------------------------------

def local_attention(q, k, v, causal: bool = False, q_offset: int = 0,
                    kv_offset: int = 0):
    """Plain softmax(QK^T/sqrt(d))V on one chip.  Offsets give the global
    sequence positions of the q and k/v blocks for causal masking; rows
    whose mask hides every key yield zeros (not NaN) so blockwise callers
    can fold partial blocks safely.  Supports GQA/MQA (fewer K/V heads
    than Q heads)."""
    k, v = _expand_kv(q, k, v)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    # [B,H,Sq,Sk]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_offset + jnp.arange(sq)[:, None]
        kpos = kv_offset + jnp.arange(sk)[None, :]
        s = jnp.where(qpos >= kpos, s, -jnp.inf)
    # -inf-safe softmax: all-masked rows produce 0 weights, not NaN
    m = s.max(axis=-1, keepdims=True)
    m = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(s - m)
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    l = p.sum(axis=-1, keepdims=True)
    p = p / jnp.where(l == 0.0, 1.0, l)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


# ---- pallas flash kernel (local block) ------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, blk_k: int, scale: float,
                  causal: bool):
    """One (batch*head, q-block) program: stream K/V blocks through VMEM
    with an online-softmax accumulator.  Grid: (BH, n_q_blocks).

    Causal: the K-block loop's trip count is CUT at the q-block's
    diagonal (blocks entirely above it are never loaded or computed —
    the ~2x FLOP saving that makes flash causal attention pay), and the
    blocks straddling the diagonal get a per-element position mask."""
    from jax.experimental import pallas as pl

    q = q_ref[...].astype(jnp.float32) * scale          # [blk_q, d]
    blk_q, d = q.shape
    sk = k_ref.shape[0]
    n_kb = sk // blk_k
    q_start = pl.program_id(1) * blk_q if causal else 0

    def body(i, carry, masked: bool = False):
        o, m, l = carry
        # dynamic-slice the REF (pl.ds lowers to Mosaic vector loads);
        # slicing a loaded VALUE emits the dynamic_slice primitive, which
        # Mosaic's TC lowering rejects — interpret mode hides that, so
        # only a real-TPU run catches it
        k_blk = k_ref[pl.ds(i * blk_k, blk_k), :]
        v_blk = v_ref[pl.ds(i * blk_k, blk_k), :]
        s = jnp.dot(q, k_blk.astype(jnp.float32).T,
                    preferred_element_type=jnp.float32)  # [blk_q, blk_k]
        if masked:
            qpos = q_start + lax.broadcasted_iota(jnp.int32,
                                                  (blk_q, blk_k), 0)
            kpos = i * blk_k + lax.broadcasted_iota(jnp.int32,
                                                    (blk_q, blk_k), 1)
            s = jnp.where(qpos >= kpos, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[:, None] + jnp.dot(
            p, v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return o, m_new, l

    carry = (jnp.zeros((blk_q, d), jnp.float32),
             jnp.full((blk_q,), -jnp.inf, jnp.float32),
             jnp.zeros((blk_q,), jnp.float32))
    if causal:
        # split at the diagonal: blocks whose LAST key is visible to the
        # q block's FIRST row need no mask; only the straddling block(s)
        # pay the iota/compare/select VPU work, and blocks entirely above
        # the diagonal are never loaded at all
        n_full = lax.div(q_start + 1, blk_k)
        n_vis = lax.div(q_start + blk_q + blk_k - 1, blk_k)
        carry = lax.fori_loop(0, n_full, body, carry)
        carry = lax.fori_loop(
            n_full, n_vis,
            functools.partial(body, masked=True), carry)
    else:
        carry = lax.fori_loop(0, n_kb, body, carry)
    o, _, l = carry
    o_ref[...] = (o / l[:, None]).astype(o_ref.dtype)


def flash_attention(q, k, v, blk_q: int = 256, blk_k: int = 256,
                    causal: bool = False,
                    interpret: Optional[bool] = None):
    """Blockwise (flash) attention as a Pallas TPU kernel.  Falls back
    to interpret mode off-TPU so the same code path tests on the virtual
    CPU mesh.  Shapes [B, S, H, D] -> [B, S, H, D].  GQA/MQA K/V are
    expanded up front (the kernel's grid is per query-head).  causal=True
    skips K blocks above each q block's diagonal entirely (~2x fewer
    FLOPs) and position-masks only the straddling blocks — measured
    numbers live in BENCH_DEVICE_SESSION_r05.json session4 (v5 lite,
    B4 S4096 H8 D128: 69.7 vs 23.6 TFLOP/s non-causal, 4.1x on
    causal)."""
    from jax.experimental import pallas as pl

    k, v = _expand_kv(q, k, v)
    b, s, h, d = q.shape
    blk_q = min(blk_q, s)
    blk_k = min(blk_k, s)
    if s % blk_q or s % blk_k:
        return local_attention(q, k, v, causal=causal)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = 1.0 / math.sqrt(d)
    # [B,S,H,D] -> [B*H, S, D]
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    out = pl.pallas_call(
        functools.partial(_flash_kernel, blk_k=blk_k, scale=scale,
                          causal=causal),
        grid=(b * h, s // blk_q),
        in_specs=[
            pl.BlockSpec((None, blk_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, blk_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


# ---- ring attention (sequence parallel, exact) -----------------------------

def ring_attention(q, k, v, axis_name: str, causal: bool = False):
    """Exact attention with the sequence sharded over `axis_name`.

    Each chip starts with its local K/V shard; n-1 ppermute steps rotate
    the shards around the ring while an online-softmax accumulator folds
    each block in.  Memory per chip stays O(S/n); the full S x S score
    matrix never materializes anywhere.  Must be called inside shard_map
    with q/k/v sequence-sharded on `axis_name`.  Supports GQA/MQA: K/V
    with fewer heads are expanded AFTER each ring hop, so the ring moves
    the small grouped shards (g-times less ICI traffic than expanded).
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    perm = [(i, (i + 1) % n) for i in range(n)]

    qf = q.astype(jnp.float32)
    qpos = my * sq + jnp.arange(sq)          # global q positions

    def step(i, carry):
        o, m, l, k_blk, v_blk = carry
        src = (my - i) % n                   # whose shard we now hold
        # expand grouped K/V heads AFTER the hop (ICI carries the small
        # tensors; the broadcast fuses into the einsum)
        ke, ve = _expand_kv(qf, k_blk, v_blk)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, ke.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * scale
        if causal:
            kpos = src * sq + jnp.arange(k_blk.shape[1])
            mask = qpos[:, None] >= kpos[None, :]       # [sq, sk]
            s = jnp.where(mask[None, None, :, :], s, -jnp.inf)
        blk_max = s.max(axis=-1)                        # [b,h,sq]
        m_new = jnp.maximum(m, blk_max)
        # fully-masked rows produce -inf maxima; guard every exp
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        l_new = l * alpha + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p,
                        ve.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return o_new, m_new, l_new, k_nxt, v_nxt

    o0 = jnp.zeros((b, sq, h, d), jnp.float32)
    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    # the loop's ppermute makes carries device-varying over the mesh axis;
    # mark the constant initials to match (shard_map vma typing)
    try:
        o0, m0, l0 = (lax.pcast(x, (axis_name,), to="varying")
                      for x in (o0, m0, l0))
    except (AttributeError, TypeError):  # older jax: untyped carries
        pass
    o, m, l, _, _ = lax.fori_loop(0, n, step, (o0, m0, l0, k, v))
    l = jnp.where(l == 0.0, 1.0, l)          # rows with no visible keys
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


# ---- Ulysses (all_to_all) attention ---------------------------------------

def ulysses_attention(q, k, v, axis_name: str, causal: bool = False):
    """DeepSpeed-Ulysses style: all_to_all swaps the sharded axis from
    sequence to heads, each chip runs FULL-sequence attention for its head
    group, and a second all_to_all swaps back.  Heads must divide the axis
    size.  Exact; two collectives instead of n-1 ring hops — better when
    heads >= chips and the fabric favors all_to_all.  GQA/MQA K/V are
    expanded BEFORE the reshard (the head-split needs n to divide the
    head count; grouped counts usually don't — ring_attention keeps the
    traffic saving when that matters)."""
    k, v = _expand_kv(q, k, v)
    n = lax.psum(1, axis_name)
    b, sq, h, d = q.shape

    def seq_to_heads(x):
        # [b, sq, h, d] -> [b, n*sq, h/n, d]
        x = x.reshape(b, sq, n, h // n, d)
        x = lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                           tiled=False)
        return x.reshape(b, n * sq, h // n, d)

    def heads_to_seq(x):
        x = x.reshape(b, n, sq, h // n, d)
        # received (source-chip) axis must land OUTSIDE the local-head
        # axis: chip c computed global heads [c*h/n, (c+1)*h/n), so the
        # flatten below must see [n, h/n] in that order.  (concat_axis=3
        # would interleave heads for any n < h — invisible at n == h
        # where h/n == 1.)
        x = lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                           tiled=False)
        return x.reshape(b, sq, h, d)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    og = local_attention(qg, kg, vg, causal=causal)
    return heads_to_seq(og)
