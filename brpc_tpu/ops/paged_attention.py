"""Paged attention over the KV cache's HBM page layout (ISSUE 10).

The serving stack's KV lives in fixed-size refcounted pages carved out
of leased HBM blocks (``kvcache/pages.py``); the engine already gathers
a fixed-shape per-slot page table every step.  This module is the
kernel that CONSUMES that layout: queries attend over K/V gathered
through the page table, so prefix-shared pages, copy-on-write forks and
radix-cached chunks all feed the model without ever being flattened
into per-sequence contiguous buffers.

Two backends, one contract:

  * ``gather`` — pure jax (``jnp.take`` over the arena + one masked
    softmax).  Runs anywhere; the CPU-valid default, so tier-1 under
    ``JAX_PLATFORMS=cpu`` exercises exactly this path.
  * ``pallas`` — a ``pallas_call`` TPU kernel using
    ``PrefetchScalarGridSpec``: the page table is a SCALAR-PREFETCH
    argument, so each grid step's K/V block is DMA'd straight from the
    arena row the table names (the classic paged-attention pattern —
    the gather never materializes).  Online-softmax accumulation over
    the page axis, exactly the flash discipline of
    ``ops/attention.py``.  ``interpret=True`` off-TPU keeps the kernel
    testable on the virtual CPU mesh.

Shapes (one query per row — decode steps batch rows across slots,
prefill batches rows across suffix positions):

  q        [N, H, D]        query vectors
  k_pages  [P, T, Hkv, D]   the arena view: P pages of T token slots
  v_pages  [P, T, Hkv, D]
  tables   [N, MP] int32    per-row page table: FLAT arena indices
                            (``PagePool.flat_ids``), -1 padded
  lengths  [N] int32        per-row valid KEY positions: key j of row i
                            participates iff j < lengths[i] — causal
                            masking IS the lengths vector
  extra_k/extra_v [N, Hkv, D] optional one-key append per row: the
                            decode step's own just-computed K/V, merged
                            into the same softmax (its key position is
                            lengths[i], i.e. always visible)
  local_k/local_v [G, W, Hkv, D] optional LOCAL KEY BLOCK (ISSUE 11):
                            rows reshape into G groups of W queries
                            (N == G*W — a speculative-verify batch is
                            one group per decode slot, W = draft rows),
                            and every query in group g may additionally
                            attend over that group's W in-call keys —
                            the draft positions' K/V, computed in the
                            same forward pass, never materialized into
                            pages.  Visibility is the boolean
  local_mask      [G, W, W]  ancestry mask: query row i of group g sees
                            local key j iff ``local_mask[g, i, j]`` —
                            lower-triangular for a linear draft chain,
                            the tree mask for branching drafts.  The
                            fold is one more online-softmax merge, so a
                            row with one visible local key (itself) is
                            numerically the ``extra_k`` decode-step
                            fold.  Mutually exclusive with extra_k.

GQA/MQA: fewer K/V heads than query heads are expanded per group, the
``_expand_kv`` contract of ops/attention.py.

Rows with lengths <= 0 and no extra key yield zeros (never NaN), so
inactive decode slots cost nothing to mask upstream.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["paged_attention", "paged_attention_gather",
           "paged_attention_pallas", "arena_kv_view"]


def arena_kv_view(arena_u8, page_tokens: int, n_layers: int,
                  n_kv_heads: int, head_dim: int):
    """Bitcast a PagePool :meth:`~brpc_tpu.kvcache.pages.PagePool.arena`
    byte array ``[P, page_bytes]`` into the packed K/V view
    ``[P, T, L, 2, Hkv, D]`` f32 — the token-major slot layout the
    ModelRunner writes (``models/runner.py``): one token's slot holds
    all layers' K then V vectors contiguously, so a decode step
    materializes a position with ONE page splice."""
    p = arena_u8.shape[0]
    flat = arena_u8.reshape(p, page_tokens, n_layers, 2, n_kv_heads,
                            head_dim, 4)
    return jax.lax.bitcast_convert_type(flat, jnp.float32)


def _expand_heads(x, n_heads: int):
    """[..., Hkv, D] -> [..., H, D] by repeating each K/V head across
    its query-head group (GQA; the broadcast fuses into the einsum)."""
    hkv = x.shape[-2]
    if hkv == n_heads:
        return x
    if n_heads % hkv:
        raise ValueError(f"n_heads ({n_heads}) must be a multiple of "
                         f"n_kv_heads ({hkv})")
    return jnp.repeat(x, n_heads // hkv, axis=-2)


# ---- gather backend (pure jax; the CPU-valid default) ----------------------

def _check_local(extra_k, local_k, local_v, local_mask, n):
    if local_k is None:
        return
    if extra_k is not None:
        raise ValueError("extra_k and local_k are mutually exclusive")
    if local_v is None or local_mask is None:
        raise ValueError("local_k needs local_v and local_mask")
    g, w = local_mask.shape[0], local_mask.shape[1]
    if g * w != n:
        raise ValueError(f"local block groups {g}x{w} != {n} query rows")


def paged_attention_gather(q, k_pages, v_pages, tables, lengths,
                           extra_k=None, extra_v=None,
                           local_k=None, local_v=None, local_mask=None):
    n, h, d = q.shape
    p, t, hkv, _ = k_pages.shape
    mp = tables.shape[1]
    _check_local(extra_k, local_k, local_v, local_mask, n)
    scale = 1.0 / math.sqrt(d)
    safe = jnp.clip(tables, 0, p - 1)
    # [N, MP, T, Hkv, D] -> [N, MP*T, H, D]; clipped -1 rows are masked
    # below (key position >= lengths), so their values never matter
    k = jnp.take(k_pages, safe, axis=0).reshape(n, mp * t, hkv, d)
    v = jnp.take(v_pages, safe, axis=0).reshape(n, mp * t, hkv, d)
    k = _expand_heads(k, h)
    v = _expand_heads(v, h)
    qf = q.astype(jnp.float32) * scale
    s = jnp.einsum("nhd,nkhd->nhk", qf, k.astype(jnp.float32),
                   preferred_element_type=jnp.float32)  # [N, H, MP*T]
    kpos = jnp.arange(mp * t, dtype=jnp.int32)
    # a key participates iff its position is visible AND its table
    # entry names a real page — same contract as the pallas kernel's
    # tab >= 0 mask; without it a -1 entry mid-table (a page freed
    # between the engine's gather and this call) would fold page 0's
    # K/V into the softmax through the clip above
    mask = (kpos[None, None, :] < lengths[:, None, None]) \
        & jnp.repeat(tables >= 0, t, axis=1)[:, None, :]
    s = jnp.where(mask, s, -jnp.inf)
    if extra_k is not None:
        ek = _expand_heads(extra_k, h).astype(jnp.float32)  # [N, H, D]
        ev = _expand_heads(extra_v, h)
        es = jnp.einsum("nhd,nhd->nh", qf, ek)[..., None]   # [N, H, 1]
        s = jnp.concatenate([s, es], axis=-1)
        v = jnp.concatenate([v, ev[:, None]], axis=1)       # [N, K+1, H, D]
    if local_k is not None:
        g, w = local_mask.shape[0], local_mask.shape[1]
        lk = _expand_heads(local_k, h).astype(jnp.float32)  # [G, W, H, D]
        lv = _expand_heads(local_v, h)
        qg = qf.reshape(g, w, h, d)
        # [G, Wq, H, Wk]: every query row of the group scores every
        # local key; the ancestry mask decides visibility (a masked
        # entry folds in as exp(-inf)=0, bit-preserving the visible sum)
        ls = jnp.einsum("gihd,gjhd->gihj", qg, lk,
                        preferred_element_type=jnp.float32)
        ls = jnp.where(local_mask[:, :, None, :], ls, -jnp.inf)
        s = jnp.concatenate([s, ls.reshape(n, h, w)], axis=-1)
        lvb = jnp.broadcast_to(lv[:, None], (g, w, w, h, d))
        v = jnp.concatenate([v, lvb.reshape(n, w, h, d)], axis=1)
    # -inf-safe softmax: rows with no visible key yield zeros, not NaN
    m = s.max(axis=-1, keepdims=True)
    m = jnp.where(jnp.isneginf(m), 0.0, m)
    pr = jnp.exp(s - m)
    pr = jnp.where(jnp.isneginf(s), 0.0, pr)
    l = pr.sum(axis=-1, keepdims=True)
    pr = pr / jnp.where(l == 0.0, 1.0, l)
    return jnp.einsum("nhk,nkhd->nhd",
                      pr.astype(jnp.float32),
                      v.astype(jnp.float32)).astype(q.dtype)


# ---- pallas backend --------------------------------------------------------

def _paged_kernel(tab_ref, len_ref, q_ref, k_ref, v_ref,
                  o_ref, m_ref, l_ref, *, page_tokens: int, scale: float,
                  n_heads: int):
    """One (row, page) program: fold page ``tables[n, m]``'s K/V block
    into row n's online-softmax accumulator.  The page table and
    lengths ride SCALAR PREFETCH, so the BlockSpec index_map DMA'd
    k_ref/v_ref straight from the arena row the table names — no
    gathered copy of the K/V ever exists.  Outputs stay UNNORMALIZED
    (o, m, l); the wrapper merges the optional self-key and divides."""
    from jax.experimental import pallas as pl
    n = pl.program_id(0)
    m_i = pl.program_id(1)

    @pl.when(m_i == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[...].astype(jnp.float32) * scale          # [H, D]
    k = k_ref[...].astype(jnp.float32)                  # [T, Hkv, D]
    v = v_ref[...].astype(jnp.float32)
    hkv = k.shape[1]
    if hkv != n_heads:
        k = jnp.repeat(k, n_heads // hkv, axis=1)
        v = jnp.repeat(v, n_heads // hkv, axis=1)
    s = jnp.einsum("hd,thd->ht", q, k,
                   preferred_element_type=jnp.float32)  # [H, T]
    # mask: global key position of slot t in page m is m*T + t; valid
    # iff < lengths[n] AND the table entry is a real page (>= 0)
    kpos = m_i * page_tokens + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1)
    valid = (kpos < len_ref[n]) & (tab_ref[n, m_i] >= 0)
    s = jnp.where(valid, s, -jnp.inf)
    m_prev = m_ref[...]
    l_prev = l_ref[...]
    blk_max = s.max(axis=-1)
    m_new = jnp.maximum(m_prev, blk_max)
    # all-masked-so-far rows keep -inf maxima; guard every exp
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    alpha = jnp.where(jnp.isneginf(m_prev), 0.0,
                      jnp.exp(m_prev - m_safe))
    p = jnp.exp(s - m_safe[:, None])
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    m_ref[...] = m_new
    l_ref[...] = l_prev * alpha + p.sum(axis=-1)
    o_ref[...] = o_ref[...] * alpha[:, None] + jnp.einsum(
        "ht,thd->hd", p, v, preferred_element_type=jnp.float32)


def paged_attention_pallas(q, k_pages, v_pages, tables, lengths,
                           extra_k=None, extra_v=None,
                           local_k=None, local_v=None, local_mask=None,
                           interpret: Optional[bool] = None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    n, h, d = q.shape
    p, t, hkv, _ = k_pages.shape
    mp = tables.shape[1]
    _check_local(extra_k, local_k, local_v, local_mask, n)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = 1.0 / math.sqrt(d)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,           # tables, lengths
        grid=(n, mp),
        in_specs=[
            pl.BlockSpec((None, h, d), lambda i, m, tab, ln: (i, 0, 0)),
            pl.BlockSpec((None, t, hkv, d),
                         lambda i, m, tab, ln:
                         (jnp.clip(tab[i, m], 0, p - 1), 0, 0, 0)),
            pl.BlockSpec((None, t, hkv, d),
                         lambda i, m, tab, ln:
                         (jnp.clip(tab[i, m], 0, p - 1), 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, h, d), lambda i, m, tab, ln: (i, 0, 0)),
            pl.BlockSpec((None, h), lambda i, m, tab, ln: (i, 0)),
            pl.BlockSpec((None, h), lambda i, m, tab, ln: (i, 0)),
        ],
    )
    o, mx, l = pl.pallas_call(
        functools.partial(_paged_kernel, page_tokens=t, scale=scale,
                          n_heads=h),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n, h, d), jnp.float32),
            jax.ShapeDtypeStruct((n, h), jnp.float32),
            jax.ShapeDtypeStruct((n, h), jnp.float32),
        ],
        interpret=interpret,
    )(tables, lengths, q, k_pages, v_pages)
    if extra_k is not None:
        # merge the self key into the accumulated (o, m, l) — one more
        # online-softmax fold, in plain jax
        ek = _expand_heads(extra_k, h).astype(jnp.float32)  # [N, H, D]
        ev = _expand_heads(extra_v, h).astype(jnp.float32)
        es = jnp.einsum("nhd,nhd->nh",
                        q.astype(jnp.float32) * scale, ek)  # [N, H]
        m_new = jnp.maximum(mx, es)
        alpha = jnp.where(jnp.isneginf(mx), 0.0, jnp.exp(mx - m_new))
        pe = jnp.exp(es - m_new)
        o = o * alpha[..., None] + pe[..., None] * ev
        l = l * alpha + pe
    if local_k is not None:
        # fold the whole local key block at once — the multi-key
        # generalization of the extra_k merge, masked by ancestry
        g, w = local_mask.shape[0], local_mask.shape[1]
        lk = _expand_heads(local_k, h).astype(jnp.float32)
        lv = _expand_heads(local_v, h).astype(jnp.float32)
        qg = (q.astype(jnp.float32) * scale).reshape(g, w, h, d)
        ls = jnp.einsum("gihd,gjhd->gihj", qg, lk,
                        preferred_element_type=jnp.float32)
        ls = jnp.where(local_mask[:, :, None, :], ls,
                       -jnp.inf).reshape(n, h, w)
        m_new = jnp.maximum(mx, ls.max(axis=-1))
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        alpha = jnp.where(jnp.isneginf(mx), 0.0, jnp.exp(mx - m_safe))
        pe = jnp.exp(ls - m_safe[..., None])
        pe = jnp.where(jnp.isneginf(ls), 0.0, pe)
        lvb = jnp.broadcast_to(lv[:, None],
                               (g, w, w, h, d)).reshape(n, w, h, d)
        o = o * alpha[..., None] + jnp.einsum(
            "nhw,nwhd->nhd", pe, lvb,
            preferred_element_type=jnp.float32)
        l = l * alpha + pe.sum(axis=-1)
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l[..., None]).astype(q.dtype)


# ---- dispatcher ------------------------------------------------------------

def paged_attention(q, k_pages, v_pages, tables, lengths,
                    extra_k=None, extra_v=None,
                    local_k=None, local_v=None, local_mask=None,
                    backend: Optional[str] = None,
                    interpret: Optional[bool] = None):
    """Paged attention (see module docstring).  ``backend`` picks
    "gather" (pure jax — the default off-TPU so the CPU tier-1 path
    never touches the pallas interpreter) or "pallas" (the TPU kernel;
    ``interpret=True`` runs it on CPU for equivalence tests)."""
    if backend is None:
        backend = "pallas" if jax.default_backend() == "tpu" else "gather"
    if backend == "gather":
        return paged_attention_gather(q, k_pages, v_pages, tables,
                                      lengths, extra_k, extra_v,
                                      local_k, local_v, local_mask)
    if backend == "pallas":
        return paged_attention_pallas(q, k_pages, v_pages, tables,
                                      lengths, extra_k, extra_v,
                                      local_k, local_v, local_mask,
                                      interpret=interpret)
    raise ValueError(f"unknown paged_attention backend {backend!r}")
