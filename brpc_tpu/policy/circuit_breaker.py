"""CircuitBreaker — per-endpoint error-rate isolation (reference
circuit_breaker.h:25-81; SURVEY.md §2.5).

Two EMA windows (long/short) accumulate "error cost"; crossing the threshold
isolates the endpoint (marked broken → health check takes over revival).
Repeated isolations back off the revival horizon, like the reference's
isolation_duration growth.
"""
from __future__ import annotations

import threading
import time

from brpc_tpu.butil.endpoint import EndPoint


class _WindowState:
    __slots__ = ("ema_error", "samples")

    def __init__(self):
        self.ema_error = 0.0
        self.samples = 0


class CircuitBreaker:
    SHORT_DECAY = 0.7       # reacts in ~tens of calls
    LONG_DECAY = 0.98       # reacts in ~hundreds
    SHORT_THRESHOLD = 0.5   # >50% recent errors
    LONG_THRESHOLD = 0.2
    MIN_SAMPLES = 16

    def __init__(self):
        self._mu = threading.Lock()
        self._short: dict[EndPoint, _WindowState] = {}
        self._long: dict[EndPoint, _WindowState] = {}
        self._isolation_count: dict[EndPoint, int] = {}

    def on_call_end(self, ep: EndPoint, error_code: int) -> None:
        err = 1.0 if error_code != 0 else 0.0
        isolate = False
        with self._mu:
            s = self._short.setdefault(ep, _WindowState())
            l = self._long.setdefault(ep, _WindowState())
            s.ema_error = self.SHORT_DECAY * s.ema_error + \
                (1 - self.SHORT_DECAY) * err
            l.ema_error = self.LONG_DECAY * l.ema_error + \
                (1 - self.LONG_DECAY) * err
            s.samples += 1
            l.samples += 1
            if s.samples >= self.MIN_SAMPLES and (
                    s.ema_error > self.SHORT_THRESHOLD or
                    l.ema_error > self.LONG_THRESHOLD):
                isolate = True
                s.ema_error = 0.0
                s.samples = 0
                self._isolation_count[ep] = \
                    self._isolation_count.get(ep, 0) + 1
        if isolate:
            self.mark_as_broken(ep)

    def mark_as_broken(self, ep: EndPoint) -> None:
        from brpc_tpu.policy.health_check import mark_broken
        mark_broken(ep)

    def on_socket_failed(self, ep: EndPoint) -> None:
        with self._mu:
            self._isolation_count[ep] = self._isolation_count.get(ep, 0) + 1

    def reset(self, ep: EndPoint) -> None:
        with self._mu:
            self._short.pop(ep, None)
            self._long.pop(ep, None)

    def isolation_count(self, ep: EndPoint) -> int:
        with self._mu:
            return self._isolation_count.get(ep, 0)


_breaker = None
_breaker_mu = threading.Lock()


def global_breaker() -> CircuitBreaker:
    global _breaker
    with _breaker_mu:
        if _breaker is None:
            _breaker = CircuitBreaker()
        return _breaker
