"""CircuitBreaker — per-endpoint error *and latency* isolation (reference
circuit_breaker.h:25-81; SURVEY.md §2.5, §5.4; VERDICT r2 task 6).

Two EMA windows per endpoint (short: reacts in tens of calls; long:
hundreds), each tracking BOTH error rate and latency:

- error isolation: short error EMA > 50% or long error EMA > 20%;
- latency isolation: the short latency EMA exceeding LATENCY_RATIO x the
  long (baseline) latency EMA isolates the endpoint even with a 0% error
  rate — a replica that silently got 5x slower is broken in every way that
  matters (the reference folds latency into "error cost" for the same
  effect).

Isolation hands the endpoint to the health checker with a hold duration
that doubles per consecutive isolation (100ms -> 30s cap, mirroring the
reference's isolation_duration_ms growth), so a flapping server is kept
out longer each time.  After revival the endpoint enters a RECOVERY ramp:
load balancers re-admit it with probability growing linearly over
RECOVERY_WINDOW_S (gradual recovery — don't dogpile a replica that just
came back).

A ClusterRecoverPolicy (cluster_recover_policy.py) can veto isolation when
too few servers would remain — protecting availability over precision,
like the reference's cluster_recover_policy.{h,cpp}.
"""
from __future__ import annotations

import random
import threading
import time

from brpc_tpu.butil.endpoint import EndPoint


class _WindowState:
    __slots__ = ("ema_error", "ema_latency", "samples", "lat_samples")

    def __init__(self):
        self.ema_error = 0.0
        self.ema_latency = 0.0
        self.samples = 0
        self.lat_samples = 0

    def add_error(self, decay: float, err: float) -> None:
        self.ema_error = decay * self.ema_error + (1 - decay) * err
        self.samples += 1

    def add_latency(self, decay: float, latency_us: int) -> None:
        if self.ema_latency == 0.0:
            self.ema_latency = float(latency_us)
        else:
            self.ema_latency = decay * self.ema_latency + \
                (1 - decay) * latency_us
        self.lat_samples += 1


class CircuitBreaker:
    SHORT_DECAY = 0.7       # reacts in ~tens of calls
    LONG_DECAY = 0.98       # reacts in ~hundreds
    SHORT_THRESHOLD = 0.5   # >50% recent errors
    LONG_THRESHOLD = 0.2
    MIN_SAMPLES = 16
    # latency isolation: short EMA > RATIO x long (baseline) EMA, with a
    # floor so micro-latency jitter on sub-ms calls can't trip it
    LATENCY_RATIO = 4.0
    MIN_BASELINE_US = 200
    MIN_LATENCY_SAMPLES = 32      # long-window baseline maturity
    MIN_SHORT_LATENCY_SAMPLES = 8  # short window must have real evidence —
    # without this, the first slow success after a reset/revival seeds the
    # short EMA to its full value and instantly re-isolates on one sample
    # isolation hold: doubles per consecutive isolation (reference
    # min/max isolation_duration_ms)
    BASE_HOLD_S = 0.1
    MAX_HOLD_S = 30.0
    # gradual re-admission ramp after revival
    RECOVERY_WINDOW_S = 3.0

    def __init__(self):
        self._mu = threading.Lock()
        self._short: dict[EndPoint, _WindowState] = {}
        self._long: dict[EndPoint, _WindowState] = {}
        self._isolation_count: dict[EndPoint, int] = {}
        self._recovering_until: dict[EndPoint, float] = {}

    def on_call_end(self, ep: EndPoint, error_code: int,
                    latency_us: int = 0, cluster=None) -> None:
        """Feed one call result (reference OnCallEnd).  `cluster` is an
        optional ClusterRecoverPolicy-bound guard consulted before
        isolating."""
        err = 1.0 if error_code != 0 else 0.0
        isolate = False
        with self._mu:
            s = self._short.setdefault(ep, _WindowState())
            l = self._long.setdefault(ep, _WindowState())
            s.add_error(self.SHORT_DECAY, err)
            l.add_error(self.LONG_DECAY, err)
            # latency tracks successful calls only (a failed call's latency
            # is its timeout, which would poison the baseline)
            if err == 0.0 and latency_us > 0:
                s.add_latency(self.SHORT_DECAY, latency_us)
                # baseline-poisoning guard: once the long baseline is
                # mature, suspicious samples (>2x baseline) do NOT feed it.
                # Without this the degradation contaminates its own
                # yardstick — with both windows fed, s>4*l is only ever
                # reachable for slowdowns >~7.7x, and the documented 4-5x
                # degradation never isolates.  Freezing the baseline under
                # suspicion makes a sustained r-times slowdown trip once
                # s -> r*baseline > RATIO*baseline, i.e. any r > RATIO.
                if (l.lat_samples < self.MIN_LATENCY_SAMPLES
                        or l.ema_latency == 0.0
                        or latency_us <= 2 * l.ema_latency):
                    l.add_latency(self.LONG_DECAY, latency_us)
            if s.samples >= self.MIN_SAMPLES and (
                    s.ema_error > self.SHORT_THRESHOLD or
                    l.ema_error > self.LONG_THRESHOLD):
                isolate = True
            elif (l.lat_samples >= self.MIN_LATENCY_SAMPLES
                    and s.lat_samples >= self.MIN_SHORT_LATENCY_SAMPLES
                    and l.ema_latency > 0 and s.ema_latency >
                    self.LATENCY_RATIO * max(l.ema_latency,
                                             self.MIN_BASELINE_US)):
                # pure latency degradation: no errors required
                isolate = True
            if isolate:
                if cluster is not None and not cluster.can_isolate(ep):
                    # availability floor wins.  Reset the short window so
                    # evidence must re-accumulate (MIN_SAMPLES calls)
                    # before the next isolation attempt — otherwise every
                    # subsequent call re-trips this branch and re-walks
                    # the cluster guard's O(servers) scan while the
                    # cluster is already degraded
                    isolate = False
                    self._short[ep] = _WindowState()
                else:
                    self._short[ep] = _WindowState()
                    self._isolation_count[ep] = \
                        self._isolation_count.get(ep, 0) + 1
        if isolate:
            self.mark_as_broken(ep)

    def _hold_s(self, ep: EndPoint) -> float:
        # cap the exponent BEFORE exponentiating: a flapping endpoint can
        # accumulate thousands of isolations and 2**n overflows float
        # (OverflowError on the response thread under sustained timeouts)
        n = min(self._isolation_count.get(ep, 1), 32)
        return min(self.MAX_HOLD_S, self.BASE_HOLD_S * (2 ** (n - 1)))

    def mark_as_broken(self, ep: EndPoint) -> None:
        from brpc_tpu.policy.health_check import mark_broken
        with self._mu:
            hold = self._hold_s(ep)
        mark_broken(ep, hold_s=hold)

    def on_socket_failed(self, ep: EndPoint) -> None:
        with self._mu:
            self._isolation_count[ep] = self._isolation_count.get(ep, 0) + 1

    def on_revived(self, ep: EndPoint) -> None:
        """Health check succeeded: start the gradual re-admission ramp.
        BOTH windows reset — a retained long-window error EMA near 1.0
        would re-isolate a now-healthy endpoint after its first
        MIN_SAMPLES successes (0.98-decay needs ~80 successes to cross
        back under the 0.2 threshold)."""
        with self._mu:
            self._short.pop(ep, None)
            self._long.pop(ep, None)
            self._recovering_until[ep] = \
                time.monotonic() + self.RECOVERY_WINDOW_S

    def _ramp_done_locked(self, ep: EndPoint) -> None:
        del self._recovering_until[ep]
        # a survived ramp is one unit of forgiveness, not amnesty:
        # decrement so a slow flapper (up-time > ramp) still climbs
        # the exponential hold ladder across cycles
        n = self._isolation_count.get(ep, 0)
        if n <= 1:
            self._isolation_count.pop(ep, None)
        else:
            self._isolation_count[ep] = n - 1

    def admit(self, ep: EndPoint) -> bool:
        """Gradual recovery gate for load balancers: during the ramp a
        freshly-revived endpoint receives a linearly-growing fraction of
        selections instead of its full share at once."""
        if not self._recovering_until:
            return True   # GIL-atomic empty check: no lock on the hot path
        with self._mu:
            now = time.monotonic()
            # sweep ALL expired entries, not just ep's: an endpoint removed
            # from the cluster mid-ramp is never passed to admit() again,
            # and a leaked entry would disable the lock-free fast path
            # above for every selection in the process, forever
            for other in [e for e, u in self._recovering_until.items()
                          if now >= u]:
                self._ramp_done_locked(other)
            until = self._recovering_until.get(ep)
            if until is None:
                return True
            frac = 1.0 - (until - now) / self.RECOVERY_WINDOW_S
        return random.random() < max(0.1, frac)

    def reset(self, ep: EndPoint) -> None:
        with self._mu:
            self._short.pop(ep, None)
            self._long.pop(ep, None)
            self._recovering_until.pop(ep, None)

    def isolation_count(self, ep: EndPoint) -> int:
        with self._mu:
            return self._isolation_count.get(ep, 0)


_breaker = None
_breaker_mu = threading.Lock()


def global_breaker() -> CircuitBreaker:
    global _breaker
    with _breaker_mu:
        if _breaker is None:
            _breaker = CircuitBreaker()
        return _breaker
