"""ClusterRecoverPolicy — availability floor for circuit breaking
(reference cluster_recover_policy.{h,cpp}; SURVEY.md §5.4; VERDICT r2
task 6).

When most of a cluster is already isolated, isolating one more server
trades a little precision for a lot of availability — the wrong trade.
The policy vetoes further isolation whenever it would leave fewer than
`min_working` healthy servers (or fewer than `min_working_ratio` of the
cluster), and reports `in_recovery()` so operators can see the cluster is
running degraded.  The reference's DefaultClusterRecoverPolicy plays the
same role: below the usable-server threshold it suspends isolation and
lets traffic feel out the cluster until it heals.
"""
from __future__ import annotations

import math
import threading

from brpc_tpu.bvar import Adder

_vetoed = Adder("rpc_cluster_recover_vetoed_isolations")


class ClusterRecoverPolicy:
    def __init__(self, min_working: int = 1,
                 min_working_ratio: float = 0.0):
        self.min_working = min_working
        self.min_working_ratio = min_working_ratio
        self._mu = threading.Lock()
        self._recovering = False

    def _floor(self, total: int) -> int:
        return max(self.min_working,
                   math.ceil(total * self.min_working_ratio))

    def can_isolate(self, total: int, healthy: int) -> bool:
        """True iff isolating one more server keeps the cluster at or
        above the availability floor."""
        ok = healthy - 1 >= self._floor(total)
        with self._mu:
            self._recovering = not ok
        if not ok:
            _vetoed.add(1)
        return ok

    def in_recovery(self) -> bool:
        with self._mu:
            return self._recovering


class _ChannelClusterGuard:
    """Binds a channel's live server view to the policy so the circuit
    breaker can ask 'may I isolate this endpoint?' without knowing about
    clusters (the reference passes the policy into the LB the same way)."""

    def __init__(self, policy: ClusterRecoverPolicy, lb):
        self._policy = policy
        self._lb = lb

    def can_isolate(self, ep) -> bool:
        from brpc_tpu.policy.health_check import is_broken
        if is_broken(ep):
            # already isolated: "isolating" again removes nothing from the
            # pool, so vetoing would only inflate the veto metric and stall
            # the endpoint's exponential hold ladder
            return True
        nodes = self._lb.servers()
        total = len(nodes)
        if total == 0:
            return True
        healthy = sum(1 for n in nodes if not is_broken(n.endpoint))
        return self._policy.can_isolate(total, healthy)
