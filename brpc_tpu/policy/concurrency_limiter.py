"""Concurrency limiters (reference policy/auto_concurrency_limiter.*,
policy/timeout_concurrency_limiter.*; SURVEY.md §2.6).

"constant": fixed cap.  "auto": gradient limiter in the spirit of the
reference (auto_concurrency_limiter.cpp:30-80) — tracks the EMA of no-load
latency and recent peak qps, sets limit ≈ peak_qps × min_latency × (1+α)
with periodic downward exploration to re-measure min latency.  "timeout":
rejects when the estimated queueing delay exceeds the budget.
"""
from __future__ import annotations

import threading
import time


class ConcurrencyLimiter:
    def on_requested(self, current_concurrency: int) -> bool:
        raise NotImplementedError

    def on_responded(self, error_code: int, latency_us: int) -> None:
        pass

    def max_concurrency(self) -> int:
        return 0


class ConstantLimiter(ConcurrencyLimiter):
    def __init__(self, limit: int):
        self._limit = int(limit)

    def on_requested(self, current_concurrency: int) -> bool:
        return self._limit <= 0 or current_concurrency <= self._limit

    def max_concurrency(self) -> int:
        return self._limit


class AutoConcurrencyLimiter(ConcurrencyLimiter):
    ALPHA = 0.3            # headroom over the latency-bandwidth product
    EMA_DECAY = 0.9
    SAMPLE_WINDOW_S = 1.0
    EXPLORE_EVERY = 20     # windows between downward explorations
    MIN_LIMIT = 8

    def __init__(self):
        self._mu = threading.Lock()
        self._limit = 64
        self._min_latency_us = None     # EMA of observed floor
        self._window_start = time.monotonic()
        self._window_count = 0
        self._window_lat_sum = 0
        self._windows_seen = 0
        self._exploring = False

    def on_requested(self, current_concurrency: int) -> bool:
        return current_concurrency <= self._limit

    def on_responded(self, error_code: int, latency_us: int) -> None:
        if error_code != 0:
            return
        with self._mu:
            self._window_count += 1
            self._window_lat_sum += latency_us
            now = time.monotonic()
            span = now - self._window_start
            if span < self.SAMPLE_WINDOW_S or self._window_count < 4:
                return
            avg_lat = self._window_lat_sum / self._window_count
            qps = self._window_count / span
            self._window_start = now
            self._window_count = 0
            self._window_lat_sum = 0
            self._windows_seen += 1
            if self._min_latency_us is None:
                self._min_latency_us = avg_lat
            elif self._exploring or avg_lat < self._min_latency_us:
                # during exploration the server is unloaded: trust the sample
                self._min_latency_us = (self.EMA_DECAY * self._min_latency_us +
                                        (1 - self.EMA_DECAY) * avg_lat)
            # latency-bandwidth product with headroom
            target = qps * (self._min_latency_us / 1e6) * (1 + self.ALPHA)
            if self._exploring:
                self._exploring = False
                self._limit = max(self.MIN_LIMIT, int(target) + 1)
            elif self._windows_seen % self.EXPLORE_EVERY == 0:
                # drop concurrency to re-measure the no-load latency floor
                self._exploring = True
                self._limit = max(self.MIN_LIMIT, self._limit // 2)
            else:
                self._limit = max(self.MIN_LIMIT, int(
                    0.5 * self._limit + 0.5 * (target + 1)))

    def max_concurrency(self) -> int:
        return self._limit


class TimeoutLimiter(ConcurrencyLimiter):
    """Reject when expected wait (concurrency × avg latency) exceeds the
    budget (reference timeout_concurrency_limiter)."""

    def __init__(self, timeout_ms: float = 500.0):
        self._timeout_us = timeout_ms * 1e3
        self._avg_latency_us = 0.0
        self._mu = threading.Lock()

    def on_requested(self, current_concurrency: int) -> bool:
        if self._avg_latency_us <= 0:
            return True
        return current_concurrency * self._avg_latency_us <= self._timeout_us

    def on_responded(self, error_code: int, latency_us: int) -> None:
        if error_code != 0:
            return
        with self._mu:
            if self._avg_latency_us == 0:
                self._avg_latency_us = latency_us
            else:
                self._avg_latency_us = (0.9 * self._avg_latency_us +
                                        0.1 * latency_us)


def create_limiter(spec) -> ConcurrencyLimiter:
    """spec: int (constant), "auto", "constant:N", "timeout[:ms]" —
    the adaptive string-typed option scheme (§5.9)."""
    if isinstance(spec, ConcurrencyLimiter):
        return spec
    if isinstance(spec, int):
        return ConstantLimiter(spec)
    s = str(spec).strip().lower()
    if s == "auto":
        return AutoConcurrencyLimiter()
    if s.startswith("timeout"):
        _, _, ms = s.partition(":")
        return TimeoutLimiter(float(ms) if ms else 500.0)
    if s.startswith("constant:"):
        return ConstantLimiter(int(s.split(":", 1)[1]))
    return ConstantLimiter(int(s))
