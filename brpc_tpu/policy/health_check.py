"""Health checking + circuit breaking glue (reference
details/health_check.cpp:146-235, circuit_breaker.{h,cpp}; SURVEY.md §5.4).

When a connection to an endpoint fails, the endpoint is marked broken and a
probe task reconnects every `health_check_interval_s`; on success the mark
clears and load balancers resume selecting it (they consult is_broken()).
The CircuitBreaker tracks per-endpoint error EMAs in long/short windows and
can isolate an endpoint before the socket actually dies.
"""
from __future__ import annotations

import socket as _socket
import threading
import time

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.bvar import Adder

health_check_interval_s = 1.0

_broken: dict[EndPoint, float] = {}     # endpoint -> since (monotonic)
_hold_until: dict[EndPoint, float] = {}  # CB isolation hold deadline
# bumped by reset_all(); probe loops from an older generation exit instead
# of reviving endpoints into state that was deliberately cleared (tests,
# operator resets)
_generation = 0
_mu = threading.Lock()
_probe_threads: dict[EndPoint, threading.Thread] = {}
_revived_counter = Adder("rpc_health_check_revived")
_broken_counter = Adder("rpc_health_check_broken")


def is_broken(ep: EndPoint) -> bool:
    with _mu:
        return ep in _broken


def broken_endpoints() -> list[EndPoint]:
    with _mu:
        return list(_broken)


def mark_broken(ep: EndPoint, hold_s: float = 0.0) -> None:
    """Mark and start the probe loop (Socket::SetFailed → StartHealthCheck).

    `hold_s` is the circuit breaker's isolation duration: the probe loop
    will not revive the endpoint before it elapses even if the server is
    already reachable (the reference's isolation_duration_ms backoff)."""
    if ep.scheme != "tcp":
        return
    with _mu:
        if hold_s > 0.0:
            _hold_until[ep] = max(_hold_until.get(ep, 0.0),
                                  time.monotonic() + hold_s)
        if ep in _broken:
            return
        _broken[ep] = time.monotonic()
        _broken_counter.add(1)
        t = threading.Thread(target=_probe_loop, args=(ep, _generation),
                             daemon=True, name=f"health-check-{ep}")
        _probe_threads[ep] = t
        t.start()


def on_connection_failed(ep: EndPoint) -> None:
    mark_broken(ep)
    from brpc_tpu.policy.circuit_breaker import global_breaker
    global_breaker().on_socket_failed(ep)


def _probe_loop(ep: EndPoint, gen: int) -> None:
    while True:
        time.sleep(health_check_interval_s)
        with _mu:
            if gen != _generation:
                _probe_threads.pop(ep, None)
                return              # state was reset under us: stand down
            hold = _hold_until.get(ep, 0.0)
        if time.monotonic() < hold:
            continue   # still inside the CB isolation hold
        try:
            with _socket.create_connection((ep.host, ep.port), timeout=1.0):
                pass
            break  # connectable again
        except OSError:
            continue
    with _mu:
        if gen != _generation:
            _probe_threads.pop(ep, None)
            return
        _broken.pop(ep, None)
        _hold_until.pop(ep, None)
        _probe_threads.pop(ep, None)
    _revived_counter.add(1)
    from brpc_tpu.policy.circuit_breaker import global_breaker
    global_breaker().on_revived(ep)   # start the gradual re-admission ramp


def reset(ep: EndPoint) -> None:
    """Force-clear (tests / manual revive)."""
    with _mu:
        _broken.pop(ep, None)
        _hold_until.pop(ep, None)


def reset_all() -> None:
    """Clear every endpoint's state and retire in-flight probe loops (the
    generation bump makes them exit instead of reviving endpoints into
    the cleared state)."""
    global _generation
    with _mu:
        _generation += 1
        _broken.clear()
        _hold_until.clear()
