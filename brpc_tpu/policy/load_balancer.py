"""Load balancers (reference policy/*_load_balancer.cpp; SURVEY.md §2.5).

All balancers read the server set through a DoublyBufferedData snapshot
(wait-free reads, like the reference's backing store) and implement
select_server/feedback.  Registered: rr, wrr, random, wr, c_murmurhash,
c_md5, c_ketama, la (locality-aware: EWMA latency × inflight, the
locality_aware_load_balancer.cpp design), and prefix_affinity
(cache-aware: consistent-hash on the prompt's prefix fingerprint so
repeat prefixes land on the replica holding their KV pages —
kvcache/radix.py).
"""
from __future__ import annotations

import bisect
import hashlib
import itertools
import random
import threading
import time
from dataclasses import dataclass, field

from brpc_tpu.butil.doubly_buffered import DoublyBufferedData
from brpc_tpu.butil.endpoint import EndPoint


@dataclass(frozen=True)
class ServerNode:
    endpoint: EndPoint
    weight: int = 1
    tag: str = ""


class LoadBalancer:
    name = "base"

    def __init__(self):
        self._servers: DoublyBufferedData[tuple[ServerNode, ...]] = \
            DoublyBufferedData(())

    # ---- membership (pushed by naming services) ----

    def reset_servers(self, nodes: list[ServerNode]) -> None:
        self._servers.modify(lambda _old: tuple(nodes))
        self._on_servers_changed()

    def add_server(self, node: ServerNode) -> None:
        self._servers.modify(lambda old: tuple(list(old) + [node]))
        self._on_servers_changed()

    def remove_server(self, endpoint: EndPoint) -> None:
        self._servers.modify(
            lambda old: tuple(n for n in old if n.endpoint != endpoint))
        self._on_servers_changed()

    def server_count(self) -> int:
        return len(self._servers.read())

    def servers(self) -> tuple[ServerNode, ...]:
        return self._servers.read()

    def _on_servers_changed(self) -> None:
        pass

    def _alive(self, exclude=None):
        from brpc_tpu.policy.circuit_breaker import global_breaker
        from brpc_tpu.policy.health_check import is_broken
        breaker = global_breaker()
        nodes = self._servers.read()
        # admit() is the gradual-recovery gate: a freshly-revived endpoint
        # gets a linearly-growing fraction of selections (circuit_breaker
        # RECOVERY ramp) instead of its full share the instant it revives
        healthy = [n for n in nodes
                   if (exclude is None or n.endpoint not in exclude)
                   and not is_broken(n.endpoint)]
        out = [n for n in healthy if breaker.admit(n.endpoint)]
        if not out and healthy:
            # admit() probabilistically rejected every healthy node (all
            # are mid-recovery-ramp): prefer a recovering-but-healthy node
            # over falling through to known-broken ones
            out = healthy
        if not out and nodes:
            # all broken/excluded: let the caller retry anything rather than
            # fast-failing the whole cluster (cluster_recover_policy spirit)
            out = [n for n in nodes if exclude is None or
                   n.endpoint not in exclude]
        return out

    # ---- selection ----

    def select_server(self, exclude: set | None = None,
                      request_code: int | None = None) -> EndPoint | None:
        raise NotImplementedError

    def feedback(self, endpoint: EndPoint, error_code: int,
                 latency_us: int) -> None:
        pass


class RoundRobinLB(LoadBalancer):
    name = "rr"

    def __init__(self):
        super().__init__()
        self._counter = itertools.count()

    def select_server(self, exclude=None, request_code=None):
        nodes = self._alive(exclude)
        if not nodes:
            return None
        return nodes[next(self._counter) % len(nodes)].endpoint


class RandomLB(LoadBalancer):
    name = "random"

    def select_server(self, exclude=None, request_code=None):
        nodes = self._alive(exclude)
        if not nodes:
            return None
        return random.choice(nodes).endpoint


class WeightedRoundRobinLB(LoadBalancer):
    """Smooth weighted RR (same behavior class as policy/weighted_round_robin_
    load_balancer.cpp; smooth-WRR algorithm keeps bursts interleaved)."""

    name = "wrr"

    def __init__(self):
        super().__init__()
        self._mu = threading.Lock()
        self._current: dict[EndPoint, int] = {}

    def select_server(self, exclude=None, request_code=None):
        nodes = self._alive(exclude)
        if not nodes:
            return None
        with self._mu:
            total = 0
            best = None
            for n in nodes:
                w = max(1, n.weight)
                total += w
                cur = self._current.get(n.endpoint, 0) + w
                self._current[n.endpoint] = cur
                if best is None or cur > self._current[best.endpoint]:
                    best = n
            self._current[best.endpoint] -= total
            return best.endpoint


class WeightedRandomLB(LoadBalancer):
    name = "wr"

    def select_server(self, exclude=None, request_code=None):
        nodes = self._alive(exclude)
        if not nodes:
            return None
        weights = [max(1, n.weight) for n in nodes]
        return random.choices(nodes, weights=weights, k=1)[0].endpoint


def _hash_murmur_like(data: bytes) -> int:
    # fast stable 64-bit hash (fnv-1a variant; role of murmurhash in c_murmur)
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


class ConsistentHashLB(LoadBalancer):
    """Ketama-style ring (reference policy/consistent_hashing_load_balancer.*):
    N virtual nodes per server; requests route by request_code."""

    name = "c_murmurhash"
    VIRTUAL_NODES = 100

    def __init__(self, hash_fn=None):
        super().__init__()
        self._hash = hash_fn or _hash_murmur_like
        self._ring: list[tuple[int, EndPoint]] = []
        self._ring_keys: list[int] = []
        self._mu = threading.Lock()

    def _on_servers_changed(self):
        ring = []
        for n in self._servers.read():
            base = str(n.endpoint).encode()
            for i in range(self.VIRTUAL_NODES * max(1, n.weight)):
                ring.append((self._hash(base + b"#%d" % i), n.endpoint))
        ring.sort()
        with self._mu:
            self._ring = ring
            self._ring_keys = [k for k, _ in ring]

    @staticmethod
    def _code_bytes(code) -> bytes:
        # mask into u64: hash()-derived codes are frequently negative and
        # to_bytes(signed=False) would raise OverflowError
        return (int(code) & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")

    def _map_code(self, request_code) -> int:
        code = request_code if request_code is not None \
            else random.getrandbits(63)
        # hash the request code onto the ring (raw codes would all land at
        # one end of the 64-bit key space)
        return self._hash(self._code_bytes(code))

    def select_server(self, exclude=None, request_code=None):
        from brpc_tpu.policy.health_check import is_broken
        with self._mu:
            ring, keys = self._ring, self._ring_keys
        if not ring:
            return None
        h = self._map_code(request_code)
        i = bisect.bisect_left(keys, h) % len(ring)
        # walk the ring past excluded/broken nodes
        for step in range(len(ring)):
            ep = ring[(i + step) % len(ring)][1]
            if (exclude is None or ep not in exclude) and not is_broken(ep):
                return ep
        # nothing is both healthy and unexcluded: prefer a BROKEN but
        # unexcluded node (it may be mid-recovery — e.g. latency-
        # isolated yet alive) over one the caller JUST failed on.
        # Without this, a cluster whose survivors are transiently
        # isolated hands every retry back to the known-dead endpoint
        # the exclusion was recording (ISSUE 8 router churn).
        if exclude:
            for step in range(len(ring)):
                ep = ring[(i + step) % len(ring)][1]
                if ep not in exclude:
                    return ep
        return ring[i][1]

    def placement(self, request_code, n: int,
                  exclude: set | None = None) -> list:
        """The request's N-WAY PLACEMENT (ISSUE 16): up to `n` DISTINCT
        endpoints walking the ring from the code's position — the
        owner (what ``select_server`` returns) first, then the ring
        successors a failover would land on, i.e. exactly where a
        replica of this prefix is worth keeping warm.  Healthy
        endpoints are taken first; broken ones fill remaining slots
        only when the fleet is too degraded to satisfy `n` otherwise
        (a placement must stay stable across a brief quarantine, not
        shrink the replica set)."""
        from brpc_tpu.policy.health_check import is_broken
        with self._mu:
            ring = self._ring
            keys = self._ring_keys
        if not ring or n <= 0:
            return []
        h = self._map_code(request_code)
        i = bisect.bisect_left(keys, h) % len(ring)
        out: list = []
        broken: list = []
        for step in range(len(ring)):
            ep = ring[(i + step) % len(ring)][1]
            if exclude is not None and ep in exclude:
                continue
            if ep in out or ep in broken:
                continue
            if is_broken(ep):
                broken.append(ep)
            else:
                out.append(ep)
            if len(out) >= n:
                return out
        return (out + broken)[:n]


class ConsistentHashMd5LB(ConsistentHashLB):
    name = "c_md5"

    def __init__(self):
        super().__init__(hash_fn=lambda d: int.from_bytes(
            hashlib.md5(d).digest()[:8], "little"))


class KetamaLB(ConsistentHashLB):
    """libketama-compatible ring (reference c_ketama,
    policy/consistent_hashing_load_balancer.cpp KetamaReplicaPolicy):
    per virtual-node GROUP one md5 of "host:port-<g>" yields FOUR ring
    points (digest split into 4 little-endian u32s), 40 groups => 160
    points per unit weight — the memcached client ecosystem's exact
    placement, so a ketama client and this LB agree on key ownership."""

    name = "c_ketama"
    GROUPS = 40   # x4 points/group = 160 points per weight unit

    def _on_servers_changed(self):
        ring = []
        for n in self._servers.read():
            base = str(n.endpoint)
            for g in range(self.GROUPS * max(1, n.weight)):
                digest = hashlib.md5(f"{base}-{g}".encode()).digest()
                for part in range(4):
                    point = int.from_bytes(
                        digest[part * 4:part * 4 + 4], "little")
                    ring.append((point, n.endpoint))
        ring.sort()
        with self._mu:
            self._ring = ring
            self._ring_keys = [k for k, _ in ring]

    def _map_code(self, request_code) -> int:
        if request_code is None:
            return random.getrandbits(32)
        # ketama hashes the KEY with md5 and takes the first 4 bytes —
        # request_code is already the caller's key hash, so map it into
        # the u32 ring space the same way
        digest = hashlib.md5(self._code_bytes(request_code)).digest()
        return int.from_bytes(digest[:4], "little")


def prefix_fingerprint(tokens, chunk_tokens: int = 16) -> int:
    """Stable 64-bit fingerprint of a prompt's leading page-aligned
    chunk(s) — the routing key for prefix-affinity balancing.  Prompts
    sharing their first ``chunk_tokens``-aligned prefix (the unit the
    paged KV cache shares at, `kvcache/pages.py`) produce the SAME
    fingerprint; anything shorter than one chunk fingerprints whole.
    """
    # only the FIRST chunk decides affinity: a shared system prompt
    # routes all its continuations to one replica's radix tree even
    # though their tails diverge
    head = [int(t) for t in tokens[:chunk_tokens]]
    if not head:
        return 0
    return _hash_murmur_like(b"".join(
        (t & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little") for t in head))


class PrefixAffinityLB(ConsistentHashLB):
    """Cache-aware routing: consistent-hash on the PREFIX FINGERPRINT
    (``prefix_fingerprint``) so repeat prefixes land on the replica
    whose radix tree already holds their pages — a cache hit on the
    right machine instead of a recompute on the wrong one.  The
    virtual-node ring underneath means replica churn only remaps the
    departed replica's share of prefixes (the rest keep their warm
    caches), which is the first step toward cross-host serving over
    DCN.

    MIGRATE-ON-REBALANCE (ISSUE 7): with a hook installed via
    :meth:`migrate_on_rebalance`, the balancer remembers which replica
    each routed prefix landed on (bounded LRU of fingerprints); when a
    ring change remaps a tracked prefix to a NEW owner, the hook fires
    with ``(tokens, old_ep, new_ep)`` — the default
    (`brpc_tpu.migrate.rebalance_pusher`) asks the old owner to PUSH
    its warm pages to the new one over the ``_kvmig`` service, so the
    remapped replica prefix-hits instead of re-prefilling.  Hooks run
    on a dedicated ``migrate``-stage-tagged thread; a failing push
    degrades to recompute, never blocks the remap.

    Use ``select_server(request_code=prefix_fingerprint(prompt))``, or
    :meth:`select_for_prompt` as sugar."""

    name = "prefix_affinity"

    def __init__(self):
        super().__init__()
        self._aff_mu = threading.Lock()
        # fingerprint -> [longest prompt seen, current owner] (ordered
        # for LRU bounding; populated only while a hook is installed)
        from collections import OrderedDict
        self._routed: "OrderedDict[int, list]" = OrderedDict()
        self._routed_cap = 1024
        self._migrate_hook = None
        self._migration_threads: list = []
        self.remaps = 0
        self.remap_migrations = 0
        self.remap_failures = 0

    def migrate_on_rebalance(self, hook, *,
                             track_capacity: int = 1024) -> None:
        """Install ``hook(tokens, old_ep, new_ep)`` to fire for every
        tracked prefix a ring change hands to a new owner.  Pass
        ``None`` to uninstall (tracking stops and the table drops)."""
        with self._aff_mu:
            self._migrate_hook = hook
            self._routed_cap = int(track_capacity)
            if hook is None:
                self._routed.clear()

    def select_for_prompt(self, prompt, exclude=None,
                          chunk_tokens: int = 16):
        code = prefix_fingerprint(prompt, chunk_tokens)
        ep = self.select_server(exclude=exclude, request_code=code)
        if ep is not None and self._migrate_hook is not None:
            with self._aff_mu:
                rec = self._routed.get(code)
                if rec is None:
                    self._routed[code] = [
                        [int(t) for t in prompt], ep]
                    while len(self._routed) > self._routed_cap:
                        self._routed.popitem(last=False)
                else:
                    # keep the LONGEST prompt seen for this prefix:
                    # migration ships whole committed pages, and the
                    # longest continuation names the most of them
                    if len(prompt) > len(rec[0]):
                        rec[0] = [int(t) for t in prompt]
                    rec[1] = ep
                    self._routed.move_to_end(code)
        return ep

    def _on_servers_changed(self):
        super()._on_servers_changed()
        hook = self._migrate_hook
        if hook is None:
            return
        with self._aff_mu:
            snapshot = [(fp, list(rec[0]), rec[1])
                        for fp, rec in self._routed.items()]
        remaps = []
        for fp, toks, old_ep in snapshot:
            new_ep = self.select_server(request_code=fp)
            if new_ep is None or new_ep == old_ep:
                continue
            remaps.append((toks, old_ep, new_ep))
            with self._aff_mu:
                rec = self._routed.get(fp)
                if rec is not None:
                    rec[1] = new_ep
        if not remaps:
            return
        self.remaps += len(remaps)
        # hooks do network IO (PushTo to the old owner): a dedicated
        # migrate-stage thread keeps the membership-update path fast
        # and shows up on /hotspots under its own stage
        t = threading.Thread(target=self._run_migrations,
                             args=(hook, remaps), daemon=True,
                             name="kv-migrate-rebalance")
        with self._aff_mu:
            # keep EVERY live batch: back-to-back ring changes each
            # spawn one, and join_migrations must wait them all out
            self._migration_threads = [
                x for x in self._migration_threads if x.is_alive()]
            self._migration_threads.append(t)
        t.start()

    def _run_migrations(self, hook, remaps) -> None:
        from brpc_tpu.butil import stagetag
        with stagetag.stage("migrate"):
            for toks, old_ep, new_ep in remaps:
                try:
                    hook(toks, old_ep, new_ep)
                    self.remap_migrations += 1
                except Exception:
                    # the new owner recomputes — degraded, not broken
                    self.remap_failures += 1
                    import logging
                    logging.getLogger(__name__).info(
                        "rebalance migration %s -> %s failed",
                        old_ep, new_ep, exc_info=True)

    def join_migrations(self, timeout_s: float = 10.0) -> bool:
        """Wait out EVERY outstanding remap migration batch (tests,
        graceful membership changes — tearing an old owner down while
        an earlier batch is still pushing would fail those pushes)."""
        deadline = threading.TIMEOUT_MAX if timeout_s is None \
            else time.monotonic() + timeout_s
        with self._aff_mu:
            threads = list(self._migration_threads)
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                return False
        with self._aff_mu:
            self._migration_threads = [
                x for x in self._migration_threads if x.is_alive()]
        return True


class LocalityAwareLB(LoadBalancer):
    """Locality-aware: weight ∝ 1 / (EWMA latency × (inflight+1))
    (reference policy/locality_aware_load_balancer.cpp design: dividing
    qps by latency while penalizing inflight explorers)."""

    name = "la"
    DECAY = 0.8

    def __init__(self):
        super().__init__()
        self._mu = threading.Lock()
        self._lat: dict[EndPoint, float] = {}       # EWMA latency us
        self._inflight: dict[EndPoint, int] = {}

    def select_server(self, exclude=None, request_code=None):
        nodes = self._alive(exclude)
        if not nodes:
            return None
        with self._mu:
            weights = []
            for n in nodes:
                lat = self._lat.get(n.endpoint, 1000.0)
                inflight = self._inflight.get(n.endpoint, 0)
                weights.append(max(1, n.weight) * 1e6 /
                               (lat * (inflight + 1)))
            ep = random.choices(nodes, weights=weights, k=1)[0].endpoint
            self._inflight[ep] = self._inflight.get(ep, 0) + 1
            return ep

    def feedback(self, endpoint, error_code, latency_us):
        with self._mu:
            self._inflight[endpoint] = max(
                0, self._inflight.get(endpoint, 1) - 1)
            if error_code == 0:
                old = self._lat.get(endpoint, float(latency_us))
                self._lat[endpoint] = (self.DECAY * old +
                                       (1 - self.DECAY) * latency_us)
            else:
                # errors look like huge latency so traffic shifts away
                self._lat[endpoint] = max(
                    self._lat.get(endpoint, 1000.0) * 2, 1e5)


_LBS = {cls.name: cls for cls in
        (RoundRobinLB, RandomLB, WeightedRoundRobinLB, WeightedRandomLB,
         ConsistentHashLB, ConsistentHashMd5LB, KetamaLB, LocalityAwareLB,
         PrefixAffinityLB)}


def create_load_balancer(name: str) -> LoadBalancer:
    cls = _LBS.get(name or "rr")
    if cls is None:
        raise KeyError(f"unknown load balancer {name!r}; "
                       f"have {sorted(_LBS)}")
    return cls()


def register_load_balancer(name: str, cls) -> None:
    _LBS[name] = cls


class ExcludedServers:
    """Bounded record of servers already tried during one RPC's retries;
    retry selection skips them so a second attempt lands on a different
    replica (reference excluded_servers.h — pooled, capacity-bounded).
    Channel retries build one per call from Controller state; this named
    surface exists for users implementing custom RetryPolicy/LBs."""

    def __init__(self, capacity: int = 8):
        self._capacity = capacity
        self._eps: list = []

    def add(self, endpoint) -> None:
        if len(self._eps) < self._capacity:
            self._eps.append(endpoint)

    def is_excluded(self, endpoint) -> bool:
        return endpoint in self._eps

    def as_set(self) -> set:
        return set(self._eps)

    def __len__(self) -> int:
        return len(self._eps)

    def __contains__(self, endpoint) -> bool:
        return endpoint in self._eps
