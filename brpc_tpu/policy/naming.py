"""Naming services (reference policy/*_naming_service.cpp; SURVEY.md §2.5).

A NamingService runs in a dedicated daemon thread per cluster and pushes
ServerNode lists to its listener (the load balancer) whenever membership
changes — the cluster is elastic by subscription (naming_service.h:36-61).

Schemes: list://h1:p1,h2:p2[(w)]   static list
         file://path               one "host:port [weight] [tag]" per line,
                                   re-read periodically (reference file NS)
         dns://host:port           resolve A records periodically
         ici://slice               every chip in the local mesh (TPU-native:
                                   membership = jax devices, no DNS in a pod)
"""
from __future__ import annotations

import os
import socket as _socket
import threading
import time

from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.policy.load_balancer import LoadBalancer, ServerNode

DEFAULT_INTERVAL_S = 5.0

from brpc_tpu import flags as _flags  # noqa: E402

_flags.define_flag("naming_log_refresh_failures", True,
                   "log naming-service refresh failures (kept-list notes)",
                   reloadable=True)


class NamingService:
    interval_s = DEFAULT_INTERVAL_S

    def __init__(self, param: str):
        self.param = param

    def get_servers(self) -> list[ServerNode]:
        raise NotImplementedError


class ListNamingService(NamingService):
    """list://host:port[(weight)][ tag],... — static membership; the
    optional space-separated tag carries partition labels like "0/4"
    (reference list_naming_service.cpp tag support for PartitionChannel)."""

    interval_s = 0  # never re-resolves

    def get_servers(self):
        nodes = []
        for part in self.param.split(","):
            part = part.strip()
            if not part:
                continue
            tag = ""
            if " " in part:
                part, _, tag = part.partition(" ")
                tag = tag.strip()
            weight = 1
            if part.endswith(")") and "(" in part:
                part, _, w = part[:-1].rpartition("(")
                weight = int(w)
            nodes.append(ServerNode(str2endpoint(part), weight, tag))
        return nodes


def _parse_server_lines(text: str) -> list[ServerNode]:
    """'host:port [weight] [tag]' per line, # comments — THE one parser for
    file:// and remotefile:// so moving a list between them never changes
    weights or partition tags."""
    nodes = []
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        weight, tag = 1, ""
        if len(parts) >= 2:
            if parts[1].isdigit():
                weight = int(parts[1])
                tag = parts[2] if len(parts) >= 3 else ""
            else:
                tag = parts[1]
        try:
            nodes.append(ServerNode(str2endpoint(parts[0]), weight, tag))
        except (ValueError, TypeError, IndexError):
            continue
    return nodes


class FileNamingService(NamingService):
    """file://path — 'host:port [weight] [tag]' per line, # comments."""

    def get_servers(self):
        with open(self.param) as f:   # OSError propagates: the naming
            return _parse_server_lines(f.read())  # thread keeps the old
                                                  # list on refresh errors


class DnsNamingService(NamingService):
    """dns://host:port — A/AAAA records of host."""

    def get_servers(self):
        host, _, port = self.param.partition(":")
        port = int(port or 80)
        try:
            infos = _socket.getaddrinfo(host, port, type=_socket.SOCK_STREAM)
        except OSError:
            return []
        seen = set()
        nodes = []
        for family, _, _, _, sockaddr in infos:
            ip = sockaddr[0]
            if ip not in seen:
                seen.add(ip)
                nodes.append(ServerNode(EndPoint(ip, port)))
        return nodes


class IciNamingService(NamingService):
    """ici://slice — one node per local jax device (TPU-pod membership)."""

    interval_s = 0

    def get_servers(self):
        import jax
        return [ServerNode(EndPoint(self.param or "slice0", d.id, "ici"))
                for d in jax.devices()]


class RemoteFileNamingService(NamingService):
    """remotefile://host:port/path — periodically fetch a server list over
    HTTP in the file:// format: 'host:port [weight] [tag]' per line, #
    comments (reference policy/remotefile_naming_service.cpp)."""

    interval_s = 5.0

    def _fetch(self) -> str:
        """Raises on network error or non-200: the NamingServiceThread
        preserves the last-known-good server list on refresh failures
        (the reference's behavior) — returning [] here would wipe the LB
        on a transient registry outage."""
        from brpc_tpu.rpc.http import HttpChannel
        addr, slash, path = self.param.partition("/")
        ch = HttpChannel(addr, timeout_ms=4000)
        try:
            r = ch.request("GET", "/" + path if slash else "/")
            if r.status != 200:
                raise OSError(f"registry returned HTTP {r.status}")
            return r.body.decode("utf-8", "replace")
        finally:
            ch.close()

    def get_servers(self):
        return _parse_server_lines(self._fetch())


class HttpJsonNamingService(RemoteFileNamingService):
    """discovery://host:port/path — periodically fetch a JSON server list
    (the consul/discovery/nacos slot, reference
    policy/{consul,discovery,nacos}_naming_service.cpp — all three poll an
    HTTP registry and differ only in JSON shape).  Accepted shapes:

      ["host:port", ...]
      [{"addr": "host:port", "weight": 2, "tag": "0/4"}, ...]
      {"servers": [... either of the above ...]}   (nacos/discovery style)
    """

    interval_s = 5.0

    def get_servers(self):
        import json
        # fetch/parse errors propagate: keep the last-known-good list
        doc = json.loads(self._fetch() or "null")
        if isinstance(doc, dict):
            doc = doc.get("servers") or doc.get("hosts") or []
        if not isinstance(doc, list):
            raise ValueError("registry JSON is not a server list")
        nodes = []
        for item in doc:
            try:
                if isinstance(item, str):
                    nodes.append(ServerNode(str2endpoint(item)))
                elif isinstance(item, dict):
                    nodes.append(ServerNode(
                        str2endpoint(item["addr"]),
                        int(item.get("weight") or 1),
                        str(item.get("tag") or "")))
            except (ValueError, KeyError, TypeError, AttributeError):
                continue
        return nodes


_SCHEMES = {
    "list": ListNamingService,
    "file": FileNamingService,
    "dns": DnsNamingService,
    "ici": IciNamingService,
    "remotefile": RemoteFileNamingService,
    "discovery": HttpJsonNamingService,
    "consul": HttpJsonNamingService,
    "nacos": HttpJsonNamingService,
}


def register_naming_service(scheme: str, cls) -> None:
    _SCHEMES[scheme] = cls


class NamingServiceFilter:
    """Hook to drop nodes before they reach the LB (naming_service_filter.h)."""

    def accept(self, node: ServerNode) -> bool:
        return True


class NamingServiceThread(threading.Thread):
    """Dedicated refresher per cluster (details/naming_service_thread.*)."""

    def __init__(self, ns: NamingService, lb: LoadBalancer,
                 ns_filter: NamingServiceFilter | None = None):
        super().__init__(daemon=True, name=f"ns-{ns.param}")
        self.ns = ns
        self.lb = lb
        self.filter = ns_filter
        self._stop = threading.Event()
        self._resolved_once = threading.Event()

    def run(self):
        while not self._stop.is_set():
            try:
                nodes = self.ns.get_servers()
                if self.filter is not None:
                    nodes = [n for n in nodes if self.filter.accept(n)]
                if nodes or self._resolved_once.is_set():
                    self.lb.reset_servers(nodes)
                self._resolved_once.set()
            except Exception as e:
                # refresh failed: keep the last-known-good list (reference
                # behavior); one-line note, not a traceback — transient
                # registry outages are expected in elastic clusters.
                # Reloadable flag: test suites silence it (dead loopback
                # registries from finished tests are pure noise there)
                if _flags.get_flag("naming_log_refresh_failures"):
                    print(f"[naming] refresh of {self.ns.param!r} failed: "
                          f"{type(e).__name__}: {e} "
                          f"(keeping previous list)")
            if self.ns.interval_s <= 0:
                break
            self._stop.wait(self.ns.interval_s)

    def wait_first_resolution(self, timeout: float = 5.0) -> bool:
        return self._resolved_once.wait(timeout)

    def stop(self):
        self._stop.set()


def start_naming_service(url: str, lb: LoadBalancer,
                         ns_filter: NamingServiceFilter | None = None,
                         ) -> NamingServiceThread:
    scheme, _, param = url.partition("://")
    cls = _SCHEMES.get(scheme)
    if cls is None:
        raise KeyError(f"unknown naming service scheme {scheme!r}; "
                       f"have {sorted(_SCHEMES)}")
    t = NamingServiceThread(cls(param), lb, ns_filter)
    t.start()
    t.wait_first_resolution()
    return t
