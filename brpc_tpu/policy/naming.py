"""Naming services (reference policy/*_naming_service.cpp; SURVEY.md §2.5).

A NamingService runs in a dedicated daemon thread per cluster and pushes
ServerNode lists to its listener (the load balancer) whenever membership
changes — the cluster is elastic by subscription (naming_service.h:36-61).

Schemes: list://h1:p1,h2:p2[(w)]   static list
         file://path               one "host:port [weight] [tag]" per line,
                                   re-read periodically (reference file NS)
         dns://host:port           resolve A records periodically
         ici://slice               every chip in the local mesh (TPU-native:
                                   membership = jax devices, no DNS in a pod)
"""
from __future__ import annotations

import os
import socket as _socket
import threading
import time

from brpc_tpu.butil.endpoint import EndPoint, str2endpoint
from brpc_tpu.policy.load_balancer import LoadBalancer, ServerNode

DEFAULT_INTERVAL_S = 5.0


class NamingService:
    interval_s = DEFAULT_INTERVAL_S

    def __init__(self, param: str):
        self.param = param

    def get_servers(self) -> list[ServerNode]:
        raise NotImplementedError


class ListNamingService(NamingService):
    """list://host:port[(weight)][ tag],... — static membership; the
    optional space-separated tag carries partition labels like "0/4"
    (reference list_naming_service.cpp tag support for PartitionChannel)."""

    interval_s = 0  # never re-resolves

    def get_servers(self):
        nodes = []
        for part in self.param.split(","):
            part = part.strip()
            if not part:
                continue
            tag = ""
            if " " in part:
                part, _, tag = part.partition(" ")
                tag = tag.strip()
            weight = 1
            if part.endswith(")") and "(" in part:
                part, _, w = part[:-1].rpartition("(")
                weight = int(w)
            nodes.append(ServerNode(str2endpoint(part), weight, tag))
        return nodes


class FileNamingService(NamingService):
    """file://path — 'host:port [weight] [tag]' per line, # comments."""

    def get_servers(self):
        nodes = []
        try:
            with open(self.param) as f:
                for line in f:
                    line = line.split("#", 1)[0].strip()
                    if not line:
                        continue
                    parts = line.split()
                    weight = int(parts[1]) if len(parts) > 1 and \
                        parts[1].isdigit() else 1
                    tag = parts[-1] if len(parts) > 1 and \
                        not parts[-1].isdigit() else ""
                    nodes.append(ServerNode(str2endpoint(parts[0]), weight,
                                            tag))
        except OSError:
            return []
        return nodes


class DnsNamingService(NamingService):
    """dns://host:port — A/AAAA records of host."""

    def get_servers(self):
        host, _, port = self.param.partition(":")
        port = int(port or 80)
        try:
            infos = _socket.getaddrinfo(host, port, type=_socket.SOCK_STREAM)
        except OSError:
            return []
        seen = set()
        nodes = []
        for family, _, _, _, sockaddr in infos:
            ip = sockaddr[0]
            if ip not in seen:
                seen.add(ip)
                nodes.append(ServerNode(EndPoint(ip, port)))
        return nodes


class IciNamingService(NamingService):
    """ici://slice — one node per local jax device (TPU-pod membership)."""

    interval_s = 0

    def get_servers(self):
        import jax
        return [ServerNode(EndPoint(self.param or "slice0", d.id, "ici"))
                for d in jax.devices()]


_SCHEMES = {
    "list": ListNamingService,
    "file": FileNamingService,
    "dns": DnsNamingService,
    "ici": IciNamingService,
}


def register_naming_service(scheme: str, cls) -> None:
    _SCHEMES[scheme] = cls


class NamingServiceFilter:
    """Hook to drop nodes before they reach the LB (naming_service_filter.h)."""

    def accept(self, node: ServerNode) -> bool:
        return True


class NamingServiceThread(threading.Thread):
    """Dedicated refresher per cluster (details/naming_service_thread.*)."""

    def __init__(self, ns: NamingService, lb: LoadBalancer,
                 ns_filter: NamingServiceFilter | None = None):
        super().__init__(daemon=True, name=f"ns-{ns.param}")
        self.ns = ns
        self.lb = lb
        self.filter = ns_filter
        self._stop = threading.Event()
        self._resolved_once = threading.Event()

    def run(self):
        while not self._stop.is_set():
            try:
                nodes = self.ns.get_servers()
                if self.filter is not None:
                    nodes = [n for n in nodes if self.filter.accept(n)]
                if nodes or self._resolved_once.is_set():
                    self.lb.reset_servers(nodes)
                self._resolved_once.set()
            except Exception:  # pragma: no cover
                import traceback
                traceback.print_exc()
            if self.ns.interval_s <= 0:
                break
            self._stop.wait(self.ns.interval_s)

    def wait_first_resolution(self, timeout: float = 5.0) -> bool:
        return self._resolved_once.wait(timeout)

    def stop(self):
        self._stop.set()


def start_naming_service(url: str, lb: LoadBalancer,
                         ns_filter: NamingServiceFilter | None = None,
                         ) -> NamingServiceThread:
    scheme, _, param = url.partition("://")
    cls = _SCHEMES.get(scheme)
    if cls is None:
        raise KeyError(f"unknown naming service scheme {scheme!r}; "
                       f"have {sorted(_SCHEMES)}")
    t = NamingServiceThread(cls(param), lb, ns_filter)
    t.start()
    t.wait_first_resolution()
    return t
