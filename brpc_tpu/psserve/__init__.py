"""brpc_tpu.psserve — sharded embedding / parameter-server service.

The BASELINE.json north-star workload running on the full stack
(ROADMAP item 1): an embedding table row-sharded over partitions,
served as ``PS.Lookup`` / ``PS.Update`` (sparse scatter-add) plus
dense ``PS.Pull``/``PS.Push`` RPCs, with

  * client-side routing through **PartitionChannel** — each request's
    key-set split by shard ownership, fanned out sub-call-per-
    partition, reassembled in key order (client.py),
  * the co-located lowering: the same fan-out as ONE compiled
    ``shard_map`` collective program over the ``tp`` ICI mesh
    (lowered.py — ppermute/psum key exchange + local gather, the
    SNIPPETS.md [2] shape),
  * server-side coalescing through the **DynamicBatcher** (service.py
    — bucketed key-count padding, one compile per bucket; the first
    non-generate traffic shape the batcher has coalesced),
  * idempotent updates (53-bit update_ids) + per-shard version
    counters giving read-your-writes and chaos-provable exactly-once
    apply.

The ``/psserve`` console page renders :func:`psserve_snapshot`;
``psserve_*`` bvars ride /brpc_metrics.
"""
from __future__ import annotations

import threading
import weakref

_mu = threading.Lock()
_shards: list = []      # weakrefs to (EmbeddingShardServer, PSService)
_clients: list = []     # weakrefs to PSClient
_tables: list = []      # weakrefs to ShardedEmbeddingTable


def _register_shard(shard, svc=None) -> None:
    with _mu:
        _shards.append((weakref.ref(shard),
                        weakref.ref(svc) if svc is not None else None))


def _register_client(client) -> None:
    with _mu:
        _clients.append(weakref.ref(client))


def _register_table(table) -> None:
    with _mu:
        _tables.append(weakref.ref(table))


def psserve_snapshot() -> dict:
    """Live PS components' stats — the /psserve console page's data:
    per-shard row counts + version counters + hot-key histograms,
    batcher coalescing stats, client routing counters."""
    shards = []
    clients = []
    tables = []
    with _mu:
        shard_refs = list(_shards)
        client_refs = list(_clients)
        table_refs = list(_tables)
    for sref, vref in shard_refs:
        s = sref()
        if s is None:
            continue
        entry = s.stats()
        svc = vref() if vref is not None else None
        if svc is not None:
            entry["batchers"] = {
                b.name: b.stats() for b in
                (svc._lookup_b, svc._update_b) if b is not None}
        shards.append(entry)
    for cref in client_refs:
        c = cref()
        if c is not None:
            clients.append(c.stats())
    for tref in table_refs:
        t = tref()
        if t is not None:
            tables.append(t.stats())
    # prune dead refs opportunistically
    with _mu:
        _shards[:] = [e for e in _shards if e[0]() is not None]
        _clients[:] = [r for r in _clients if r() is not None]
        _tables[:] = [r for r in _tables if r() is not None]
    return {"shards": shards, "clients": clients, "lowered": tables}


from brpc_tpu.psserve.shard import (  # noqa: E402,F401
    EmbeddingShardServer, init_embedding_table, owners_for, shard_bounds,
)
from brpc_tpu.psserve.lowered import ShardedEmbeddingTable  # noqa: E402,F401
from brpc_tpu.psserve.client import PSClient  # noqa: E402,F401
from brpc_tpu.psserve.service import (  # noqa: E402,F401
    PSService, register_psserve, unregister_psserve,
)
