"""brpc_tpu.psserve — sharded embedding / parameter-server service.

The BASELINE.json north-star workload running on the full stack
(ROADMAP item 1): an embedding table row-sharded over partitions,
served as ``PS.Lookup`` / ``PS.Update`` (sparse scatter-add) plus
dense ``PS.Pull``/``PS.Push`` RPCs, with

  * client-side routing through **PartitionChannel** — each request's
    key-set split by shard ownership, fanned out sub-call-per-
    partition, reassembled in key order (client.py),
  * the co-located lowering: the same fan-out as ONE compiled
    ``shard_map`` collective program over the ``tp`` ICI mesh
    (lowered.py — ppermute/psum key exchange + local gather, the
    SNIPPETS.md [2] shape),
  * server-side coalescing through the **DynamicBatcher** (service.py
    — bucketed key-count padding, one compile per bucket; the first
    non-generate traffic shape the batcher has coalesced),
  * idempotent updates (53-bit update_ids) + per-shard version
    counters giving read-your-writes and chaos-provable exactly-once
    apply.

The ``/psserve`` console page renders :func:`psserve_snapshot`;
``psserve_*`` bvars ride /brpc_metrics.
"""
from __future__ import annotations

import threading
import weakref

from brpc_tpu.butil.lockprof import InstrumentedLock

_mu = InstrumentedLock("psserve.registry")
_shards: list = []      # weakrefs to (EmbeddingShardServer, PSService)
_clients: list = []     # weakrefs to PSClient
_tables: list = []      # weakrefs to ShardedEmbeddingTable


def _register_shard(shard, svc=None) -> None:
    with _mu:
        _shards.append((weakref.ref(shard),
                        weakref.ref(svc) if svc is not None else None))


def _register_client(client) -> None:
    with _mu:
        _clients.append(weakref.ref(client))


def _register_table(table) -> None:
    with _mu:
        _tables.append(weakref.ref(table))


# ---- the ICI fast path's local-table registry (ISSUE 13) ----
#
# A process that co-locates a ShardedEmbeddingTable with its PSClients
# registers the table here (ShardedEmbeddingTable(serve_local=True) or
# an explicit register_local_table call); PSClient(ici="auto") then
# short-circuits Lookup/Update to the lowered shard_map program behind
# the unchanged client API.  Registration is the explicit opt-in: a
# table constructed for tests/oracles never hijacks RPC clients.

_local_tables: dict[str, "weakref.ref"] = {}
# bumped on every register/unregister: clients cache a MISS against
# this generation so the common no-local-table case never takes _mu
# on the lookup/update hot path
_local_tables_gen = 0


def register_local_table(table, name: str = "ps") -> None:
    """Publish ``table`` as THE local lowered table for PS clients
    named after the same logical table (default ``"ps"``)."""
    global _local_tables_gen
    with _mu:
        _local_tables[str(name)] = weakref.ref(table)
        _local_tables_gen += 1


def unregister_local_table(name: str = "ps") -> None:
    global _local_tables_gen
    with _mu:
        _local_tables.pop(str(name), None)
        _local_tables_gen += 1


def find_local_table(name: str, vocab: int, dim: int):
    """The registered local table matching (name, vocab, dim), or None
    — geometry must match exactly or the fast path stays off."""
    with _mu:
        ref = _local_tables.get(str(name))
    t = ref() if ref is not None else None
    if t is None or t.vocab != int(vocab) or t.dim != int(dim):
        return None
    return t


def psserve_snapshot() -> dict:
    """Live PS components' stats — the /psserve console page's data:
    per-shard row counts + version counters + hot-key histograms,
    batcher coalescing stats, client routing counters."""
    shards = []
    clients = []
    tables = []
    with _mu:
        shard_refs = list(_shards)
        client_refs = list(_clients)
        table_refs = list(_tables)
    for sref, vref in shard_refs:
        s = sref()
        if s is None:
            continue
        entry = s.stats()
        svc = vref() if vref is not None else None
        if svc is not None:
            entry["batchers"] = {
                b.name: b.stats() for b in
                (svc._lookup_b, svc._update_b, svc._update_tb)
                if b is not None}
        shards.append(entry)
    for cref in client_refs:
        c = cref()
        if c is not None:
            clients.append(c.stats())
    for tref in table_refs:
        t = tref()
        if t is not None:
            tables.append(t.stats())
    # prune dead refs opportunistically
    with _mu:
        _shards[:] = [e for e in _shards if e[0]() is not None]
        _clients[:] = [r for r in _clients if r() is not None]
        _tables[:] = [r for r in _tables if r() is not None]
    from brpc_tpu.psserve.service import wire_counters
    return {"shards": shards, "clients": clients, "lowered": tables,
            "wire": wire_counters()}


from brpc_tpu.psserve.shard import (  # noqa: E402,F401
    EmbeddingShardServer, init_embedding_table, owners_for, shard_bounds,
)
from brpc_tpu.psserve.lowered import ShardedEmbeddingTable  # noqa: E402,F401
from brpc_tpu.psserve.client import PSClient  # noqa: E402,F401
from brpc_tpu.psserve.service import (  # noqa: E402,F401
    PSService, register_psserve, unregister_psserve,
)
