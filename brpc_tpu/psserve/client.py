"""PSClient — the embedding service's client-side router.

``lookup(keys)`` / ``update(keys, grads)`` take GLOBAL keys in request
order.  The client splits each request's key-set by shard ownership
(:func:`~brpc_tpu.psserve.shard.owners_for` over the contiguous range
map), fans the owned subsets out sub-call-per-partition through a
:class:`~brpc_tpu.rpc.combo_channels.PartitionChannel` (retry/backup:
failed partitions re-issue, rotating replicas under ``lb=``), and
reassembles responses IN KEY ORDER — duplicates and shard-straddling
key-sets fall out of the position bookkeeping naturally.

Updates are idempotent end-to-end: every sub-call carries a distinct
53-bit ``update_id`` (per-process random salt + process-wide counter +
partition), so a retry after a lost ack re-acks the ORIGINAL apply
instead of double scatter-adding; the shard's version counters prove
it.

With a co-located mesh the same client surface runs over a
:class:`~brpc_tpu.psserve.lowered.ShardedEmbeddingTable` instead: the
split/fan-out/merge plan is lowered to one compiled collective program
(all-to-all / ppermute key exchange + local gather) and never touches a
socket.  ``Pull``/``Push`` route dense parameters to an owner shard by
stable name hash.
"""
from __future__ import annotations

import threading
import zlib
from typing import Optional

import numpy as np

from brpc_tpu import errors
from brpc_tpu.bvar import Adder, LatencyRecorder
from brpc_tpu.psserve.shard import owners_for, shard_bounds

CLIENT_LOOKUPS = Adder("psserve_client_lookups")
CLIENT_UPDATES = Adder("psserve_client_updates")
CLIENT_RETRIES = Adder("psserve_client_retries")
CLIENT_STALE_READS = Adder("psserve_client_stale_reads")
LOOKUP_LATENCY = LatencyRecorder("psserve_client_lookup")

# update_id construction: ids must stay unique across every client in
# every process sharing the shards (a collision silently drops a fresh
# update as a "duplicate"), and must survive float64 packing exactly
# (<= 2^53, the largest float64-exact integer).  Layout: (18-bit
# per-process random salt << 30 | 30-bit process-wide counter)
# * n_shards + partition + 1 — 48 bits of sequence * up to 32 shards
# tops out at exactly 2^53 (saturated salt/counter/partition), which
# the service's inclusive bound accepts; the salt makes
# cross-process collisions ~2^-18 per process pair, and the counter is
# process-wide so client construction churn can never wrap it back
# onto a live id.
import os as _os

_uid_mu = threading.Lock()
_uid_salt = int.from_bytes(_os.urandom(3), "big") & 0x3FFFF
_uid_counter = [0]


def _next_uid_seq() -> int:
    with _uid_mu:
        _uid_counter[0] += 1
        if _uid_counter[0] >= (1 << 30):
            # re-salt rather than wrap onto ids that may still sit in
            # a shard's applied window
            globals()["_uid_salt"] = \
                int.from_bytes(_os.urandom(3), "big") & 0x3FFFF
            _uid_counter[0] = 1
        return (_uid_salt << 30) | _uid_counter[0]


class PSClient:
    """Route Lookup/Update/Pull/Push over a partitioned embedding
    service.

    ``backend`` is either a PartitionChannel (RPC fan-out; needs
    ``n_shards`` partitions registered) or a ShardedEmbeddingTable
    (collective lowering, co-located mesh).
    """

    def __init__(self, backend, *, vocab: int, dim: int,
                 n_shards: Optional[int] = None,
                 timeout_ms: int = 5000, max_retry: int = 2,
                 name: str = "psclient"):
        from brpc_tpu.rpc.combo_channels import PartitionChannel
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.name = name
        self.timeout_ms = int(timeout_ms)
        self.max_retry = int(max_retry)
        self._pc = None
        self._lowered = None
        if isinstance(backend, PartitionChannel):
            self._pc = backend
            self.n_shards = int(n_shards or backend.partition_count)
            # only the RPC path mints update_ids; the lowered backend
            # (which may legitimately span >32 chips) never does
            if self.n_shards > 32:
                raise ValueError("update_id space covers <= 32 shards")
        else:       # duck-typed lowered table (lookup/update/stats)
            self._lowered = backend
            self.n_shards = int(getattr(backend, "p", n_shards or 1))
        self.bounds = shard_bounds(self.vocab, self.n_shards)
        self._mu = threading.Lock()
        # read-your-writes bookkeeping: highest acked version per shard
        self.acked_version = [0] * self.n_shards
        self.n_lookups = 0
        self.n_updates = 0
        self.n_retries = 0
        self.n_stale_reads = 0
        from brpc_tpu import psserve as _ps
        _ps._register_client(self)

    # ---- id + split helpers ----

    def _uid_for(self, token: int, part: int) -> int:
        """Per-partition update_id for one LOGICAL update: pure
        function of (token, partition), so replaying a token re-sends
        the same ids and already-applied partitions dedup."""
        return token * self.n_shards + part + 1

    def _split(self, keys: np.ndarray) -> dict[int, np.ndarray]:
        """partition -> positions (indices into the request) owned."""
        owner = owners_for(keys, self.bounds)
        return {int(s): np.flatnonzero(owner == s)
                for s in np.unique(owner)}

    # ---- Lookup ----

    def lookup(self, keys) -> np.ndarray:
        """rows [n, dim] for GLOBAL keys, reassembled in key order."""
        import time
        keys = np.asarray(keys, np.int64)
        if keys.ndim != 1:
            raise ValueError("keys must be 1-D")
        if keys.size and (keys.min() < 0 or keys.max() >= self.vocab):
            raise ValueError(f"keys outside [0, {self.vocab})")
        t0 = time.monotonic()
        if self._lowered is not None:
            rows, _ver = self._lowered.lookup(keys)
        else:
            split = self._split(keys)
            sub = {part: {"keys": keys[pos].tolist()}
                   for part, pos in split.items()}
            resp = self._call(sub, "Lookup")
            rows = np.empty((keys.shape[0], self.dim), np.float32)
            for part, pos in split.items():
                r = resp[part]
                rows[pos] = np.asarray(r["rows"], np.float32)
                self._note_version(part, int(r.get("version", 0)))
        with self._mu:
            self.n_lookups += 1
        CLIENT_LOOKUPS.add(1)
        LOOKUP_LATENCY.add(int((time.monotonic() - t0) * 1e6))
        return rows

    # ---- Update ----

    def update(self, keys, grads,
               update_token: Optional[int] = None) -> dict[int, int]:
        """Sparse scatter-add; returns {partition: acked version}.
        Exactly-once per partition even across retries (update_ids).

        If the fan-out fails PARTIALLY (some partitions acked, some
        exhausted their retries), the raised RpcError carries
        ``update_token`` — replay the SAME logical update with
        ``update(keys, grads, update_token=e.update_token)`` and the
        partitions that already applied will dedup instead of double
        scatter-adding.  A retry WITHOUT the token mints fresh ids and
        re-applies everywhere."""
        keys = np.asarray(keys, np.int64)
        grads = np.asarray(grads, np.float32)
        if keys.ndim != 1:
            raise ValueError("keys must be 1-D")
        if keys.size and (keys.min() < 0 or keys.max() >= self.vocab):
            # same validation as lookup: a clear local error, not a
            # permanent server EREQUEST retried max_retry times (or a
            # baffling ENODATA for a negative key's partition)
            raise ValueError(f"keys outside [0, {self.vocab})")
        if grads.shape != (keys.shape[0], self.dim):
            raise ValueError(f"grads shape {grads.shape} != "
                             f"({keys.shape[0]}, {self.dim})")
        if self._lowered is not None:
            ver = self._lowered.update(keys, grads)
            with self._mu:
                self.n_updates += 1
            CLIENT_UPDATES.add(1)
            return {0: ver}
        token = update_token if update_token is not None \
            else _next_uid_seq()
        split = self._split(keys)
        sub = {}
        for part, pos in split.items():
            sub[part] = {"keys": keys[pos].tolist(),
                         "grads": grads[pos].tolist(),
                         "update_id": self._uid_for(token, part)}
        try:
            resp = self._call(sub, "Update")
        except errors.RpcError as e:
            # stamp the token so the caller can replay THIS logical
            # update idempotently (partitions that acked will dedup)
            e.update_token = token
            raise
        out = {}
        for part, r in resp.items():
            ver = int(r["version"])
            out[part] = ver
            self._note_ack(part, ver)
        with self._mu:
            self.n_updates += 1
        CLIENT_UPDATES.add(1)
        return out

    # ---- dense Pull/Push ----

    def _owner_of(self, pname: str) -> int:
        return zlib.crc32(pname.encode()) % self.n_shards

    def pull(self, pname: str) -> np.ndarray:
        if self._lowered is not None:
            raise errors.RpcError(errors.ENOMETHOD,
                                  "lowered backend serves embeddings only")
        part = self._owner_of(pname)
        r = self._call({part: {"name": pname}}, "Pull")[part]
        return np.asarray(r["value"], np.float32)

    def push(self, pname: str, delta) -> int:
        if self._lowered is not None:
            raise errors.RpcError(errors.ENOMETHOD,
                                  "lowered backend serves embeddings only")
        part = self._owner_of(pname)
        req = {part: {"name": pname,
                      "delta": np.asarray(delta, np.float32).tolist(),
                      "update_id": self._uid_for(_next_uid_seq(), part)}}
        r = self._call(req, "Push")[part]
        ver = int(r["version"])
        self._note_ack(part, ver)
        return ver

    # ---- fan-out plumbing ----

    def _call(self, sub_requests: dict, method: str) -> dict:
        def on_retry(idx, err):
            with self._mu:
                self.n_retries += 1
            CLIENT_RETRIES.add(1)
        return self._pc.call_partitioned(
            "PS", method, sub_requests, serializer="json",
            timeout_ms=self.timeout_ms, max_retry=self.max_retry,
            on_retry=on_retry)

    def _note_ack(self, part: int, ver: int) -> None:
        with self._mu:
            if ver > self.acked_version[part]:
                self.acked_version[part] = ver

    def _note_version(self, part: int, ver: int) -> None:
        """Read-your-writes check: a lookup must observe every update
        THIS client already got acked on that shard."""
        with self._mu:
            if ver < self.acked_version[part]:
                self.n_stale_reads += 1
                CLIENT_STALE_READS.add(1)

    def close(self) -> None:
        if self._pc is not None:
            self._pc.close()

    def stats(self) -> dict:
        with self._mu:
            return {
                "name": self.name,
                "n_shards": self.n_shards,
                "backend": "lowered" if self._lowered is not None
                           else "partition_channel",
                "lookups": self.n_lookups,
                "updates": self.n_updates,
                "stale_reads": self.n_stale_reads,
                "acked_versions": list(self.acked_version),
            }
