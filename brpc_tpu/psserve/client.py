"""PSClient — the embedding service's client-side router.

``lookup(keys)`` / ``update(keys, grads)`` take GLOBAL keys in request
order.  The client splits each request's key-set by shard ownership
(:func:`~brpc_tpu.psserve.shard.owners_for` over the contiguous range
map), fans the owned subsets out sub-call-per-partition through a
:class:`~brpc_tpu.rpc.combo_channels.PartitionChannel` (retry/backup:
failed partitions re-issue, rotating replicas under ``lb=``), and
reassembles responses IN KEY ORDER — duplicates and shard-straddling
key-sets fall out of the position bookkeeping naturally.

Updates are idempotent end-to-end: every sub-call carries a distinct
53-bit ``update_id`` (per-process random salt + process-wide counter +
partition), so a retry after a lost ack re-acks the ORIGINAL apply
instead of double scatter-adding; the shard's version counters prove
it.

With a co-located mesh the same client surface runs over a
:class:`~brpc_tpu.psserve.lowered.ShardedEmbeddingTable` instead: the
split/fan-out/merge plan is lowered to one compiled collective program
(all-to-all / ppermute key exchange + local gather) and never touches a
socket.  ``Pull``/``Push`` route dense parameters to an owner shard by
stable name hash.
"""
from __future__ import annotations

import threading
from brpc_tpu.butil.lockprof import InstrumentedLock
import weakref
import zlib
from typing import Optional

import numpy as np

from brpc_tpu import errors
from brpc_tpu.bvar import Adder, LatencyRecorder
from brpc_tpu.psserve.shard import owners_for, shard_bounds

CLIENT_LOOKUPS = Adder("psserve_client_lookups")
CLIENT_UPDATES = Adder("psserve_client_updates")
CLIENT_RETRIES = Adder("psserve_client_retries")
CLIENT_STALE_READS = Adder("psserve_client_stale_reads")
# binary-wire negotiation (ISSUE 13): a partition answering ENOMETHOD
# to LookupT/UpdateT is an old peer — it falls back to JSON, sticky
# per partition, and this counts each such downgrade
CLIENT_NEGOTIATION_FALLBACKS = Adder(
    "psserve_client_negotiation_fallbacks")
# calls short-circuited to a co-located lowered table (the ICI fast
# path) instead of the RPC fan-out
CLIENT_ICI_CALLS = Adder("psserve_client_ici_calls")
LOOKUP_LATENCY = LatencyRecorder("psserve_client_lookup")

# update_id construction: ids must stay unique across every client in
# every process sharing the shards (a collision silently drops a fresh
# update as a "duplicate"), and must survive float64 packing exactly
# (<= 2^53, the largest float64-exact integer).  Layout: (18-bit
# per-process random salt << 30 | 30-bit process-wide counter)
# * n_shards + partition + 1 — 48 bits of sequence * up to 32 shards
# tops out at exactly 2^53 (saturated salt/counter/partition), which
# the service's inclusive bound accepts; the salt makes
# cross-process collisions ~2^-18 per process pair, and the counter is
# process-wide so client construction churn can never wrap it back
# onto a live id.
import os as _os

_uid_mu = InstrumentedLock("psserve.uid")
_uid_salt = int.from_bytes(_os.urandom(3), "big") & 0x3FFFF
_uid_counter = [0]


def _next_uid_seq() -> int:
    with _uid_mu:
        _uid_counter[0] += 1
        if _uid_counter[0] >= (1 << 30):
            # re-salt rather than wrap onto ids that may still sit in
            # a shard's applied window
            globals()["_uid_salt"] = \
                int.from_bytes(_os.urandom(3), "big") & 0x3FFFF
            _uid_counter[0] = 1
        return (_uid_salt << 30) | _uid_counter[0]


class PSClient:
    """Route Lookup/Update/Pull/Push over a partitioned embedding
    service.

    ``backend`` is either a PartitionChannel (RPC fan-out; needs
    ``n_shards`` partitions registered) or a ShardedEmbeddingTable
    (collective lowering, co-located mesh).
    """

    def __init__(self, backend, *, vocab: int, dim: int,
                 n_shards: Optional[int] = None,
                 timeout_ms: int = 5000, max_retry: int = 2,
                 name: str = "psclient",
                 serializer: str = "tensorframe",
                 ici: object = "auto", table_name: str = "ps"):
        from brpc_tpu.rpc.combo_channels import PartitionChannel
        if serializer not in ("tensorframe", "json"):
            raise ValueError("serializer must be tensorframe|json, got "
                             f"{serializer!r}")
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.name = name
        self.timeout_ms = int(timeout_ms)
        self.max_retry = int(max_retry)
        # preferred wire format; per-partition negotiation downgrades
        # to "json" (sticky) when a partition answers ENOMETHOD to the
        # binary methods (an old peer)
        self.serializer = serializer
        self._wire_mode: dict[int, str] = {}
        # ICI fast path: "auto" engages when a ShardedEmbeddingTable
        # matching (table_name, vocab, dim) is registered locally
        # (psserve.register_local_table / serve_local=True); "off"
        # never; a table instance pins it explicitly
        self._ici_mode = ici
        self.table_name = str(table_name)
        self._ici_ref = None
        self._ici_gen = None        # registry generation of cached miss
        self._ici_acked_version = 0
        self._pc = None
        self._lowered = None
        if isinstance(backend, PartitionChannel):
            self._pc = backend
            self.n_shards = int(n_shards or backend.partition_count)
            # only the RPC path mints update_ids; the lowered backend
            # (which may legitimately span >32 chips) never does
            if self.n_shards > 32:
                raise ValueError("update_id space covers <= 32 shards")
        else:       # duck-typed lowered table (lookup/update/stats)
            self._lowered = backend
            self.n_shards = int(getattr(backend, "p", n_shards or 1))
        self.bounds = shard_bounds(self.vocab, self.n_shards)
        self._mu = InstrumentedLock("psserve.client")
        # read-your-writes bookkeeping: highest acked version per shard
        self.acked_version = [0] * self.n_shards
        self.n_lookups = 0
        self.n_updates = 0
        self.n_retries = 0
        self.n_stale_reads = 0
        self.n_negotiation_fallbacks = 0
        self.n_ici_calls = 0
        from brpc_tpu import psserve as _ps
        _ps._register_client(self)

    # ---- id + split helpers ----

    def _uid_for(self, token: int, part: int) -> int:
        """Per-partition update_id for one LOGICAL update: pure
        function of (token, partition), so replaying a token re-sends
        the same ids and already-applied partitions dedup."""
        return token * self.n_shards + part + 1

    def _split(self, keys: np.ndarray) -> dict[int, np.ndarray]:
        """partition -> positions (indices into the request) owned."""
        owner = owners_for(keys, self.bounds)
        return {int(s): np.flatnonzero(owner == s)
                for s in np.unique(owner)}

    # ---- the ICI fast path (ISSUE 13) ----

    def _ici_table(self):
        """The co-located lowered table this client short-circuits to,
        or None.  "auto" resolves against the psserve local-table
        registry (geometry must match); hits cache by weakref, misses
        cache by registry GENERATION — the common no-local-table case
        costs one plain attribute read per call, never the registry
        lock (a hot-path client must not serialize on a process-wide
        mutex that exists for the rare co-located case)."""
        if self._pc is None:
            return None         # already a lowered backend
        mode = self._ici_mode
        if mode in (None, False, "off"):
            return None
        if not isinstance(mode, str):   # an explicit table instance
            return mode
        from brpc_tpu import psserve as _ps
        gen = _ps._local_tables_gen     # plain int read, GIL-atomic
        if self._ici_gen == gen:
            # registry unchanged since the cached resolution — hit or
            # miss, the cache is authoritative (an unregister/replace
            # bumps the generation, so a stale hit can never keep
            # short-circuiting to an orphaned table)
            return self._ici_ref() if self._ici_ref is not None else None
        t = _ps.find_local_table(self.table_name, self.vocab, self.dim)
        self._ici_gen = gen
        self._ici_ref = weakref.ref(t) if t is not None else None
        return t

    def _note_ici(self, ver: int, acked: bool) -> None:
        """Fast-path read-your-writes bookkeeping — tracked apart from
        the per-shard RPC counters (the lowered table's version is one
        counter, not n_shards of them)."""
        with self._mu:
            self.n_ici_calls += 1
            if acked:
                if ver > self._ici_acked_version:
                    self._ici_acked_version = ver
            elif ver < self._ici_acked_version:
                self.n_stale_reads += 1
                CLIENT_STALE_READS.add(1)
        CLIENT_ICI_CALLS.add(1)

    # ---- Lookup ----

    def lookup(self, keys) -> np.ndarray:
        """rows [n, dim] for GLOBAL keys, reassembled in key order."""
        import time
        keys = np.asarray(keys, np.int64)
        if keys.ndim != 1:
            raise ValueError("keys must be 1-D")
        if keys.size and (keys.min() < 0 or keys.max() >= self.vocab):
            raise ValueError(f"keys outside [0, {self.vocab})")
        t0 = time.monotonic()
        if self._lowered is not None:
            rows, _ver = self._lowered.lookup(keys)
        else:
            tbl = self._ici_table()
            if tbl is not None:
                # co-located lowered table: one compiled collective
                # program, no socket — same client API, same rows
                rows, ver = tbl.lookup(keys)
                self._note_ici(ver, acked=False)
            else:
                split = self._split(keys)
                resp = self._fan_out(
                    split, "Lookup",
                    lambda part, pos: {"keys": keys[pos].tolist()},
                    lambda part, pos: {"keys": keys[pos]})
                rows = np.empty((keys.shape[0], self.dim), np.float32)
                for part, pos in split.items():
                    r = resp[part]
                    rows[pos] = np.asarray(r["rows"], np.float32)
                    self._note_version(part, int(r.get("version", 0)))
        with self._mu:
            self.n_lookups += 1
        CLIENT_LOOKUPS.add(1)
        LOOKUP_LATENCY.add(int((time.monotonic() - t0) * 1e6))
        return rows

    # ---- Update ----

    def update(self, keys, grads,
               update_token: Optional[int] = None,
               optimizer=None) -> dict[int, int]:
        """Sparse scatter-add; returns {partition: acked version}.
        Exactly-once per partition even across retries (update_ids).

        With ``optimizer`` (an :class:`OptimizerSpec` or its wire
        dict, ISSUE 17) ``grads`` are RAW gradients and each shard
        runs the FUSED scatter+slot-step program against its
        co-located momentum/Adam rows — the slots never cross the
        wire.  The spec rides the JSON wire as an ``"optimizer"``
        object and the binary wire as flattened ``opt_*`` fields.

        If the fan-out fails PARTIALLY (some partitions acked, some
        exhausted their retries), the raised RpcError carries
        ``update_token`` — replay the SAME logical update with
        ``update(keys, grads, update_token=e.update_token)`` and the
        partitions that already applied will dedup instead of double
        scatter-adding (or double-stepping momentum).  A retry WITHOUT
        the token mints fresh ids and re-applies everywhere."""
        keys = np.asarray(keys, np.int64)
        grads = np.asarray(grads, np.float32)
        if keys.ndim != 1:
            raise ValueError("keys must be 1-D")
        if keys.size and (keys.min() < 0 or keys.max() >= self.vocab):
            # same validation as lookup: a clear local error, not a
            # permanent server EREQUEST retried max_retry times (or a
            # baffling ENODATA for a negative key's partition)
            raise ValueError(f"keys outside [0, {self.vocab})")
        if grads.shape != (keys.shape[0], self.dim):
            raise ValueError(f"grads shape {grads.shape} != "
                             f"({keys.shape[0]}, {self.dim})")
        spec = None
        if optimizer is not None:
            from brpc_tpu.train.optimizer import OptimizerSpec
            spec = OptimizerSpec.from_wire(optimizer)
        if self._lowered is not None:
            ver = self._lowered.update(keys, grads, optimizer=spec) \
                if spec is not None else \
                self._lowered.update(keys, grads)
            with self._mu:
                self.n_updates += 1
            CLIENT_UPDATES.add(1)
            return {0: ver}
        token = update_token if update_token is not None \
            else _next_uid_seq()
        tbl = self._ici_table()
        if tbl is not None:
            # fast path: ONE atomic apply against the lowered table,
            # idempotent by the token itself (a replayed update_token
            # hits the table's applied set and acks the original —
            # the same discipline the RPC shards run per partition)
            ver = tbl.update(keys, grads, update_id=token,
                             optimizer=spec) \
                if spec is not None else \
                tbl.update(keys, grads, update_id=token)
            self._note_ici(ver, acked=True)
            with self._mu:
                self.n_updates += 1
            CLIENT_UPDATES.add(1)
            return {0: ver}
        split = self._split(keys)

        def make_json(part, pos):
            req = {"keys": keys[pos].tolist(),
                   "grads": grads[pos].tolist(),
                   "update_id": self._uid_for(token, part)}
            if spec is not None:
                req["optimizer"] = spec.to_wire()
            return req

        def make_frame(part, pos):
            # tensors ride as raw int64/float32 bytes (fancy-index
            # slices, one vectorized copy each), never Python lists;
            # the optimizer spec flattens to inline scalar fields
            req = {"keys": keys[pos], "grads": grads[pos],
                   "update_id": self._uid_for(token, part)}
            if spec is not None:
                req.update(spec.to_frame_fields())
            return req

        try:
            resp = self._fan_out(split, "Update", make_json, make_frame)
        except errors.RpcError as e:
            # stamp the token so the caller can replay THIS logical
            # update idempotently (partitions that acked will dedup)
            e.update_token = token
            raise
        out = {}
        for part, r in resp.items():
            ver = int(r["version"])
            out[part] = ver
            self._note_ack(part, ver)
        with self._mu:
            self.n_updates += 1
        CLIENT_UPDATES.add(1)
        return out

    # ---- dense Pull/Push ----

    def _owner_of(self, pname: str) -> int:
        return zlib.crc32(pname.encode()) % self.n_shards

    def pull(self, pname: str) -> np.ndarray:
        if self._lowered is not None:
            raise errors.RpcError(errors.ENOMETHOD,
                                  "lowered backend serves embeddings only")
        part = self._owner_of(pname)
        r = self._call({part: {"name": pname}}, "Pull")[part]
        return np.asarray(r["value"], np.float32)

    def push(self, pname: str, delta) -> int:
        if self._lowered is not None:
            raise errors.RpcError(errors.ENOMETHOD,
                                  "lowered backend serves embeddings only")
        part = self._owner_of(pname)
        req = {part: {"name": pname,
                      "delta": np.asarray(delta, np.float32).tolist(),
                      "update_id": self._uid_for(_next_uid_seq(), part)}}
        r = self._call(req, "Push")[part]
        ver = int(r["version"])
        self._note_ack(part, ver)
        return ver

    # ---- fan-out plumbing ----

    def _call(self, sub_requests: dict, method: str,
              serializer: str = "json") -> dict:
        def on_retry(idx, err):
            with self._mu:
                self.n_retries += 1
            CLIENT_RETRIES.add(1)
        return self._pc.call_partitioned(
            "PS", method, sub_requests, serializer=serializer,
            timeout_ms=self.timeout_ms, max_retry=self.max_retry,
            on_retry=on_retry)

    def _mode_for(self, part: int) -> str:
        return self._wire_mode.get(part, self.serializer)

    def _mark_json(self, part: int) -> None:
        with self._mu:
            if self._wire_mode.get(part) == "json":
                return      # already downgraded (a concurrent fan-out
                            # won the race) — count the change once
            self._wire_mode[part] = "json"
            self.n_negotiation_fallbacks += 1
        CLIENT_NEGOTIATION_FALLBACKS.add(1)

    @staticmethod
    def _group_failures(e, parts, out) -> dict:
        """One group call raised: absorb its partial responses into
        ``out`` and return {part: error} for the parts that failed (an
        error with no per-partition detail blames every unanswered
        part)."""
        out.update(getattr(e, "partial_responses", {}) or {})
        fj = getattr(e, "failed_partitions", None)
        if fj:
            return dict(fj)
        return {p: e for p in parts if p not in out}

    def _fan_out(self, split: dict, base_method: str,
                 make_json, make_frame) -> dict:
        """Issue one sub-call per partition in each partition's
        negotiated wire format: ``base_method`` + JSON for "json"
        partitions, ``base_method + "T"`` + tensorframe for binary
        ones — the two groups run CONCURRENTLY (a steady-state mixed
        fleet after a rolling upgrade must pay max of the two
        fan-outs, not their sum).  A binary partition failing
        ENOMETHOD is an OLD PEER: it downgrades to JSON (sticky) and
        its sub-call re-issues — sub-requests are idempotent
        (per-partition update_ids are a pure function of the logical
        token), so the re-issue is safe even if the first attempt
        applied.  On any partition failing for real, ONE error
        aggregates the whole fan-out (single shared code preserved,
        else ETOOMANYFAILS; failed_partitions + partial_responses
        carry the detail)."""
        modes = {part: self._mode_for(part) for part in split}
        out: dict = {}
        failures: dict = {}
        bin_parts = [p for p in split if modes[p] == "tensorframe"]
        json_parts = [p for p in split if modes[p] == "json"]

        json_out: dict = {}
        json_exc: list = [None]

        def run_json(parts):
            sub = {p: make_json(p, split[p]) for p in parts}
            try:
                json_out.update(self._call(sub, base_method,
                                           serializer="json"))
            except errors.RpcError as e:
                json_exc[0] = e
            except Exception as e:     # a non-Rpc bug must not leave
                # the group silently unanswered (the caller would then
                # KeyError outside the RpcError/update_token contract)
                json_exc[0] = errors.RpcError(
                    errors.EINTERNAL,
                    f"json fan-out failed: {type(e).__name__}: {e}")

        jt = None
        if json_parts:
            if bin_parts:
                # one short-lived thread per MIXED-fleet call: mixed
                # wire modes are the rolling-upgrade transitional state
                # (steady fleets take one group and never spawn), and
                # the thread buys max-of-the-two-fan-outs latency
                jt = threading.Thread(target=run_json,
                                      args=(json_parts,), daemon=True)
                jt.start()
            else:
                run_json(json_parts)

        fallback = []
        if bin_parts:
            sub = {p: make_frame(p, split[p]) for p in bin_parts}
            try:
                out.update(self._call(sub, base_method + "T",
                                      serializer="tensorframe"))
            except errors.RpcError as e:
                for p, err in self._group_failures(
                        e, bin_parts, out).items():
                    if isinstance(err, errors.RpcError) \
                            and err.code == errors.ENOMETHOD:
                        self._mark_json(p)
                        fallback.append(p)
                    else:
                        failures[p] = err
        if fallback:
            # one-time re-issue for freshly-downgraded old peers
            # (first contact only; steady state rides the concurrent
            # JSON group above)
            sub = {p: make_json(p, split[p]) for p in fallback}
            try:
                out.update(self._call(sub, base_method,
                                      serializer="json"))
            except errors.RpcError as e:
                failures.update(self._group_failures(e, fallback, out))
        if jt is not None:
            jt.join()
        out.update(json_out)
        if json_exc[0] is not None:
            failures.update(self._group_failures(json_exc[0],
                                                 json_parts, out))
        if failures:
            codes = {err.code for err in failures.values()
                     if isinstance(err, errors.RpcError)}
            code = codes.pop() if len(codes) == 1 \
                else errors.ETOOMANYFAILS
            first_p = next(iter(failures))
            err = errors.RpcError(
                code, f"{len(failures)}/{len(split)} partitions "
                      f"failed (first: partition {first_p}: "
                      f"{failures[first_p]})")
            err.failed_partitions = dict(failures)
            err.partial_responses = dict(out)
            raise err
        return out

    def _note_ack(self, part: int, ver: int) -> None:
        with self._mu:
            if ver > self.acked_version[part]:
                self.acked_version[part] = ver

    def _note_version(self, part: int, ver: int) -> None:
        """Read-your-writes check: a lookup must observe every update
        THIS client already got acked on that shard."""
        with self._mu:
            if ver < self.acked_version[part]:
                self.n_stale_reads += 1
                CLIENT_STALE_READS.add(1)

    def close(self) -> None:
        if self._pc is not None:
            self._pc.close()

    def stats(self) -> dict:
        with self._mu:
            return {
                "name": self.name,
                "n_shards": self.n_shards,
                "backend": "lowered" if self._lowered is not None
                           else "partition_channel",
                "serializer": self.serializer,
                "wire_modes": dict(self._wire_mode),
                "negotiation_fallbacks": self.n_negotiation_fallbacks,
                "ici_calls": self.n_ici_calls,
                "lookups": self.n_lookups,
                "updates": self.n_updates,
                "stale_reads": self.n_stale_reads,
                "acked_versions": list(self.acked_version),
            }
