"""ShardedEmbeddingTable — the PartitionChannel fan-out LOWERED to
collectives (SURVEY.md §5.8; the SNIPPETS.md [2] shard_map+ppermute
shape).

When every partition of the embedding service is a chip in the LOCAL
mesh, the client's split → N sub-calls → reassemble plan wastes the
fabric: the idiomatic lowering runs the whole exchange as ONE jitted
``shard_map`` over the ``tp`` axis.  The table lives row-sharded
(``P("tp", None)`` — each chip owns a contiguous row range; when
``vocab % p == 0`` this is exactly the
:func:`~brpc_tpu.psserve.shard.shard_bounds` ownership map the RPC
shards use, otherwise the table pads to even ``vocab/p`` blocks and
the two layouts differ — don't use ``shard_bounds`` to locate a key's
CHIP here), and a lookup is

  * ``mode="psum"``  — broadcast the keys, every chip gathers the rows
    it owns (masked local gather), ``psum`` over ``tp`` merges: one
    all-reduce instead of N socket round-trips;
  * ``mode="ring"``  — shard the keys, then ``ppermute`` the key block
    (and its accumulating rows) around the ring: after ``p`` hops every
    block visited every owner and is back home — the classic all-to-all
    embedding exchange, the exact SNIPPETS.md [2] pattern.

Updates scatter-add locally under an ownership mask (no collective on
the way out — the table STAYS sharded).  Key counts pad up to buckets
so each mode compiles once per bucket.  Both modes are bit-identical to
the dense single-host oracle: gathers are exact, and scatter-adds see
the same per-key operand order the dense op does (all duplicates of a
key land on its one owner, in request order).
"""
from __future__ import annotations

import threading
from typing import Optional, Sequence

import numpy as np

from brpc_tpu.bvar import Adder
from brpc_tpu.butil.lockprof import InstrumentedLock
from brpc_tpu.psserve.shard import (DEFAULT_KEY_BUCKETS, _bucket_up,
                                    init_embedding_table)

LOWERED_LOOKUPS = Adder("psserve_lowered_lookups")
LOWERED_UPDATES = Adder("psserve_lowered_updates")


class ShardedEmbeddingTable:
    """One logical [vocab, dim] table row-sharded over a ``tp`` mesh;
    lookup/update run as single compiled collective programs."""

    def __init__(self, vocab: int, dim: int, *, mesh=None,
                 n_shards: Optional[int] = None, seed: int = 0,
                 table: Optional[np.ndarray] = None,
                 key_buckets: Sequence[int] = DEFAULT_KEY_BUCKETS,
                 mode: str = "psum", serve_local: bool = False,
                 name: str = "ps", applied_cap: int = 65536):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from brpc_tpu.ici.collective import shard_map
        if mode not in ("psum", "ring"):
            raise ValueError(f"mode must be psum|ring, got {mode!r}")
        if mesh is None:
            from brpc_tpu.models.runner import make_tp_mesh
            mesh = make_tp_mesh(n_shards)
        self.mesh = mesh
        self.p = int(mesh.shape["tp"])
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.mode = mode
        self.key_buckets = tuple(sorted(key_buckets))
        full = table if table is not None else \
            init_embedding_table(vocab, dim, seed)
        full = np.asarray(full, np.float32)
        # pad rows so the shard axis divides evenly; the pad rows are
        # unaddressable (keys < vocab) and stay zero
        self.vpad = ((self.vocab + self.p - 1) // self.p) * self.p
        if self.vpad != self.vocab:
            full = np.concatenate(
                [full, np.zeros((self.vpad - self.vocab, self.dim),
                                np.float32)])
        self.rows_per = self.vpad // self.p
        self._table = jax.device_put(
            full, NamedSharding(mesh, P("tp", None)))
        self._mu = InstrumentedLock("psserve.table")
        self.version = 0
        self.n_lookups = 0
        self.n_updates = 0
        self.n_opt_updates = 0
        self.n_dup_updates = 0
        self.name = str(name)
        # the ICI fast path's idempotence (ISSUE 13): the same
        # update_id-checked-against-an-applied-set discipline the RPC
        # shards run, so a co-located client's replayed update_token
        # acks the ORIGINAL apply instead of double scatter-adding
        from collections import OrderedDict
        self._applied: "OrderedDict[int, int]" = OrderedDict()
        self._applied_cap = int(applied_cap)
        from brpc_tpu import psserve as _ps
        _ps._register_table(self)
        if serve_local:
            # explicit opt-in: THIS table serves co-located PSClients
            # (PSClient(ici="auto") short-circuits to it)
            _ps.register_local_table(self, name=self.name)

        jnp_ = jnp
        rows_per = self.rows_per
        p = self.p

        def _local_gather(tbl, keys):
            # tbl: this chip's [rows_per, dim] block; keys: global ids
            lo = jax.lax.axis_index("tp") * rows_per
            local = keys - lo
            mask = (local >= 0) & (local < rows_per)
            safe = jnp_.clip(local, 0, rows_per - 1)
            rows = tbl[safe]
            return jnp_.where(mask[:, None], rows, 0.0), mask

        def _lookup_psum(tbl, keys):
            rows, _ = _local_gather(tbl, keys)
            return jax.lax.psum(rows, "tp")

        def _lookup_ring(tbl, blk):
            # blk: this chip's key block [n/p]; rotate (block, acc)
            # around the ring — after p ppermute hops the block has
            # visited every owner and is back at its home chip
            acc = jnp_.zeros((blk.shape[0], self.dim), jnp_.float32)
            perm = [(i, (i + 1) % p) for i in range(p)]

            def hop(carry, _):
                b, a = carry
                rows, _ = _local_gather(tbl, b)
                a = a + rows
                b = jax.lax.ppermute(b, "tp", perm)
                a = jax.lax.ppermute(a, "tp", perm)
                return (b, a), None

            (blk, acc), _ = jax.lax.scan(hop, (blk, acc), None, length=p)
            return acc

        def _update(tbl, keys, grads):
            lo = jax.lax.axis_index("tp") * rows_per
            local = keys - lo
            mask = (local >= 0) & (local < rows_per)
            safe = jnp_.clip(local, 0, rows_per - 1)
            g = jnp_.where(mask[:, None], grads, 0.0)
            return tbl.at[safe].add(g)

        # the fused co-located optimizer updates (ISSUE 17): the SAME
        # ownership-mask discipline as _update, with the slot step
        # from train/optimizer.py running on each chip's block — the
        # whole train step stays ONE shard_map program and the slot
        # rows stay sharded exactly like their table rows.  Pad keys
        # (-1) are owned by nobody: mask-zeroed gradient AND zero
        # touch count, so padding can't decay row 0's momentum.
        from brpc_tpu.train.optimizer import adam_step, sgdm_step

        def _local_acc(tbl, keys, grads):
            lo = jax.lax.axis_index("tp") * rows_per
            local = keys - lo
            mask = (local >= 0) & (local < rows_per)
            safe = jnp_.clip(local, 0, rows_per - 1)
            g = jnp_.where(mask[:, None], grads, 0.0)
            g_acc = jnp_.zeros_like(tbl).at[safe].add(g)
            cnt = jnp_.zeros((tbl.shape[0],), jnp_.float32
                             ).at[safe].add(mask.astype(jnp_.float32))
            return g_acc, cnt > 0.0

        def _update_sgdm(tbl, m, keys, grads, lr, mu):
            g_acc, touched = _local_acc(tbl, keys, grads)
            return sgdm_step(jnp_, tbl, m, g_acc, touched, lr, mu)

        def _update_adam(tbl, m, v, t, keys, grads, lr, b1, b2, eps):
            g_acc, touched = _local_acc(tbl, keys, grads)
            return adam_step(jnp_, tbl, m, v, t, g_acc, touched,
                             lr, b1, b2, eps)

        self._lookup_psum = jax.jit(shard_map(
            _lookup_psum, mesh, in_specs=(P("tp", None), P()),
            out_specs=P()))
        self._lookup_ring = jax.jit(shard_map(
            _lookup_ring, mesh, in_specs=(P("tp", None), P("tp")),
            out_specs=P("tp", None)))
        self._update = jax.jit(shard_map(
            _update, mesh, in_specs=(P("tp", None), P(), P()),
            out_specs=P("tp", None)))
        self._update_sgdm = jax.jit(shard_map(
            _update_sgdm, mesh,
            in_specs=(P("tp", None), P("tp", None), P(), P(), P(), P()),
            out_specs=(P("tp", None), P("tp", None))))
        self._update_adam = jax.jit(shard_map(
            _update_adam, mesh,
            in_specs=(P("tp", None), P("tp", None), P("tp", None),
                      P("tp"), P(), P(), P(), P(), P(), P()),
            out_specs=(P("tp", None), P("tp", None), P("tp", None),
                       P("tp"))))
        self._slots: dict = {}

    # ---- client surface (PSClient's co-located backend) ----

    def _pad_keys(self, keys, multiple_of: int = 1) -> tuple:
        keys = np.asarray(keys, np.int64)
        n = keys.shape[0]
        b = _bucket_up(max(n, 1), self.key_buckets)
        if b % multiple_of:
            b = ((b + multiple_of - 1) // multiple_of) * multiple_of
        padded = np.full((b,), -1, np.int64)   # -1: owned by nobody
        padded[:n] = keys
        return padded, n

    def lookup(self, keys) -> tuple[np.ndarray, int]:
        """Gather rows for GLOBAL keys (any owner, duplicates legal):
        one compiled collective program per key bucket."""
        if self.mode == "ring":
            padded, n = self._pad_keys(keys, multiple_of=self.p)
            out = self._lookup_ring(self._table, padded)
        else:
            padded, n = self._pad_keys(keys)
            out = self._lookup_psum(self._table, padded)
        with self._mu:
            ver = self.version
            self.n_lookups += 1
        LOWERED_LOOKUPS.add(1)
        return np.asarray(out)[:n], ver

    def _ensure_slots_locked(self, spec) -> None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        if "m" not in self._slots:
            self._slots["m"] = jnp.zeros_like(self._table)
        if spec.kind == "adam":
            if "v" not in self._slots:
                self._slots["v"] = jnp.zeros_like(self._table)
            if "t" not in self._slots:
                self._slots["t"] = jax.device_put(
                    np.zeros((self.vpad,), np.float32),
                    NamedSharding(self.mesh, P("tp")))

    def update(self, keys, grads, update_id: Optional[int] = None,
               optimizer=None) -> int:
        """Scatter-add grads into the sharded table; one compiled
        program, table stays sharded.  With ``update_id`` the apply is
        idempotent exactly like the RPC shards: a duplicate id acks
        the ORIGINAL apply's version and touches nothing.

        With ``optimizer`` (an :class:`OptimizerSpec`, ISSUE 17) the
        grads are RAW gradients and the apply is the fused
        scatter+slot-step shard_map program under the ownership mask —
        momentum/Adam slots stay sharded with their rows, and the dup
        check above covers them: a replayed wave steps nothing."""
        padded, n = self._pad_keys(keys)
        g = np.zeros((padded.shape[0], self.dim), np.float32)
        g[:n] = np.asarray(grads, np.float32)
        with self._mu:
            if update_id is not None and update_id in self._applied:
                self.n_dup_updates += 1
                return self._applied[update_id]
            if optimizer is None:
                self._table = self._update(self._table, padded, g)
            else:
                self._ensure_slots_locked(optimizer)
                s = self._slots
                f32 = np.float32
                if optimizer.kind == "sgdm":
                    self._table, s["m"] = self._update_sgdm(
                        self._table, s["m"], padded, g,
                        f32(optimizer.lr), f32(optimizer.momentum))
                else:
                    self._table, s["m"], s["v"], s["t"] = \
                        self._update_adam(
                            self._table, s["m"], s["v"], s["t"],
                            padded, g, f32(optimizer.lr),
                            f32(optimizer.beta1), f32(optimizer.beta2),
                            f32(optimizer.eps))
                self.n_opt_updates += 1
            self.version += 1
            ver = self.version
            if update_id is not None:
                self._applied[update_id] = ver
                while len(self._applied) > self._applied_cap:
                    self._applied.popitem(last=False)
            self.n_updates += 1
        LOWERED_UPDATES.add(1)
        return ver

    # ---- introspection / oracle ----

    def snapshot(self) -> np.ndarray:
        """Current table (vocab rows, pad stripped) as numpy."""
        with self._mu:
            return np.asarray(self._table)[:self.vocab]

    def snapshot_slots(self) -> dict:
        """Optimizer slots (vocab rows, pad stripped) as numpy."""
        with self._mu:
            return {k: np.asarray(v)[:self.vocab]
                    for k, v in self._slots.items()}

    def stats(self) -> dict:
        with self._mu:
            return {
                "name": self.name,
                "partitions": self.p,
                "vocab": self.vocab,
                "dim": self.dim,
                "mode": self.mode,
                "version": self.version,
                "lookups": self.n_lookups,
                "updates": self.n_updates,
                "opt_updates": self.n_opt_updates,
                "opt_slots": sorted(self._slots),
                "dup_updates": self.n_dup_updates,
                "applied_ids": len(self._applied),
                "mesh": dict(self.mesh.shape),
            }
