"""PS service registration — one shard's RPC surface.

``PS.Lookup`` / ``PS.Update`` / ``PS.Pull`` / ``PS.Push`` / ``PS.Stats``
ride the normal dispatch path (auth, interceptors, limiters,
MethodStatus all apply).  With ``batch=True`` (the default) concurrent
Lookup and Update RPCs COALESCE through two DynamicBatchers — the first
non-autoregressive traffic shape the batcher has ever coalesced:

  * lookups queue as int64 key vectors, bucket-padded by KEY COUNT; one
    jitted [B, Lb] -> [B, Lb, D] gather serves the whole batch (one
    compile per bucket pair, the serving discipline);
  * updates queue as packed float64 rows (update_id + interleaved
    key/grad groups, length buckets 1 + k*(1+D)); one jitted scatter-add
    applies the whole batch, with idempotence decided per row at apply
    time under the shard lock.

``PS.LookupT`` / ``PS.UpdateT`` (ISSUE 13) are the same semantics over
the BINARY tensor wire (rpc/tensorframe.py): requests arrive as frames
whose tensors are zero-copy views over the transport body, lookups
submit the int64 key view straight to the batcher, and updates pack
byte records (no float64 round-trip) into a third uint8-record
batcher — all three batchers default to EAGER mode (idle cut-through,
no window wait; see register_psserve), and an idle-batcher request
bypasses the defer machinery entirely.  Per-serializer request/wire-
byte Adders feed /psserve and /brpc_metrics.

Fault sites ``psserve.lookup`` / ``psserve.update`` cover the fan-out's
failure modes on BOTH wires: ``stage="pre"`` fails a sub-call before
any apply, ``stage="post"`` drops the ack AFTER the apply — the
retried sub-call must then dedup (chaos scenario 16 proves the version
counter advances exactly once).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from brpc_tpu import errors, fault
from brpc_tpu.bvar import Adder
from brpc_tpu.rpc.service import Service, method
from brpc_tpu.psserve.shard import EmbeddingShardServer

# per-serializer wire accounting (ISSUE 13): request counts and
# REQUEST-direction wire bytes per format, served from the decode
# phase's exact cntl.request_body_size — /psserve renders them and
# /brpc_metrics scrapes them; rpc_press --embedding turns the deltas
# into wire bytes/request for the reproducible A/B
REQUESTS_JSON = Adder("psserve_requests_json")
REQUESTS_TENSORFRAME = Adder("psserve_requests_tensorframe")
WIRE_BYTES_JSON = Adder("psserve_wire_bytes_json")
WIRE_BYTES_TENSORFRAME = Adder("psserve_wire_bytes_tensorframe")


def _coerce_uid(uid):
    """ONE update_id validation for BOTH wires (a retry may cross
    formats after a negotiation fallback and the dedup set is shared,
    so accept/reject must not differ): integers (and integral floats —
    some JSON encoders emit 123.0) in (0, 2**53]; strings and
    fractional floats are refused — int("123")/int(123.9) coercion
    would record the apply under an id the caller never sent, the
    exact rounded-onto-another-id hazard the bound exists to refuse.
    Returns (ok, value, error_text)."""
    if uid is None:
        return True, None, ""
    if isinstance(uid, bool) or not isinstance(uid, (int, float)):
        return False, None, "update_id must be an integer"
    if isinstance(uid, float):
        if not uid.is_integer():
            return False, None, "update_id must be an integer"
        uid = int(uid)
    if not (0 < uid <= (1 << 53)):
        # inclusive upper bound: 2**53 itself is exactly representable
        # in float64 (it's 2**53 + 1 that isn't), and PSClient's max
        # mintable id lands exactly there (salt/counter saturated at
        # n_shards=32)
        return False, None, "update_id must be in (0, 2**53]"
    return True, uid, ""


def wire_counters() -> dict:
    """The per-serializer counters as one dict (the /psserve page's
    "wire" section)."""
    return {
        "requests_json": REQUESTS_JSON.get_value(),
        "requests_tensorframe": REQUESTS_TENSORFRAME.get_value(),
        "wire_bytes_json": WIRE_BYTES_JSON.get_value(),
        "wire_bytes_tensorframe": WIRE_BYTES_TENSORFRAME.get_value(),
    }


class PSService(Service):
    NAME = "PS"

    def __init__(self, shard: EmbeddingShardServer,
                 lookup_batcher=None, update_batcher=None,
                 update_record_batcher=None):
        self.shard = shard
        self._lookup_b = lookup_batcher
        self._update_b = update_batcher
        # the BINARY update path's batcher (uint8 records, no float64
        # packing); None falls back to direct per-request apply
        self._update_tb = update_record_batcher

    @staticmethod
    def _count_wire(cntl, binary: bool) -> None:
        n = int(getattr(cntl, "request_body_size", 0) or 0)
        if binary:
            REQUESTS_TENSORFRAME.add(1)
            WIRE_BYTES_TENSORFRAME.add(n)
        else:
            REQUESTS_JSON.add(1)
            WIRE_BYTES_JSON.add(n)

    @staticmethod
    def _claim_bypass(b) -> bool:
        """Idle bypass (ISSUE 13): with an EAGER batcher that has no
        queue and no batch in flight, this request would execute alone
        anyway — serve it straight on the handler thread and skip the
        defer/enqueue/scatter bookkeeping entirely (~300us on CPU
        loopback).  The claim (``DynamicBatcher.try_claim_idle``) holds
        the batcher's execution slot, so concurrent arrivals queue and
        coalesce behind the bypassed request; brownout refuses the
        claim so degraded batchers keep their shed policy."""
        return b is not None and b.try_claim_idle()

    @staticmethod
    def _release_bypass(b) -> None:
        b.release_idle()

    # ---- the fused co-located optimizer apply (ISSUE 17) ----
    #
    # An optimizer-carrying Update takes the DIRECT path: the wave is
    # already trainer-batched (one RPC per partition per step), its
    # semantics (slot step per touched row) can't coalesce with plain
    # scatter-adds in a batcher row, and the apply is one fused jitted
    # program either way.  Same lock, same version counter, same
    # applied-id dedup set as every other update — a retry on EITHER
    # wire acks the original apply and steps nothing.

    def _apply_opt(self, cntl, keys, grads, uid, spec):
        if fault.ENABLED and fault.hit(
                "psserve.opt_apply", shard=self.shard.shard_index,
                stage="pre") is not None:
            # pre-apply: no slot stepped, no row written; a retried
            # wave applies normally
            cntl.set_failed(errors.EINTERNAL,
                            "injected psserve.opt_apply fault "
                            "(pre-apply)")
            return None
        try:
            ver, dup = self.shard.update_opt(keys, grads, spec,
                                             update_id=uid)
        except ValueError as e:
            cntl.set_failed(errors.EREQUEST, str(e))
            return None
        if fault.ENABLED and fault.hit(
                "psserve.opt_apply", shard=self.shard.shard_index,
                stage="post") is not None:
            # post-apply ack drop: momentum DID step; the retried wave
            # must dedup by update_id or the slot double-steps (chaos
            # scenario 18 proves it doesn't)
            cntl.set_failed(errors.EINTERNAL,
                            "injected psserve.opt_apply fault "
                            "(post-apply)")
            return None
        return {"version": int(ver), "duplicate": bool(dup)}

    # ---- Lookup ----

    @method(request="json", response="json")
    def Lookup(self, cntl, req):
        self._count_wire(cntl, binary=False)
        keys = (req or {}).get("keys")
        if keys is None:
            cntl.set_failed(errors.EREQUEST, 'missing "keys"')
            return None
        if fault.ENABLED and fault.hit(
                "psserve.lookup", shard=self.shard.shard_index,
                n_keys=len(keys)) is not None:
            cntl.set_failed(errors.EINTERNAL,
                            "injected psserve.lookup fault")
            return None
        try:
            local = self.shard._to_local(np.asarray(keys, np.int64))
        except ValueError as e:
            cntl.set_failed(errors.EREQUEST, str(e))
            return None
        b = self._lookup_b
        claimed = self._claim_bypass(b)
        if b is None or claimed:
            try:
                try:
                    rows, ver = self.shard.lookup(keys)  # counts + hot
                except ValueError as e:
                    # e.g. a key-set larger than the biggest bucket: a
                    # deterministic bad request, never EINTERNAL
                    cntl.set_failed(errors.EREQUEST, str(e))
                    return None
                return {"rows": rows.tolist(), "version": ver}
            finally:
                if claimed:
                    self._release_bypass(b)

        shard = self.shard

        def transform(row):
            # row: [n_keys, D] trimmed by the batcher's padded-output
            # scatter; version read at COMPLETION so any update acked
            # before this lookup's batch executed is covered.  Hot-key
            # and counter accounting happens HERE — only lookups that
            # were actually served shape the histogram (a shed/ELIMIT
            # reject never runs the transform), matching the unbatched
            # path
            shard._note_hot(local)
            with shard._mu:
                ver = shard.version
                shard.n_lookups += 1
            from brpc_tpu.psserve.shard import LOOKUPS, LOOKUP_KEYS
            LOOKUPS.add(1)
            LOOKUP_KEYS.add(int(row.shape[0]))
            return {"rows": np.asarray(row).tolist(), "version": ver}

        self._lookup_b.submit(cntl, local, transform=transform)
        return None     # deferred: the batch drainer completes the RPC

    # ---- Update ----

    @method(request="json", response="json")
    def Update(self, cntl, req):
        self._count_wire(cntl, binary=False)
        req = req or {}
        keys = req.get("keys")
        grads = req.get("grads")
        uid = req.get("update_id")
        if keys is None or grads is None:
            cntl.set_failed(errors.EREQUEST, 'missing "keys"/"grads"')
            return None
        # the batched apply packs ids into float64 rows and uses 0 as
        # the padding sentinel — an id outside (0, 2^53] would be
        # silently discarded (acked but never applied) or rounded onto
        # another id; ONE validation shared with the binary wire
        ok, uid, msg = _coerce_uid(uid)
        if not ok:
            cntl.set_failed(errors.EREQUEST, msg)
            return None
        spec = None
        if req.get("optimizer") is not None:
            from brpc_tpu.train.optimizer import OptimizerSpec
            try:
                spec = OptimizerSpec.from_wire(req["optimizer"])
            except ValueError as e:
                cntl.set_failed(errors.EREQUEST, str(e))
                return None
        if fault.ENABLED and fault.hit(
                "psserve.update", shard=self.shard.shard_index,
                stage="pre") is not None:
            # pre-apply failure: nothing was written; a retry applies
            # normally
            cntl.set_failed(errors.EINTERNAL,
                            "injected psserve.update fault (pre-apply)")
            return None
        try:
            local = self.shard._to_local(np.asarray(keys, np.int64))
            g = np.asarray(grads, np.float32)
            if g.shape != (local.shape[0], self.shard.dim):
                raise ValueError(f"grads shape {g.shape} != "
                                 f"({local.shape[0]}, {self.shard.dim})")
        except ValueError as e:
            cntl.set_failed(errors.EREQUEST, str(e))
            return None
        if spec is not None:
            return self._apply_opt(cntl, keys, g, uid, spec)

        def ack(ver: int, dup: bool):
            if fault.ENABLED and fault.hit(
                    "psserve.update", shard=self.shard.shard_index,
                    stage="post") is not None:
                # post-apply ack drop: the update IS in the table; the
                # client's retry must be deduped by update_id or the
                # scatter-add doubles (chaos proves it doesn't)
                raise RuntimeError(
                    "injected psserve.update fault (post-apply)")
            return {"version": int(ver), "duplicate": bool(dup)}

        b = self._update_b
        claimed = False
        if b is not None and uid is not None:
            claimed = self._claim_bypass(b)
        if b is None or uid is None or claimed:
            try:
                try:
                    ver, dup = self.shard.update(keys, grads,
                                                 update_id=uid)
                except ValueError as e:
                    # oversize key-set etc.: deterministic bad request
                    cntl.set_failed(errors.EREQUEST, str(e))
                    return None
                try:
                    return ack(ver, dup)
                except RuntimeError as e:
                    cntl.set_failed(errors.EINTERNAL, str(e))
                    return None
            finally:
                if claimed:
                    self._release_bypass(b)
        row = EmbeddingShardServer.pack_update(int(uid), local, g)
        n_keys = int(local.shape[0])

        def transform(a):
            # a raising transform completes the RPC with EINTERNAL —
            # the post-apply ack-drop path above rides that contract.
            # UPDATE_KEYS counts here (the batch fn can't recover live
            # key counts from zero-padded rows), applied rows only
            if not bool(a[1]):
                from brpc_tpu.psserve.shard import UPDATE_KEYS
                UPDATE_KEYS.add(n_keys)
            return ack(int(a[0]), bool(a[1]))

        self._update_b.submit(cntl, row, transform=transform)
        return None

    # ---- the binary tensor wire (tensorframe, ISSUE 13) ----
    #
    # Same semantics as Lookup/Update — same fault sites, same dedup
    # set, same batchers' bucket discipline — but the request arrives
    # as a tensorframe whose tensors are ZERO-COPY views over the
    # transport body, and batches form directly from those views: the
    # lookup batcher takes the int64 key view as-is, and updates pack
    # byte records (pack_update_record) instead of the float64
    # 1+k*(1+D) rows.  A client that calls LookupT/UpdateT on an old
    # server gets ENOMETHOD and falls back to JSON per channel
    # (PSClient negotiation).

    @method(request="tensorframe", response="tensorframe")
    def LookupT(self, cntl, req):
        self._count_wire(cntl, binary=True)
        keys = (req or {}).get("keys")
        if keys is None or not isinstance(keys, np.ndarray) \
                or keys.dtype != np.int64 or keys.ndim != 1:
            cntl.set_failed(errors.EREQUEST,
                            'need int64[n] tensor field "keys"')
            return None
        if fault.ENABLED and fault.hit(
                "psserve.lookup", shard=self.shard.shard_index,
                n_keys=len(keys)) is not None:
            cntl.set_failed(errors.EINTERNAL,
                            "injected psserve.lookup fault")
            return None
        try:
            local = self.shard._to_local(keys)
        except ValueError as e:
            cntl.set_failed(errors.EREQUEST, str(e))
            return None
        b = self._lookup_b
        claimed = self._claim_bypass(b)
        if b is None or claimed:
            try:
                try:
                    rows, ver = self.shard.lookup(keys)
                except ValueError as e:
                    cntl.set_failed(errors.EREQUEST, str(e))
                    return None
                return {"rows": rows, "version": ver}
            finally:
                if claimed:
                    self._release_bypass(b)

        shard = self.shard

        def transform(row):
            # identical accounting to the JSON transform; the response
            # rows ride out as raw float32 bytes, never a list
            shard._note_hot(local)
            with shard._mu:
                ver = shard.version
                shard.n_lookups += 1
            from brpc_tpu.psserve.shard import LOOKUPS, LOOKUP_KEYS
            LOOKUPS.add(1)
            LOOKUP_KEYS.add(int(row.shape[0]))
            return {"rows": np.asarray(row), "version": ver}

        self._lookup_b.submit(cntl, local, transform=transform)
        return None

    @method(request="tensorframe", response="tensorframe")
    def UpdateT(self, cntl, req):
        self._count_wire(cntl, binary=True)
        req = req or {}
        keys = req.get("keys")
        grads = req.get("grads")
        uid = req.get("update_id")
        if keys is None or grads is None \
                or not isinstance(keys, np.ndarray) \
                or not isinstance(grads, np.ndarray) \
                or keys.dtype != np.int64 or keys.ndim != 1 \
                or grads.dtype != np.float32:
            cntl.set_failed(errors.EREQUEST,
                            'need int64[n] "keys" + float32[n,D] '
                            '"grads" tensor fields')
            return None
        # the SAME validation as the JSON path: dedup is one applied
        # set, and a retry may cross wire formats after a negotiation
        # fallback — accept/reject must not differ between wires
        ok, uid, msg = _coerce_uid(uid)
        if not ok:
            cntl.set_failed(errors.EREQUEST, msg)
            return None
        # the binary wire's optimizer spec rides as FLATTENED inline
        # fields (opt_kind + opt_* floats — tensorframe has no nested
        # dicts); same validation → EREQUEST contract as JSON
        from brpc_tpu.train.optimizer import OptimizerSpec
        try:
            spec = OptimizerSpec.from_frame_fields(req)
        except ValueError as e:
            cntl.set_failed(errors.EREQUEST, str(e))
            return None
        if fault.ENABLED and fault.hit(
                "psserve.update", shard=self.shard.shard_index,
                stage="pre") is not None:
            cntl.set_failed(errors.EINTERNAL,
                            "injected psserve.update fault (pre-apply)")
            return None
        try:
            local = self.shard._to_local(keys)
            if grads.shape != (local.shape[0], self.shard.dim):
                raise ValueError(f"grads shape {grads.shape} != "
                                 f"({local.shape[0]}, {self.shard.dim})")
        except ValueError as e:
            cntl.set_failed(errors.EREQUEST, str(e))
            return None
        if spec is not None:
            return self._apply_opt(cntl, keys, grads, uid, spec)

        def ack(ver: int, dup: bool):
            if fault.ENABLED and fault.hit(
                    "psserve.update", shard=self.shard.shard_index,
                    stage="post") is not None:
                raise RuntimeError(
                    "injected psserve.update fault (post-apply)")
            return {"version": int(ver), "duplicate": bool(dup)}

        b = self._update_tb
        claimed = False
        if b is not None and uid is not None:
            claimed = self._claim_bypass(b)
        if b is None or uid is None or claimed:
            try:
                try:
                    ver, dup = self.shard.update(keys, grads,
                                                 update_id=uid)
                except ValueError as e:
                    cntl.set_failed(errors.EREQUEST, str(e))
                    return None
                try:
                    return ack(ver, dup)
                except RuntimeError as e:
                    cntl.set_failed(errors.EINTERNAL, str(e))
                    return None
            finally:
                if claimed:
                    self._release_bypass(b)
        rec = EmbeddingShardServer.pack_update_record(int(uid), local,
                                                     grads)
        n_keys = int(local.shape[0])

        def transform(a):
            if not bool(a[1]):
                from brpc_tpu.psserve.shard import UPDATE_KEYS
                UPDATE_KEYS.add(n_keys)
            return ack(int(a[0]), bool(a[1]))

        self._update_tb.submit(cntl, rec, transform=transform)
        return None

    # ---- dense params ----

    @method(request="json", response="json")
    def Pull(self, cntl, req):
        pname = (req or {}).get("name")
        if not pname:
            cntl.set_failed(errors.EREQUEST, 'missing "name"')
            return None
        try:
            v = self.shard.pull(pname)
        except KeyError:
            cntl.set_failed(errors.ENODATA, f"no dense param {pname!r}")
            return None
        return {"name": pname, "value": v.tolist(),
                "shape": list(v.shape)}

    @method(request="json", response="json")
    def Push(self, cntl, req):
        req = req or {}
        pname = req.get("name")
        delta = req.get("delta")
        if not pname or delta is None:
            cntl.set_failed(errors.EREQUEST, 'missing "name"/"delta"')
            return None
        try:
            ver, dup = self.shard.push(pname, delta,
                                       update_id=req.get("update_id"))
        except ValueError as e:
            cntl.set_failed(errors.EREQUEST, str(e))
            return None
        return {"version": int(ver), "duplicate": bool(dup)}

    @method(request="json", response="json")
    def Stats(self, cntl, req):
        return self.shard.stats()


def register_psserve(server, shard: EmbeddingShardServer, *,
                     batch: bool = True, max_batch_size: int = 16,
                     max_delay_us: int = 1000, eager: bool = True,
                     name: Optional[str] = None):
    """Expose one shard on an rpc Server; returns the PSService (its
    batchers close with ``unregister_psserve``).

    The PS batchers default to EAGER mode (ISSUE 13): an idle arrival
    cuts through inline (no window, no cross-thread hop) and batches
    form from whatever accumulated while the previous batch executed —
    small-request embedding traffic is latency-sensitive, and the
    batching window was measured costing ~1ms per request of pure idle
    latency on CPU loopback.  ``eager=False`` restores the windowed
    ``max_delay_us`` policy."""
    from brpc_tpu import psserve as _ps
    lookup_b = update_b = update_tb = None
    safe = name or f"{shard.name}_{shard.shard_index}"
    if batch:
        from brpc_tpu.serving.batcher import DynamicBatcher
        lookup_b = DynamicBatcher(
            shard.lookup_batch_fn,
            max_batch_size=max_batch_size, max_delay_us=max_delay_us,
            length_buckets=shard.key_buckets,
            dtype=np.int64, padded_output=True, eager=eager,
            name=f"ps_lookup_{safe}")
        update_b = DynamicBatcher(
            shard.update_batch_fn,
            max_batch_size=max_batch_size, max_delay_us=max_delay_us,
            length_buckets=shard.update_length_buckets(),
            dtype=np.float64, padded_output=False, eager=eager,
            name=f"ps_update_{safe}")
        # the binary wire's update batcher: uint8 records, byte-length
        # buckets — coalesces UpdateT exactly like Update, against the
        # same shard lock and applied set
        update_tb = DynamicBatcher(
            shard.update_batch_fn_binary,
            max_batch_size=max_batch_size, max_delay_us=max_delay_us,
            length_buckets=shard.update_record_buckets(),
            dtype=np.uint8, padded_output=False, eager=eager,
            name=f"ps_updatet_{safe}")
    svc = PSService(shard, lookup_batcher=lookup_b,
                    update_batcher=update_b,
                    update_record_batcher=update_tb)
    server.add_service(svc)
    _ps._register_shard(shard, svc)
    return svc


def unregister_psserve(svc: PSService) -> None:
    """Close the service's batchers (flushes queued batches)."""
    for b in (svc._lookup_b, svc._update_b, svc._update_tb):
        if b is not None:
            b.close()
